"""Worker abstraction for distributed stage execution.

Reference: the flotilla Worker/WorkerManager traits
(``src/daft-distributed/src/scheduling/worker.rs:13-25``) whose first
implementation is a Ray actor per node; here the first implementation is an
in-process worker (one per mesh device group / CPU slice), and the seam is
identical: ``submit`` returns a future of materialized partitions, so a
multi-host gRPC worker drops in without touching the scheduler.
"""

from __future__ import annotations

import concurrent.futures as cf
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..micropartition import MicroPartition
from ..physical import plan as pp

#: map-side combine: merge buffered per-partition state only once the
#: buffer rivals the state (LSM-style amortization, same rule as the
#: local fused reducer in execution/pipeline.py)
_COMBINE_REAGG_ROWS = 1 << 16


@dataclass
class ShuffleOutSpec:
    """Map-side instruction: partition this task's output into the
    worker-local shuffle cache instead of returning rows.

    ``kind``:
    - ``hash``  — hash-partition by ``by`` into ``num_partitions``.
    - ``store`` — store the whole output as partition 0 and (when
      ``sample_k`` > 0) return a key sample for driver-side boundary
      computation: phase 1 of the distributed range/sort protocol.
    - ``range`` — range-partition by ``by`` against ``boundaries_ipc``
      (arrow-IPC boundary rows): phase 2; rows move worker→worker, the
      driver only ever sees samples, boundaries and receipts.

    ``combine_aggs``/``combine_by`` (hash only) switch on the MAP-SIDE
    COMBINE: each partition's morsels are pre-aggregated to one
    group-state table before ``ShuffleCache.push``, so the wire carries
    group states instead of per-morsel rows (Partial Partial Aggregates).
    The combine exprs are self-merge aggs over the map-output (wire)
    schema and PRESERVE it, so the reduce side is byte-compatible with the
    uncombined plan; the stage planner only attaches them when the
    consumer is a decomposable final aggregation and the cost model prices
    the wire savings above the extra agg pass
    (``stages.combine_for_boundary`` + ``costmodel.shuffle_combine_wins``)."""

    num_partitions: int
    by: tuple  # key Expressions
    kind: str = "hash"
    descending: tuple = ()
    boundaries_ipc: Optional[bytes] = None
    sample_k: int = 0
    combine_aggs: Optional[tuple] = None  # merge exprs over the wire schema
    combine_by: tuple = ()                # combine group keys (boundary keys)


@dataclass
class ShuffleResult:
    """Map-side receipt: where a task's shuffled output is served from
    (flotilla: the shuffle cache registration a reduce task fetches by).

    ``rows``/``nbytes`` are the EXACT pushed cardinality and on-disk
    bytes of this map output, and ``state_rows`` (combine path only) the
    pushed group-state count — an upper bound on the boundary keys' NDV
    this task saw. The runtime re-planner (round 20) folds these actuals
    into downstream stage decisions before dispatching them."""

    address: str
    shuffle_id: str
    num_partitions: int
    rows: int
    samples_ipc: Optional[bytes] = None
    nbytes: int = 0
    state_rows: Optional[int] = None


@dataclass
class FetchSpec:
    """Reduce-side stage input: pull partition ``partition`` from every
    listed (address, shuffle_id) map output and concat. ``keys`` are
    stable per-source identities (stage/map-task derived, NOT the
    run-specific shuffle uuid) so fault-injection decisions replay
    bit-identically across runs."""

    sources: List  # [(address, shuffle_id)]
    partition: int
    keys: Optional[List[str]] = None


@dataclass
class StageTask:
    """One dispatchable unit: an exchange-free plan fragment plus its
    stage-input bindings (flotilla's SwordfishTask shape,
    ``scheduling/task.rs:80``). ``stage_inputs`` values are either
    materialized partition lists or a ``FetchSpec`` the worker resolves
    through the shuffle service."""

    stage_id: int
    plan: pp.PhysicalPlan
    stage_inputs: Dict[int, object]
    task_idx: int = 0
    preferred_worker: Optional[str] = None
    shuffle_out: Optional[ShuffleOutSpec] = None
    # resilience plane: stable task identity for fault injection/lineage
    # (minted by the stage planner) and the dispatch attempt number (set
    # by the task supervisor; travels over the remote-worker wire)
    fault_key: str = ""
    attempt: int = 0
    # tracing plane: (trace_id, run_span_id, parent_span_id) minted by
    # the task supervisor from the stable fault key — the worker records
    # its task-run span under exactly these ids (travels over the
    # remote-worker wire too); None = untraced query
    trace_ctx: Optional[tuple] = None


def _chaos_serialized() -> bool:
    from ..analysis import knobs
    return bool(knobs.env_bool("DAFT_TPU_CHAOS_SERIALIZE"))


def fetch_parallelism() -> int:
    """Bounded per-source fetch concurrency for a reduce task's stage
    input (``DAFT_TPU_SHUFFLE_FETCH_PARALLELISM``, default 4).
    ``DAFT_TPU_CHAOS_SERIALIZE=1`` forces 1 — deterministic sequential
    source order, bit-identical to the pre-parallel fetch path, which is
    what keeps the chaos-replay contract. An ACTIVE FAULT PLAN also
    defaults to 1 (explicit env setting wins): the parallel pool rolls
    EVERY source's injection decision on every attempt — a failing source
    no longer short-circuits the later ones — which multiplies injected
    faults (crash faults really destroy their sources) per retry and
    exhausts retry budgets the resilience plane's chaos scenarios were
    tuned for. Chaos runs measure recovery, not fetch throughput."""
    if _chaos_serialized():
        return 1
    from ..analysis import knobs
    env = knobs.env_raw("DAFT_TPU_SHUFFLE_FETCH_PARALLELISM")
    if env is not None:
        try:
            return max(int(env), 1)
        except ValueError:
            pass  # unparsable → the fault-plan-aware default below
    from .resilience import active_fault_plan
    return 1 if active_fault_plan() is not None else 4


def _stream_safe(plan: pp.PhysicalPlan, sid: int,
                 has_shuffle_out: bool) -> bool:
    """True when delivering a FetchSpec binding as MULTIPLE morsels (one
    per source, as fetches land) preserves the fragment's semantics:

    - the unique direct consumer of ``StageInput(sid)`` is a final
      grouped/global Aggregate whose aggs are all self-merges — the
      executor's streaming merge-agg re-merges across morsels
      (``LocalExecutor._merge_agg_stream``), so fetch overlaps reduce
      compute; or
    - every node between the root and the StageInput is row-local
      (Project/Filter/UDFProject/Explode/Unpivot) AND the task shuffles
      out — the morsels are re-partitioned into the cache, so output
      granularity is invisible downstream.

    Everything else (Dedup, joins, limits, bare passthrough returning
    partitions) gets today's single concatenated morsel."""
    from ..aggs import merge_exprs_for
    parents: List = []
    row_local = (pp.Project, pp.Filter, pp.UDFProject, pp.Explode,
                 pp.Unpivot)

    def walk(n, ancestors_row_local):
        for c in n.children:
            if isinstance(c, pp.StageInput) and c.stage_id == sid:
                parents.append((n, ancestors_row_local))
            walk(c, ancestors_row_local and isinstance(n, row_local))

    if isinstance(plan, pp.StageInput) and plan.stage_id == sid:
        return has_shuffle_out  # bare passthrough → repartitioned anyway
    walk(plan, True)
    if len(parents) != 1:
        return False
    parent, chain_row_local = parents[0]
    if isinstance(parent, pp.Aggregate) \
            and merge_exprs_for(parent.aggs, alias_to="out") is not None:
        return True
    return has_shuffle_out and chain_row_local \
        and isinstance(parent, row_local)


class _ParallelFetch:
    """Lazy reduce-side stage-input binding: fans a FetchSpec's per-source
    fetches onto a bounded thread pool the moment the task resolves its
    inputs, and yields the per-source tables IN SOURCE ORDER as morsels —
    fetch overlaps whatever the executor is doing instead of blocking on a
    full ``pa.concat_tables`` barrier.

    - ``streaming=True`` yields one MicroPartition per source (consumers
      vetted by ``_stream_safe``); ``False`` concatenates to a single
      morsel at the end — the sources still fetched concurrently.
    - Failures surface on iteration as ``ShuffleFetchError`` for the first
      failing source in order; ``FetchRetryState`` at the task supervisor
      (or the driver's backed-off fetch) stays the SINGLE retry policy —
      this class adds none of its own.
    - Per-source ``keys`` keep their stable identities, so injected fault
      decisions replay exactly; under ``DAFT_TPU_CHAOS_SERIALIZE=1`` the
      supervisor resolves inputs eagerly+sequentially instead (see
      ``resolve_stage_inputs``) and this class is never constructed."""

    def __init__(self, spec: FetchSpec, streaming: bool = False):
        from .. import tracing
        self.spec = spec
        self.streaming = streaming
        self._pool: Optional[cf.ThreadPoolExecutor] = None
        self._futs: Optional[List] = None
        self._cached: Optional[List[MicroPartition]] = None
        self._t0 = time.perf_counter()
        k = min(fetch_parallelism(), max(len(spec.sources), 1))
        if k > 1:
            from .shuffle_service import fetch_partition
            # carry the task thread's span context onto the fetch pool so
            # per-source fetch spans join the query trace
            tctx = tracing.current()
            self._pool = cf.ThreadPoolExecutor(
                max_workers=k, thread_name_prefix="daft-tpu-fetch")
            self._futs = [
                self._pool.submit(tracing.run_attached, tctx,
                                  fetch_partition, address, shuffle_id,
                                  spec.partition, fault_key=self._key(j))
                for j, (address, shuffle_id) in enumerate(spec.sources)]

    def _key(self, j: int) -> Optional[str]:
        keys = self.spec.keys
        return keys[j] if keys and j < len(keys) else None

    def __del__(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)

    def _tables(self):
        """Per-source tables in source order (None/empty skipped)."""
        if self._futs is not None:
            try:
                for fut in self._futs:
                    t = fut.result()
                    if t is not None and t.num_rows:
                        yield t
            finally:
                self._pool.shutdown(wait=False, cancel_futures=True)
                self._futs = None
        else:
            from .shuffle_service import fetch_partition
            for j, (address, shuffle_id) in enumerate(self.spec.sources):
                t = fetch_partition(address, shuffle_id,
                                    self.spec.partition,
                                    fault_key=self._key(j))
                if t is not None and t.num_rows:
                    yield t

    def __iter__(self):
        from ..recordbatch import RecordBatch
        from .shuffle_service import shuffle_count
        if self._cached is not None:
            # a plan can reference the same StageInput twice (e.g. a
            # self-join over one shuffled upstream): the second
            # consumption replays the materialized morsels like the
            # pre-parallel list binding did — never refetches (which
            # would double wire traffic AND roll fresh injection
            # decisions mid-task)
            yield from self._cached
            return
        tables = self._tables()
        if not self.streaming:
            import pyarrow as pa
            buf = list(tables)
            tables = iter([pa.concat_tables(buf)]
                          if len(buf) > 1 else buf)
        acc: List[MicroPartition] = []
        try:
            for t in tables:
                mp = MicroPartition.from_recordbatch(
                    RecordBatch.from_arrow_table(t))
                acc.append(mp)
                yield mp
        finally:
            # actual wall the multi-source fetch occupied (overlapped);
            # compare against the per-call fetch_wall_us sum for the
            # parallel-vs-serial evidence
            shuffle_count("fetch_span_us",
                          (time.perf_counter() - self._t0) * 1e6)
        self._cached = acc  # only a fully-drained iteration is replayable


def _fetch_spec_eager(binding: FetchSpec) -> List[MicroPartition]:
    """The pre-parallel fetch path: sequential source order, one fully
    concatenated morsel. Kept verbatim as the DAFT_TPU_CHAOS_SERIALIZE
    mode so PR 2's replay tests observe bit-identical event sequences."""
    from ..recordbatch import RecordBatch
    from .shuffle_service import fetch_partition
    tables = []
    for j, (address, shuffle_id) in enumerate(binding.sources):
        fkey = binding.keys[j] \
            if binding.keys and j < len(binding.keys) else None
        t = fetch_partition(address, shuffle_id, binding.partition,
                            fault_key=fkey)
        if t is not None and t.num_rows:
            tables.append(t)
    if not tables:
        return []
    import pyarrow as pa
    merged = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
    return [MicroPartition.from_recordbatch(
        RecordBatch.from_arrow_table(merged))]


def resolve_stage_inputs(stage_inputs: Dict[int, object],
                         plan: Optional[pp.PhysicalPlan] = None,
                         shuffle_out: Optional[ShuffleOutSpec] = None
                         ) -> Dict[int, object]:
    """Resolve FetchSpec bindings through the shuffle service.

    Default: each FetchSpec becomes a lazy :class:`_ParallelFetch` whose
    per-source fetches start immediately on a bounded pool; when ``plan``
    shows multi-morsel delivery is safe (``_stream_safe``) the executor
    consumes sources as they land — pipelined fetch. Under
    ``DAFT_TPU_CHAOS_SERIALIZE=1`` everything degrades to the eager,
    sequential, fully-concatenating path for bit-identical chaos replay."""
    out: Dict[int, object] = {}
    serialized = _chaos_serialized()
    for sid, binding in stage_inputs.items():
        if isinstance(binding, FetchSpec):
            if serialized:
                out[sid] = _fetch_spec_eager(binding)
            else:
                streaming = plan is not None \
                    and len(binding.sources) > 1 \
                    and _stream_safe(plan, sid, shuffle_out is not None)
                out[sid] = _ParallelFetch(binding, streaming=streaming)
        else:
            out[sid] = binding
    return out


def _worker_lane() -> str:
    """Trace lane for this worker thread (the InProcessWorker pool names
    threads ``daft-tpu-<worker_id>_N``)."""
    name = threading.current_thread().name
    if name.startswith("daft-tpu-"):
        name = name[len("daft-tpu-"):]
    return f"worker:{name.rsplit('_', 1)[0]}"


def run_task(task: StageTask) -> object:
    """Execute one stage task on the local streaming executor. Returns a
    partition list, or a ShuffleResult when the task shuffles out. A
    traced task (``task.trace_ctx``) records its ``task:run`` span —
    and everything under it (fetches, operators, device dispatches) —
    under the supervisor-minted span ids."""
    import time as _time

    from .. import observability as obs
    from .. import tracing
    rec = span_id = parent_id = None
    if task.trace_ctx is not None:
        trace_id, span_id, parent_id = task.trace_ctx
        rec = tracing.recorder_for(trace_id)
    t0_us = int(_time.time() * 1e6)
    status = "ok"
    try:
        with obs.nested_scope(), \
                tracing.attach(tracing.SpanContext(rec, span_id)
                               if rec is not None else None):
            return _run_task_body(task)
    except BaseException:
        status = "error"
        raise
    finally:
        if rec is not None:
            rec.add("task:run", span_id, parent_id, t0_us,
                    int(_time.time() * 1e6) - t0_us,
                    attrs={"task": task.fault_key
                           or f"s{task.stage_id}.t{task.task_idx}",
                           "attempt": task.attempt},
                    lane=_worker_lane(), status=status)


def _run_task_body(task: StageTask) -> object:
    from ..execution.executor import LocalExecutor
    from .resilience import active_fault_plan
    plan = active_fault_plan()
    if plan is not None:  # injection site 1: task execution
        plan.maybe_fail("task",
                        task.fault_key or f"s{task.stage_id}.t{task.task_idx}",
                        attempt=task.attempt)
    ex = LocalExecutor()
    inputs = resolve_stage_inputs(task.stage_inputs, plan=task.plan,
                                  shuffle_out=task.shuffle_out)
    stream = ex.run(task.plan, stage_inputs=inputs)
    if task.shuffle_out is None:
        return list(stream)
    from ..recordbatch import RecordBatch
    from .shuffle_service import ShuffleCache, get_local_shuffle_server
    spec = task.shuffle_out
    by = list(spec.by)
    cache = ShuffleCache()
    rows = 0
    state_rows = None
    samples_ipc = None
    # a failure while draining the stream (task fault, fetch fault on a
    # lazily resolved input, partitioning error) must delete the cache's
    # spill directory NOW: until server.register() below transfers
    # ownership, nothing else will — the orphan TTL sweep only covers
    # crashed processes, so every retried task used to leak a
    # daft_tpu_shuffle dir for the process lifetime (found by daft-lint's
    # shuffle-cache-leak flow check)
    try:
        if spec.kind == "hash":
            if spec.combine_aggs:
                rows, state_rows = _hash_shuffle_combined(stream, cache,
                                                          spec, by)
            else:
                for mp in stream:
                    rows += len(mp)
                    for i, piece in enumerate(
                            mp.partition_by_hash(by, spec.num_partitions)):
                        if len(piece):
                            cache.push(i,
                                       piece.combined().to_arrow_table())
        elif spec.kind == "store":
            sampled = []
            for mp in stream:
                rows += len(mp)
                if len(mp):
                    rb = mp.combined()
                    cache.push(0, rb.to_arrow_table())
                    if spec.sample_k > 0:
                        s = rb.sample(size=min(spec.sample_k, len(rb)))
                        sampled.append(s.eval_expression_list(by))
            if sampled:
                merged = RecordBatch.concat(sampled)
                if len(merged) > spec.sample_k:
                    merged = merged.sample(size=spec.sample_k)
                samples_ipc = _ipc_bytes(merged.to_arrow_table())
        elif spec.kind == "range":
            boundaries = RecordBatch.from_arrow_table(
                _ipc_table(spec.boundaries_ipc))
            desc = list(spec.descending) or [False] * len(by)
            for mp in stream:
                rows += len(mp)
                for i, piece in enumerate(
                        mp.combined().partition_by_range(
                            by, boundaries, desc)):
                    if len(piece):
                        cache.push(i, piece.to_arrow_table())
        else:
            raise ValueError(f"shuffle-out kind {spec.kind!r}")
        server = get_local_shuffle_server()
        server.register(cache)
    except BaseException:
        cache.cleanup()
        raise
    _, nbytes, _ = cache.stats()  # sealed by register(): sizes are final
    return ShuffleResult(server.address, cache.shuffle_id,
                         spec.num_partitions, rows, samples_ipc,
                         nbytes=nbytes, state_rows=state_rows)


def _hash_shuffle_combined(stream, cache, spec: ShuffleOutSpec,
                           by: list) -> tuple:
    """Map-side combine (Partial Partial Aggregates): hash-partition every
    morsel, but pre-aggregate each partition's buffered pieces to ONE
    group-state table before pushing — the wire carries group states, not
    per-morsel rows. The combine exprs are self-merge aggs over the wire
    schema (``stages.combine_for_boundary``), so the pushed schema is
    byte-identical to the uncombined path and the reduce side needs no
    changes. Buffers merge LSM-style (only once the buffer rivals the
    state) so re-aggregation stays O(log n) passes; peak residency is
    BUDGET-BOUNDED (round 19): when the summed partition states outgrow
    the breaker budget, the largest state flushes to the (always-on-disk)
    ShuffleCache mid-stream and restarts — pushing a partition's state in
    several pieces is exactly what the uncombined path does with raw
    rows, so the reduce side's merge agg is unchanged and a map task
    over an unbounded-NDV boundary composes with the exchange paths
    instead of holding its whole group state."""
    from ..execution.memory import breaker_budget_bytes, spill_count
    from .shuffle_service import shuffle_count
    n = spec.num_partitions
    budget = breaker_budget_bytes()
    caggs = list(spec.combine_aggs)
    cby = list(spec.combine_by)
    state: List[Optional[MicroPartition]] = [None] * n
    sbytes = [0] * n
    buf: List[List[MicroPartition]] = [[] for _ in range(n)]
    bufrows = [0] * n
    rows = 0
    pushed = 0
    wire_schema = None

    def merge(i: int) -> None:
        if not buf[i]:
            return
        fresh = buf[i][0].concat(buf[i][1:]) if len(buf[i]) > 1 \
            else buf[i][0]
        merged = fresh if state[i] is None else state[i].concat([fresh])
        out = merged.agg(caggs, cby)
        state[i] = out.cast_to_schema(wire_schema) \
            if wire_schema is not None else out
        sbytes[i] = int(state[i].size_bytes() or 0)
        buf[i], bufrows[i] = [], 0

    def flush(i: int) -> None:
        nonlocal pushed
        if state[i] is not None and len(state[i]):
            pushed += len(state[i])
            cache.push(i, state[i].combined().to_arrow_table())
        state[i], sbytes[i] = None, 0

    for mp in stream:
        rows += len(mp)
        if wire_schema is None and len(mp):
            wire_schema = mp.schema
        for i, piece in enumerate(mp.partition_by_hash(by, n)):
            if len(piece):
                buf[i].append(piece)
                bufrows[i] += len(piece)
                if bufrows[i] >= max(
                        _COMBINE_REAGG_ROWS,
                        0 if state[i] is None else len(state[i])):
                    merge(i)
                    while sum(sbytes) > budget:
                        j = max(range(n), key=lambda x: sbytes[x])
                        if sbytes[j] == 0:
                            break
                        spill_count("combine_state_flushes")
                        flush(j)
    for i in range(n):
        merge(i)
        flush(i)
    shuffle_count("combine_rows_in", rows)
    shuffle_count("combine_rows_out", pushed)
    # → (input rows, pushed group-state rows): the state count rides the
    # receipt as this task's exact boundary-key NDV bound (re-planner
    # evidence; mid-stream budget flushes only ever over-count it)
    return rows, pushed


def _ipc_bytes(table) -> bytes:
    import io

    import pyarrow as pa
    buf = io.BytesIO()
    with pa.ipc.new_stream(buf, table.schema) as w:
        w.write_table(table)
    return buf.getvalue()


def _ipc_table(data: bytes):
    import io

    import pyarrow as pa
    with pa.ipc.open_stream(io.BytesIO(data)) as r:
        return r.read_all()


class Worker:
    """Abstract worker: executes StageTasks, reports capacity."""

    id: str
    num_slots: int

    def submit(self, task: StageTask) -> "cf.Future":
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class InProcessWorker(Worker):
    """Runs stage fragments on a local streaming executor (per-host worker
    in a pod deployment; the only worker type on a single host)."""

    def __init__(self, worker_id: str, num_slots: int = 2):
        self.id = worker_id
        self.num_slots = num_slots
        self._pool = cf.ThreadPoolExecutor(
            max_workers=num_slots, thread_name_prefix=f"daft-tpu-{worker_id}")

    def submit(self, task: StageTask) -> "cf.Future":
        return self._pool.submit(run_task, task)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


@dataclass
class WorkerState:
    worker: Worker
    active: int = 0


class WorkerManager:
    """Tracks workers and in-flight load; routes submissions through a
    scheduling policy (reference: ``scheduling/worker.rs`` WorkerManager +
    dispatcher)."""

    def __init__(self, workers: List[Worker]):
        self._lock = threading.Lock()
        self.states: Dict[str, WorkerState] = {
            w.id: WorkerState(w) for w in workers}

    @property
    def worker_ids(self) -> List[str]:
        return list(self.states)

    def snapshot(self) -> List[WorkerState]:
        with self._lock:
            return list(self.states.values())

    def dispatch(self, task: StageTask, worker_id: str
                 ) -> "cf.Future[List[MicroPartition]]":
        with self._lock:
            st = self.states[worker_id]
            st.active += 1
        fut = st.worker.submit(task)

        def _done(_):
            with self._lock:
                st.active -= 1

        fut.add_done_callback(_done)
        return fut

    def shutdown(self) -> None:
        for st in self.snapshot():
            st.worker.shutdown()
