"""Pluggable scheduling policies + the stage-driving runner.

Reference: flotilla's ``Scheduler`` trait and scheduler actor
(``src/daft-distributed/src/scheduling/scheduler/mod.rs:18-23``; default
locality/spread policy ``scheduler/default.rs``, linear policy
``scheduler/linear.rs``) — policies are pure functions over worker snapshots
so they unit-test against mock workers with no hardware, exactly like the
reference's ``scheduling/tests.rs``.
"""

from __future__ import annotations

import itertools
from typing import Dict, Iterator, List, Optional

from ..micropartition import MicroPartition
from ..physical import plan as pp
from .resilience import (FetchRetryState, ResilienceContext, RetryPolicy,
                         ShuffleFetchError, TaskSupervisor, count)
from .stages import Boundary, Stage, StagePlan
from .topology import WorkerTopology
from .worker import (FetchSpec, ShuffleOutSpec, StageTask, WorkerManager,
                     WorkerState)


def _sort_fragment_root(remainder, pid: int):
    """The remainder's global Sort node, when the fragment is shaped
    Project* → Sort(col keys) → StageInput(pid) — the shape the
    worker-side range-sort protocol handles. Projects above the sort are
    row-order-preserving, so per-range outputs concatenate to the global
    order."""
    n = remainder
    while isinstance(n, pp.Project):
        n = n.children[0]
    if isinstance(n, pp.Sort) \
            and isinstance(n.children[0], pp.StageInput) \
            and n.children[0].stage_id == pid \
            and all(e.op == "col" for e in n.sort_by):
        return n
    return None


class Scheduler:
    """Policy: pick a worker for a task given current worker states."""

    def pick(self, task: StageTask, states: List[WorkerState]) -> str:
        raise NotImplementedError


class RoundRobinScheduler(Scheduler):
    """Spread tasks evenly regardless of load (reference linear policy)."""

    def __init__(self):
        self._counter = itertools.count()

    def pick(self, task: StageTask, states: List[WorkerState]) -> str:
        if task.preferred_worker is not None:
            for st in states:
                if st.worker.id == task.preferred_worker:
                    return st.worker.id
        return states[next(self._counter) % len(states)].worker.id


class LeastLoadedScheduler(Scheduler):
    """Soft-affinity + least-active placement (reference default policy:
    WorkerAffinity falls back to Spread)."""

    def pick(self, task: StageTask, states: List[WorkerState]) -> str:
        if task.preferred_worker is not None:
            for st in states:
                if st.worker.id == task.preferred_worker \
                        and st.active < st.worker.num_slots:
                    return st.worker.id
        return min(states, key=lambda s: (s.active, s.worker.id)).worker.id


class StageRunner:
    """Drives a StagePlan: dispatches each stage's tasks through the
    scheduler, feeds results downstream. Hash boundaries whose consumer
    fragment is partition-local are planned by the PLACEMENT LAYER
    (``topology.WorkerTopology`` + the exchange-path decision ladder):

    - ``collective`` — producer and consumer live on one device mesh;
      the boundary repartitions through the ICI all_to_all programs
      (``parallel/exchange.py``) and never touches the Flight wire;
    - ``hierarchical`` — across meshes; each mesh's map outputs exchange
      intra-mesh, then ONE Flight stream per mesh (not per worker)
      crosses the wire; per-mesh streams are all-or-nothing lineage
      units recomputed as whole exchange groups;
    - ``flight`` — today's per-worker shuffle service: map tasks spill
      hash-partitioned output into their worker's cache, reduce tasks
      fan out one-per-partition and fetch their slice from every map
      worker (the reference's flight-shuffle map/serve/fetch pipeline).

    Every other boundary materializes through the driver. Failures route
    through the resilience plane (``resilience.py``): bounded retries
    with backoff on other workers, per-worker quarantine, lineage
    recomputation of lost shuffle partitions, and speculative backups
    for stragglers. ``DAFT_TPU_DISTRIBUTED_SHUFFLE=driver`` forces the
    materializing path; ``DAFT_TPU_CHAOS_SERIALIZE=1`` degrades every
    boundary to the verbatim flight path for bit-identical replay."""

    def __init__(self, manager: WorkerManager,
                 scheduler: Optional[Scheduler] = None,
                 max_retries: Optional[int] = None):
        self.manager = manager
        self.scheduler = scheduler or LeastLoadedScheduler()
        self.max_retries = max_retries  # None → DAFT_TPU_MAX_RETRIES
        self._rctx: Optional[ResilienceContext] = None
        # set by the distributed runner's AQE loop so the runtime
        # re-planner folds its decisions into the SAME history the
        # materialize-and-reoptimize rounds record into
        self._aqe_planner = None

    def _resilience(self) -> ResilienceContext:
        if self._rctx is None:
            self._rctx = ResilienceContext(
                policy=RetryPolicy(max_retries=self.max_retries))
        return self._rctx

    def _supervisor(self) -> TaskSupervisor:
        return TaskSupervisor(self._resilience(), self.manager,
                              self.scheduler)

    # ------------------------------------------------------------------
    @staticmethod
    def _shuffle_enabled() -> bool:
        from ..analysis import knobs
        return knobs.env_str("DAFT_TPU_DISTRIBUTED_SHUFFLE") != "driver"

    def run(self, stage_plan: StagePlan) -> Iterator[MicroPartition]:
        from . import replan
        # fresh resilience state per query: quarantines/lineage span
        # stages but not queries
        self._rctx = ResilienceContext(
            policy=RetryPolicy(max_retries=self.max_retries))
        consumer: Dict[int, tuple] = {}
        for s in stage_plan.stages:
            for b in s.boundaries:
                consumer[b.upstream] = (s, b)
        outputs: Dict[int, list] = {}
        #: producer output mode per stage: "mat" (partition list),
        #: "shuffled" (map receipts — per-worker OR per-mesh streams),
        #: "collective" (per-partition lists from an intra-mesh exchange)
        out_mode: Dict[int, str] = {}
        use_shuffle = self._shuffle_enabled()
        topo = WorkerTopology.detect(self.manager.worker_ids) \
            if use_shuffle else None
        # runtime re-planning (round 20, DAFT_TPU_ADAPTIVE): boundary
        # actuals fold back into not-yet-dispatched stages — estimate
        # rewrites, combine gating, broadcast demotion, exchange rung —
        # disabled under the chaos-determinism contract
        rp = replan.StageReplanner(stage_plan,
                                   planner=self._aqe_planner) \
            if replan.adaptive_enabled() else None
        for stage in stage_plan.stages:
            if rp is not None:
                rp.before_stage(stage, consumer.get(stage.id), outputs,
                                out_mode)
            # this stage's output mode: the placement layer picks the
            # exchange path for its consumer boundary (collective /
            # hierarchical / flight), flight shuffles out when the
            # consumer can fan out over the hash partitions
            shuffle_out = None
            exch_path = None
            cons = consumer.get(stage.id)
            if use_shuffle and cons is not None:
                cstage, b = cons
                if b.num_partitions > 1 and b.kind == "hash" \
                        and all(ob.kind in ("hash", "gather")
                                for ob in cstage.boundaries):
                    inputs_mat = all(
                        out_mode.get(ob.upstream, "mat") == "mat"
                        for ob in stage.boundaries)
                    if stage_plan.collective_safe(cstage, b):
                        exch_path = self._plan_exchange_path(
                            topo, stage, b, inputs_mat, rp)
                    if exch_path in (None, "flight") and (
                            stage_plan.fanout_safe(cstage, b)
                            or stage_plan.split_for_fanout(cstage, b)
                            is not None):
                        exch_path = "flight"
                        shuffle_out = ShuffleOutSpec(b.num_partitions,
                                                     tuple(b.by))
                        combo = self._plan_combine(stage_plan, cstage, b,
                                                   stage, rp)
                        if combo is not None:
                            shuffle_out.combine_aggs, \
                                shuffle_out.combine_by = combo
            fetch_srcs: Dict[int, list] = {}
            fetch_n: Dict[int, int] = {}
            coll_inputs: Dict[int, list] = {}
            mat_inputs: Dict[int, List[MicroPartition]] = {}
            first_exchanged: Optional[Boundary] = None
            for b in stage.boundaries:
                up_out = outputs.pop(b.upstream)
                mode = out_mode.get(b.upstream, "mat")
                if mode == "shuffled":
                    fetch_srcs[b.upstream] = [(r.address, r.shuffle_id)
                                              for r in up_out]
                    fetch_n[b.upstream] = b.num_partitions
                    first_exchanged = first_exchanged or b
                elif mode == "collective":
                    coll_inputs[b.upstream] = up_out
                    first_exchanged = first_exchanged or b
                else:
                    mat_inputs[b.upstream] = self._apply_exchange(b, up_out)
            if exch_path == "hierarchical":
                # two-level exchange replaces the stage run entirely: the
                # producer's map tasks execute per mesh group, each
                # group's output repartitions intra-mesh and serves as
                # ONE stream (decision gated on all-materialized inputs)
                outputs[stage.id] = self._run_hierarchical_producer(
                    stage, mat_inputs, cons[1], topo)
                out_mode[stage.id] = "shuffled"
                continue
            if fetch_srcs or coll_inputs:
                ns = set(fetch_n.values()) \
                    | {len(pl) for pl in coll_inputs.values()}
                if len(ns) > 1:
                    # boundaries disagree on partition count — no shared
                    # fan-out exists; materialize driver-side instead
                    for up, srcs in fetch_srcs.items():
                        mat_inputs[up] = self._driver_fetch_resilient(
                            srcs, fetch_n[up], up)
                    for up, plists in coll_inputs.items():
                        mat_inputs[up] = [p for pl in plists for p in pl]
                    result = self._run_stage(stage, mat_inputs,
                                             shuffle_out)
                else:
                    result = self._run_shuffled_stage(
                        stage_plan, stage, fetch_srcs, coll_inputs,
                        mat_inputs, next(iter(ns)), first_exchanged,
                        shuffle_out)
                self._cleanup_shuffles(fetch_srcs)
            else:
                result = self._run_stage(stage, mat_inputs, shuffle_out)
            if exch_path == "collective":
                outputs[stage.id] = self._collective_repartition(
                    stage, result, cons[1])
                out_mode[stage.id] = "collective"
            else:
                outputs[stage.id] = result
                out_mode[stage.id] = "shuffled" \
                    if shuffle_out is not None else "mat"
            if rp is not None:
                rp.after_stage(stage, outputs[stage.id],
                               out_mode.get(stage.id, "mat"))
        yield from outputs[stage_plan.root.id]

    def _plan_combine(self, stage_plan: StagePlan, cstage: Stage,
                      b: Boundary, up_stage: Stage, rp=None):
        """Decide the map-side combine for one hash boundary: structural
        eligibility comes from the stage planner
        (``StagePlan.combine_for_boundary`` — the boundary must feed a
        final grouped aggregation whose aggs are all self-merges), then
        the cost model prices the modeled wire savings against the extra
        map-side agg pass (``costmodel.shuffle_combine_wins`` over the
        planner's row/NDV evidence). With the runtime re-planner active
        (round 20) the pricing uses the producing stage's MEASURED rows
        and — when affordable — the EXACT key NDV instead of footer
        estimates; a decision the static evidence would have gotten
        wrong is counted as a ``combine_flip``.
        ``DAFT_TPU_SHUFFLE_COMBINE=1`` forces it, ``0`` is the escape
        hatch, default ``auto``."""
        from ..analysis import knobs
        mode = knobs.env_str("DAFT_TPU_SHUFFLE_COMBINE").lower()
        if mode in ("0", "off", "false", "none"):
            return None
        combo = stage_plan.combine_for_boundary(cstage, b, up_stage)
        if combo is None:
            return None
        combine_aggs, combine_by, agg_node = combo
        if mode not in ("1", "on", "force", "true"):
            from ..device import costmodel
            from ..physical import adaptive
            rows = getattr(agg_node, "group_rows_est", None)
            groups = getattr(agg_node, "group_ndv", None)
            n_cols = len(combine_aggs) + len(combine_by)
            ev = rp.combine_evidence(up_stage) if rp is not None else None
            e_rows, e_groups, exact = rows, groups, False
            if ev is not None:
                m_rows, m_ndv, m_exact = ev
                e_rows = m_rows
                if m_ndv is not None:
                    e_groups, exact = m_ndv, m_exact
            decision = costmodel.shuffle_combine_wins(
                e_rows, e_groups, b.num_partitions, n_cols=n_cols,
                exact_groups=exact)
            if rp is not None and ev is not None:
                static = costmodel.combine_wins_pure(
                    rows, groups, b.num_partitions, n_cols=n_cols)
                if static != decision:
                    adaptive.count("combine_flips")
                    rp.planner.record_replan(
                        f"stage s{up_stage.id}: map-side combine "
                        f"{'enabled' if decision else 'declined'} from "
                        f"measured evidence (rows={e_rows} "
                        f"groups={e_groups} exact={exact}; static said "
                        f"{'combine' if static else 'no combine'})",
                        int(e_rows or 0))
            if not decision:
                return None
        return combine_aggs, combine_by

    # ---------------------------------------- pod-native exchange paths
    def _plan_exchange_path(self, topo: WorkerTopology, stage: Stage,
                            b: Boundary, inputs_mat: bool,
                            rp=None) -> str:
        """Placement decision for one structurally-eligible hash
        boundary (consumer whole-stage fanout-safe): collective /
        hierarchical / flight per the topology decision ladder
        (``topology.plan_exchange_path``). Hierarchical additionally
        requires the producer's own inputs to be driver-materialized —
        its map tasks re-dispatch per mesh group, which the shuffled
        input bindings don't survive. With the runtime re-planner
        active, the ladder prices from the producing stage's MEASURED
        rows and row widths instead of the evidence-free default-accept;
        a rung the evidence changed is counted ``exchange_repicks``.
        Every decision is counted in the shuffle data plane
        (``exchange_path_*``)."""
        from ..physical import adaptive
        from . import topology as tp
        from .shuffle_service import shuffle_count
        ev = rp.exchange_evidence(stage) if rp is not None else None
        if ev is not None:
            rows_est, row_bytes = ev
            path = tp.plan_exchange_path(topo, b.num_partitions,
                                         rows_est=rows_est,
                                         row_bytes=row_bytes)
            # evidence-free, the auto ladder default-accepts the
            # collective family on structural grounds alone — a flip to
            # flight here is the measured evidence talking
            structural = "collective" if topo.single_mesh() else (
                "hierarchical" if topo.multi_worker_groups() >= 1
                else "flight")
            forced = tp._path_setting() in tp.PATHS
            if not forced and path != structural \
                    and structural != "flight":
                adaptive.count("exchange_repicks")
                rp.planner.record_replan(
                    f"stage s{stage.id}: exchange rung "
                    f"{structural}→{path} from measured rows="
                    f"{int(rows_est)} row_bytes={row_bytes:.1f}",
                    int(rows_est))
        else:
            path = tp.plan_exchange_path(topo, b.num_partitions)
        if path == "hierarchical" and not inputs_mat:
            path = "flight"
        shuffle_count(f"exchange_path_{path}")
        return path

    def _collective_repartition(self, stage: Stage, parts: list,
                                b: Boundary) -> list:
        """Execute one hash boundary as an intra-mesh collective: the
        stage's output repartitions through the device mesh
        (``sharded_hash_repartition`` — memoized, shape-bucketed) with a
        host hash fanout as the admission fallback, and NEVER touches
        the Flight wire. Returns per-partition partition lists the
        consumer's reduce tasks bind directly."""
        from . import topology as tp
        from .. import tracing
        key = stage.task_key(0, "cx")
        lease = tp.acquire_collective(key)
        try:
            with tracing.span("exchange:collective",
                              key=f"exchange:{key}",
                              attrs={"partitions": b.num_partitions},
                              lane="shuffle") as sp:
                return self._intra_mesh_repartition(
                    parts, list(b.by), b.num_partitions, sp)
        finally:
            tp.release_collective(lease)

    def _intra_mesh_repartition(self, parts: list, by: list, n: int,
                                sp=None) -> list:
        """One hash repartition that stays inside the mesh: the ICI
        collective program when the admission gate prices it in
        (``mesh.mesh_admits`` over the exact bytes), else a host hash
        fanout of the same pid chain — both agree with
        ``partition_by_hash``, so every path is bit-co-partitioned.
        → n bucket lists."""
        from ..execution.executor import LocalExecutor
        parts = [p for p in parts if len(p)]
        rows = sum(len(p) for p in parts)
        mesh_out = None
        if parts:
            try:
                mesh_out = LocalExecutor()._mesh_hash_repartition(
                    list(parts), list(by), n)
            except Exception:
                mesh_out = None  # host fallback below is always sound
        if sp is not None:
            from ..device import costmodel
            sp.set("rows", rows)
            sp.set("bytes", sum(p.size_bytes() for p in parts))
            sp.set("ici", mesh_out is not None)
            if mesh_out is not None:
                sp.set("ici_bps", int(costmodel.ici_bps()))
        if mesh_out is not None:
            return [[p] for p in mesh_out]
        buckets: List[list] = [[] for _ in range(n)]
        for mp in parts:
            for i, piece in enumerate(mp.partition_by_hash(list(by), n)):
                if len(piece):
                    buckets[i].append(piece)
        # one combined morsel per bucket — the binding a reduce task
        # receives must look exactly like a fetched+concatenated flight
        # partition (a multi-piece binding would execute the consumer
        # fragment per piece, not per partition)
        return [[b0[0].concat(b0[1:])] if len(b0) > 1 else b0
                for b0 in buckets]

    def _run_hierarchical_producer(self, stage: Stage,
                                   stage_inputs: Dict[int, list],
                                   b: Boundary, topo: WorkerTopology
                                   ) -> list:
        """Two-level hierarchical exchange, map side: the stage's tasks
        split across mesh groups; each group's outputs repartition
        intra-mesh (the collective leg) and register as ONE shuffle
        stream per mesh — the wire carries one stream per mesh instead
        of one per worker. Each per-mesh stream is an ALL-OR-NOTHING
        lineage unit: its producer is the whole exchange group
        (``topology.CollectiveExchangeGroup``), so losing the stream
        recomputes every member map task plus the collective, never one
        map task."""
        import concurrent.futures as cf
        import dataclasses as dc

        from . import topology as tp
        from .. import tracing
        from .resilience import active_fault_plan
        from .shuffle_service import shuffle_count
        tasks = self._make_tasks(stage, stage_inputs, None)
        groups = topo.groups
        lineage = self._resilience().lineage
        work = []  # (gi, group, its tasks) — deterministic split
        for gi, g in enumerate(groups):
            # round-robin tasks over groups; WITHIN a group spread over
            # its workers by group-local position (indexing by the raw
            # task_idx would alias with the group split whenever g.size
            # divides the group count, pinning a whole mesh to one
            # worker)
            gtasks = [dc.replace(
                t, preferred_worker=g.workers[
                    (t.task_idx // len(groups)) % g.size])
                for t in tasks if t.task_idx % len(groups) == gi]
            if gtasks:
                work.append((gi, g, gtasks))
        # meshes exchange CONCURRENTLY (the flight path dispatches every
        # map task at once — serializing per mesh would cost sum-of-mesh
        # walls instead of the max); fault-plan runs stay sequential so
        # injected-fault attempt counters advance in one total order
        if len(work) > 1 and active_fault_plan() is None:
            tctx = tracing.current()
            with cf.ThreadPoolExecutor(
                    max_workers=len(work),
                    thread_name_prefix="daft-tpu-meshgrp") as pool:
                futs = [pool.submit(tracing.run_attached, tctx,
                                    self._run_one_mesh_group, stage, b,
                                    gi, g, gtasks)
                        for gi, g, gtasks in work]
                done = [f.result() for f in futs]  # group order
        else:
            done = [self._run_one_mesh_group(stage, b, gi, g, gtasks)
                    for gi, g, gtasks in work]
        receipts = []
        for (gi, g, gtasks), (receipt, rebuild) in zip(work, done):
            lineage.register(receipt, tp.CollectiveExchangeGroup(
                fault_key=stage.task_key(gi, "g"),
                group_tasks=list(gtasks), rebuild=rebuild))
            receipts.append(receipt)
        shuffle_count("hierarchical_streams", len(receipts))
        return receipts

    def _run_one_mesh_group(self, stage: Stage, b: Boundary, gi: int,
                            g, gtasks: list):
        """Run ONE mesh group's map tasks and build its merged per-mesh
        stream → (receipt, rebuild). The group lease spans the whole
        exchange; the rebuild closure is the lineage recovery recipe."""
        from . import topology as tp
        from .. import tracing
        gkey = stage.task_key(gi, "g")
        rebuild = self._group_receipt_builder(b, gkey)
        lease = tp.acquire_collective(gkey)
        try:
            with tracing.span("exchange:collective",
                              key=f"exchange:{gkey}",
                              attrs={"mesh": g.name,
                                     "tasks": len(gtasks),
                                     "partitions": b.num_partitions},
                              lane="shuffle"):
                outs = self._collect(gtasks)
                return rebuild(outs), rebuild
        finally:
            tp.release_collective(lease)

    def _group_receipt_builder(self, b: Boundary, gkey: str):
        """→ rebuild(task outputs) → per-mesh ShuffleResult. A closure so
        lineage recovery re-derives the receipt the same deterministic
        way the first run did (same boundary keys, same partition
        count)."""
        by = list(b.by)
        n = b.num_partitions

        def rebuild(outs: list):
            from .shuffle_service import (ShuffleCache,
                                          get_local_shuffle_server)
            from .worker import ShuffleResult
            parts: List[MicroPartition] = []
            for res in outs:
                parts.extend(res if isinstance(res, list) else [res])
            buckets = self._intra_mesh_repartition(parts, by, n)
            cache = ShuffleCache()
            rows = 0
            try:
                for i, plist in enumerate(buckets):
                    for p in plist:
                        rows += len(p)
                        cache.push(i, p.combined().to_arrow_table())
                server = get_local_shuffle_server()
                server.register(cache)
            except BaseException:
                cache.cleanup()
                raise
            _, nbytes, _ = cache.stats()
            return ShuffleResult(server.address, cache.shuffle_id, n,
                                 rows, nbytes=nbytes)

        return rebuild

    def _cleanup_shuffles(self, fetch_srcs: Dict[int, list]) -> None:
        """Best-effort release of consumed map outputs when the consuming
        stage completes, addressed straight to each serving host through
        the shuffle transport (the address is part of the map receipt —
        one call per shuffle id). Recovered outputs are released through
        their whole lineage translation chain (the recomputed replacement
        lives at a different address than the receipt)."""
        from .shuffle_service import unregister_remote
        lineage = self._resilience().lineage
        for srcs in fetch_srcs.values():
            for src in srcs:
                for address, shuffle_id in lineage.chain(tuple(src)):
                    try:
                        unregister_remote(address, shuffle_id)
                    except Exception:
                        pass

    # ------------------------------------------------------------------
    def _make_tasks(self, stage: Stage,
                    stage_inputs: Dict[int, List[MicroPartition]],
                    shuffle_out: Optional[ShuffleOutSpec] = None
                    ) -> List[StageTask]:
        """Shard a map-like scan stage across workers (contiguous chunks —
        preserves partition order); everything else is one task."""
        n_workers = len(self.manager.worker_ids)
        src = stage.scan_source()
        if n_workers > 1 and src is not None and len(src.tasks) > 1 \
                and stage.is_map_like():
            k = min(n_workers, len(src.tasks))
            per = (len(src.tasks) + k - 1) // k
            tasks = []
            for i in range(k):
                chunk = src.tasks[i * per:(i + 1) * per]
                if not chunk:
                    continue
                tasks.append(StageTask(stage.id, stage.with_scan_tasks(chunk),
                                       stage_inputs, task_idx=i,
                                       shuffle_out=shuffle_out,
                                       fault_key=stage.task_key(i)))
            return tasks
        return [StageTask(stage.id, stage.plan, stage_inputs,
                          shuffle_out=shuffle_out,
                          fault_key=stage.task_key(0))]

    def _run_stage(self, stage: Stage,
                   stage_inputs: Dict[int, List[MicroPartition]],
                   shuffle_out: Optional[ShuffleOutSpec] = None) -> list:
        tasks = self._make_tasks(stage, stage_inputs, shuffle_out)
        return self._collect(tasks)

    def _run_shuffled_stage(self, stage_plan: StagePlan, stage: Stage,
                            fetch_srcs: Dict[int, list],
                            coll_inputs: Dict[int, list],
                            mat_inputs: Dict[int, List[MicroPartition]],
                            n: int, b: Boundary,
                            shuffle_out: Optional[ShuffleOutSpec]) -> list:
        """Stage with shuffle- or collective-backed inputs: fan the whole
        fragment out when it is partition-local; otherwise fan out its
        safe frontier (e.g. the merge-agg under a Sort) and run the
        global remainder as one task; if neither applies, fetch
        partitions onto the driver."""
        # replicating a driver-materialized input to every reduce task is
        # only sound for GATHER boundaries (broadcast-by-design, join-type
        # gated at translate time). A materialized hash/range/split input
        # replicated beside a partitioned side would duplicate non-inner
        # join results — fall back to the driver for the whole stage.
        replication_ok = all(
            ob.kind == "gather" for ob in stage.boundaries
            if ob.upstream in mat_inputs)
        exchanged = set(fetch_srcs) | set(coll_inputs)
        if replication_ok and stage_plan.fanout_safe(stage, b) and all(
                stage_plan.fanout_safe(stage, ob)
                for ob in stage.boundaries if ob.upstream in exchanged):
            return self._run_reduce_fanout(stage, fetch_srcs, mat_inputs,
                                           n, shuffle_out, coll_inputs)
        if coll_inputs:
            # defensive: a collective input reaching a fanout-unsafe
            # consumer materializes EVERYTHING driver-side — a
            # hash-partitioned input must never replicate beside a
            # partitioned sibling (same rule as mat hash inputs above)
            for up, plists in coll_inputs.items():
                mat_inputs[up] = [p for pl in plists for p in pl]
            for up, srcs in fetch_srcs.items():
                mat_inputs[up] = self._driver_fetch_resilient(srcs, n, up)
            return self._run_stage(stage, mat_inputs, shuffle_out)
        split = stage_plan.split_for_fanout(stage, b) if replication_ok \
            else None
        if split is not None:
            sub, remainder, pid = split
            if all(StagePlan._contains_input(sub, up)
                   for up in fetch_srcs):
                sub_stage = Stage(stage.id, sub, [])
                sort_node = _sort_fragment_root(remainder, pid)
                if sort_node is not None and shuffle_out is None \
                        and self._shuffle_enabled():
                    return self._range_sort_remainder(
                        sub_stage, remainder, pid, sort_node,
                        fetch_srcs, mat_inputs, n)
                parts = self._run_reduce_fanout(sub_stage, fetch_srcs,
                                                mat_inputs, n, None)
                rest = Stage(stage.id, remainder, [])
                bindings: Dict[int, object] = {pid: parts}
                bindings.update(mat_inputs)
                return self._run_stage(rest, bindings, shuffle_out)
        # defensive fallback: materialize the shuffled inputs driver-side
        for up, srcs in fetch_srcs.items():
            mat_inputs[up] = self._driver_fetch_resilient(srcs, n, up)
        return self._run_stage(stage, mat_inputs, shuffle_out)

    def _range_sort_remainder(self, sub_stage: Stage, remainder, pid: int,
                              sort_node, fetch_srcs: Dict[int, list],
                              mat_inputs: Dict[int, List[MicroPartition]],
                              n: int) -> Optional[list]:
        """Distributed global sort with rows never touching the driver
        (the r2 verdict's scale ceiling: every range/sort boundary funneled
        through the driver). Three worker-side phases:

        1. the partition-local sub-fragment runs per hash partition with
           ``store`` shuffle-out: outputs stay in worker shuffle caches,
           each task returns a sort-key SAMPLE with its receipt;
        2. the driver computes range boundaries from the samples alone
           (KB, not rows) and dispatches per-receipt ``range`` repartition
           tasks — rows move worker→worker through the shuffle transport;
        3. one reduce task per range sorts its partition locally; the
           driver concatenates results in partition order, which IS the
           global order (ranges are disjoint and ordered).

        Shape gating happens in ``_sort_fragment_root`` BEFORE this is
        called; failures inside the protocol abort the query (same
        contract as the hash-shuffle path)."""
        from ..context import get_context
        from ..execution.executor import sample_boundaries
        from .worker import FetchSpec, ShuffleOutSpec, StageTask, _ipc_bytes
        cfg = get_context().execution_config
        by = list(sort_node.sort_by)
        desc = list(sort_node.descending)
        nf = list(sort_node.nulls_first)

        store = ShuffleOutSpec(1, tuple(by), kind="store",
                               sample_k=cfg.sample_size_for_sort)
        receipts = self._run_reduce_fanout(sub_stage, fetch_srcs,
                                           mat_inputs, n, store)
        try:
            from ..recordbatch import RecordBatch
            from .worker import _ipc_table
            samples = [RecordBatch.from_arrow_table(
                _ipc_table(r.samples_ipc))
                for r in receipts if r.samples_ipc]
            k = max(len(receipts), 1)
            names = [e.name() for e in by]
            boundaries = sample_boundaries(samples, names, desc, nf, k) \
                if samples else None
            if boundaries is None or k == 1:
                # no keys to sample or single partition: one sort task
                # reading every stored output through the shuffle service
                rest = Stage(sub_stage.id, remainder, [])
                bindings: Dict[int, object] = {pid: FetchSpec(
                    [(r.address, r.shuffle_id) for r in receipts], 0,
                    keys=[sub_stage.task_key(j, "p1")
                          for j in range(len(receipts))])}
                bindings.update(mat_inputs)
                return self._run_stage(rest, bindings, None)
            bipc = _ipc_bytes(boundaries.to_arrow_table())
            range_spec = ShuffleOutSpec(k, tuple(by), kind="range",
                                        descending=tuple(desc),
                                        boundaries_ipc=bipc)
            phase2 = [StageTask(
                sub_stage.id, pp.StageInput(pid, sort_node.schema()),
                {pid: FetchSpec([(r.address, r.shuffle_id)], 0,
                                keys=[sub_stage.task_key(j, "p1")])},
                task_idx=j, shuffle_out=range_spec,
                fault_key=sub_stage.task_key(j, "p2"))
                for j, r in enumerate(receipts)]
            receipts2 = self._collect(phase2)
        finally:
            self._cleanup_shuffles(
                {0: [(r.address, r.shuffle_id) for r in receipts]})
        srcs2 = [(r.address, r.shuffle_id) for r in receipts2]
        keys2 = [sub_stage.task_key(j, "p2") for j in range(len(receipts2))]
        try:
            tasks = []
            for i in range(k):
                bindings = {pid: FetchSpec(srcs2, i, keys=keys2)}
                bindings.update(mat_inputs)
                tasks.append(StageTask(sub_stage.id, remainder, bindings,
                                       task_idx=i,
                                       fault_key=sub_stage.task_key(i,
                                                                    "p3")))
            return self._collect(tasks)
        finally:
            self._cleanup_shuffles({0: srcs2})

    @staticmethod
    def _driver_fetch(srcs: list, n: int, keys: Optional[list] = None,
                      partition: Optional[int] = None
                      ) -> List[MicroPartition]:
        """Fetch partitions [0, n) — or just ``partition`` — from every
        source onto the driver."""
        from .worker import resolve_stage_inputs
        parts = range(n) if partition is None else [partition]
        out: List[MicroPartition] = []
        for i in parts:
            out.extend(resolve_stage_inputs(
                {0: FetchSpec(srcs, i, keys=keys)})[0])
        return out

    def _driver_fetch_resilient(self, srcs: list, n: int, up: int
                                ) -> List[MicroPartition]:
        """Driver-side materialization with the same fetch-failure
        handling the worker-side reduce tasks get (one shared
        ``FetchRetryState`` policy): a backed-off refetch first, lineage
        recomputation of the producing map task when the same source
        fails twice (its data is gone). Retries are per-partition, so
        one flaky fetch never refetches the whole boundary."""
        import time
        ctx = self._resilience()
        keys = [f"s{up}.m{j}" for j in range(len(srcs))]
        out: List[MicroPartition] = []
        for i in range(n):
            state = FetchRetryState(ctx.policy)
            while True:
                cur = [ctx.lineage.resolve(tuple(s)) for s in srcs]
                try:
                    out.extend(self._driver_fetch(cur, n, keys,
                                                  partition=i))
                    break
                except ShuffleFetchError as exc:
                    if state.should_recover(exc) \
                            and not self._supervisor().recover_source(
                                (exc.address, exc.shuffle_id), exc):
                        raise
                    count("retries")
                    time.sleep(ctx.policy.backoff_s(f"s{up}.p{i}",
                                                    state.attempts))
        return out

    def _run_reduce_fanout(self, stage: Stage, fetch_srcs: Dict[int, list],
                           mat_inputs: Dict[int, List[MicroPartition]],
                           n: int, shuffle_out: Optional[ShuffleOutSpec],
                           coll_inputs: Optional[Dict[int, list]] = None
                           ) -> list:
        """One reduce task per hash partition: task i binds each shuffled
        input to FetchSpec(partition=i) and each collective input to its
        already-exchanged partition-i bucket; driver-materialized
        bindings (broadcast/gather sides) replicate to every task. Fetch
        sources carry stable ``s<upstream>.m<map_idx>`` keys so injected
        faults replay identically across runs (the shuffle uuid does
        not)."""
        tasks = []
        for i in range(n):
            si: Dict[int, object] = {
                up: FetchSpec(srcs, i,
                              keys=[f"s{up}.m{j}"
                                    for j in range(len(srcs))])
                for up, srcs in fetch_srcs.items()}
            for up, plists in (coll_inputs or {}).items():
                si[up] = list(plists[i])
            si.update(mat_inputs)
            tasks.append(StageTask(stage.id, stage.plan, si, task_idx=i,
                                   shuffle_out=shuffle_out,
                                   fault_key=stage.task_key(i, "r")))
        return self._collect(tasks)

    def _collect(self, tasks: List[StageTask]) -> list:
        """Dispatch one batch of tasks through the resilient task
        supervisor (retry/quarantine/lineage/speculation live there) and
        flatten the per-task results in task order. A traced query gets
        one ``stage`` span per batch; the supervisor's per-task spans
        nest under it."""
        from .. import tracing
        sid = tasks[0].stage_id if tasks else -1
        with tracing.span("stage", key=f"stage:s{sid}",
                          attrs={"tasks": len(tasks)}):
            per_task = self._supervisor().run(tasks)
        out: list = []
        for res in per_task:
            out.extend(res if isinstance(res, list) else [res])
        return out

    # ------------------------------------------------------------------
    def _apply_exchange(self, b: Boundary, parts: List[MicroPartition]
                        ) -> List[MicroPartition]:
        """Execute one exchange boundary on the driver: the materializing
        map/reduce transport between stages (mesh-collective exchanges run
        inside stages as DeviceExchangeAgg programs instead)."""
        from ..execution.executor import LocalExecutor
        if not parts:
            return parts
        schema = parts[0].schema
        node = pp.Exchange(pp.InMemorySource(parts, schema), b.kind,
                           b.num_partitions, b.by, b.descending,
                           engine_inserted=b.engine_inserted)
        ex = LocalExecutor()
        return list(ex.run(node))
