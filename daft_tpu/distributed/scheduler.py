"""Pluggable scheduling policies + the stage-driving runner.

Reference: flotilla's ``Scheduler`` trait and scheduler actor
(``src/daft-distributed/src/scheduling/scheduler/mod.rs:18-23``; default
locality/spread policy ``scheduler/default.rs``, linear policy
``scheduler/linear.rs``) — policies are pure functions over worker snapshots
so they unit-test against mock workers with no hardware, exactly like the
reference's ``scheduling/tests.rs``.
"""

from __future__ import annotations

import itertools
from typing import Dict, Iterator, List, Optional

from ..micropartition import MicroPartition
from ..physical import plan as pp
from .resilience import (FetchRetryState, ResilienceContext, RetryPolicy,
                         ShuffleFetchError, TaskSupervisor, count)
from .stages import Boundary, Stage, StagePlan
from .worker import (FetchSpec, ShuffleOutSpec, StageTask, WorkerManager,
                     WorkerState)


def _sort_fragment_root(remainder, pid: int):
    """The remainder's global Sort node, when the fragment is shaped
    Project* → Sort(col keys) → StageInput(pid) — the shape the
    worker-side range-sort protocol handles. Projects above the sort are
    row-order-preserving, so per-range outputs concatenate to the global
    order."""
    n = remainder
    while isinstance(n, pp.Project):
        n = n.children[0]
    if isinstance(n, pp.Sort) \
            and isinstance(n.children[0], pp.StageInput) \
            and n.children[0].stage_id == pid \
            and all(e.op == "col" for e in n.sort_by):
        return n
    return None


class Scheduler:
    """Policy: pick a worker for a task given current worker states."""

    def pick(self, task: StageTask, states: List[WorkerState]) -> str:
        raise NotImplementedError


class RoundRobinScheduler(Scheduler):
    """Spread tasks evenly regardless of load (reference linear policy)."""

    def __init__(self):
        self._counter = itertools.count()

    def pick(self, task: StageTask, states: List[WorkerState]) -> str:
        if task.preferred_worker is not None:
            for st in states:
                if st.worker.id == task.preferred_worker:
                    return st.worker.id
        return states[next(self._counter) % len(states)].worker.id


class LeastLoadedScheduler(Scheduler):
    """Soft-affinity + least-active placement (reference default policy:
    WorkerAffinity falls back to Spread)."""

    def pick(self, task: StageTask, states: List[WorkerState]) -> str:
        if task.preferred_worker is not None:
            for st in states:
                if st.worker.id == task.preferred_worker \
                        and st.active < st.worker.num_slots:
                    return st.worker.id
        return min(states, key=lambda s: (s.active, s.worker.id)).worker.id


class StageRunner:
    """Drives a StagePlan: dispatches each stage's tasks through the
    scheduler, feeds results downstream. Hash boundaries whose consumer
    fragment is partition-local execute through the SHUFFLE SERVICE — map
    tasks spill hash-partitioned output into their worker's cache, reduce
    tasks fan out one-per-partition and fetch their slice from every map
    worker (the reference's flight-shuffle map/serve/fetch pipeline);
    every other boundary materializes through the driver. Failures route
    through the resilience plane (``resilience.py``): bounded retries
    with backoff on other workers, per-worker quarantine, lineage
    recomputation of lost shuffle partitions, and speculative backups
    for stragglers. ``DAFT_TPU_DISTRIBUTED_SHUFFLE=driver`` forces the
    materializing path."""

    def __init__(self, manager: WorkerManager,
                 scheduler: Optional[Scheduler] = None,
                 max_retries: Optional[int] = None):
        self.manager = manager
        self.scheduler = scheduler or LeastLoadedScheduler()
        self.max_retries = max_retries  # None → DAFT_TPU_MAX_RETRIES
        self._rctx: Optional[ResilienceContext] = None

    def _resilience(self) -> ResilienceContext:
        if self._rctx is None:
            self._rctx = ResilienceContext(
                policy=RetryPolicy(max_retries=self.max_retries))
        return self._rctx

    def _supervisor(self) -> TaskSupervisor:
        return TaskSupervisor(self._resilience(), self.manager,
                              self.scheduler)

    # ------------------------------------------------------------------
    @staticmethod
    def _shuffle_enabled() -> bool:
        from ..analysis import knobs
        return knobs.env_str("DAFT_TPU_DISTRIBUTED_SHUFFLE") != "driver"

    def run(self, stage_plan: StagePlan) -> Iterator[MicroPartition]:
        # fresh resilience state per query: quarantines/lineage span
        # stages but not queries
        self._rctx = ResilienceContext(
            policy=RetryPolicy(max_retries=self.max_retries))
        consumer: Dict[int, tuple] = {}
        for s in stage_plan.stages:
            for b in s.boundaries:
                consumer[b.upstream] = (s, b)
        outputs: Dict[int, list] = {}
        shuffled: Dict[int, bool] = {}
        use_shuffle = self._shuffle_enabled()
        for stage in stage_plan.stages:
            # this stage's output mode: shuffle out when its consumer can
            # fan out over the hash partitions
            shuffle_out = None
            cons = consumer.get(stage.id)
            if use_shuffle and cons is not None:
                cstage, b = cons
                if b.num_partitions > 1 and b.kind == "hash" \
                        and all(ob.kind in ("hash", "gather")
                                for ob in cstage.boundaries) \
                        and (stage_plan.fanout_safe(cstage, b)
                             or stage_plan.split_for_fanout(cstage, b)
                             is not None):
                    shuffle_out = ShuffleOutSpec(b.num_partitions,
                                                 tuple(b.by))
                    combo = self._plan_combine(stage_plan, cstage, b, stage)
                    if combo is not None:
                        shuffle_out.combine_aggs, \
                            shuffle_out.combine_by = combo
            fetch_srcs: Dict[int, list] = {}
            fetch_n: Dict[int, int] = {}
            mat_inputs: Dict[int, List[MicroPartition]] = {}
            first_shuffled: Optional[Boundary] = None
            for b in stage.boundaries:
                up_out = outputs.pop(b.upstream)
                if shuffled.get(b.upstream):
                    fetch_srcs[b.upstream] = [(r.address, r.shuffle_id)
                                              for r in up_out]
                    fetch_n[b.upstream] = b.num_partitions
                    first_shuffled = first_shuffled or b
                else:
                    mat_inputs[b.upstream] = self._apply_exchange(b, up_out)
            if fetch_srcs:
                if len(set(fetch_n.values())) > 1:
                    # boundaries disagree on partition count — no shared
                    # fan-out exists; materialize driver-side instead
                    for up, srcs in fetch_srcs.items():
                        mat_inputs[up] = self._driver_fetch_resilient(
                            srcs, fetch_n[up], up)
                    outputs[stage.id] = self._run_stage(stage, mat_inputs,
                                                        shuffle_out)
                else:
                    outputs[stage.id] = self._run_shuffled_stage(
                        stage_plan, stage, fetch_srcs, mat_inputs,
                        next(iter(fetch_n.values())), first_shuffled,
                        shuffle_out)
                self._cleanup_shuffles(fetch_srcs)
            else:
                outputs[stage.id] = self._run_stage(stage, mat_inputs,
                                                    shuffle_out)
            shuffled[stage.id] = shuffle_out is not None
        yield from outputs[stage_plan.root.id]

    def _plan_combine(self, stage_plan: StagePlan, cstage: Stage,
                      b: Boundary, up_stage: Stage):
        """Decide the map-side combine for one hash boundary: structural
        eligibility comes from the stage planner
        (``StagePlan.combine_for_boundary`` — the boundary must feed a
        final grouped aggregation whose aggs are all self-merges), then
        the cost model prices the modeled wire savings against the extra
        map-side agg pass (``costmodel.shuffle_combine_wins`` over the
        planner's row/NDV evidence). ``DAFT_TPU_SHUFFLE_COMBINE=1``
        forces it, ``0`` is the escape hatch, default ``auto``."""
        from ..analysis import knobs
        mode = knobs.env_str("DAFT_TPU_SHUFFLE_COMBINE").lower()
        if mode in ("0", "off", "false", "none"):
            return None
        combo = stage_plan.combine_for_boundary(cstage, b, up_stage)
        if combo is None:
            return None
        combine_aggs, combine_by, agg_node = combo
        if mode not in ("1", "on", "force", "true"):
            from ..device import costmodel
            rows = getattr(agg_node, "group_rows_est", None)
            groups = getattr(agg_node, "group_ndv", None)
            if not costmodel.shuffle_combine_wins(
                    rows, groups, b.num_partitions,
                    n_cols=len(combine_aggs) + len(combine_by)):
                return None
        return combine_aggs, combine_by

    def _cleanup_shuffles(self, fetch_srcs: Dict[int, list]) -> None:
        """Best-effort release of consumed map outputs when the consuming
        stage completes, addressed straight to each serving host through
        the shuffle transport (the address is part of the map receipt —
        one call per shuffle id). Recovered outputs are released through
        their whole lineage translation chain (the recomputed replacement
        lives at a different address than the receipt)."""
        from .shuffle_service import unregister_remote
        lineage = self._resilience().lineage
        for srcs in fetch_srcs.values():
            for src in srcs:
                for address, shuffle_id in lineage.chain(tuple(src)):
                    try:
                        unregister_remote(address, shuffle_id)
                    except Exception:
                        pass

    # ------------------------------------------------------------------
    def _make_tasks(self, stage: Stage,
                    stage_inputs: Dict[int, List[MicroPartition]],
                    shuffle_out: Optional[ShuffleOutSpec] = None
                    ) -> List[StageTask]:
        """Shard a map-like scan stage across workers (contiguous chunks —
        preserves partition order); everything else is one task."""
        n_workers = len(self.manager.worker_ids)
        src = stage.scan_source()
        if n_workers > 1 and src is not None and len(src.tasks) > 1 \
                and stage.is_map_like():
            k = min(n_workers, len(src.tasks))
            per = (len(src.tasks) + k - 1) // k
            tasks = []
            for i in range(k):
                chunk = src.tasks[i * per:(i + 1) * per]
                if not chunk:
                    continue
                tasks.append(StageTask(stage.id, stage.with_scan_tasks(chunk),
                                       stage_inputs, task_idx=i,
                                       shuffle_out=shuffle_out,
                                       fault_key=stage.task_key(i)))
            return tasks
        return [StageTask(stage.id, stage.plan, stage_inputs,
                          shuffle_out=shuffle_out,
                          fault_key=stage.task_key(0))]

    def _run_stage(self, stage: Stage,
                   stage_inputs: Dict[int, List[MicroPartition]],
                   shuffle_out: Optional[ShuffleOutSpec] = None) -> list:
        tasks = self._make_tasks(stage, stage_inputs, shuffle_out)
        return self._collect(tasks)

    def _run_shuffled_stage(self, stage_plan: StagePlan, stage: Stage,
                            fetch_srcs: Dict[int, list],
                            mat_inputs: Dict[int, List[MicroPartition]],
                            n: int, b: Boundary,
                            shuffle_out: Optional[ShuffleOutSpec]) -> list:
        """Stage with shuffle-backed inputs: fan the whole fragment out
        when it is partition-local; otherwise fan out its safe frontier
        (e.g. the merge-agg under a Sort) and run the global remainder as
        one task; if neither applies, fetch partitions onto the driver."""
        # replicating a driver-materialized input to every reduce task is
        # only sound for GATHER boundaries (broadcast-by-design, join-type
        # gated at translate time). A materialized hash/range/split input
        # replicated beside a partitioned side would duplicate non-inner
        # join results — fall back to the driver for the whole stage.
        replication_ok = all(
            ob.kind == "gather" for ob in stage.boundaries
            if ob.upstream in mat_inputs)
        if replication_ok and stage_plan.fanout_safe(stage, b) and all(
                stage_plan.fanout_safe(stage, ob)
                for ob in stage.boundaries if ob.upstream in fetch_srcs):
            return self._run_reduce_fanout(stage, fetch_srcs, mat_inputs,
                                           n, shuffle_out)
        split = stage_plan.split_for_fanout(stage, b) if replication_ok \
            else None
        if split is not None:
            sub, remainder, pid = split
            if all(StagePlan._contains_input(sub, up)
                   for up in fetch_srcs):
                sub_stage = Stage(stage.id, sub, [])
                sort_node = _sort_fragment_root(remainder, pid)
                if sort_node is not None and shuffle_out is None \
                        and self._shuffle_enabled():
                    return self._range_sort_remainder(
                        sub_stage, remainder, pid, sort_node,
                        fetch_srcs, mat_inputs, n)
                parts = self._run_reduce_fanout(sub_stage, fetch_srcs,
                                                mat_inputs, n, None)
                rest = Stage(stage.id, remainder, [])
                bindings: Dict[int, object] = {pid: parts}
                bindings.update(mat_inputs)
                return self._run_stage(rest, bindings, shuffle_out)
        # defensive fallback: materialize the shuffled inputs driver-side
        for up, srcs in fetch_srcs.items():
            mat_inputs[up] = self._driver_fetch_resilient(srcs, n, up)
        return self._run_stage(stage, mat_inputs, shuffle_out)

    def _range_sort_remainder(self, sub_stage: Stage, remainder, pid: int,
                              sort_node, fetch_srcs: Dict[int, list],
                              mat_inputs: Dict[int, List[MicroPartition]],
                              n: int) -> Optional[list]:
        """Distributed global sort with rows never touching the driver
        (the r2 verdict's scale ceiling: every range/sort boundary funneled
        through the driver). Three worker-side phases:

        1. the partition-local sub-fragment runs per hash partition with
           ``store`` shuffle-out: outputs stay in worker shuffle caches,
           each task returns a sort-key SAMPLE with its receipt;
        2. the driver computes range boundaries from the samples alone
           (KB, not rows) and dispatches per-receipt ``range`` repartition
           tasks — rows move worker→worker through the shuffle transport;
        3. one reduce task per range sorts its partition locally; the
           driver concatenates results in partition order, which IS the
           global order (ranges are disjoint and ordered).

        Shape gating happens in ``_sort_fragment_root`` BEFORE this is
        called; failures inside the protocol abort the query (same
        contract as the hash-shuffle path)."""
        from ..context import get_context
        from ..execution.executor import sample_boundaries
        from .worker import FetchSpec, ShuffleOutSpec, StageTask, _ipc_bytes
        cfg = get_context().execution_config
        by = list(sort_node.sort_by)
        desc = list(sort_node.descending)
        nf = list(sort_node.nulls_first)

        store = ShuffleOutSpec(1, tuple(by), kind="store",
                               sample_k=cfg.sample_size_for_sort)
        receipts = self._run_reduce_fanout(sub_stage, fetch_srcs,
                                           mat_inputs, n, store)
        try:
            from ..recordbatch import RecordBatch
            from .worker import _ipc_table
            samples = [RecordBatch.from_arrow_table(
                _ipc_table(r.samples_ipc))
                for r in receipts if r.samples_ipc]
            k = max(len(receipts), 1)
            names = [e.name() for e in by]
            boundaries = sample_boundaries(samples, names, desc, nf, k) \
                if samples else None
            if boundaries is None or k == 1:
                # no keys to sample or single partition: one sort task
                # reading every stored output through the shuffle service
                rest = Stage(sub_stage.id, remainder, [])
                bindings: Dict[int, object] = {pid: FetchSpec(
                    [(r.address, r.shuffle_id) for r in receipts], 0,
                    keys=[sub_stage.task_key(j, "p1")
                          for j in range(len(receipts))])}
                bindings.update(mat_inputs)
                return self._run_stage(rest, bindings, None)
            bipc = _ipc_bytes(boundaries.to_arrow_table())
            range_spec = ShuffleOutSpec(k, tuple(by), kind="range",
                                        descending=tuple(desc),
                                        boundaries_ipc=bipc)
            phase2 = [StageTask(
                sub_stage.id, pp.StageInput(pid, sort_node.schema()),
                {pid: FetchSpec([(r.address, r.shuffle_id)], 0,
                                keys=[sub_stage.task_key(j, "p1")])},
                task_idx=j, shuffle_out=range_spec,
                fault_key=sub_stage.task_key(j, "p2"))
                for j, r in enumerate(receipts)]
            receipts2 = self._collect(phase2)
        finally:
            self._cleanup_shuffles(
                {0: [(r.address, r.shuffle_id) for r in receipts]})
        srcs2 = [(r.address, r.shuffle_id) for r in receipts2]
        keys2 = [sub_stage.task_key(j, "p2") for j in range(len(receipts2))]
        try:
            tasks = []
            for i in range(k):
                bindings = {pid: FetchSpec(srcs2, i, keys=keys2)}
                bindings.update(mat_inputs)
                tasks.append(StageTask(sub_stage.id, remainder, bindings,
                                       task_idx=i,
                                       fault_key=sub_stage.task_key(i,
                                                                    "p3")))
            return self._collect(tasks)
        finally:
            self._cleanup_shuffles({0: srcs2})

    @staticmethod
    def _driver_fetch(srcs: list, n: int, keys: Optional[list] = None,
                      partition: Optional[int] = None
                      ) -> List[MicroPartition]:
        """Fetch partitions [0, n) — or just ``partition`` — from every
        source onto the driver."""
        from .worker import resolve_stage_inputs
        parts = range(n) if partition is None else [partition]
        out: List[MicroPartition] = []
        for i in parts:
            out.extend(resolve_stage_inputs(
                {0: FetchSpec(srcs, i, keys=keys)})[0])
        return out

    def _driver_fetch_resilient(self, srcs: list, n: int, up: int
                                ) -> List[MicroPartition]:
        """Driver-side materialization with the same fetch-failure
        handling the worker-side reduce tasks get (one shared
        ``FetchRetryState`` policy): a backed-off refetch first, lineage
        recomputation of the producing map task when the same source
        fails twice (its data is gone). Retries are per-partition, so
        one flaky fetch never refetches the whole boundary."""
        import time
        ctx = self._resilience()
        keys = [f"s{up}.m{j}" for j in range(len(srcs))]
        out: List[MicroPartition] = []
        for i in range(n):
            state = FetchRetryState(ctx.policy)
            while True:
                cur = [ctx.lineage.resolve(tuple(s)) for s in srcs]
                try:
                    out.extend(self._driver_fetch(cur, n, keys,
                                                  partition=i))
                    break
                except ShuffleFetchError as exc:
                    if state.should_recover(exc) \
                            and not self._supervisor().recover_source(
                                (exc.address, exc.shuffle_id), exc):
                        raise
                    count("retries")
                    time.sleep(ctx.policy.backoff_s(f"s{up}.p{i}",
                                                    state.attempts))
        return out

    def _run_reduce_fanout(self, stage: Stage, fetch_srcs: Dict[int, list],
                           mat_inputs: Dict[int, List[MicroPartition]],
                           n: int, shuffle_out: Optional[ShuffleOutSpec]
                           ) -> list:
        """One reduce task per hash partition: task i binds each shuffled
        input to FetchSpec(partition=i); driver-materialized bindings
        (broadcast/gather sides) replicate to every task. Fetch sources
        carry stable ``s<upstream>.m<map_idx>`` keys so injected faults
        replay identically across runs (the shuffle uuid does not)."""
        tasks = []
        for i in range(n):
            si: Dict[int, object] = {
                up: FetchSpec(srcs, i,
                              keys=[f"s{up}.m{j}"
                                    for j in range(len(srcs))])
                for up, srcs in fetch_srcs.items()}
            si.update(mat_inputs)
            tasks.append(StageTask(stage.id, stage.plan, si, task_idx=i,
                                   shuffle_out=shuffle_out,
                                   fault_key=stage.task_key(i, "r")))
        return self._collect(tasks)

    def _collect(self, tasks: List[StageTask]) -> list:
        """Dispatch one batch of tasks through the resilient task
        supervisor (retry/quarantine/lineage/speculation live there) and
        flatten the per-task results in task order. A traced query gets
        one ``stage`` span per batch; the supervisor's per-task spans
        nest under it."""
        from .. import tracing
        sid = tasks[0].stage_id if tasks else -1
        with tracing.span("stage", key=f"stage:s{sid}",
                          attrs={"tasks": len(tasks)}):
            per_task = self._supervisor().run(tasks)
        out: list = []
        for res in per_task:
            out.extend(res if isinstance(res, list) else [res])
        return out

    # ------------------------------------------------------------------
    def _apply_exchange(self, b: Boundary, parts: List[MicroPartition]
                        ) -> List[MicroPartition]:
        """Execute one exchange boundary on the driver: the materializing
        map/reduce transport between stages (mesh-collective exchanges run
        inside stages as DeviceExchangeAgg programs instead)."""
        from ..execution.executor import LocalExecutor
        if not parts:
            return parts
        schema = parts[0].schema
        node = pp.Exchange(pp.InMemorySource(parts, schema), b.kind,
                           b.num_partitions, b.by, b.descending,
                           engine_inserted=b.engine_inserted)
        ex = LocalExecutor()
        return list(ex.run(node))
