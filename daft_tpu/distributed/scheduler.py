"""Pluggable scheduling policies + the stage-driving runner.

Reference: flotilla's ``Scheduler`` trait and scheduler actor
(``src/daft-distributed/src/scheduling/scheduler/mod.rs:18-23``; default
locality/spread policy ``scheduler/default.rs``, linear policy
``scheduler/linear.rs``) — policies are pure functions over worker snapshots
so they unit-test against mock workers with no hardware, exactly like the
reference's ``scheduling/tests.rs``.
"""

from __future__ import annotations

import itertools
from typing import Dict, Iterator, List, Optional

from ..micropartition import MicroPartition
from ..physical import plan as pp
from .stages import Boundary, Stage, StagePlan
from .worker import StageTask, WorkerManager, WorkerState


class Scheduler:
    """Policy: pick a worker for a task given current worker states."""

    def pick(self, task: StageTask, states: List[WorkerState]) -> str:
        raise NotImplementedError


class RoundRobinScheduler(Scheduler):
    """Spread tasks evenly regardless of load (reference linear policy)."""

    def __init__(self):
        self._counter = itertools.count()

    def pick(self, task: StageTask, states: List[WorkerState]) -> str:
        if task.preferred_worker is not None:
            for st in states:
                if st.worker.id == task.preferred_worker:
                    return st.worker.id
        return states[next(self._counter) % len(states)].worker.id


class LeastLoadedScheduler(Scheduler):
    """Soft-affinity + least-active placement (reference default policy:
    WorkerAffinity falls back to Spread)."""

    def pick(self, task: StageTask, states: List[WorkerState]) -> str:
        if task.preferred_worker is not None:
            for st in states:
                if st.worker.id == task.preferred_worker \
                        and st.active < st.worker.num_slots:
                    return st.worker.id
        return min(states, key=lambda s: (s.active, s.worker.id)).worker.id


class StageRunner:
    """Drives a StagePlan: dispatches each stage's tasks through the
    scheduler, executes exchange boundaries on the driver, feeds results
    downstream. Failed tasks are retried once on a different worker
    (reference: per-task retry semantics delegated to Ray in the original;
    here the runner owns them)."""

    def __init__(self, manager: WorkerManager,
                 scheduler: Optional[Scheduler] = None, max_retries: int = 1):
        self.manager = manager
        self.scheduler = scheduler or LeastLoadedScheduler()
        self.max_retries = max_retries

    # ------------------------------------------------------------------
    def run(self, stage_plan: StagePlan) -> Iterator[MicroPartition]:
        outputs: Dict[int, List[MicroPartition]] = {}
        for stage in stage_plan.stages:
            stage_inputs: Dict[int, List[MicroPartition]] = {}
            for b in stage.boundaries:
                stage_inputs[b.upstream] = self._apply_exchange(
                    b, outputs.pop(b.upstream))
            outputs[stage.id] = self._run_stage(stage, stage_inputs)
        yield from outputs[stage_plan.root.id]

    # ------------------------------------------------------------------
    def _make_tasks(self, stage: Stage,
                    stage_inputs: Dict[int, List[MicroPartition]]
                    ) -> List[StageTask]:
        """Shard a map-like scan stage across workers (contiguous chunks —
        preserves partition order); everything else is one task."""
        n_workers = len(self.manager.worker_ids)
        src = stage.scan_source()
        if n_workers > 1 and src is not None and len(src.tasks) > 1 \
                and stage.is_map_like():
            k = min(n_workers, len(src.tasks))
            per = (len(src.tasks) + k - 1) // k
            tasks = []
            for i in range(k):
                chunk = src.tasks[i * per:(i + 1) * per]
                if not chunk:
                    continue
                tasks.append(StageTask(stage.id, stage.with_scan_tasks(chunk),
                                       stage_inputs, task_idx=i))
            return tasks
        return [StageTask(stage.id, stage.plan, stage_inputs)]

    def _run_stage(self, stage: Stage,
                   stage_inputs: Dict[int, List[MicroPartition]]
                   ) -> List[MicroPartition]:
        tasks = self._make_tasks(stage, stage_inputs)
        futures = []
        for t in tasks:
            wid = self.scheduler.pick(t, self.manager.snapshot())
            futures.append((t, wid, self.manager.dispatch(t, wid)))
        parts: List[MicroPartition] = []
        for t, wid, fut in futures:
            try:
                parts.extend(fut.result())
            except Exception:
                if self.max_retries < 1:
                    raise
                parts.extend(self._retry(t, exclude=wid))
        return parts

    def _retry(self, task: StageTask, exclude: str) -> List[MicroPartition]:
        states = [s for s in self.manager.snapshot()
                  if s.worker.id != exclude] or self.manager.snapshot()
        wid = self.scheduler.pick(task, states)
        return self.manager.dispatch(task, wid).result()

    # ------------------------------------------------------------------
    def _apply_exchange(self, b: Boundary, parts: List[MicroPartition]
                        ) -> List[MicroPartition]:
        """Execute one exchange boundary on the driver: the materializing
        map/reduce transport between stages (mesh-collective exchanges run
        inside stages as DeviceExchangeAgg programs instead)."""
        from ..execution.executor import LocalExecutor
        if not parts:
            return parts
        schema = parts[0].schema
        node = pp.Exchange(pp.InMemorySource(parts, schema), b.kind,
                           b.num_partitions, b.by, b.descending,
                           engine_inserted=b.engine_inserted)
        ex = LocalExecutor()
        return list(ex.run(node))
