"""Distributed runtime re-planning: boundary actuals → stage decisions.

The local runner's ``_run_adaptive`` loop (materialize → replace subtree
with actuals → re-optimize) never existed in the distributed tier, yet
the stage runner sits on EXACT evidence at every materialized boundary:
map receipts carry pushed rows/bytes (and, on combined boundaries, the
pushed group-state count — a bound on the boundary keys' NDV), driver-
materialized partitions carry exact sizes, and in-memory sources are
right there to measure. This module closes loop (b) of the self-tuning
plan (ROADMAP item 4): before the :class:`~.scheduler.StageRunner`
dispatches a stage, a :class:`StageReplanner` folds those actuals back
into the remaining plan —

- **estimate rewrites** — ``Aggregate.group_rows_est`` /
  ``Aggregate.group_ndv`` and ``HashJoin.left/right_bytes_est`` inside
  the not-yet-dispatched fragment are replaced with measured boundary
  actuals, so the kernel strategy ladder (``groupby_strategy``), the
  fused-gate, and the grace-join spill fanout (``plan_partitions`` /
  ``spill_plan_wins``) price from evidence instead of footer guesses;
- **combine gating** — ``shuffle_combine_wins`` re-priced with the
  stage's measured input rows and (when affordable) the EXACT key NDV
  of in-memory sources: a near-unique boundary flips a default-accepted
  combine off, a mis-estimated-near-unique footer flips a declined
  combine on;
- **broadcast demotion** — a hash boundary feeding one side of a
  downstream hash join demotes to a replicated ``gather`` when the
  producing stage's measured output bound fits the broadcast threshold
  (join-type gated exactly like the static translate decision);
- **exchange rung** — the r18 collective/hierarchical/flight ladder is
  re-priced with measured rows and row widths instead of the
  evidence-free default-accept.

Chaos-determinism contract: ``DAFT_TPU_CHAOS_SERIALIZE=1`` or an active
fault plan disables re-planning entirely (``adaptive_enabled`` returns
False and counts ``replan_frozen``) — a replayed run must plan exactly
like the recorded one. Every decision lands in the process-wide adaptive
counters (``physical/adaptive.py``) → the per-query ``adaptive`` stats
block, the flight recorder, and ``daft_tpu_adaptive_*`` metrics.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..physical import adaptive
from ..physical import plan as pp
from .stages import Boundary, Stage, StagePlan

#: exact-NDV measurement cap: a driver-side distinct over more rows than
#: this costs more than the decision it informs
_NDV_MEASURE_CAP = 1 << 21

#: only a measured side at least this factor under the threshold demotes
#: (headroom for the row-local output bound being an upper bound on a
#: *different* quantity than the broadcast build table)
_DEMOTE_HEADROOM = 1.0


def adaptive_enabled() -> bool:
    """Master gate for distributed runtime re-planning:
    ``DAFT_TPU_ADAPTIVE`` env overrides the per-query
    ``ExecutionConfig.tpu_adaptive`` mirror; chaos-serialize or an
    active fault plan freezes it regardless (counted)."""
    from ..analysis import knobs
    raw = knobs.env_raw("DAFT_TPU_ADAPTIVE")
    if raw is not None:
        want = bool(knobs.env_bool("DAFT_TPU_ADAPTIVE"))
    else:
        try:
            from ..context import get_context
            want = bool(get_context().execution_config.tpu_adaptive)
        except Exception:
            want = False
    if not want:
        return False
    if knobs.env_bool("DAFT_TPU_CHAOS_SERIALIZE"):
        adaptive.count("replan_frozen")
        return False
    from .resilience import active_fault_plan
    if active_fault_plan() is not None:
        adaptive.count("replan_frozen")
        return False
    return True


@dataclasses.dataclass
class BoundaryActuals:
    """Measured evidence for one stage input (or one stage's output
    bound): exact rows/bytes, and an NDV bound on the boundary keys —
    ``exact_ndv`` when it came from a driver-side distinct, else it is
    the summed per-task combine-state count (an upper bound)."""

    rows: int = 0
    nbytes: int = 0
    ndv: Optional[int] = None
    exact_ndv: bool = False


def measure_key_ndv(parts, names: List[str]) -> Optional[int]:
    """EXACT distinct count of ``names`` over a list of materialized
    partitions, or None when it would cost too much (row cap) or the
    columns aren't all present. Driver-side, bounded, counted, and
    vectorized (arrow count_distinct / group_by — a python set over a
    million key tuples would cost more than the decision it informs)."""
    try:
        import pyarrow as pa
        import pyarrow.compute as pc
        total = sum(len(p) for p in parts)
        if total == 0 or total > _NDV_MEASURE_CAP:
            return None
        tbls = []
        for p in parts:
            if len(p) == 0:
                continue
            tbl = p.combined().to_arrow_table()
            if any(n not in tbl.column_names for n in names):
                return None
            tbls.append(tbl.select(names))
        if not tbls:
            return None
        t = tbls[0] if len(tbls) == 1 else pa.concat_tables(tbls)
        if len(names) == 1:
            ndv = pc.count_distinct(t.column(0)).as_py()
        else:
            ndv = t.group_by(names).aggregate([]).num_rows
        adaptive.count("ndv_measured")
        return int(ndv)
    except Exception:
        return None


def _by_names(b: Boundary) -> Optional[List[str]]:
    try:
        names = [e.name() for e in b.by]
        return names if names else None
    except Exception:
        return None


#: fragment nodes through which "output bytes ≤ input bytes" holds (the
#: conservative bound the demotion and exchange evidence rely on);
#: anything else — joins, explodes, concats — can expand and disqualifies
_NON_EXPANDING = (pp.Project, pp.Filter, pp.UDFProject, pp.Aggregate,
                  pp.DeviceFragmentAgg, pp.StageInput, pp.InMemorySource,
                  pp.Limit, pp.Sample, pp.Dedup, pp.TopN, pp.ScanSource)


def _non_expanding(plan) -> bool:
    """Whole-fragment check for the bound above. A ScanSource leaf is
    structurally allowed (the allowed set is single-child chains, so a
    scan can never sit beside a measured boundary) — scan-rooted stages
    simply have no input actuals and resolve to no bound."""
    if not isinstance(plan, _NON_EXPANDING):
        return False
    return all(_non_expanding(c) for c in plan.children)


def _in_memory_parts(plan) -> Optional[list]:
    """Every partition of the fragment's in-memory sources, or None when
    there are none or any source is spill-backed (re-draining a buffer
    is not a free peek)."""
    srcs: List[pp.InMemorySource] = []

    def walk(n):
        if isinstance(n, pp.InMemorySource):
            srcs.append(n)
        for c in n.children:
            walk(c)

    walk(plan)
    if not srcs:
        return None
    parts: List = []
    for s in srcs:
        sp = getattr(s, "partitions", None)
        if not isinstance(sp, (list, tuple)):
            return None
        parts.extend(sp)
    return parts


class StageReplanner:
    """One query's runtime re-planner, driven by the StageRunner: peeks
    each stage's input actuals before dispatch, rewrites the fragment's
    estimates, and re-picks the boundary decisions. Owns an
    :class:`~daft_tpu.physical.adaptive.AdaptivePlanner` so every
    decision shows up in ``explain_analyze`` next to the local AQE
    layer's."""

    def __init__(self, stage_plan: StagePlan, planner=None):
        from ..context import get_context
        self.stage_plan = stage_plan
        self.cfg = get_context().execution_config
        # share the distributed AQE loop's planner when one is active so
        # both layers' decisions interleave in ONE explain_analyze log
        self.planner = planner if planner is not None \
            else adaptive.new_planner(self.cfg)
        #: per-stage output-bound evidence (set in before_stage, used
        #: when pricing that stage's own consumer-boundary decisions)
        self._evidence: Dict[int, BoundaryActuals] = {}

    # ------------------------------------------------------------ inputs
    def _input_actuals(self, stage: Stage, outputs: Dict[int, list],
                       out_mode: Dict[int, str]
                       ) -> Dict[int, BoundaryActuals]:
        """Measured actuals per input boundary, PEEKED from the producer
        outputs the runner has not bound yet."""
        acts: Dict[int, BoundaryActuals] = {}
        for b in stage.boundaries:
            up_out = outputs.get(b.upstream)
            if up_out is None:
                continue
            mode = out_mode.get(b.upstream, "mat")
            a = None
            if mode == "shuffled":
                rows = sum(int(getattr(r, "rows", 0)) for r in up_out)
                nbytes = sum(int(getattr(r, "nbytes", 0)) for r in up_out)
                states = [getattr(r, "state_rows", None) for r in up_out]
                ndv = sum(states) if states and \
                    all(s is not None for s in states) else None
                a = BoundaryActuals(rows, nbytes, ndv, exact_ndv=False)
            elif mode == "collective":
                parts = [p for pl in up_out for p in pl]
                a = BoundaryActuals(sum(len(p) for p in parts),
                                    sum(int(p.size_bytes() or 0)
                                        for p in parts))
            else:  # driver-materialized
                a = BoundaryActuals(sum(len(p) for p in up_out),
                                    sum(int(p.size_bytes() or 0)
                                        for p in up_out))
                names = _by_names(b)
                if b.kind == "hash" and names:
                    ndv = measure_key_ndv(up_out, names)
                    if ndv is not None:
                        a.ndv, a.exact_ndv = ndv, True
            acts[b.upstream] = a
        return acts

    def _source_actuals(self, stage: Stage, b: Optional[Boundary]
                        ) -> Optional[BoundaryActuals]:
        """Exact evidence from the fragment's own in-memory sources
        (first stages have no input boundaries, but their data is right
        here): rows/bytes always, key NDV when the boundary keys are
        plain source columns and the row cap affords a distinct."""
        parts = _in_memory_parts(stage.plan)
        if parts is None:
            return None
        a = BoundaryActuals(sum(len(p) for p in parts),
                            sum(int(p.size_bytes() or 0) for p in parts))
        names = _by_names(b) if b is not None else None
        if b is not None and b.kind == "hash" and names:
            ndv = measure_key_ndv(parts, names)
            if ndv is not None:
                a.ndv, a.exact_ndv = ndv, True
        return a

    # ------------------------------------------------------ before_stage
    def before_stage(self, stage: Stage, cons, outputs: Dict[int, list],
                     out_mode: Dict[int, str]) -> None:
        """Fold measured evidence into ``stage`` before the runner plans
        its dispatch: rewrite fragment estimates from input actuals,
        build this stage's output-bound evidence, and demote its
        consumer boundary to a broadcast when the bound fits."""
        acts = self._input_actuals(stage, outputs, out_mode)
        if acts:
            self._rewrite_estimates(stage, acts)
        b = cons[1] if cons is not None else None
        ev = self._output_bound(stage, acts, b)
        if ev is not None and ev.ndv is None and b is not None \
                and b.kind == "hash":
            # the consumer-boundary keys' NDV wasn't carried by any
            # receipt: measure it EXACTLY over whatever materialized
            # rows the driver already holds (mat inputs + in-memory
            # sources), when the key columns pass through by name and
            # the row cap affords a distinct
            names = _by_names(b)
            parts = self._driver_resident_parts(stage, outputs, out_mode)
            if names and parts is not None:
                ndv = measure_key_ndv(parts, names)
                if ndv is not None:
                    ev.ndv, ev.exact_ndv = ndv, True
        self._evidence[stage.id] = ev
        if cons is not None:
            self._maybe_demote(stage, cons[0], cons[1])

    def _driver_resident_parts(self, stage: Stage,
                               outputs: Dict[int, list],
                               out_mode: Dict[int, str]):
        """Every materialized partition of this stage's inputs the
        driver holds right now (mat boundary outputs + in-memory source
        partitions), or None when any input is NOT driver-resident —
        the NDV of a partial view is not the NDV of the stage."""
        parts: List = []
        for ob in stage.boundaries:
            if out_mode.get(ob.upstream, "mat") != "mat":
                return None
            up_out = outputs.get(ob.upstream)
            if up_out is None:
                return None
            parts.extend(up_out)
        src_parts = _in_memory_parts(stage.plan)
        if src_parts is not None:
            parts.extend(src_parts)
        return parts if parts else None

    def _output_bound(self, stage: Stage,
                      acts: Dict[int, BoundaryActuals],
                      b: Optional[Boundary]) -> Optional[BoundaryActuals]:
        """Upper bound on this stage's output (rows/bytes/key-NDV) —
        only claimed when the fragment is non-expanding end to end and
        every input is measured (or the data is an in-memory source)."""
        if not _non_expanding(stage.plan):
            return None
        if stage.boundaries and acts \
                and all(ob.upstream in acts for ob in stage.boundaries):
            rows = sum(a.rows for a in acts.values())
            nbytes = sum(a.nbytes for a in acts.values())
            ndvs = [a for a in acts.values() if a.ndv is not None]
            ndv = min((a.ndv for a in ndvs), default=None) \
                if len(ndvs) == len(acts) and acts else None
            exact = bool(ndvs) and all(a.exact_ndv for a in ndvs) \
                and ndv is not None
            return BoundaryActuals(rows, nbytes, ndv, exact)
        if not stage.boundaries:
            return self._source_actuals(stage, b)
        return None

    # ------------------------------------------------------- est rewrite
    def _rewrite_estimates(self, stage: Stage,
                           acts: Dict[int, BoundaryActuals]) -> None:
        """Replace the fragment's planner estimates with boundary
        actuals — the distributed analogue of the local AQE loop's
        replace-subtree-with-in-memory-source step."""
        rewrites = 0

        def feeding(n, up: int) -> bool:
            return StagePlan._contains_input(n, up)

        def walk(n):
            nonlocal rewrites
            if isinstance(n, pp.Aggregate) and n.mode == "final":
                ups = [u for u in acts if feeding(n, u)]
                if ups:
                    rows = sum(acts[u].rows for u in ups)
                    old_ndv = getattr(n, "group_ndv", None)
                    n.group_rows_est = rows
                    rewrites += 1
                    ndvs = [acts[u].ndv for u in ups]
                    if all(v is not None for v in ndvs) and ndvs:
                        ndv = sum(ndvs)
                        if not hasattr(n, "group_ndv_footer"):
                            # stash the ORIGINAL footer evidence (even
                            # None): the NDV_FOOTER_RATIO observation
                            # must compare actuals against what the
                            # footer CLAIMED — a rewritten EXACT value
                            # observing ratio≈1.0 would EWMA-erase the
                            # learned damping
                            n.group_ndv_footer = old_ndv
                        n.group_ndv = ndv
                        if old_ndv and (old_ndv >= 2 * ndv
                                        or ndv >= 2 * old_ndv):
                            adaptive.count("ndv_corrections")
            if isinstance(n, pp.HashJoin):
                lups = [u for u in acts if feeding(n.children[0], u)]
                if lups:
                    n.left_bytes_est = sum(acts[u].nbytes for u in lups)
                    rewrites += 1
                rups = [u for u in acts if feeding(n.children[1], u)]
                if rups:
                    n.right_bytes_est = sum(acts[u].nbytes for u in rups)
                    rewrites += 1
            for c in n.children:
                walk(c)

        walk(stage.plan)
        if rewrites:
            adaptive.count("est_rewrites", rewrites)
            rows = sum(a.rows for a in acts.values())
            nbytes = sum(a.nbytes for a in acts.values())
            self.planner.record_replan(
                f"stage s{stage.id}: {rewrites} fragment estimate(s) "
                f"rewritten from boundary actuals", rows, nbytes)

    # -------------------------------------------------------- demotion
    def _maybe_demote(self, stage: Stage, cstage: Stage,
                      b: Boundary) -> None:
        """Hash-boundary → broadcast demotion from measured evidence:
        when this stage's output bound fits the broadcast threshold and
        its consumer is a hash join whose join type tolerates a
        replicated build side, the boundary becomes a ``gather`` — the
        small side skips the worker-cache shuffle entirely and
        replicates to the reduce tasks instead (the distributed
        analogue of the executor's ``_adaptive_hash_join`` demotion).
        Guards: only join-side co-partitioning exchanges (the pair
        translate marked strategy-adaptable), never when the sibling
        side is already demoted (one side must stay partitioned), and
        never the LARGER side when both are measured."""
        if b.kind != "hash" or b.num_partitions <= 1 or not b.join_side:
            return
        ev = self._evidence.get(stage.id)
        if ev is None or ev.nbytes <= 0:
            return
        threshold = self.cfg.broadcast_join_size_bytes_threshold
        if ev.nbytes > threshold * _DEMOTE_HEADROOM:
            return
        side_how = self._join_side(cstage.plan, stage.id)
        if side_how is None:
            return
        side, how, join_node = side_how
        if side == "right" and how not in ("inner", "left", "semi",
                                           "anti"):
            return
        if side == "left" and how not in ("inner", "right"):
            return
        sib = self._sibling_boundary(cstage, join_node, side, stage.id)
        if sib is not None:
            if sib.kind != "hash":
                return  # sibling already demoted: keep this side fanned
            sib_ev = self._sibling_evidence(sib)
            if sib_ev is not None and sib_ev.nbytes < ev.nbytes:
                return  # the smaller side should broadcast, not this one
        b.kind = "gather"
        b.num_partitions = 1
        adaptive.count("broadcast_demotions")
        self.planner.record_join(
            f"s{stage.id} hash→broadcast_{side} (measured {ev.nbytes} "
            f"bytes ≤ threshold {threshold})", ev.nbytes)

    def _sibling_boundary(self, cstage: Stage, join_node, side: str,
                          upstream: int) -> Optional[Boundary]:
        """The consumer boundary feeding the OTHER side of the join."""
        other = join_node.children[1 if side == "left" else 0]
        for ob in cstage.boundaries:
            if ob.upstream != upstream \
                    and StagePlan._contains_input(other, ob.upstream):
                return ob
        return None

    def _sibling_evidence(self, sib: Boundary
                          ) -> Optional[BoundaryActuals]:
        """Best available output bound for the sibling side's producer:
        its recorded evidence when that stage was already processed,
        else a recursive bound over its (not-yet-processed) stage chain
        down to in-memory sources — parquet scans stay unknown."""
        return self._recursive_bound(sib.upstream, depth=0)

    def _recursive_bound(self, stage_id: int, depth: int
                         ) -> Optional[BoundaryActuals]:
        if depth > 8:
            return None
        ev = self._evidence.get(stage_id)
        if ev is not None:
            return ev
        st = next((s for s in self.stage_plan.stages
                   if s.id == stage_id), None)
        if st is None:
            return None
        if not _non_expanding(st.plan):
            return None
        if not st.boundaries:
            return self._source_actuals(st, None)
        bounds = [self._recursive_bound(ob.upstream, depth + 1)
                  for ob in st.boundaries]
        if any(b is None for b in bounds):
            return None
        return BoundaryActuals(sum(b.rows for b in bounds),
                               sum(b.nbytes for b in bounds))

    @staticmethod
    def _join_side(plan, upstream: int):
        """→ ("left"|"right", how, node) when the UNIQUE hash-strategy
        HashJoin consuming ``StageInput(upstream)`` does so through
        exactly one side; None otherwise."""
        found = []

        def walk(n):
            if isinstance(n, pp.HashJoin) and n.strategy == "hash":
                in_l = StagePlan._contains_input(n.children[0], upstream)
                in_r = StagePlan._contains_input(n.children[1], upstream)
                if in_l != in_r:
                    found.append(("left" if in_l else "right", n.how, n))
            for c in n.children:
                walk(c)

        walk(plan)
        return found[0] if len(found) == 1 else None

    # ------------------------------------------------- boundary pricing
    def combine_evidence(self, stage: Stage):
        """(rows, ndv, exact) evidence for this stage's map-side combine
        decision, or None when nothing was measured."""
        ev = self._evidence.get(stage.id)
        if ev is None or ev.rows <= 0:
            return None
        return ev.rows, ev.ndv, ev.exact_ndv

    def exchange_evidence(self, stage: Stage):
        """(rows, row_bytes) evidence for the exchange-path ladder."""
        ev = self._evidence.get(stage.id)
        if ev is None or ev.rows <= 0:
            return None
        return ev.rows, max(ev.nbytes / ev.rows, 1.0)

    # ------------------------------------------------------ after_stage
    def after_stage(self, stage: Stage, result: list, mode: str) -> None:
        """Post-completion feedback: a driver-materialized stage whose
        fragment holds a final grouped Aggregate with footer NDV
        evidence reveals the TRUE group count — observed into the
        calibrated ``NDV_FOOTER_RATIO`` so future footer evidence is
        damped toward reality."""
        if mode != "mat" or not result:
            return
        agg = self._final_agg_with_footer(stage.plan)
        if agg is None:
            return
        if hasattr(agg, "group_ndv_footer"):
            # a rewrite happened: only the stashed ORIGINAL footer (which
            # may be None — no footer evidence existed) may be observed
            footer = agg.group_ndv_footer
        else:
            footer = getattr(agg, "group_ndv", None)
        try:
            actual = sum(len(p) for p in result)
        except Exception:
            return
        if not footer or footer <= 0 or actual <= 0:
            return
        from ..device import calibration
        calibration.observe("NDV_FOOTER_RATIO", actual / float(footer))
        self.planner.record_replan(
            f"stage s{stage.id}: observed {actual} groups vs footer NDV "
            f"{int(footer)} (ratio {actual / float(footer):.3g})", actual)

    @staticmethod
    def _final_agg_with_footer(plan):
        found = []

        def walk(n):
            if not (isinstance(n, pp.Aggregate)
                    and n.mode in ("final", "single") and n.group_by):
                for c in n.children:
                    walk(c)
                return
            footer = n.group_ndv_footer \
                if hasattr(n, "group_ndv_footer") \
                else getattr(n, "group_ndv", None)
            if footer:
                found.append(n)
            for c in n.children:
                walk(c)

        walk(plan)
        return found[0] if len(found) == 1 else None
