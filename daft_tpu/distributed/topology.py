"""Worker topology: which workers share a device mesh (one pod / host).

The placement layer behind the pod-native hierarchical shuffle
(ROADMAP item 2): a hash boundary between workers that are *devices in
one mesh* should repartition over ICI collectives
(``parallel/exchange.py``), not serialize to Arrow IPC and cross a
socket. This module answers the one question that decision needs —
*which workers share a mesh* — and tracks the in-flight collective
exchange groups the resilience plane treats as all-or-nothing units.

Topology sources, in precedence order:

1. ``DAFT_TPU_WORKER_TOPOLOGY`` — explicit ``name=w0,w1;name2=w2,w3``
   spec (the deployment knows its pods); workers the spec does not name
   fall into singleton groups (Flight-only).
2. Autodetect — every in-process worker shares the process device mesh,
   so when a multi-device mesh is up they form ONE group; remote workers
   (and everything else when no mesh is up) are singleton groups.

The exchange-path decision (``plan_exchange_path``) is the decision
ladder the README documents: ``collective`` when producer and consumer
live on one mesh, ``hierarchical`` (intra-mesh collective, one Flight
stream per mesh) across meshes, else today's per-worker ``flight``
path — forced by ``DAFT_TPU_EXCHANGE_PATH``, priced by
``device/costmodel`` (calibrated ICI vs wire link rates), and degraded
to verbatim ``flight`` under ``DAFT_TPU_CHAOS_SERIALIZE=1`` so chaos
replay stays bit-identical.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class MeshGroup:
    """A set of workers sharing one device mesh (a pod / host mesh)."""

    name: str
    workers: Tuple[str, ...]

    @property
    def size(self) -> int:
        return len(self.workers)


class WorkerTopology:
    """Immutable worker → mesh-group map for one query."""

    def __init__(self, groups: List[MeshGroup]):
        self.groups = list(groups)
        self._of: Dict[str, MeshGroup] = {
            w: g for g in self.groups for w in g.workers}

    @property
    def n_groups(self) -> int:
        return len(self.groups)

    def group_of(self, worker_id: str) -> Optional[MeshGroup]:
        return self._of.get(worker_id)

    def single_mesh(self) -> bool:
        """All workers on one multi-worker mesh (or one worker total) —
        the shape where an intra-mesh collective replaces the wire."""
        return len(self.groups) == 1

    def multi_worker_groups(self) -> int:
        """Groups where the hierarchical stream merge actually saves
        streams (one stream replaces ≥2)."""
        return sum(1 for g in self.groups if g.size > 1)

    def __repr__(self) -> str:
        return "WorkerTopology(" + "; ".join(
            f"{g.name}={','.join(g.workers)}" for g in self.groups) + ")"

    # ------------------------------------------------------- detection
    @classmethod
    def from_spec(cls, spec: str, worker_ids: List[str]
                  ) -> "WorkerTopology":
        """Parse ``name=w0,w1;name2=w2``. Unknown workers in the spec are
        ignored (the spec describes the deployment, not one query's
        worker set); workers the spec does not place become singleton
        groups."""
        groups: List[MeshGroup] = []
        placed = set()
        known = set(worker_ids)
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            name, eq, members = entry.partition("=")
            if not eq or not name.strip():
                raise ValueError(
                    f"DAFT_TPU_WORKER_TOPOLOGY: bad entry {entry!r} "
                    f"(expected name=w0,w1;...)")
            ws = tuple(w.strip() for w in members.split(",")
                       if w.strip() and w.strip() in known)
            dup = [w for w in ws if w in placed]
            if dup:
                raise ValueError(
                    f"DAFT_TPU_WORKER_TOPOLOGY: worker(s) {dup} appear "
                    f"in more than one mesh group")
            placed.update(ws)
            if ws:
                groups.append(MeshGroup(name.strip(), ws))
        for w in worker_ids:
            if w not in placed:
                groups.append(MeshGroup(w, (w,)))
        return cls(groups)

    @classmethod
    def detect(cls, worker_ids: List[str]) -> "WorkerTopology":
        """Topology for this query's workers: the explicit spec when set,
        else autodetect from the process device mesh."""
        spec = _topology_spec()
        if spec:
            return cls.from_spec(spec, worker_ids)
        if local_mesh_up():
            return cls([MeshGroup("local", tuple(worker_ids))])
        return cls([MeshGroup(w, (w,)) for w in worker_ids])


def local_mesh_up() -> bool:
    """True when this process has a usable multi-device mesh for
    intra-group collectives (never raises: no device tier → no mesh)."""
    try:
        from ..device import runtime as drt
        from ..parallel import mesh as pmesh
        return drt.device_enabled() and pmesh.mesh_size() >= 2
    except Exception:
        return False


def _topology_spec() -> Optional[str]:
    """The worker-topology spec: the env var is the per-process
    override; unset, the per-query ``ExecutionConfig.tpu_worker_topology``
    field applies (the registry's config_field contract)."""
    from ..analysis import knobs
    spec = knobs.env_str("DAFT_TPU_WORKER_TOPOLOGY")
    if spec:
        return spec
    try:
        from ..context import get_context
        return get_context().execution_config.tpu_worker_topology or None
    except Exception:
        return None


def _path_setting() -> str:
    """The exchange-path setting (env override, else the per-query
    ``ExecutionConfig.tpu_exchange_path`` field), validated: a typo'd
    rung must fail loudly, not silently behave like ``auto``."""
    from ..analysis import knobs
    raw = knobs.env_raw("DAFT_TPU_EXCHANGE_PATH")
    if raw is None:
        try:
            from ..context import get_context
            raw = get_context().execution_config.tpu_exchange_path
        except Exception:
            raw = "auto"
    raw = (raw or "auto").lower()
    if raw != "auto" and raw not in PATHS:
        raise ValueError(
            f"DAFT_TPU_EXCHANGE_PATH / ExecutionConfig.tpu_exchange_path: "
            f"unknown exchange path {raw!r} (expected 'auto' or one of "
            f"{PATHS})")
    return raw


# ------------------------------------------------ exchange path decision

PATHS = ("collective", "hierarchical", "flight")


def plan_exchange_path(topo: WorkerTopology, num_partitions: int,
                       rows_est: Optional[int] = None,
                       row_bytes: float = 32.0) -> str:
    """The decision ladder for one hash boundary whose structural
    eligibility the stage planner already vetted:

    1. ``DAFT_TPU_CHAOS_SERIALIZE=1`` → ``flight`` (the verbatim
       pre-topology path; chaos replay is bit-identical by contract).
    2. ``DAFT_TPU_EXCHANGE_PATH`` / ``tpu_exchange_path`` forces any
       rung (an unknown value raises).
    3. An active fault plan (no explicit force) → ``flight``: recorded
       fault keys live on the flight path's task/fetch sites, so the
       auto ladder must not reroute them — the same explicit-wins
       contract as ``DAFT_TPU_SHUFFLE_FETCH_PARALLELISM``.
    4. One mesh group → ``collective`` when the cost model prices the
       ICI trip under the Flight trip (unknown sizes default-accept:
       the runtime admission gate re-prices with exact rows).
    5. Multiple groups with at least one multi-worker mesh →
       ``hierarchical`` (one stream per mesh instead of per worker).
    6. Otherwise ``flight``.
    """
    from ..analysis import knobs
    from ..device import costmodel
    if knobs.env_bool("DAFT_TPU_CHAOS_SERIALIZE"):
        return "flight"
    forced = _path_setting()
    if forced in PATHS:
        return forced
    from .resilience import active_fault_plan
    if active_fault_plan() is not None:
        return "flight"
    if topo.single_mesh():
        if costmodel.exchange_collective_wins(rows_est, row_bytes):
            return "collective"
        return "flight"
    if topo.multi_worker_groups() >= 1 \
            and costmodel.exchange_collective_wins(rows_est, row_bytes):
        return "hierarchical"
    return "flight"


# --------------------------------------------- collective lease registry
# Every in-flight collective exchange group holds a LEASE for its mesh
# resources (the all-or-nothing unit the resilience plane recomputes as
# one). The registry is the /metrics gauge AND the invariant daft-lint's
# Contract table proves: an acquired lease is released on every path —
# a leaked lease would make a finished exchange group look forever
# in-flight to operators and keep its group key shadowed.

_lease_lock = threading.Lock()
_leases: Dict[str, int] = {}


def acquire_collective(key: str) -> str:
    """Register one in-flight collective exchange group; returns the
    lease key to pass to :func:`release_collective` (pair them in
    try/finally — the ``collective-lease-leak`` contract row proves it
    statically)."""
    with _lease_lock:
        _leases[key] = _leases.get(key, 0) + 1
    return key


def release_collective(key: str) -> None:
    with _lease_lock:
        n = _leases.get(key, 0) - 1
        if n <= 0:
            _leases.pop(key, None)
        else:
            _leases[key] = n


def collective_inflight() -> int:
    """Gauge: collective exchange groups currently in flight."""
    with _lease_lock:
        return sum(_leases.values())


# ----------------------------------------------- collective group lineage


@dataclasses.dataclass
class CollectiveExchangeGroup:
    """Lineage producer for one mesh group's merged exchange output.

    Collective stages are ALL-OR-NOTHING: the per-mesh stream a
    hierarchical exchange serves is one fused artifact of every member
    map task plus the intra-mesh collective — there is no per-map-task
    receipt to recover. When the resilience plane loses the stream, it
    re-runs ``group_tasks`` as one unit and rebuilds the merged receipt
    through ``rebuild`` (``resilience.TaskSupervisor.recover_source``
    dispatches on ``group_tasks``)."""

    fault_key: str
    group_tasks: List[object]                 # member StageTasks
    rebuild: Callable[[List[object]], object]  # task outputs → receipt
