"""Inter-host shuffle transport: spill-backed partition server + client.

Reference: the flight shuffle (``src/daft-shuffles``) — the map side
partitions morsels and spills per-partition Arrow IPC files
(``shuffle_cache.rs:14-80``); each node runs an Arrow Flight gRPC server
serving ``do_get(partition_idx)`` (``server/flight_server.rs:17-170``) and
the reduce side fetches over the network. Here the same design has two
transports behind one seam: a ``ShuffleCache`` accumulates map outputs into
per-partition spill files, and a per-host server exposes them — an actual
**Arrow Flight** gRPC server (``FlightShuffleServer``, default when
``pyarrow.flight`` is importable: ``do_get(<shuffle_id>/<partition>)``
streams record batches straight off the spill files) or a stdlib-HTTP
fallback (``ShuffleServer``: ``GET /shuffle/<id>/<partition>``).
``fetch_partition`` dispatches on the address scheme (``grpc://`` vs
``http://``), so the reduce side is transport-blind. On a TPU pod this is
the DCN tier — intra-pod exchanges ride ICI collectives instead
(``parallel/exchange.py``)."""

from __future__ import annotations

import http.server
import io
import os
import threading
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import Dict, List, Optional, Tuple

import pyarrow as pa
import pyarrow.ipc as paipc

try:
    import pyarrow.flight as paflight
except ImportError:  # pragma: no cover - flight is baked into this image
    paflight = None


# ------------------------------------------------------- shuffle counters
# Process-wide data-plane accounting, mirroring the device-kernel dispatch
# ledger and the resilience counters: ``RuntimeStatsContext`` snapshots at
# query start and diffs at finish() for the per-query ``shuffle`` block
# (bytes written/fetched, compression ratio, combine reduction, fetch wall
# vs serial-equivalent time).

_shuffle_counters_lock = threading.Lock()
_shuffle_counters: Dict[str, float] = {}


def shuffle_count(name: str, n: float = 1) -> None:
    with _shuffle_counters_lock:
        _shuffle_counters[name] = _shuffle_counters.get(name, 0) + n
    # context-local attribution for the serving plane (overlapping
    # queries each see only their own shuffle traffic)
    from .. import observability as obs
    obs.bump_plane("shuffle", name, n)


def shuffle_counters_snapshot() -> Dict[str, float]:
    with _shuffle_counters_lock:
        return dict(_shuffle_counters)


def shuffle_counters_delta(before: Dict[str, float],
                           after: Optional[Dict[str, float]] = None
                           ) -> Dict[str, float]:
    if after is None:
        after = shuffle_counters_snapshot()
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d:
            out[k] = d
    return out


def shuffle_counters_reset() -> None:
    with _shuffle_counters_lock:
        _shuffle_counters.clear()


# ------------------------------------------------------ wire compression

#: spill/wire chunk size: the HTTP handler sends (and the orphan of a
#: partition occupies) at most this much resident memory per partition,
#: regardless of partition size
_CHUNK_BYTES = 1 << 20

#: fetches below this decoded size are RTT-dominated and must not feed
#: the calibrated wire-rate profile
_WIRE_OBS_MIN_BYTES = 256 << 10

_ipc_opts_cache: Dict[str, Tuple[Optional[object], Optional[str]]] = {}


def _ipc_write_options() -> Tuple[Optional["paipc.IpcWriteOptions"],
                                  Optional[str]]:
    """(IPC write options, codec name) for shuffle spill writers.
    ``DAFT_TPU_SHUFFLE_COMPRESSION=lz4|zstd|none`` (default ``lz4``)
    selects Arrow IPC *buffer* compression — self-describing on the wire,
    so readers (``_spill_streams`` / ``_spill_file_batches`` / the fetch
    path, including the post-seal straggler-append single-write branch)
    need no configuration. Auto-falls back to uncompressed when the codec
    is missing from this pyarrow build."""
    from ..analysis import knobs
    pref = knobs.env_str("DAFT_TPU_SHUFFLE_COMPRESSION").lower()
    if pref in ("none", "off", "0", ""):
        return None, None
    hit = _ipc_opts_cache.get(pref)
    if hit is not None:
        return hit
    try:
        opts = paipc.IpcWriteOptions(compression=pref)
    except Exception:
        opts = None  # unknown codec / not built in → uncompressed
    out = (opts, pref if opts is not None else None)
    _ipc_opts_cache[pref] = out
    return out


class ShuffleCache:
    """Map-side output accumulator: morsels are hash-partitioned by the
    caller; each partition's batches append to one Arrow IPC spill file
    (reference: InProgressShuffleCache → per-partition writer tasks)."""

    def __init__(self, shuffle_id: Optional[str] = None,
                 dirs: Optional[List[str]] = None):
        from ..execution.memory import spill_dir
        self.shuffle_id = shuffle_id or uuid.uuid4().hex
        self._root = os.path.join((dirs or [spill_dir()])[0],
                                  f"shuffle_{self.shuffle_id}")
        os.makedirs(self._root, exist_ok=True)
        self._lock = threading.Lock()
        self._writers: Dict[int, Tuple[object, object]] = {}
        self._rows: Dict[int, int] = {}
        self._sealed = False

    def _writer(self, partition: int, schema: pa.Schema):
        w = self._writers.get(partition)
        if w is None:
            f = open(self._path(partition), "ab")
            opts, _ = _ipc_write_options()
            w = (paipc.new_stream(f, schema, options=opts), f)
            self._writers[partition] = w
        return w[0]

    def _path(self, partition: int) -> str:
        return os.path.join(self._root, f"part-{partition}.arrow")

    def push(self, partition: int, table: pa.Table) -> None:
        with self._lock:
            if self._sealed:
                # straggler after seal: append one complete, flushed IPC
                # stream in a single write so a concurrent fetch never sees
                # a torn header mid-stream (fetch also tolerates a
                # truncated tail — see _spill_streams)
                buf = io.BytesIO()
                opts, _ = _ipc_write_options()
                with paipc.new_stream(buf, table.schema, options=opts) as w:
                    w.write_table(table)
                payload = buf.getvalue()
                # daft-lint: allow(blocking-under-lock) -- post-seal
                # straggler append must be atomic vs concurrent fetches
                # reading this file; local spill-dir write, rare path
                with open(self._path(partition), "ab") as f:
                    f.write(payload)
                    f.flush()
                    os.fsync(f.fileno())
                shuffle_count("bytes_written", len(payload))
            else:
                # daft-lint: allow(blocking-under-lock) -- per-partition
                # writer state and the sealed check are one atomic unit;
                # the open is a once-per-partition local file create
                self._writer(partition, table.schema).write_table(table)
            self._rows[partition] = self._rows.get(partition, 0) + len(table)
        shuffle_count("rows_pushed", table.num_rows)
        shuffle_count("bytes_pushed_raw", table.nbytes)

    def close(self) -> None:
        with self._lock:
            if self._sealed:
                return
            for w, f in self._writers.values():
                w.close()
                f.close()
            self._writers = {}
            self._sealed = True
            # on-disk == on-wire bytes (the server streams the spill files
            # verbatim); straggler appends are counted at push time
            written = 0
            for p in self._rows:
                try:
                    written += os.path.getsize(self._path(p))
                except OSError:
                    pass
        shuffle_count("bytes_written", written)

    def partition_chunks(self, partition: int, limit: Optional[int] = None,
                         chunk_bytes: int = _CHUNK_BYTES):
        """Yield one partition's spill-file bytes in bounded chunks — the
        serving side's resident memory is ``chunk_bytes``, never the
        partition size. Reads exactly ``limit`` bytes when given (the size
        an HTTP Content-Length was announced from), else the size at open,
        so a concurrent straggler append can't outgrow an announced
        length."""
        p = self._path(partition)
        if limit is None:
            try:
                limit = os.path.getsize(p)
            except OSError:
                return
        try:
            f = open(p, "rb")
        except OSError:
            return
        with f:
            remaining = limit
            while remaining > 0:
                chunk = f.read(min(chunk_bytes, remaining))
                if not chunk:
                    return
                remaining -= len(chunk)
                yield chunk

    def partition_size(self, partition: int) -> int:
        try:
            return os.path.getsize(self._path(partition))
        except OSError:
            return 0

    def stats(self) -> Tuple[int, int, Dict[int, int]]:
        """(total rows pushed, total on-disk bytes, per-partition rows) —
        the EXACT boundary cardinalities the runtime re-planner consumes
        (they ride back to the driver on the map receipt)."""
        with self._lock:
            part_rows = dict(self._rows)
        rows = sum(part_rows.values())
        nbytes = sum(self.partition_size(p) for p in part_rows)
        return rows, nbytes, part_rows

    def touch(self) -> None:
        """Refresh the spill dir's mtime: an actively-served output must
        never look orphaned to the TTL sweep (the TTL is an IDLE bound,
        not a lifetime bound)."""
        try:
            os.utime(self._root, None)
        except OSError:
            pass

    def partitions(self) -> List[int]:
        return sorted(self._rows)

    def cleanup(self) -> None:
        self.close()
        for f in os.listdir(self._root):
            try:
                os.unlink(os.path.join(self._root, f))
            except OSError:
                pass
        try:
            os.rmdir(self._root)
        except OSError:
            pass


class ShuffleServer:
    """Per-host partition server (reference: per-node Flight server).
    ``host`` is the bind address — pass ``0.0.0.0`` (or set
    ``DAFT_TPU_SHUFFLE_HOST``) to serve other hosts; ``advertise_host`` is
    what ``address`` reports to peers (defaults to the bind host)."""

    def __init__(self, port: int = 0, host: Optional[str] = None,
                 advertise_host: Optional[str] = None):
        from ..analysis import knobs
        self._host = host or knobs.env_str("DAFT_TPU_SHUFFLE_HOST")
        self._advertise = advertise_host \
            or knobs.env_str("DAFT_TPU_SHUFFLE_ADVERTISE") \
            or ("127.0.0.1" if self._host == "0.0.0.0" else self._host)
        self._caches: Dict[str, ShuffleCache] = {}
        self._lock = threading.Lock()
        caches = self._caches
        lock = self._lock

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_DELETE(self):
                # reduce-side release of a consumed map output
                parts = self.path.strip("/").split("/")
                if len(parts) == 2 and parts[0] == "shuffle":
                    with lock:
                        cache = caches.pop(parts[1], None)
                    if cache is not None:
                        cache.cleanup()
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                from .. import tracing
                parts = self.path.strip("/").split("/")
                if len(parts) != 3 or parts[0] != "shuffle":
                    self.send_response(404)
                    self.end_headers()
                    return
                sid, pidx = parts[1], int(parts[2])
                with lock:
                    cache = caches.get(sid)
                if cache is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                cache.touch()
                # serve-side child span when the fetch carried span
                # context headers and the trace lives in this process
                # span key derives from the PARENT (fetch) span id — a
                # stable identity; the shuffle uuid is run-specific and
                # would break bit-identical chaos replay
                tctx = tracing.context_from_headers(self.headers)
                skey = f"serve:{tctx.span_id}" if tctx is not None \
                    else None
                with tracing.attach(tctx), \
                        tracing.span("shuffle:serve", key=skey,
                                     lane="shuffle") as sp:
                    # chunked send off the spill file: resident memory is
                    # one chunk, never the partition (Content-Length comes
                    # from a stat, and partition_chunks sends exactly that
                    # many bytes even under a concurrent straggler append)
                    size = cache.partition_size(pidx)
                    sp.set("bytes", size)
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/vnd.apache.arrow.stream")
                    self.send_header("Content-Length", str(size))
                    self.end_headers()
                    for chunk in cache.partition_chunks(pidx, limit=size):
                        self.wfile.write(chunk)

        self._server = http.server.ThreadingHTTPServer((self._host, port),
                                                       Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="daft-tpu-shuffle").start()

    @property
    def address(self) -> str:
        return f"http://{self._advertise}:{self._server.server_port}"

    def register(self, cache: ShuffleCache) -> None:
        cache.close()  # seal files before serving
        with self._lock:
            self._caches[cache.shuffle_id] = cache
        # one served stream source per registered map output — the
        # stream-count evidence behind the hierarchical exchange (one
        # stream per MESH instead of one per worker)
        shuffle_count("streams_registered")

    def unregister(self, shuffle_id: str) -> None:
        with self._lock:
            cache = self._caches.pop(shuffle_id, None)
        if cache is not None:
            cache.cleanup()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class FlightShuffleServer:
    """Per-host Arrow Flight partition server (the reference's actual
    transport: ``server/flight_server.rs:17-170`` serves ``do_get``; clients
    fetch with ``flight_client.rs``). Tickets are ``<shuffle_id>/<part>``;
    batches stream straight off the spill files, never materializing a
    partition in server memory."""

    def __init__(self, port: int = 0, host: Optional[str] = None,
                 advertise_host: Optional[str] = None):
        if paflight is None:
            raise RuntimeError("pyarrow.flight not available; "
                               "use ShuffleServer (HTTP)")
        from ..analysis import knobs
        self._host = host or knobs.env_str("DAFT_TPU_SHUFFLE_HOST")
        self._advertise = advertise_host \
            or knobs.env_str("DAFT_TPU_SHUFFLE_ADVERTISE") \
            or ("127.0.0.1" if self._host == "0.0.0.0" else self._host)
        self._caches: Dict[str, ShuffleCache] = {}
        self._lock = threading.Lock()
        outer = self

        class _Server(paflight.FlightServerBase):
            def do_action(self, context, action):
                if action.type == "unregister":
                    outer.unregister(action.body.to_pybytes().decode())
                    return iter(())
                raise paflight.FlightServerError(
                    f"unknown action {action.type!r}")

            def do_get(self, context, ticket):
                from .. import tracing
                # ticket: <sid>/<part>[/<trace_id>/<parent_span>] — the
                # trailing pair is the span context riding the Flight wire
                fields = ticket.ticket.decode().split("/")
                sid, pidx = fields[0], fields[1]
                if len(fields) >= 4:
                    tctx = tracing.remote_context(fields[2], fields[3])
                    if tctx is not None:
                        # keyed on the stable parent (fetch) span id, not
                        # the run-specific shuffle uuid — replay contract
                        tracing.event("shuffle:serve",
                                      key=f"serve:{fields[3]}",
                                      lane="shuffle", ctx=tctx)
                with outer._lock:
                    cache = outer._caches.get(sid)
                if cache is None:
                    raise paflight.FlightServerError(
                        f"unknown shuffle {sid!r}")
                cache.touch()
                path = cache._path(int(pidx))
                gen = _spill_file_batches(path)
                first = next(gen, None)
                if first is None:
                    # empty partition: marked-schema sentinel (out-of-band —
                    # a real zero-column partition with rows must survive)
                    empty = pa.schema(
                        [], metadata={b"daft_tpu_empty": b"1"})
                    return paflight.GeneratorStream(empty, iter(()))
                schema, batch0 = first

                def batches():
                    yield batch0
                    for _, b in gen:
                        yield b

                opts, _ = _ipc_write_options()
                if opts is not None:
                    try:  # compress the Flight wire like the spill files
                        return paflight.GeneratorStream(schema, batches(),
                                                        options=opts)
                    except TypeError:  # pyarrow without the options kwarg
                        pass
                return paflight.GeneratorStream(schema, batches())

        # the port is bound in __init__ (so .port is valid immediately);
        # serve() blocks until shutdown() — run it on a daemon thread
        self._server = _Server(f"grpc://{self._host}:{port}")
        threading.Thread(target=self._server.serve, daemon=True,
                         name="daft-tpu-flight-shuffle").start()

    @property
    def address(self) -> str:
        return f"grpc://{self._advertise}:{self._server.port}"

    def register(self, cache: ShuffleCache) -> None:
        cache.close()  # seal files before serving
        with self._lock:
            self._caches[cache.shuffle_id] = cache
        # stream-count evidence, same as the HTTP server (hierarchical
        # exchanges register one stream per mesh, flight one per worker)
        shuffle_count("streams_registered")

    def unregister(self, shuffle_id: str) -> None:
        with self._lock:
            cache = self._caches.pop(shuffle_id, None)
        if cache is not None:
            cache.cleanup()

    def shutdown(self) -> None:
        self._server.shutdown()


def sweep_orphaned_shuffles(root: Optional[str] = None,
                            ttl_s: Optional[float] = None) -> List[str]:
    """Startup sweep: delete ``shuffle_<id>/`` spill dirs IDLE longer
    than a TTL (``DAFT_TPU_SHUFFLE_TTL``, seconds, default 86400) — the
    remains of crashed workers that never reached
    ``ShuffleCache.cleanup()``. Serving a partition refreshes the dir's
    mtime, so an actively-fetched output never ages out.
    With no explicit ``root``, sweeps this process's spill dir AND every
    sibling ``daft_tpu_spill_*`` root under the tmpdir (a crashed
    process's per-process mkdtemp root is exactly where its orphans
    live). The TTL guards live dirs of concurrent processes. Returns the
    removed paths."""
    import glob
    import shutil
    import tempfile
    import time as _time
    if root is None:
        from ..execution.memory import spill_dir
        roots = [spill_dir()]
        roots += [p for p in glob.glob(os.path.join(
            tempfile.gettempdir(), "daft_tpu_spill_*"))
            if p not in roots and os.path.isdir(p)]
    else:
        roots = [root]
    if ttl_s is None:
        from ..analysis import knobs
        ttl_s = knobs.env_float("DAFT_TPU_SHUFFLE_TTL")
    removed: List[str] = []
    cutoff = _time.time() - ttl_s
    for r in roots:
        try:
            entries = os.listdir(r)
        except OSError:
            continue
        for name in entries:
            if not name.startswith("shuffle_"):
                continue
            path = os.path.join(r, name)
            try:
                if os.path.isdir(path) and os.path.getmtime(path) < cutoff:
                    shutil.rmtree(path, ignore_errors=True)
                    removed.append(path)
            except OSError:
                continue
    return removed


_swept_once = False
_swept_lock = threading.Lock()


def make_shuffle_server(port: int = 0, host: Optional[str] = None):
    """Transport factory: Arrow Flight when available (the reference's
    design), stdlib HTTP otherwise; ``DAFT_TPU_SHUFFLE_TRANSPORT=http``
    forces the fallback. The first server created in a process also
    sweeps orphaned shuffle dirs crashed processes left behind (once —
    the glob+stat walk is not worth repeating per server)."""
    global _swept_once
    with _swept_lock:
        sweep = not _swept_once
        _swept_once = True
    if sweep:
        try:
            sweep_orphaned_shuffles()
        except Exception:
            pass  # janitorial; must never block serving
    from ..analysis import knobs
    pref = knobs.env_str("DAFT_TPU_SHUFFLE_TRANSPORT")
    if pref != "http" and paflight is not None:
        return FlightShuffleServer(port, host=host)
    return ShuffleServer(port, host=host)


_local_server = None
_local_server_lock = threading.Lock()


def get_local_shuffle_server():
    """One lazily-started shuffle server per process (each worker host runs
    one, like the reference's per-node flight server)."""
    global _local_server
    with _local_server_lock:
        if _local_server is None:
            _local_server = make_shuffle_server()
        return _local_server


def configure_local_shuffle_server(host: str, advertise_host: str):
    """Eagerly create the process shuffle server with explicit networking
    (worker startup calls this BEFORE any map task can lazily boot a
    loopback-bound one). Conflicting reconfiguration is an error — the
    advertised address is baked into outstanding map receipts."""
    global _local_server
    with _local_server_lock:
        if _local_server is not None:
            current = _local_server.address
            want_host = advertise_host or host
            cur_host = urllib.parse.urlparse(
                current if "://" in current else f"http://{current}").hostname
            if cur_host != want_host.lower():  # urlparse lowercases hostname
                raise RuntimeError(
                    f"shuffle server already running at {current}; cannot "
                    f"re-advertise as {want_host}")
            return _local_server
        _local_server = make_shuffle_server(host=host)
        if advertise_host:
            _local_server._advertise = advertise_host
        return _local_server


def _spill_streams(body: bytes):
    """Yield (schema, batch-list) per concatenated IPC stream in a spill
    file (one stream per writer reopen). A truncated trailing stream — a
    straggler append caught mid-write — is skipped; the dropped tail is
    logged so mid-file corruption (which also truncates everything after
    it) is never silent."""
    if not body:
        return
    buf = pa.BufferReader(body)
    while buf.tell() < buf.size():
        start = buf.tell()
        try:
            with paipc.open_stream(buf) as rd:
                batches = list(rd)
        except pa.ArrowInvalid:
            _log_truncated_tail(start, buf.size())
            return
        yield rd.schema, batches


def _log_truncated_tail(pos: int, size: int) -> None:
    import logging
    logging.getLogger(__name__).warning(
        "shuffle spill file: unreadable IPC stream at byte %d; dropping "
        "%d trailing bytes (torn straggler append, or corruption if not "
        "at the tail)", pos, size - pos)


def _spill_file_batches(path: str):
    """Lazily yield (schema, batch) straight off a spill file, one record
    batch at a time (never materializes the partition in memory). Tolerates
    (and logs) a truncated trailing stream like _spill_streams."""
    if not os.path.exists(path):
        return
    size = os.path.getsize(path)
    with pa.OSFile(path, "rb") as f:
        while f.tell() < size:
            start = f.tell()
            try:
                rd = paipc.open_stream(f)
            except pa.ArrowInvalid:
                _log_truncated_tail(start, size)
                return
            schema = rd.schema
            while True:
                try:
                    batch = rd.read_next_batch()
                except StopIteration:
                    break
                except pa.ArrowInvalid:
                    _log_truncated_tail(start, size)
                    return
                yield schema, batch


def unregister_remote(address: str, shuffle_id: str) -> None:
    """Release a consumed map output on its serving host (reduce-side
    cleanup; dispatches on the address scheme like fetch_partition)."""
    if address.startswith("grpc://"):
        if paflight is None:
            return
        client = paflight.connect(address)
        try:
            list(client.do_action(
                paflight.Action("unregister", shuffle_id.encode())))
        finally:
            client.close()
        return
    req = urllib.request.Request(f"{address}/shuffle/{shuffle_id}",
                                 method="DELETE")
    with urllib.request.urlopen(req, timeout=30):
        pass


def fetch_partition(address: str, shuffle_id: str, partition: int,
                    fault_key: Optional[str] = None) -> Optional[pa.Table]:
    """Reduce-side fetch: partition bytes → Arrow table (reference:
    flight_client do_get). Dispatches on the address scheme. Any failure
    raises ``ShuffleFetchError`` carrying the (address, shuffle_id)
    identity the scheduler's lineage recovery keys on. ``fault_key`` is
    the stable (run-independent) source identity used for deterministic
    fault injection; it defaults to the shuffle id."""
    from .. import tracing
    from .resilience import ShuffleFetchError, active_fault_plan
    key = fault_key or shuffle_id
    # one span per fetch attempt, keyed by the stable source identity —
    # injected faults and transport failures land as error-status spans
    with tracing.span("shuffle:fetch", key=f"fetch:{key}/p{partition}",
                      attrs={"address": address, "partition": partition},
                      lane="shuffle") as sp:
        plan = active_fault_plan()
        if plan is not None:  # injection site 2: partition fetch
            if plan.decide("crash", f"{key}/p{partition}"):
                # a dead map worker: the served data is really gone —
                # every later fetch of this shuffle fails too, until the
                # scheduler recomputes the producing map task
                try:
                    unregister_remote(address, shuffle_id)
                except Exception:
                    pass
                raise ShuffleFetchError(address, shuffle_id, partition,
                                        detail="injected worker crash",
                                        injected=True)
            if plan.decide("fetch", f"{key}/p{partition}"):
                raise ShuffleFetchError(address, shuffle_id, partition,
                                        detail="injected fetch fault",
                                        injected=True)
        import time as _time
        t0 = _time.perf_counter()
        try:
            out = _fetch_partition_raw(address, shuffle_id, partition)
        except Exception as exc:
            raise ShuffleFetchError(address, shuffle_id, partition,
                                    detail=f"{type(exc).__name__}: "
                                           f"{str(exc)[:200]}") from exc
        elapsed = _time.perf_counter() - t0
        # serial-equivalent fetch time: the per-call sum the parallel
        # fetch's span is compared against in the overlap evidence
        shuffle_count("fetch_wall_us", elapsed * 1e6)
        shuffle_count("fetches")
        # calibration chokepoint (round 20): sizable fetches feed the
        # observed wire rate (tiny partitions measure RTT, not bandwidth)
        if out is not None and out.nbytes >= _WIRE_OBS_MIN_BYTES \
                and elapsed > 1e-3:
            from ..device import calibration
            calibration.observe("SHUFFLE_WIRE_BPS", out.nbytes / elapsed)
        sp.set("rows", out.num_rows if out is not None else 0)
        return out


class _CountingStream:
    """Minimal file-like over an HTTP response: counts wire bytes and
    supports 1-probe pushback so concatenated IPC streams can be read
    incrementally (never buffering the whole body)."""

    def __init__(self, raw):
        self._raw = raw
        self._buf = b""
        self.nread = 0

    def read(self, n=-1):
        if n is None or n < 0:
            out = self._buf + self._raw.read()
            self._buf = b""
        elif self._buf:
            out, self._buf = self._buf[:n], self._buf[n:]
            if len(out) < n:
                out += self._raw.read(n - len(out))
        else:
            out = self._raw.read(n)
        self.nread += len(out)
        return out

    def push(self, data: bytes) -> None:
        self._buf = data + self._buf
        self.nread -= len(data)

    def readable(self):
        return True

    def seekable(self):
        return False

    def writable(self):
        return False

    @property
    def closed(self):
        return False

    def flush(self):
        pass

    def close(self):
        # pyarrow's PythonFile closes the source when the reader closes;
        # the SAME response must stay readable for the next concatenated
        # stream, so closing is a no-op (the with-block on the response
        # owns the socket)
        pass


def _iter_stream_tables(f: "_CountingStream"):
    """Yield one Table per concatenated IPC stream, read INCREMENTALLY off
    a file-like (the HTTP fetch path: resident memory is the decoded
    batches of the current stream, never the raw body). A truncated
    trailing stream — a torn straggler append — is logged and dropped,
    same contract as ``_spill_streams``."""
    while True:
        head = f.read(1)  # probe: clean EOF between streams?
        if not head:
            return
        f.push(head)
        start = f.nread
        try:
            with paipc.open_stream(f) as rd:
                batches = list(rd)
                schema = rd.schema
        except pa.ArrowInvalid:
            # drain-and-count in chunks: the dropped-tail size for the log
            # without materializing the remaining body (this path must stay
            # as memory-bounded as the happy path)
            rest = 0
            while True:
                chunk = f.read(_CHUNK_BYTES)
                if not chunk:
                    break
                rest += len(chunk)
            _log_truncated_tail(start, f.nread + rest)
            return
        yield pa.Table.from_batches(batches, schema=schema)


def _fetch_partition_raw(address: str, shuffle_id: str, partition: int
                         ) -> Optional[pa.Table]:
    from .. import tracing
    if address.startswith("grpc://"):
        if paflight is None:
            raise RuntimeError(
                f"shuffle peer advertises Flight ({address}) but "
                "pyarrow.flight is unavailable on this host; set "
                "DAFT_TPU_SHUFFLE_TRANSPORT=http on the serving hosts")
        client = paflight.connect(address)
        try:
            # span context rides the ticket (Flight's header equivalent):
            # <sid>/<part>[/<trace_id>/<parent_span>]
            tstr = f"{shuffle_id}/{partition}"
            tctx = tracing.current()
            if tctx is not None:
                tid, psid = tctx.wire()
                tstr += f"/{tid}/{psid}"
            reader = client.do_get(paflight.Ticket(tstr.encode()))
            t = reader.read_all()
        finally:
            client.close()
        # decoded batch bytes — the flight client API exposes no
        # compressed-frame size; wire compression shows on the WRITE side
        # (bytes_written vs bytes_pushed_raw), which both transports share
        shuffle_count("bytes_fetched", t.nbytes)
        meta = t.schema.metadata or {}
        return None if meta.get(b"daft_tpu_empty") == b"1" else t
    url = f"{address}/shuffle/{shuffle_id}/{partition}"
    from ..analysis import knobs
    timeout = knobs.env_float("DAFT_TPU_SHUFFLE_TIMEOUT")
    try:
        # span context propagates as headers over the HTTP shuffle wire
        r = urllib.request.urlopen(
            urllib.request.Request(url, headers=tracing.wire_headers()),
            timeout=timeout)
    except urllib.error.HTTPError as exc:
        # urlopen raises on every non-200 — surface the status explicitly
        # so ShuffleFetchError.detail carries it (a 404 here usually means
        # the serving worker unregistered/lost the shuffle: lineage
        # recovery's cue)
        raise RuntimeError(
            f"shuffle server returned HTTP {exc.code} for "
            f"{shuffle_id}/p{partition}") from exc
    with r:
        src = _CountingStream(r)
        tables = list(_iter_stream_tables(src))
        shuffle_count("bytes_fetched", max(src.nread, 0))
    if not tables:
        return None
    return pa.concat_tables(tables) if len(tables) > 1 else tables[0]
