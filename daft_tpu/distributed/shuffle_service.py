"""Inter-host shuffle transport: spill-backed partition server + client.

Reference: the flight shuffle (``src/daft-shuffles``) — the map side
partitions morsels and spills per-partition Arrow IPC files
(``shuffle_cache.rs:14-80``); each node runs an Arrow Flight gRPC server
serving ``do_get(partition_idx)`` (``server/flight_server.rs:17-170``) and
the reduce side fetches over the network. Here the same design has two
transports behind one seam: a ``ShuffleCache`` accumulates map outputs into
per-partition spill files, and a per-host server exposes them — an actual
**Arrow Flight** gRPC server (``FlightShuffleServer``, default when
``pyarrow.flight`` is importable: ``do_get(<shuffle_id>/<partition>)``
streams record batches straight off the spill files) or a stdlib-HTTP
fallback (``ShuffleServer``: ``GET /shuffle/<id>/<partition>``).
``fetch_partition`` dispatches on the address scheme (``grpc://`` vs
``http://``), so the reduce side is transport-blind. On a TPU pod this is
the DCN tier — intra-pod exchanges ride ICI collectives instead
(``parallel/exchange.py``)."""

from __future__ import annotations

import http.server
import io
import os
import threading
import urllib.parse
import urllib.request
import uuid
from typing import Dict, List, Optional, Tuple

import pyarrow as pa
import pyarrow.ipc as paipc

try:
    import pyarrow.flight as paflight
except ImportError:  # pragma: no cover - flight is baked into this image
    paflight = None


class ShuffleCache:
    """Map-side output accumulator: morsels are hash-partitioned by the
    caller; each partition's batches append to one Arrow IPC spill file
    (reference: InProgressShuffleCache → per-partition writer tasks)."""

    def __init__(self, shuffle_id: Optional[str] = None,
                 dirs: Optional[List[str]] = None):
        from ..execution.memory import spill_dir
        self.shuffle_id = shuffle_id or uuid.uuid4().hex
        self._root = os.path.join((dirs or [spill_dir()])[0],
                                  f"shuffle_{self.shuffle_id}")
        os.makedirs(self._root, exist_ok=True)
        self._lock = threading.Lock()
        self._writers: Dict[int, Tuple[object, object]] = {}
        self._rows: Dict[int, int] = {}
        self._sealed = False

    def _writer(self, partition: int, schema: pa.Schema):
        w = self._writers.get(partition)
        if w is None:
            f = open(self._path(partition), "ab")
            w = (paipc.new_stream(f, schema), f)
            self._writers[partition] = w
        return w[0]

    def _path(self, partition: int) -> str:
        return os.path.join(self._root, f"part-{partition}.arrow")

    def push(self, partition: int, table: pa.Table) -> None:
        with self._lock:
            if self._sealed:
                # straggler after seal: append one complete, flushed IPC
                # stream in a single write so a concurrent fetch never sees
                # a torn header mid-stream (fetch also tolerates a
                # truncated tail — see _spill_streams)
                buf = io.BytesIO()
                with paipc.new_stream(buf, table.schema) as w:
                    w.write_table(table)
                with open(self._path(partition), "ab") as f:
                    f.write(buf.getvalue())
                    f.flush()
                    os.fsync(f.fileno())
            else:
                self._writer(partition, table.schema).write_table(table)
            self._rows[partition] = self._rows.get(partition, 0) + len(table)

    def close(self) -> None:
        with self._lock:
            for w, f in self._writers.values():
                w.close()
                f.close()
            self._writers = {}
            self._sealed = True

    def partition_bytes(self, partition: int) -> bytes:
        p = self._path(partition)
        if not os.path.exists(p):
            return b""
        with open(p, "rb") as f:
            return f.read()

    def touch(self) -> None:
        """Refresh the spill dir's mtime: an actively-served output must
        never look orphaned to the TTL sweep (the TTL is an IDLE bound,
        not a lifetime bound)."""
        try:
            os.utime(self._root, None)
        except OSError:
            pass

    def partitions(self) -> List[int]:
        return sorted(self._rows)

    def cleanup(self) -> None:
        self.close()
        for f in os.listdir(self._root):
            try:
                os.unlink(os.path.join(self._root, f))
            except OSError:
                pass
        try:
            os.rmdir(self._root)
        except OSError:
            pass


class ShuffleServer:
    """Per-host partition server (reference: per-node Flight server).
    ``host`` is the bind address — pass ``0.0.0.0`` (or set
    ``DAFT_TPU_SHUFFLE_HOST``) to serve other hosts; ``advertise_host`` is
    what ``address`` reports to peers (defaults to the bind host)."""

    def __init__(self, port: int = 0, host: Optional[str] = None,
                 advertise_host: Optional[str] = None):
        self._host = host or os.environ.get("DAFT_TPU_SHUFFLE_HOST",
                                            "127.0.0.1")
        self._advertise = advertise_host \
            or os.environ.get("DAFT_TPU_SHUFFLE_ADVERTISE") \
            or ("127.0.0.1" if self._host == "0.0.0.0" else self._host)
        self._caches: Dict[str, ShuffleCache] = {}
        self._lock = threading.Lock()
        caches = self._caches
        lock = self._lock

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_DELETE(self):
                # reduce-side release of a consumed map output
                parts = self.path.strip("/").split("/")
                if len(parts) == 2 and parts[0] == "shuffle":
                    with lock:
                        cache = caches.pop(parts[1], None)
                    if cache is not None:
                        cache.cleanup()
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                parts = self.path.strip("/").split("/")
                if len(parts) != 3 or parts[0] != "shuffle":
                    self.send_response(404)
                    self.end_headers()
                    return
                sid, pidx = parts[1], int(parts[2])
                with lock:
                    cache = caches.get(sid)
                if cache is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                cache.touch()
                body = cache.partition_bytes(pidx)
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/vnd.apache.arrow.stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = http.server.ThreadingHTTPServer((self._host, port),
                                                       Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="daft-tpu-shuffle").start()

    @property
    def address(self) -> str:
        return f"http://{self._advertise}:{self._server.server_port}"

    def register(self, cache: ShuffleCache) -> None:
        cache.close()  # seal files before serving
        with self._lock:
            self._caches[cache.shuffle_id] = cache

    def unregister(self, shuffle_id: str) -> None:
        with self._lock:
            cache = self._caches.pop(shuffle_id, None)
        if cache is not None:
            cache.cleanup()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class FlightShuffleServer:
    """Per-host Arrow Flight partition server (the reference's actual
    transport: ``server/flight_server.rs:17-170`` serves ``do_get``; clients
    fetch with ``flight_client.rs``). Tickets are ``<shuffle_id>/<part>``;
    batches stream straight off the spill files, never materializing a
    partition in server memory."""

    def __init__(self, port: int = 0, host: Optional[str] = None,
                 advertise_host: Optional[str] = None):
        if paflight is None:
            raise RuntimeError("pyarrow.flight not available; "
                               "use ShuffleServer (HTTP)")
        self._host = host or os.environ.get("DAFT_TPU_SHUFFLE_HOST",
                                            "127.0.0.1")
        self._advertise = advertise_host \
            or os.environ.get("DAFT_TPU_SHUFFLE_ADVERTISE") \
            or ("127.0.0.1" if self._host == "0.0.0.0" else self._host)
        self._caches: Dict[str, ShuffleCache] = {}
        self._lock = threading.Lock()
        outer = self

        class _Server(paflight.FlightServerBase):
            def do_action(self, context, action):
                if action.type == "unregister":
                    outer.unregister(action.body.to_pybytes().decode())
                    return iter(())
                raise paflight.FlightServerError(
                    f"unknown action {action.type!r}")

            def do_get(self, context, ticket):
                sid, _, pidx = ticket.ticket.decode().partition("/")
                with outer._lock:
                    cache = outer._caches.get(sid)
                if cache is None:
                    raise paflight.FlightServerError(
                        f"unknown shuffle {sid!r}")
                cache.touch()
                path = cache._path(int(pidx))
                gen = _spill_file_batches(path)
                first = next(gen, None)
                if first is None:
                    # empty partition: marked-schema sentinel (out-of-band —
                    # a real zero-column partition with rows must survive)
                    empty = pa.schema(
                        [], metadata={b"daft_tpu_empty": b"1"})
                    return paflight.GeneratorStream(empty, iter(()))
                schema, batch0 = first

                def batches():
                    yield batch0
                    for _, b in gen:
                        yield b

                return paflight.GeneratorStream(schema, batches())

        # the port is bound in __init__ (so .port is valid immediately);
        # serve() blocks until shutdown() — run it on a daemon thread
        self._server = _Server(f"grpc://{self._host}:{port}")
        threading.Thread(target=self._server.serve, daemon=True,
                         name="daft-tpu-flight-shuffle").start()

    @property
    def address(self) -> str:
        return f"grpc://{self._advertise}:{self._server.port}"

    def register(self, cache: ShuffleCache) -> None:
        cache.close()  # seal files before serving
        with self._lock:
            self._caches[cache.shuffle_id] = cache

    def unregister(self, shuffle_id: str) -> None:
        with self._lock:
            cache = self._caches.pop(shuffle_id, None)
        if cache is not None:
            cache.cleanup()

    def shutdown(self) -> None:
        self._server.shutdown()


def sweep_orphaned_shuffles(root: Optional[str] = None,
                            ttl_s: Optional[float] = None) -> List[str]:
    """Startup sweep: delete ``shuffle_<id>/`` spill dirs IDLE longer
    than a TTL (``DAFT_TPU_SHUFFLE_TTL``, seconds, default 86400) — the
    remains of crashed workers that never reached
    ``ShuffleCache.cleanup()``. Serving a partition refreshes the dir's
    mtime, so an actively-fetched output never ages out.
    With no explicit ``root``, sweeps this process's spill dir AND every
    sibling ``daft_tpu_spill_*`` root under the tmpdir (a crashed
    process's per-process mkdtemp root is exactly where its orphans
    live). The TTL guards live dirs of concurrent processes. Returns the
    removed paths."""
    import glob
    import shutil
    import tempfile
    import time as _time
    if root is None:
        from ..execution.memory import spill_dir
        roots = [spill_dir()]
        roots += [p for p in glob.glob(os.path.join(
            tempfile.gettempdir(), "daft_tpu_spill_*"))
            if p not in roots and os.path.isdir(p)]
    else:
        roots = [root]
    if ttl_s is None:
        ttl_s = float(os.environ.get("DAFT_TPU_SHUFFLE_TTL", "86400"))
    removed: List[str] = []
    cutoff = _time.time() - ttl_s
    for r in roots:
        try:
            entries = os.listdir(r)
        except OSError:
            continue
        for name in entries:
            if not name.startswith("shuffle_"):
                continue
            path = os.path.join(r, name)
            try:
                if os.path.isdir(path) and os.path.getmtime(path) < cutoff:
                    shutil.rmtree(path, ignore_errors=True)
                    removed.append(path)
            except OSError:
                continue
    return removed


_swept_once = False


def make_shuffle_server(port: int = 0, host: Optional[str] = None):
    """Transport factory: Arrow Flight when available (the reference's
    design), stdlib HTTP otherwise; ``DAFT_TPU_SHUFFLE_TRANSPORT=http``
    forces the fallback. The first server created in a process also
    sweeps orphaned shuffle dirs crashed processes left behind (once —
    the glob+stat walk is not worth repeating per server)."""
    global _swept_once
    if not _swept_once:
        _swept_once = True
        try:
            sweep_orphaned_shuffles()
        except Exception:
            pass  # janitorial; must never block serving
    pref = os.environ.get("DAFT_TPU_SHUFFLE_TRANSPORT", "flight")
    if pref != "http" and paflight is not None:
        return FlightShuffleServer(port, host=host)
    return ShuffleServer(port, host=host)


_local_server = None
_local_server_lock = threading.Lock()


def get_local_shuffle_server():
    """One lazily-started shuffle server per process (each worker host runs
    one, like the reference's per-node flight server)."""
    global _local_server
    with _local_server_lock:
        if _local_server is None:
            _local_server = make_shuffle_server()
        return _local_server


def configure_local_shuffle_server(host: str, advertise_host: str):
    """Eagerly create the process shuffle server with explicit networking
    (worker startup calls this BEFORE any map task can lazily boot a
    loopback-bound one). Conflicting reconfiguration is an error — the
    advertised address is baked into outstanding map receipts."""
    global _local_server
    with _local_server_lock:
        if _local_server is not None:
            current = _local_server.address
            want_host = advertise_host or host
            cur_host = urllib.parse.urlparse(
                current if "://" in current else f"http://{current}").hostname
            if cur_host != want_host.lower():  # urlparse lowercases hostname
                raise RuntimeError(
                    f"shuffle server already running at {current}; cannot "
                    f"re-advertise as {want_host}")
            return _local_server
        _local_server = make_shuffle_server(host=host)
        if advertise_host:
            _local_server._advertise = advertise_host
        return _local_server


def _spill_streams(body: bytes):
    """Yield (schema, batch-list) per concatenated IPC stream in a spill
    file (one stream per writer reopen). A truncated trailing stream — a
    straggler append caught mid-write — is skipped; the dropped tail is
    logged so mid-file corruption (which also truncates everything after
    it) is never silent."""
    if not body:
        return
    buf = pa.BufferReader(body)
    while buf.tell() < buf.size():
        start = buf.tell()
        try:
            with paipc.open_stream(buf) as rd:
                batches = list(rd)
        except pa.ArrowInvalid:
            _log_truncated_tail(start, buf.size())
            return
        yield rd.schema, batches


def _log_truncated_tail(pos: int, size: int) -> None:
    import logging
    logging.getLogger(__name__).warning(
        "shuffle spill file: unreadable IPC stream at byte %d; dropping "
        "%d trailing bytes (torn straggler append, or corruption if not "
        "at the tail)", pos, size - pos)


def _spill_file_batches(path: str):
    """Lazily yield (schema, batch) straight off a spill file, one record
    batch at a time (never materializes the partition in memory). Tolerates
    (and logs) a truncated trailing stream like _spill_streams."""
    if not os.path.exists(path):
        return
    size = os.path.getsize(path)
    with pa.OSFile(path, "rb") as f:
        while f.tell() < size:
            start = f.tell()
            try:
                rd = paipc.open_stream(f)
            except pa.ArrowInvalid:
                _log_truncated_tail(start, size)
                return
            schema = rd.schema
            while True:
                try:
                    batch = rd.read_next_batch()
                except StopIteration:
                    break
                except pa.ArrowInvalid:
                    _log_truncated_tail(start, size)
                    return
                yield schema, batch


def unregister_remote(address: str, shuffle_id: str) -> None:
    """Release a consumed map output on its serving host (reduce-side
    cleanup; dispatches on the address scheme like fetch_partition)."""
    if address.startswith("grpc://"):
        if paflight is None:
            return
        client = paflight.connect(address)
        try:
            list(client.do_action(
                paflight.Action("unregister", shuffle_id.encode())))
        finally:
            client.close()
        return
    req = urllib.request.Request(f"{address}/shuffle/{shuffle_id}",
                                 method="DELETE")
    with urllib.request.urlopen(req, timeout=30):
        pass


def fetch_partition(address: str, shuffle_id: str, partition: int,
                    fault_key: Optional[str] = None) -> Optional[pa.Table]:
    """Reduce-side fetch: partition bytes → Arrow table (reference:
    flight_client do_get). Dispatches on the address scheme. Any failure
    raises ``ShuffleFetchError`` carrying the (address, shuffle_id)
    identity the scheduler's lineage recovery keys on. ``fault_key`` is
    the stable (run-independent) source identity used for deterministic
    fault injection; it defaults to the shuffle id."""
    from .resilience import ShuffleFetchError, active_fault_plan
    key = fault_key or shuffle_id
    plan = active_fault_plan()
    if plan is not None:  # injection site 2: partition fetch
        if plan.decide("crash", f"{key}/p{partition}"):
            # a dead map worker: the served data is really gone — every
            # later fetch of this shuffle fails too, until the scheduler
            # recomputes the producing map task
            try:
                unregister_remote(address, shuffle_id)
            except Exception:
                pass
            raise ShuffleFetchError(address, shuffle_id, partition,
                                    detail="injected worker crash",
                                    injected=True)
        if plan.decide("fetch", f"{key}/p{partition}"):
            raise ShuffleFetchError(address, shuffle_id, partition,
                                    detail="injected fetch fault",
                                    injected=True)
    try:
        return _fetch_partition_raw(address, shuffle_id, partition)
    except Exception as exc:
        raise ShuffleFetchError(address, shuffle_id, partition,
                                detail=f"{type(exc).__name__}: "
                                       f"{str(exc)[:200]}") from exc


def _fetch_partition_raw(address: str, shuffle_id: str, partition: int
                         ) -> Optional[pa.Table]:
    if address.startswith("grpc://"):
        if paflight is None:
            raise RuntimeError(
                f"shuffle peer advertises Flight ({address}) but "
                "pyarrow.flight is unavailable on this host; set "
                "DAFT_TPU_SHUFFLE_TRANSPORT=http on the serving hosts")
        client = paflight.connect(address)
        try:
            ticket = paflight.Ticket(f"{shuffle_id}/{partition}".encode())
            reader = client.do_get(ticket)
            t = reader.read_all()
        finally:
            client.close()
        meta = t.schema.metadata or {}
        return None if meta.get(b"daft_tpu_empty") == b"1" else t
    url = f"{address}/shuffle/{shuffle_id}/{partition}"
    timeout = float(os.environ.get("DAFT_TPU_SHUFFLE_TIMEOUT", "600"))
    with urllib.request.urlopen(url, timeout=timeout) as r:
        if r.status != 200:
            raise RuntimeError(f"shuffle server returned {r.status}")
        body = r.read()
    if not body:
        return None
    tables = [pa.Table.from_batches(batches, schema=schema)
              for schema, batches in _spill_streams(body)]
    return pa.concat_tables(tables) if tables else None
