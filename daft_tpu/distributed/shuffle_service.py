"""Inter-host shuffle transport: spill-backed partition server + client.

Reference: the flight shuffle (``src/daft-shuffles``) — the map side
partitions morsels and spills per-partition Arrow IPC files
(``shuffle_cache.rs:14-80``); each node runs an Arrow Flight gRPC server
serving ``do_get(partition_idx)`` (``server/flight_server.rs:17-170``) and
the reduce side fetches over the network. Here the same design rides plain
HTTP (stdlib server, Arrow IPC payloads): a ``ShuffleCache`` accumulates
map outputs into per-partition spill files, a ``ShuffleServer`` exposes
``GET /shuffle/<id>/<partition>`` streaming the concatenated IPC bytes, and
``fetch_partition`` pulls a partition from any host. On a TPU pod this is
the DCN tier — intra-pod exchanges ride ICI collectives instead
(``parallel/exchange.py``)."""

from __future__ import annotations

import http.server
import io
import os
import threading
import urllib.request
import uuid
from typing import Dict, List, Optional, Tuple

import pyarrow as pa
import pyarrow.ipc as paipc


class ShuffleCache:
    """Map-side output accumulator: morsels are hash-partitioned by the
    caller; each partition's batches append to one Arrow IPC spill file
    (reference: InProgressShuffleCache → per-partition writer tasks)."""

    def __init__(self, shuffle_id: Optional[str] = None,
                 dirs: Optional[List[str]] = None):
        from ..execution.memory import spill_dir
        self.shuffle_id = shuffle_id or uuid.uuid4().hex
        self._root = os.path.join((dirs or [spill_dir()])[0],
                                  f"shuffle_{self.shuffle_id}")
        os.makedirs(self._root, exist_ok=True)
        self._lock = threading.Lock()
        self._writers: Dict[int, Tuple[object, object]] = {}
        self._rows: Dict[int, int] = {}

    def _writer(self, partition: int, schema: pa.Schema):
        w = self._writers.get(partition)
        if w is None:
            # append: a straggler push after close() adds a new IPC stream
            # after the sealed one instead of truncating it (fetch reads
            # all concatenated streams)
            f = open(self._path(partition), "ab")
            w = (paipc.new_stream(f, schema), f)
            self._writers[partition] = w
        return w[0]

    def _path(self, partition: int) -> str:
        return os.path.join(self._root, f"part-{partition}.arrow")

    def push(self, partition: int, table: pa.Table) -> None:
        with self._lock:
            self._writer(partition, table.schema).write_table(table)
            self._rows[partition] = self._rows.get(partition, 0) + len(table)

    def close(self) -> None:
        with self._lock:
            for w, f in self._writers.values():
                w.close()
                f.close()
            self._writers = {}

    def partition_bytes(self, partition: int) -> bytes:
        p = self._path(partition)
        if not os.path.exists(p):
            return b""
        with open(p, "rb") as f:
            return f.read()

    def partitions(self) -> List[int]:
        return sorted(self._rows)

    def cleanup(self) -> None:
        self.close()
        for f in os.listdir(self._root):
            try:
                os.unlink(os.path.join(self._root, f))
            except OSError:
                pass
        try:
            os.rmdir(self._root)
        except OSError:
            pass


class ShuffleServer:
    """Per-host partition server (reference: per-node Flight server)."""

    def __init__(self, port: int = 0):
        self._caches: Dict[str, ShuffleCache] = {}
        self._lock = threading.Lock()
        caches = self._caches
        lock = self._lock

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                parts = self.path.strip("/").split("/")
                if len(parts) != 3 or parts[0] != "shuffle":
                    self.send_response(404)
                    self.end_headers()
                    return
                sid, pidx = parts[1], int(parts[2])
                with lock:
                    cache = caches.get(sid)
                if cache is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = cache.partition_bytes(pidx)
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/vnd.apache.arrow.stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                                       Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="daft-tpu-shuffle").start()

    @property
    def address(self) -> str:
        return f"http://127.0.0.1:{self._server.server_port}"

    def register(self, cache: ShuffleCache) -> None:
        cache.close()  # seal files before serving
        with self._lock:
            self._caches[cache.shuffle_id] = cache

    def unregister(self, shuffle_id: str) -> None:
        with self._lock:
            cache = self._caches.pop(shuffle_id, None)
        if cache is not None:
            cache.cleanup()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def fetch_partition(address: str, shuffle_id: str, partition: int
                    ) -> Optional[pa.Table]:
    """Reduce-side fetch: partition bytes → Arrow table (reference:
    flight_client do_get)."""
    url = f"{address}/shuffle/{shuffle_id}/{partition}"
    timeout = float(os.environ.get("DAFT_TPU_SHUFFLE_TIMEOUT", "600"))
    with urllib.request.urlopen(url, timeout=timeout) as r:
        body = r.read()
    if not body:
        return None
    tables = []
    buf = pa.BufferReader(body)
    # the spill file may hold several concatenated IPC streams (one per
    # writer reopen); read them all
    while buf.tell() < buf.size():
        with paipc.open_stream(buf) as rd:
            tables.append(rd.read_all())
    return pa.concat_tables(tables) if tables else None
