"""Stage planning: split the physical plan at exchange boundaries.

Reference model: flotilla's ``StagePlan::from_logical_plan`` splits at data
movement (``src/daft-distributed/src/stage/mod.rs:54-80``). Here the split
runs over the already-translated physical plan: every ``Exchange`` node
becomes a stage boundary — its subtree is the upstream stage, and the
downstream stage sees a ``StageInput`` leaf. The exchange itself is executed
by the driver between stages (the classic fully-materializing map/reduce
transport; the mesh-collective DeviceExchangeAgg stays *inside* a stage
because it is one fused program, not a materialization point).

A stage is therefore an exchange-free fragment whose leaves are scan tasks,
in-memory partitions, or upstream stage outputs — exactly the shape of a
dispatchable worker task (flotilla's SwordfishTask carries a LocalPhysicalPlan
fragment the same way).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..physical import plan as pp


@dataclass
class Boundary:
    """An exchange edge feeding a stage."""

    upstream: int
    kind: str
    num_partitions: int
    by: Tuple = ()
    descending: Tuple = ()
    engine_inserted: bool = False  # preserves the AQE-adaptability flag


@dataclass
class Stage:
    id: int
    plan: pp.PhysicalPlan
    boundaries: List[Boundary] = field(default_factory=list)

    def is_map_like(self) -> bool:
        """True when the fragment is partition-parallel end-to-end, so its
        scan tasks can shard across workers without changing semantics."""
        ok = (pp.ScanSource, pp.InMemorySource, pp.StageInput, pp.Project,
              pp.Filter, pp.UDFProject, pp.Explode, pp.Unpivot, pp.Sample,
              pp.DeviceFragmentAgg)

        def walk(n) -> bool:
            if isinstance(n, pp.Aggregate):
                return n.mode == "partial" and all(walk(c)
                                                   for c in n.children)
            if not isinstance(n, ok):
                return False
            return all(walk(c) for c in n.children)

        return walk(self.plan)

    def scan_source(self) -> Optional[pp.ScanSource]:
        found = []

        def walk(n):
            if isinstance(n, pp.ScanSource):
                found.append(n)
            for c in n.children:
                walk(c)

        walk(self.plan)
        return found[0] if len(found) == 1 else None

    def with_scan_tasks(self, tasks) -> pp.PhysicalPlan:
        """Shallow-clone the fragment with the (single) ScanSource's task
        list replaced — used to shard a map-like stage across workers."""

        def clone(n):
            c = copy.copy(n)
            if isinstance(c, pp.ScanSource):
                c.tasks = list(tasks)
            else:
                c.children = [clone(ch) for ch in n.children]
            return c

        return clone(self.plan)


class StagePlan:
    """Topologically-ordered stages; the last stage is the query root."""

    def __init__(self, stages: List[Stage]):
        self.stages = stages

    @property
    def root(self) -> Stage:
        return self.stages[-1]

    @classmethod
    def from_physical(cls, plan: pp.PhysicalPlan) -> "StagePlan":
        stages: List[Stage] = []
        counter = [0]

        def cut(node: pp.PhysicalPlan, boundaries: List[Boundary]):
            """Rewrite `node`'s subtree for the current stage, emitting
            upstream stages at every Exchange."""
            if isinstance(node, pp.Exchange):
                up_boundaries: List[Boundary] = []
                up_plan = cut(node.children[0], up_boundaries)
                sid = counter[0]
                counter[0] += 1
                stages.append(Stage(sid, up_plan, up_boundaries))
                boundaries.append(Boundary(
                    sid, node.kind, node.num_partitions, tuple(node.by),
                    tuple(node.descending),
                    getattr(node, "engine_inserted", False)))
                return pp.StageInput(sid, node.schema())
            n = copy.copy(node)
            n.children = [cut(c, boundaries) for c in node.children]
            return n

        root_boundaries: List[Boundary] = []
        root_plan = cut(plan, root_boundaries)
        sid = counter[0]
        stages.append(Stage(sid, root_plan, root_boundaries))
        return cls(stages)

    def repr_ascii(self) -> str:
        lines = []
        for s in self.stages:
            ins = ", ".join(f"stage{b.upstream}→{b.kind}({b.num_partitions})"
                            for b in s.boundaries) or "-"
            lines.append(f"Stage {s.id}: root={s.plan.name()} inputs=[{ins}]"
                         f" map_like={s.is_map_like()}")
        return "\n".join(lines)
