"""Stage planning: split the physical plan at exchange boundaries.

Reference model: flotilla's ``StagePlan::from_logical_plan`` splits at data
movement (``src/daft-distributed/src/stage/mod.rs:54-80``). Here the split
runs over the already-translated physical plan: every ``Exchange`` node
becomes a stage boundary — its subtree is the upstream stage, and the
downstream stage sees a ``StageInput`` leaf. The exchange itself is executed
by the driver between stages (the classic fully-materializing map/reduce
transport; the mesh-collective DeviceExchangeAgg stays *inside* a stage
because it is one fused program, not a materialization point).

A stage is therefore an exchange-free fragment whose leaves are scan tasks,
in-memory partitions, or upstream stage outputs — exactly the shape of a
dispatchable worker task (flotilla's SwordfishTask carries a LocalPhysicalPlan
fragment the same way).

Stage/task identities (``Stage.task_key``) double as the lineage keys of
the resilience plane: every shuffle receipt a boundary consumes is
registered against the producing map task, so a lost partition re-executes
only its producer (``distributed/resilience.py``).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..physical import plan as pp


@dataclass
class Boundary:
    """An exchange edge feeding a stage."""

    upstream: int
    kind: str
    num_partitions: int
    by: Tuple = ()
    descending: Tuple = ()
    engine_inserted: bool = False  # preserves the AQE-adaptability flag
    # join-side co-partitioning exchange (translate marks the pair):
    # strategy-adaptable — the runtime re-planner may demote it to a
    # broadcast from measured sizes, exactly like the local AQE path
    join_side: bool = False


@dataclass
class Stage:
    id: int
    plan: pp.PhysicalPlan
    boundaries: List[Boundary] = field(default_factory=list)

    def task_key(self, task_idx: int, phase: str = "") -> str:
        """Stable identity of one of this stage's tasks, minted at the
        planning layer: stage ids come from the deterministic plan-split
        counter and task indices from the deterministic sharding, so the
        same query produces the same keys run after run. The resilience
        plane keys fault-injection decisions and shuffle lineage on these
        (never on run-specific uuids), which is what makes chaos runs
        replay bit-identically."""
        p = f".{phase}" if phase else ""
        return f"s{self.id}{p}.t{task_idx}"

    def is_map_like(self) -> bool:
        """True when the fragment is partition-parallel end-to-end, so its
        scan tasks can shard across workers without changing semantics."""
        ok = (pp.ScanSource, pp.InMemorySource, pp.StageInput, pp.Project,
              pp.Filter, pp.UDFProject, pp.Explode, pp.Unpivot, pp.Sample,
              pp.DeviceFragmentAgg)

        def walk(n) -> bool:
            if isinstance(n, pp.Aggregate):
                return n.mode == "partial" and all(walk(c)
                                                   for c in n.children)
            if not isinstance(n, ok):
                return False
            return all(walk(c) for c in n.children)

        return walk(self.plan)

    def scan_source(self) -> Optional[pp.ScanSource]:
        found = []

        def walk(n):
            if isinstance(n, pp.ScanSource):
                found.append(n)
            for c in n.children:
                walk(c)

        walk(self.plan)
        return found[0] if len(found) == 1 else None

    def with_scan_tasks(self, tasks) -> pp.PhysicalPlan:
        """Shallow-clone the fragment with the (single) ScanSource's task
        list replaced — used to shard a map-like stage across workers."""

        def clone(n):
            c = copy.copy(n)
            if isinstance(c, pp.ScanSource):
                c.tasks = list(tasks)
            else:
                c.children = [clone(ch) for ch in n.children]
            return c

        return clone(self.plan)


class StagePlan:
    """Topologically-ordered stages; the last stage is the query root."""

    def __init__(self, stages: List[Stage]):
        self.stages = stages

    @property
    def root(self) -> Stage:
        return self.stages[-1]

    @classmethod
    def from_physical(cls, plan: pp.PhysicalPlan) -> "StagePlan":
        stages: List[Stage] = []
        counter = [0]

        def cut(node: pp.PhysicalPlan, boundaries: List[Boundary]):
            """Rewrite `node`'s subtree for the current stage, emitting
            upstream stages at every Exchange."""
            if isinstance(node, pp.Exchange):
                up_boundaries: List[Boundary] = []
                up_plan = cut(node.children[0], up_boundaries)
                sid = counter[0]
                counter[0] += 1
                stages.append(Stage(sid, up_plan, up_boundaries))
                boundaries.append(Boundary(
                    sid, node.kind, node.num_partitions, tuple(node.by),
                    tuple(node.descending),
                    getattr(node, "engine_inserted", False),
                    getattr(node, "join_side", False)))
                return pp.StageInput(sid, node.schema())
            n = copy.copy(node)
            n.children = [cut(c, boundaries) for c in node.children]
            return n

        root_boundaries: List[Boundary] = []
        root_plan = cut(plan, root_boundaries)
        sid = counter[0]
        stages.append(Stage(sid, root_plan, root_boundaries))
        return cls(stages)

    @staticmethod
    def _contains_input(node, upstream: int) -> bool:
        if isinstance(node, pp.StageInput):
            return node.stage_id == upstream
        return any(StagePlan._contains_input(c, upstream)
                   for c in node.children)

    @staticmethod
    def _subtree_safe(node, b: Boundary) -> bool:
        """True when ``node``'s subtree consumes the boundary's StageInput
        only through partition-local operators — rows sharing the exchange
        keys never need to meet rows from other partitions. Global
        operators (sort, limit, monotonic ids, windows) disqualify."""
        by_names = {e.name() for e in b.by}

        def walk(n) -> tuple:
            """→ (subtree references this boundary, safe so far)."""
            if isinstance(n, pp.StageInput):
                return n.stage_id == b.upstream, True
            has_any = False
            for c in n.children:
                has, safe = walk(c)
                if has and not safe:
                    return True, False
                has_any = has_any or has
            if not has_any:
                return False, True
            if isinstance(n, (pp.Project, pp.Filter, pp.UDFProject,
                              pp.Explode, pp.Unpivot)):
                return True, True
            if isinstance(n, pp.HashJoin):
                # hash strategy: both sides are engine-inserted hash
                # boundaries on the join keys (co-partitioned); broadcast:
                # the build side is a replicated gather boundary and the
                # probe is row-local. sort_merge inserts NO exchanges —
                # fanning it out would re-run the embedded side per task
                # and duplicate outer-side unmatched rows.
                return True, n.strategy != "sort_merge"
            if isinstance(n, pp.Aggregate):
                group_names = {e.name() for e in n.group_by}
                return True, by_names <= group_names
            if isinstance(n, pp.Dedup):
                on_names = {e.name() for e in (n.on or [])} \
                    if n.on else None
                return True, on_names is None or by_names <= on_names
            return True, False

        has, safe = walk(node)
        return has and safe

    def fanout_safe(self, stage: Stage, b: Boundary) -> bool:
        """The whole consumer fragment can run one task per hash
        partition."""
        if b.kind != "hash" or not b.by:
            return False
        return self._subtree_safe(stage.plan, b)

    def collective_safe(self, stage: Stage, b: Boundary) -> bool:
        """Structural eligibility of one hash boundary for the
        collective / hierarchical exchange family (the placement layer's
        precondition, topology- and cost-blind): the consumer fragment
        must be partition-local END TO END over this boundary — a
        collective exchange hands each reduce task one already-exchanged
        bucket, so there is no safe-frontier split to fall back on — and
        every sibling input must be hash (co-partitioned: the mesh pid
        chain and ``partition_by_hash`` agree by construction) or gather
        (replicated)."""
        return (b.kind == "hash" and b.num_partitions > 1
                and all(ob.kind in ("hash", "gather")
                        for ob in stage.boundaries)
                and self.fanout_safe(stage, b))

    def split_for_fanout(self, stage: Stage, b: Boundary):
        """Cut the consumer fragment at its SAFE FRONTIER: the highest node
        on the StageInput's path whose subtree is partition-local. →
        (sub_plan to fan out per partition, remainder plan reading the
        fan-out's output through StageInput(placeholder_id),
        placeholder_id) or None when no useful split exists (reference:
        flotilla keeps per-partition pipeline nodes below the global op
        and materializes between — the same seam)."""
        if b.kind != "hash" or not b.by:
            return None

        def descend(n):
            if self._subtree_safe(n, b):
                return n
            kids = [c for c in n.children
                    if self._contains_input(c, b.upstream)]
            if len(kids) != 1:
                return None
            return descend(kids[0])

        cut = descend(stage.plan)
        if cut is None or cut is stage.plan \
                or isinstance(cut, pp.StageInput):
            return None  # whole-stage fanout, nothing local, or no split
        placeholder_id = -(stage.id + 1) * 1000 - b.upstream
        placeholder = pp.StageInput(placeholder_id, cut.schema())

        def clone(n):
            if n is cut:
                return placeholder
            c = copy.copy(n)
            c.children = [clone(ch) for ch in n.children]
            return c

        return cut, clone(stage.plan), placeholder_id

    def combine_for_boundary(self, consumer: Stage, b: Boundary,
                             upstream: Stage):
        """Map-side combine plan for one hash boundary: when the boundary
        feeds a final grouped aggregation whose aggs are all associative
        self-merges (``aggs.AGG_DECOMPOSITION``), return
        ``(combine_aggs, combine_by, agg_node)`` — the merge expressions
        each map task applies per partition before ``ShuffleCache.push``
        (wire carries group states instead of rows), aliased so the
        combined output keeps the upstream stage's EXACT wire schema.
        None when the consumer isn't that shape, any agg falls outside
        the self-merge table (non-decomposable sets keep today's plan),
        or the combine would drop a wire column no final agg reads."""
        from ..aggs import merge_exprs_for
        if b.kind != "hash" or not b.by:
            return None
        agg = self._consumer_agg(consumer.plan, b.upstream)
        if agg is None or not agg.group_by:
            return None
        if {e.name() for e in b.by} != {e.name() for e in agg.group_by}:
            return None
        merge = merge_exprs_for(agg.aggs, alias_to="source")
        if merge is None:
            return None
        wire_cols = list(upstream.plan.schema().column_names)
        by_names = {e.name() for e in b.by}
        if {e.name() for e in merge} | by_names != set(wire_cols):
            return None  # a wire column no final agg reads would vanish
        order = {n: i for i, n in enumerate(wire_cols)}
        merge.sort(key=lambda e: order[e.name()])
        return tuple(merge), tuple(b.by), agg

    @staticmethod
    def _consumer_agg(node, upstream: int):
        """The UNIQUE final Aggregate directly consuming
        ``StageInput(upstream)``, else None (an aggregate reached through
        intermediate operators can't combine: the wire rows feed those
        operators first)."""
        found = []

        def walk(n):
            if isinstance(n, pp.Aggregate) and n.children \
                    and isinstance(n.children[0], pp.StageInput) \
                    and n.children[0].stage_id == upstream \
                    and n.mode in ("final", "single"):
                found.append(n)
            for c in n.children:
                walk(c)

        walk(node)
        return found[0] if len(found) == 1 else None

    def repr_ascii(self) -> str:
        lines = []
        for s in self.stages:
            ins = ", ".join(f"stage{b.upstream}→{b.kind}({b.num_partitions})"
                            for b in s.boundaries) or "-"
            lines.append(f"Stage {s.id}: root={s.plan.name()} inputs=[{ins}]"
                         f" map_like={s.is_map_like()}")
        return "\n".join(lines)
