"""Distributed execution: stage planning, scheduler, workers.

The engine's flotilla-equivalent (reference: ``src/daft-distributed`` — stage
split at data movement ``stage/mod.rs:54-80``, pluggable Scheduler trait
``scheduling/scheduler/mod.rs:18-23``, Worker/WorkerManager abstractions
``scheduling/worker.rs:13-25``, mock-worker tests ``scheduling/tests.rs``) —
re-expressed for a TPU pod: workers are per-host local executors, exchanges
between stages ride the mesh collectives or the driver's host exchange.
"""

from .stages import Stage, StagePlan
from .topology import (CollectiveExchangeGroup, MeshGroup, WorkerTopology,
                       plan_exchange_path)
from .worker import Worker, InProcessWorker, WorkerManager, StageTask
from .resilience import (FaultPlan, RetryPolicy, ResilienceContext,
                         TaskSupervisor, InjectedFault, ShuffleFetchError,
                         FailFastError, TaskTimeout)
from .scheduler import (Scheduler, RoundRobinScheduler, LeastLoadedScheduler,
                        StageRunner)

__all__ = ["Stage", "StagePlan", "Worker", "InProcessWorker",
           "WorkerManager", "StageTask", "Scheduler", "RoundRobinScheduler",
           "LeastLoadedScheduler", "StageRunner", "FaultPlan", "RetryPolicy",
           "ResilienceContext", "TaskSupervisor", "InjectedFault",
           "ShuffleFetchError", "FailFastError", "TaskTimeout",
           "WorkerTopology", "MeshGroup", "CollectiveExchangeGroup",
           "plan_exchange_path"]
