"""Resilience plane for the distributed tier.

Four cooperating pieces, all driven from the stage runner's task
supervisor (reference: Exoshuffle's thesis that shuffle fault tolerance
belongs in the application-level scheduler as lineage-driven
re-execution, not in the transport):

1. **Deterministic fault injection** — ``FaultPlan`` parses
   ``DAFT_TPU_FAULT_SPEC`` (seeded by ``DAFT_TPU_FAULT_SEED``) and decides
   every injection by hashing ``(seed, site, key, attempt)``: a pure
   function of stable identifiers, so the same seed reproduces the same
   fault set bit-identically regardless of thread interleaving. Hooks sit
   at the three real failure sites: task execution (``worker.run_task``,
   site ``task``), partition fetch (``shuffle_service.fetch_partition``,
   sites ``fetch`` and ``crash`` — ``crash`` additionally destroys the
   served shuffle data, simulating a dead map worker), and remote-worker
   RPC (``remote_worker.RemoteWorker._post``, site ``rpc``).

2. **Retry/health policy** — ``RetryPolicy``: bounded retries with
   exponential backoff + deterministic jitter, per-worker
   consecutive-failure quarantine (circuit breaker with timed
   re-admission), and fail-fast classification (a task failing with the
   same signature on two distinct workers raises instead of looping).

3. **Lineage-based shuffle recovery** — ``ShuffleLineage`` records which
   map task produced each shuffle receipt; when a reduce-side fetch fails
   because the serving worker is gone, the supervisor re-executes only
   the lost map task, registers the new (address, shuffle_id) as a
   translation of the old one, and redispatches the reduce task with
   translated fetch specs. Recovery composes recursively (a recomputed
   map task whose own inputs were cleaned up recovers them the same way),
   depth-bounded. Collective (pod-native) exchange stages are
   ALL-OR-NOTHING lineage units: a per-mesh stream's registered producer
   is the whole exchange group (``topology.CollectiveExchangeGroup``),
   so losing it re-executes every member map task plus the intra-mesh
   collective as one unit — never a single map task.

4. **Speculative execution** — when a task's runtime exceeds a multiple
   of the median of its completed siblings, the supervisor launches a
   backup on a quarantine-free worker; the first finisher wins and the
   loser's shuffle output is discarded idempotently.

All recovery events are counted in a process-wide registry (mirroring the
device-kernel dispatch ledger) that ``observability.RuntimeStatsContext``
snapshots per query and renders in ``explain_analyze`` / the dashboard.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple

# --------------------------------------------------------------- errors


class InjectedFault(RuntimeError):
    """A deterministically injected failure (``DAFT_TPU_FAULT_SPEC``)."""

    def __init__(self, site: str, key: str):
        super().__init__(f"injected fault at {site}:{key}")
        self.site = site
        self.key = key

    def __reduce__(self):  # picklable across the remote-worker wire
        return (InjectedFault, (self.site, self.key))


class ShuffleFetchError(RuntimeError):
    """A reduce-side partition fetch failed: the serving worker is gone,
    the shuffle was unregistered, or the transport broke. Carries the
    (address, shuffle_id) identity lineage recovery keys on."""

    def __init__(self, address: str, shuffle_id: str, partition: int,
                 detail: str = "", injected: bool = False):
        super().__init__(
            f"shuffle fetch failed: {address}/{shuffle_id}/p{partition}"
            + (f" ({detail})" if detail else ""))
        self.address = address
        self.shuffle_id = shuffle_id
        self.partition = partition
        self.detail = detail
        self.injected = injected

    def __reduce__(self):
        return (ShuffleFetchError, (self.address, self.shuffle_id,
                                    self.partition, self.detail,
                                    self.injected))


class FailFastError(RuntimeError):
    """The same failure signature on two distinct workers: the task is
    the problem, not the worker — retrying would loop forever."""


class TaskTimeout(RuntimeError):
    """A task attempt exceeded ``DAFT_TPU_TASK_TIMEOUT`` (treated as a
    retryable failure; the stale attempt's result is discarded)."""


# ----------------------------------------------------- recovery counters
# Process-wide, like the device-kernel dispatch ledger: RuntimeStatsContext
# snapshots at query start and diffs at finish() for per-query numbers.

_counters_lock = threading.Lock()
_counters: Dict[str, int] = {}


def count(name: str, n: int = 1) -> None:
    with _counters_lock:
        _counters[name] = _counters.get(name, 0) + n
    # context-local attribution for the serving plane (overlapping
    # queries each see only their own recovery events)
    from .. import observability as obs
    obs.bump_plane("recovery", name, n)


def counters_snapshot() -> Dict[str, int]:
    with _counters_lock:
        return dict(_counters)


def counters_delta(before: Dict[str, int],
                   after: Optional[Dict[str, int]] = None) -> Dict[str, int]:
    if after is None:
        after = counters_snapshot()
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d:
            out[k] = d
    return out


# -------------------------------------------------------- fault plan


def _hash01(*parts) -> float:
    """Uniform [0, 1) from stable identifiers — injection decisions are a
    pure function of these, never of shared RNG state, so chaos runs
    replay bit-identically under any thread interleaving."""
    h = hashlib.sha256("\x1f".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:8], "big") / 2 ** 64


class FaultPlan:
    """Parsed ``DAFT_TPU_FAULT_SPEC``: comma-separated
    ``site:rate[:N][:sticky]`` entries.

    - ``site`` — one of ``task`` / ``fetch`` / ``rpc`` / ``crash``.
    - ``rate`` — injection probability per decision (default 1.0).
    - ``N`` — optional cap on total injections at that site.
    - ``sticky`` — the decision ignores the attempt number, so the same
      task fails the same way on every worker (exercises fail-fast
      classification); default faults are transient (a retry re-rolls).

    Example: ``task:0.3,fetch:0.2,crash:1:1`` — 30% of task executions
    fail, 20% of fetches fail transiently, and exactly the first
    crash-eligible fetch destroys its serving shuffle data.
    """

    SITES = ("task", "fetch", "rpc", "crash")

    def __init__(self, spec: str, seed: str = "0"):
        self.spec = spec
        self.seed = seed
        self._sites: Dict[str, Tuple[float, Optional[int], bool]] = {}
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split(":")
            site = parts[0].strip()
            if site not in self.SITES:
                raise ValueError(
                    f"DAFT_TPU_FAULT_SPEC: unknown site {site!r} "
                    f"(expected one of {self.SITES})")
            rate = float(parts[1]) if len(parts) > 1 else 1.0
            cap: Optional[int] = None
            sticky = False
            for p in parts[2:]:
                if p.strip() == "sticky":
                    sticky = True
                elif p.strip():
                    cap = int(p)
            self._sites[site] = (rate, cap, sticky)
        self._lock = threading.Lock()
        self._attempt: Dict[Tuple[str, str], int] = defaultdict(int)
        self._fired: Dict[str, int] = defaultdict(int)
        self.events: List[str] = []

    def _decide(self, site: str, key: str, attempt: Optional[int]
                ) -> Tuple[bool, int, bool]:
        """→ (fired, attempt_used, sticky)."""
        ent = self._sites.get(site)
        if ent is None:
            return False, 0, False
        rate, cap, sticky = ent
        with self._lock:
            if attempt is None:
                attempt = self._attempt[(site, key)]
                self._attempt[(site, key)] += 1
            if cap is not None and self._fired[site] >= cap:
                return False, attempt, sticky
            if _hash01(self.seed, site, key,
                       0 if sticky else attempt) >= rate:
                return False, attempt, sticky
            self._fired[site] += 1
            self.events.append(f"{site}:{key}#{attempt}")
        count(f"injected_{site}")
        return True, attempt, sticky

    def decide(self, site: str, key: str,
               attempt: Optional[int] = None) -> bool:
        return self._decide(site, key, attempt)[0]

    def maybe_fail(self, site: str, key: str,
                   attempt: Optional[int] = None) -> None:
        fired, used, sticky = self._decide(site, key, attempt)
        if fired:
            # transient faults carry the attempt in their identity so the
            # fail-fast classifier doesn't mistake two independent blips
            # on different workers for a deterministic task failure;
            # sticky faults keep one identity ON PURPOSE — failing the
            # same way on two distinct workers must fail fast
            raise InjectedFault(site,
                                key if sticky else f"{key}#a{used}")


_plan_lock = threading.Lock()
_plan: Optional[FaultPlan] = None


def active_fault_plan() -> Optional[FaultPlan]:
    """The process fault plan, re-parsed whenever the env spec/seed
    change (so tests flip scenarios with monkeypatch.setenv alone)."""
    from ..analysis import knobs
    spec = knobs.env_str("DAFT_TPU_FAULT_SPEC", default="")
    if not spec:
        return None
    seed = knobs.env_str("DAFT_TPU_FAULT_SEED")
    global _plan
    with _plan_lock:
        if _plan is None or _plan.spec != spec or _plan.seed != seed:
            _plan = FaultPlan(spec, seed)
        return _plan


def fault_events() -> List[str]:
    """Injected-fault event log of the active plan (``site:key#attempt``
    strings; the replay-determinism contract is over this log)."""
    with _plan_lock:
        return list(_plan.events) if _plan is not None else []


def reset_for_tests() -> None:
    global _plan
    with _plan_lock:
        _plan = None
    with _counters_lock:
        _counters.clear()


# -------------------------------------------------------- retry policy


class RetryPolicy:
    """Bounded retries + per-worker circuit breaker.

    Env knobs (read at construction): ``DAFT_TPU_MAX_RETRIES`` (default
    3), ``DAFT_TPU_RETRY_BACKOFF`` (base seconds, default 0.05),
    ``DAFT_TPU_RETRY_BACKOFF_CAP`` (default 2.0),
    ``DAFT_TPU_QUARANTINE_AFTER`` (consecutive failures, default 3),
    ``DAFT_TPU_QUARANTINE_S`` (default 30),
    ``DAFT_TPU_TASK_TIMEOUT`` (seconds, 0 = off),
    ``DAFT_TPU_SPECULATIVE_MULTIPLIER`` (0 = off, default 4),
    ``DAFT_TPU_SPECULATIVE_MIN_S`` (default 0.5)."""

    def __init__(self, max_retries: Optional[int] = None,
                 backoff_base: Optional[float] = None,
                 backoff_cap: Optional[float] = None,
                 quarantine_after: Optional[int] = None,
                 quarantine_s: Optional[float] = None,
                 task_timeout: Optional[float] = None,
                 speculative_multiplier: Optional[float] = None,
                 speculative_min_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 seed: Optional[str] = None):
        from ..analysis import knobs

        def _f(val, name):
            return knobs.env_float(name) if val is None else val

        self.max_retries = knobs.env_int("DAFT_TPU_MAX_RETRIES") \
            if max_retries is None else max_retries
        self.backoff_base = _f(backoff_base, "DAFT_TPU_RETRY_BACKOFF")
        self.backoff_cap = _f(backoff_cap, "DAFT_TPU_RETRY_BACKOFF_CAP")
        self.quarantine_after = knobs.env_int("DAFT_TPU_QUARANTINE_AFTER") \
            if quarantine_after is None else quarantine_after
        self.quarantine_s = _f(quarantine_s, "DAFT_TPU_QUARANTINE_S")
        self.task_timeout = _f(task_timeout, "DAFT_TPU_TASK_TIMEOUT")
        self.speculative_multiplier = _f(
            speculative_multiplier, "DAFT_TPU_SPECULATIVE_MULTIPLIER")
        self.speculative_min_s = _f(
            speculative_min_s, "DAFT_TPU_SPECULATIVE_MIN_S")
        self.clock = clock
        self.seed = knobs.env_str("DAFT_TPU_FAULT_SEED") \
            if seed is None else seed
        self._lock = threading.Lock()
        self._fails: Dict[str, int] = defaultdict(int)
        self._quarantined_until: Dict[str, float] = {}

    # ---- circuit breaker -------------------------------------------
    def record_failure(self, worker_id: str) -> bool:
        """→ True when this failure opened the worker's quarantine."""
        with self._lock:
            self._fails[worker_id] += 1
            if self._fails[worker_id] >= self.quarantine_after \
                    and worker_id not in self._quarantined_until:
                self._quarantined_until[worker_id] = \
                    self.clock() + self.quarantine_s
                self._fails[worker_id] = 0
                count("quarantined")
                return True
        return False

    def record_success(self, worker_id: str) -> None:
        with self._lock:
            self._fails[worker_id] = 0

    def is_quarantined(self, worker_id: str) -> bool:
        """Timed re-admission happens here: an expired quarantine is
        lifted (and counted) on the next eligibility check."""
        with self._lock:
            until = self._quarantined_until.get(worker_id)
            if until is None:
                return False
            if until <= self.clock():
                del self._quarantined_until[worker_id]
                count("readmitted")
                return False
            return True

    def eligible(self, states: list, exclude: Optional[str] = None) -> list:
        """Quarantine-free placement candidates. Degrades gracefully:
        never returns an empty list (with every worker quarantined or
        excluded, refusing to place would deadlock the query)."""
        out = [s for s in states
               if s.worker.id != exclude
               and not self.is_quarantined(s.worker.id)]
        if not out:
            out = [s for s in states if s.worker.id != exclude] or \
                list(states)
        return out

    # ---- backoff ---------------------------------------------------
    def backoff_s(self, key: str, attempt: int) -> float:
        """Exponential backoff with deterministic jitter (0.5–1.5×,
        hashed from the seed + task key + attempt, so chaos replays pace
        identically)."""
        base = min(self.backoff_base * (2 ** max(attempt - 1, 0)),
                   self.backoff_cap)
        return base * (0.5 + _hash01(self.seed, "backoff", key, attempt))


# ------------------------------------------------------ shuffle lineage


class ShuffleLineage:
    """Receipt → producing-map-task registry plus the old→new address
    translation built up by recoveries (Exoshuffle-style lineage: the
    scheduler re-executes only the lost map task and rewrites downstream
    fetch specs)."""

    def __init__(self):
        # RLock: a recompute's own fetch failures recover recursively on
        # the same thread; the lock also dedups concurrent recoveries of
        # the same source. NOTE the lock-order sanitizer
        # (DAFT_TPU_SANITIZE=1) reports recover()'s retry-backoff sleeps
        # as blocking-while-held — intentional: holding the lock across
        # the recompute is what makes N consumers of a lost source wait
        # for ONE recompute instead of racing N. Per-source locks would
        # unserialize recoveries of unrelated sources; revisit if that
        # ever shows up as real contention.
        self._lock = threading.RLock()
        self._producer: Dict[Tuple[str, str], object] = {}
        self._translation: Dict[Tuple[str, str], Tuple[str, str]] = {}

    def register(self, receipt, task) -> None:
        with self._lock:
            self._producer[(receipt.address, receipt.shuffle_id)] = task

    def resolve(self, src: Tuple[str, str]) -> Tuple[str, str]:
        with self._lock:
            seen = set()
            while src in self._translation and src not in seen:
                seen.add(src)
                src = self._translation[src]
        return src

    def chain(self, src: Tuple[str, str]) -> List[Tuple[str, str]]:
        """``src`` plus every translated successor (for cleanup: all
        generations of a recovered output get unregistered)."""
        out = [src]
        with self._lock:
            seen = {src}
            while src in self._translation:
                src = self._translation[src]
                if src in seen:
                    break
                seen.add(src)
                out.append(src)
        return out

    def translate_spec(self, spec):
        from .worker import FetchSpec
        sources = [self.resolve(tuple(s)) for s in spec.sources]
        if sources == [tuple(s) for s in spec.sources]:
            return spec
        return FetchSpec(sources, spec.partition, keys=spec.keys)

    def translate_inputs(self, stage_inputs: Dict[int, object]
                         ) -> Dict[int, object]:
        from .worker import FetchSpec
        if not any(isinstance(v, FetchSpec) for v in stage_inputs.values()):
            return stage_inputs
        return {k: (self.translate_spec(v) if isinstance(v, FetchSpec)
                    else v)
                for k, v in stage_inputs.items()}

    def recover(self, src: Tuple[str, str],
                rerun: Callable[[object], object]) -> bool:
        """Recompute the map task that produced ``src`` and record the
        translation. → True when the source is recovered (or someone
        already recovered it); False when no lineage exists for it."""
        with self._lock:
            if self.resolve(src) != src:
                return True  # concurrent recovery already replaced it
            task = self._producer.get(src)
            if task is None:
                return False
            receipt = rerun(task)
            if receipt is None or not hasattr(receipt, "shuffle_id"):
                return False
            self._producer[(receipt.address, receipt.shuffle_id)] = task
            self._translation[src] = (receipt.address, receipt.shuffle_id)
        count("recomputed_map_tasks")
        return True


# ---------------------------------------------- fetch-retry bookkeeping


class FetchRetryState:
    """Shared fetch-failure bookkeeping for one consumer (a reduce task
    attempt series, or one driver-fetched partition). Progress-aware: a
    recovered source restarts its count under the recomputed shuffle id,
    so only a source failing repeatedly with NO progress (or a
    pathological total) exhausts the budget — a multi-source consumer
    may legitimately recover several sources in sequence."""

    def __init__(self, policy: "RetryPolicy"):
        self.policy = policy
        self.fails: Dict[Tuple[str, str], int] = defaultdict(int)
        self.attempts = 0

    def should_recover(self, exc: "ShuffleFetchError") -> bool:
        """Record one fetch failure. Raises ``exc`` when the budget is
        out; → True when the source failed again after a plain refetch
        (its data is gone — recompute via lineage)."""
        count("fetch_failures")
        self.attempts += 1
        src = (exc.address, exc.shuffle_id)
        self.fails[src] += 1
        if self.fails[src] > self.policy.max_retries + 2 \
                or self.attempts > 10 * (self.policy.max_retries + 1):
            raise exc
        return self.fails[src] >= 2


# -------------------------------------------------- resilience context


class ResilienceContext:
    """Per-query bundle: policy state (quarantines persist across
    stages), lineage registry, and recovery recursion depth."""

    MAX_RECOVERY_DEPTH = 8

    def __init__(self, policy: Optional[RetryPolicy] = None):
        self.policy = policy or RetryPolicy()
        self.lineage = ShuffleLineage()
        self.depth = 0  # mutated only under the lineage lock


# ------------------------------------------------------ task supervisor


@dataclasses.dataclass
class _Run:
    idx: int
    worker_id: str
    t0: float
    attempt: int
    backup: bool


_TICK = 0.05


class TaskSupervisor:
    """Drives one batch of StageTasks to completion under the retry /
    quarantine / lineage-recovery / speculation policy. Results come back
    in task order; fatal failures (retries exhausted, fail-fast,
    unrecoverable fetch) raise."""

    def __init__(self, ctx: ResilienceContext, manager, scheduler):
        self.ctx = ctx
        self.manager = manager
        self.scheduler = scheduler

    # ---- main loop -------------------------------------------------
    def run(self, tasks: List, speculate: bool = True) -> List:
        import concurrent.futures as cf
        from ..analysis import knobs
        if len(tasks) > 1 and knobs.env_bool("DAFT_TPU_CHAOS_SERIALIZE"):
            # exact-replay mode: one task (with all its retries and
            # recoveries) at a time, so every injection decision happens
            # in a deterministic total order — concurrent recovery of a
            # crashed shared source otherwise advances other consumers'
            # attempt counters in interleaving-dependent ways
            out: List = []
            for t in tasks:
                out.extend(self.run([t], speculate=False))
            return out
        pol = self.ctx.policy
        n = len(tasks)
        results: List = [None] * n
        done = [False] * n
        attempts = [0] * n           # compute-failure retries used
        fetch_states = [FetchRetryState(pol) for _ in range(n)]
        sig_workers: List[Dict] = [defaultdict(set) for _ in range(n)]
        has_backup = [False] * n
        live = [0] * n               # in-flight runs per task
        runs: Dict = {}              # future -> _Run
        abandoned: Dict = {}         # future -> _Run (discard on arrival)
        delayed: List = []           # (due_time, idx, attempt, exclude)

        # tracing: one parent span per TASK (stable fault-key id), child
        # attempt/retry/speculation/recompute spans hang off it; all ids
        # hash planner-minted identities, so chaos replays mint the same
        from .. import tracing as _tr
        tctx = _tr.current()
        trec = tctx.recorder if tctx is not None else None
        t_span: List = [None] * n    # (span_id, start_unix_us) per task
        self._trace = (trec, tctx.span_id if tctx is not None else None)

        def task_span(idx: int):
            if trec is None:
                return None
            if t_span[idx] is None:
                key = tasks[idx].fault_key \
                    or f"s{tasks[idx].stage_id}.t{tasks[idx].task_idx}"
                t_span[idx] = (trec.unique_span_id(f"task:{key}"),
                               _tr._now_us())
            return t_span[idx][0]

        def end_task_span(idx: int, status: str = "ok") -> None:
            if trec is None or t_span[idx] is None:
                return
            sid, t0 = t_span[idx]
            key = tasks[idx].fault_key or str(idx)
            trec.add("task", sid, tctx.span_id, t0,
                     _tr._now_us() - t0, attrs={"task": key},
                     status=status)

        def launch(idx: int, attempt: int, exclude: Optional[str] = None,
                   backup: bool = False) -> None:
            task = tasks[idx]
            fkey = task.fault_key or f"s{task.stage_id}.t{task.task_idx}"
            trace_ctx = None
            if trec is not None:
                parent = task_span(idx)
                run_id = trec.unique_span_id(
                    f"run:{fkey}#a{attempt}{'b' if backup else ''}")
                trace_ctx = (trec.trace_id, run_id, parent)
            dtask = dataclasses.replace(
                task,
                stage_inputs=self.ctx.lineage.translate_inputs(
                    task.stage_inputs),
                fault_key=fkey,
                attempt=attempt + (500 if backup else 0),
                trace_ctx=trace_ctx)
            states = pol.eligible(self.manager.snapshot(), exclude=exclude)
            wid = self.scheduler.pick(dtask, states)
            if backup and trec is not None:
                _tr.event("task:speculative", key=f"spec:{fkey}",
                          attrs={"worker": wid},
                          ctx=_tr.SpanContext(trec, task_span(idx)))
            fut = self.manager.dispatch(dtask, wid)
            live[idx] += 1
            if backup:
                has_backup[idx] = True
                count("speculative_launched")
            runs[fut] = _Run(idx, wid, pol.clock(), attempt, backup)

        durations: List[float] = []
        for i in range(n):
            launch(i, 0)

        try:
            self._run_loop(tasks, pol, results, done, attempts,
                           fetch_states, sig_workers, has_backup, live,
                           runs, abandoned, delayed, durations, launch,
                           task_span, end_task_span, speculate)
        except BaseException:
            # fatal failure (retries exhausted / fail-fast / recovery
            # dead end): still close every started task span so no
            # recorded child span is left orphaned
            for i in range(n):
                if not done[i]:
                    end_task_span(i, status="error")
            raise
        return results

    def _run_loop(self, tasks, pol, results, done, attempts, fetch_states,
                  sig_workers, has_backup, live, runs, abandoned, delayed,
                  durations, launch, task_span, end_task_span,
                  speculate) -> None:
        import concurrent.futures as cf

        while not all(done):
            if runs:
                ready, _ = cf.wait(list(runs), timeout=_TICK,
                                   return_when=cf.FIRST_COMPLETED)
            else:
                ready = ()
                if not delayed:  # defensive: nothing in flight or queued
                    raise RuntimeError("task supervisor stalled with "
                                       "unfinished tasks")
                time.sleep(_TICK)

            for fut in ready:
                run = runs.pop(fut)
                live[run.idx] -= 1
                if done[run.idx]:
                    self._discard(fut)  # losing twin: idempotent discard
                    continue
                exc = fut.exception()
                if exc is None:
                    res = fut.result()
                    results[run.idx] = res
                    done[run.idx] = True
                    durations.append(pol.clock() - run.t0)
                    pol.record_success(run.worker_id)
                    end_task_span(run.idx)
                    if has_backup[run.idx]:
                        count("speculative_wins" if run.backup
                              else "speculative_losses")
                    if hasattr(res, "shuffle_id"):  # map receipt
                        self.ctx.lineage.register(res, tasks[run.idx])
                    continue
                if live[run.idx] > 0:
                    # a twin is still running — it IS the retry; only
                    # charge the worker's health record (never for a
                    # fetch failure: the worker is healthy, its INPUT
                    # is gone)
                    if not isinstance(exc, ShuffleFetchError):
                        pol.record_failure(run.worker_id)
                    continue
                # the last twin died: this speculation cycle is over — a
                # relaunched attempt is a fresh primary (counts no
                # speculative win/loss, may speculate again)
                has_backup[run.idx] = False
                self._handle_failure(run, exc, tasks, attempts,
                                     fetch_states, sig_workers, delayed,
                                     task_span_id=task_span(run.idx))

            now = pol.clock()
            for item in [d for d in delayed if d[0] <= now]:
                delayed.remove(item)
                launch(item[1], item[2], exclude=item[3])

            # deadlines + speculation over still-running attempts
            for fut, run in list(runs.items()):
                if done[run.idx]:
                    continue
                elapsed = now - run.t0
                if pol.task_timeout > 0 and elapsed > pol.task_timeout:
                    runs.pop(fut)
                    live[run.idx] -= 1
                    abandoned[fut] = run
                    count("task_timeouts")
                    if live[run.idx] > 0:
                        pol.record_failure(run.worker_id)
                        continue
                    has_backup[run.idx] = False  # cycle over, see above
                    self._handle_failure(
                        run,
                        TaskTimeout(
                            f"task exceeded DAFT_TPU_TASK_TIMEOUT="
                            f"{pol.task_timeout}s"),
                        tasks, attempts, fetch_states, sig_workers,
                        delayed, task_span_id=task_span(run.idx))
                    continue
                if (speculate and pol.speculative_multiplier > 0
                        and not run.backup and not has_backup[run.idx]
                        and live[run.idx] == 1 and len(durations) >= 2):
                    med = sorted(durations)[len(durations) // 2]
                    if elapsed > max(pol.speculative_multiplier * med,
                                     pol.speculative_min_s):
                        launch(run.idx, run.attempt,
                               exclude=run.worker_id, backup=True)

            for fut in [f for f in abandoned if f.done()]:
                abandoned.pop(fut)
                self._discard(fut)

    # ---- failure classification ------------------------------------
    def _handle_failure(self, run: _Run, exc: BaseException, tasks,
                        attempts, fetch_states, sig_workers,
                        delayed, task_span_id: Optional[str] = None
                        ) -> None:
        from .. import tracing as _tr
        pol = self.ctx.policy
        idx = run.idx
        trec, _root = getattr(self, "_trace", (None, None))
        tspan_ctx = _tr.SpanContext(trec, task_span_id) \
            if trec is not None and task_span_id else None
        fkey = tasks[idx].fault_key or str(idx)
        if isinstance(exc, ShuffleFetchError):
            # the executing worker is healthy — its INPUT is gone; don't
            # charge its circuit breaker or the fail-fast classifier
            if fetch_states[idx].should_recover(exc):
                # failed again after a plain refetch: the data is gone —
                # recompute only the producing map task (lineage);
                # attach the failing task's span so the recompute chain
                # nests under it in the merged trace
                with _tr.attach(tspan_ctx):
                    if not self.recover_source(
                            (exc.address, exc.shuffle_id), exc):
                        raise exc
            count("retries")
            if tspan_ctx is not None:
                _tr.event("task:retry",
                          key=f"retry:{fkey}#f{fetch_states[idx].attempts}",
                          attrs={"error": type(exc).__name__,
                                 "detail": str(exc)[:120]},
                          ctx=tspan_ctx)
            delayed.append((pol.clock()
                            + pol.backoff_s(tasks[idx].fault_key or str(idx),
                                            fetch_states[idx].attempts),
                            idx, run.attempt + 1, None))
            return
        if tspan_ctx is not None:
            _tr.event("task:retry", key=f"retry:{fkey}#a{run.attempt}",
                      attrs={"error": type(exc).__name__,
                             "detail": str(exc)[:120]}, ctx=tspan_ctx)
        if not isinstance(exc, TaskTimeout):
            # fail-fast classification — timeouts are exempt: their
            # signature is timing-dependent, not task-deterministic, so
            # they stay on the plain retry budget
            sig = f"{type(exc).__name__}: {str(exc)[:160]}"
            sig_workers[idx][sig].add(run.worker_id)
            if len(sig_workers[idx][sig]) >= 2:
                count("fail_fast")
                raise FailFastError(
                    f"task {tasks[idx].fault_key or idx} failed "
                    f"identically on workers "
                    f"{sorted(sig_workers[idx][sig])}: {sig}") from exc
        pol.record_failure(run.worker_id)
        attempts[idx] += 1
        if attempts[idx] > pol.max_retries:
            raise exc
        count("retries")
        delayed.append((pol.clock()
                        + pol.backoff_s(tasks[idx].fault_key or str(idx),
                                        attempts[idx]),
                        idx, run.attempt + 1, run.worker_id))

    # ---- lineage recovery ------------------------------------------
    def recover_source(self, src: Tuple[str, str],
                       exc: BaseException) -> bool:
        if self.ctx.depth >= ResilienceContext.MAX_RECOVERY_DEPTH:
            raise RuntimeError(
                "lineage recovery recursion limit reached") from exc

        def rerun(map_task):
            from .. import tracing as _tr
            self.ctx.depth += 1  # serialized under the lineage lock
            try:
                # recompute span: child of whatever span context the
                # caller attached (the consuming task's span); the child
                # supervisor's own task spans nest under it
                with _tr.span("lineage:recompute",
                              key=f"recompute:{map_task.fault_key or 'map'}",
                              attrs={"task": map_task.fault_key}):
                    child = TaskSupervisor(self.ctx, self.manager,
                                           self.scheduler)
                    group = getattr(map_task, "group_tasks", None)
                    if group is not None:
                        # collective stage: ALL-OR-NOTHING. The lost
                        # stream is one fused artifact of every member
                        # map task plus the intra-mesh collective — no
                        # per-map-task receipt exists to recover, so the
                        # whole exchange group re-executes and the merged
                        # receipt is rebuilt
                        # (topology.CollectiveExchangeGroup)
                        outs = child.run(list(group), speculate=False)
                        receipt = map_task.rebuild(outs)
                        count("collective_group_recoveries")
                        return receipt
                    return child.run([map_task], speculate=False)[0]
            finally:
                self.ctx.depth -= 1

        return self.ctx.lineage.recover(src, rerun)

    # ---- idempotent discard ----------------------------------------
    @staticmethod
    def _discard(fut) -> None:
        """Discard a duplicate/stale result: a losing speculative twin's
        (or timed-out attempt's) shuffle output is unregistered so it
        can't leak or be fetched."""
        try:
            if fut.cancelled() or fut.exception() is not None:
                return
            res = fut.result()
            if hasattr(res, "shuffle_id"):
                from .shuffle_service import unregister_remote
                unregister_remote(res.address, res.shuffle_id)
        except Exception:
            pass
