"""Multi-host worker: stage tasks over the wire.

Reference: flotilla's RaySwordfishActor — one worker process per node
receiving whole LocalPhysicalPlan fragments and streaming MicroPartitions
back (``daft/runners/flotilla.py:53``, ``scheduling/worker.rs``). Here the
transport is HTTP + cloudpickle for the plan fragment and Arrow IPC for the
result partitions; ``RemoteWorker`` plugs into the same ``Worker`` seam the
in-process workers use, so the scheduler/stage runner is transport-blind.
A worker process is started with ``python -m
daft_tpu.distributed.remote_worker --port N`` on each host."""

from __future__ import annotations

import concurrent.futures as cf
import http.server
import io
import pickle
import threading
import urllib.request
from typing import Dict, List

import pyarrow as pa
import pyarrow.ipc as paipc

from ..micropartition import MicroPartition
from ..recordbatch import RecordBatch
from .worker import StageTask, Worker


def _dumps(obj) -> bytes:
    try:
        import cloudpickle
        return cloudpickle.dumps(obj)
    except ImportError:
        return pickle.dumps(obj)


class RemoteTaskError(RuntimeError):
    """A remote task failed with an exception that could not be
    reconstructed locally; carries the remote type name + traceback."""


def _exc_payload(exc: BaseException) -> dict:
    """Serialize a worker-side exception so the scheduler's retry
    classification (and the user) sees the TRUE cause — the exception
    object itself when picklable (ShuffleFetchError must survive the
    wire for lineage recovery), plus type/message/traceback always."""
    import traceback
    try:
        pickled = _dumps(exc)
        pickle.loads(pickled)  # prove it round-trips
    except Exception:
        pickled = None
    return {"pickled": pickled, "type": type(exc).__name__,
            "message": str(exc), "traceback": traceback.format_exc()}


def _raise_remote(payload: dict) -> None:
    if payload.get("pickled"):
        try:
            exc = pickle.loads(payload["pickled"])
        except Exception:
            exc = None
        if isinstance(exc, BaseException):
            exc.remote_traceback = payload.get("traceback", "")
            raise exc
    raise RemoteTaskError(
        f"remote worker failed: {payload.get('type')}: "
        f"{payload.get('message')}\n{payload.get('traceback', '')}")


def _parts_to_ipc(parts: List[MicroPartition]) -> bytes:
    sink = io.BytesIO()
    offsets = []
    for p in parts:
        t = p.combined().to_arrow_table()
        w = paipc.new_stream(sink, t.schema)
        w.write_table(t)
        w.close()
        offsets.append(sink.tell())
    return pickle.dumps((offsets, sink.getvalue()))


def _parts_from_ipc(blob: bytes) -> List[MicroPartition]:
    offsets, payload = pickle.loads(blob)
    out = []
    start = 0
    for end in offsets:
        with paipc.open_stream(pa.BufferReader(payload[start:end])) as r:
            out.append(MicroPartition.from_recordbatch(
                RecordBatch.from_arrow_table(r.read_all())))
        start = end
    return out


class WorkerServer:
    """Executes posted stage fragments on a local streaming executor."""

    def __init__(self, port: int = 0, num_slots: int = 2,
                 host: str = "127.0.0.1", advertise_host: str = ""):
        self.num_slots = num_slots
        self._advertise = advertise_host or (
            "127.0.0.1" if host == "0.0.0.0" else host)
        # the worker's shuffle server must be reachable by the same route
        # as the worker itself — reduce tasks on OTHER hosts fetch from it;
        # configure it eagerly so no map task lazily boots a loopback one
        if host != "127.0.0.1":
            from .shuffle_service import configure_local_shuffle_server
            configure_local_shuffle_server(host, self._advertise)
        import os as _os

        # daft-tpu prefix so run_task's lane parser yields a stable
        # per-worker-process lane instead of "ThreadPoolExecutor-0"
        pool = cf.ThreadPoolExecutor(
            max_workers=num_slots,
            thread_name_prefix=f"daft-tpu-remote-{_os.getpid()}")
        # per-trace span buffers for foreign-driver tasks: refcounted so
        # two concurrent tasks of ONE trace share a buffer and each
        # response drains (never double-ships, never drops) its spans
        trace_bufs: Dict[str, list] = {}
        trace_bufs_lock = threading.Lock()

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                import time as _time

                from .. import tracing
                n = int(self.headers.get("Content-Length", 0))
                blob = self.rfile.read(n)
                temp_rec = None
                trace_ctx = None
                try:
                    task_plan, inputs_wire, shuffle_out, *rest = \
                        pickle.loads(blob)
                    fault_key = rest[0] if rest else ""
                    attempt = rest[1] if len(rest) > 1 else 0
                    trace_ctx = rest[2] if len(rest) > 2 else None
                    if trace_ctx is not None:
                        # foreign driver: buffer this task's spans
                        # locally and ship them back with the result.
                        # Refcounted get-or-create under ONE lock: two
                        # concurrent tasks of the same trace share the
                        # buffer (a bare check-then-register would let
                        # the loser's spans vanish into an unregistered
                        # recorder)
                        with trace_bufs_lock:
                            ent = trace_bufs.get(trace_ctx[0])
                            if ent is None \
                                    and tracing.recorder_for(
                                        trace_ctx[0]) is None:
                                ent = [tracing.SpanRecorder(trace_ctx[0]),
                                       0]
                                trace_bufs[trace_ctx[0]] = ent
                                # daft-lint: allow(recorder-registration-leak) -- refcounted
                                # pairing: the drain block after the try
                                # decrements under the same lock and the
                                # LAST task out unregisters; the path-
                                # insensitive solver cannot see the
                                # refcount invariant, and the registry
                                # cap bounds the worst case (a
                                # BaseException escaping do_POST kills
                                # the server anyway)
                                tracing.register_recorder(ent[0])
                            if ent is not None:
                                ent[1] += 1
                                temp_rec = ent[0]
                    # cloudpickle-serialized closures need cloudpickle's
                    # reducers importable on this host; plan fragments
                    # without closure UDFs decode with plain pickle
                    plan = pickle.loads(task_plan)
                    from .worker import StageTask, run_task
                    stage_inputs = {
                        k: (v[1] if v[0] == "fetch"
                            else _parts_from_ipc(v[1]))
                        for k, v in inputs_wire.items()}

                    def run():
                        return run_task(StageTask(
                            -1, plan, stage_inputs,
                            shuffle_out=shuffle_out,
                            fault_key=fault_key, attempt=attempt,
                            trace_ctx=trace_ctx))

                    # daft-lint: allow(unattributed-worker) -- run_task
                    # (worker.py, cross-module so the one-level summary
                    # can't see it) installs the span context itself from
                    # StageTask.trace_ctx; stats attribution is driver-
                    # side — this process ships spans back instead
                    res = pool.submit(run).result()
                    from .worker import ShuffleResult
                    if isinstance(res, ShuffleResult):
                        body = ("shuffle", res)
                    else:
                        body = ("parts", _parts_to_ipc(res))
                    status = 200
                except Exception as exc:
                    # serialize the REAL exception (type + traceback, and
                    # the object itself when picklable) so the scheduler's
                    # retry classification sees the true cause instead of
                    # an opaque text blob
                    body = ("error", _exc_payload(exc))
                    status = 500
                trace_payload = None
                if temp_rec is not None:
                    # this task's run_task has fully recorded by now;
                    # drain (not snapshot) so a concurrent sibling task's
                    # later spans ship with ITS response, and only the
                    # last task out unregisters the shared buffer
                    with trace_bufs_lock:
                        ent = trace_bufs.get(temp_rec.trace_id)
                        if ent is not None:
                            ent[1] -= 1
                            if ent[1] <= 0:
                                trace_bufs.pop(temp_rec.trace_id)
                                tracing.unregister_recorder(
                                    temp_rec.trace_id)
                        spans = temp_rec.drain()
                    trace_payload = {"spans": spans,
                                     "now_us": int(_time.time() * 1e6)}
                body = pickle.dumps(body + (trace_payload,))
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = http.server.ThreadingHTTPServer((host, port),
                                                       Handler)
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="daft-tpu-worker").start()

    @property
    def address(self) -> str:
        return f"http://{self._advertise}:{self._server.server_port}"

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class RemoteWorker(Worker):
    """Worker-seam client for a WorkerServer on another process/host."""

    def __init__(self, worker_id: str, address: str, num_slots: int = 2):
        self.id = worker_id
        self.address = address
        self.num_slots = num_slots
        self._pool = cf.ThreadPoolExecutor(
            max_workers=num_slots, thread_name_prefix=f"daft-tpu-{worker_id}")

    def submit(self, task: StageTask):
        return self._pool.submit(self._post, task)

    def _post(self, task: StageTask):
        import os
        import time as _time
        import urllib.error

        from .. import tracing
        from .resilience import active_fault_plan
        from .worker import FetchSpec
        rec = None
        if task.trace_ctx is not None:
            rec = tracing.recorder_for(task.trace_ctx[0])
        with tracing.attach(
                tracing.SpanContext(rec, task.trace_ctx[2])
                if rec is not None else None), \
                tracing.span("rpc:post",
                             key=f"rpc:{task.fault_key}#a{task.attempt}",
                             attrs={"worker": self.id}):
            plan = active_fault_plan()
            if plan is not None:  # injection site 3: remote-worker RPC
                plan.maybe_fail("rpc", task.fault_key or f"rpc.{self.id}",
                                attempt=task.attempt)
            inputs_wire = {}
            for k, v in task.stage_inputs.items():
                if isinstance(v, FetchSpec):
                    inputs_wire[k] = ("fetch", v)
                else:
                    inputs_wire[k] = ("parts", _parts_to_ipc(v))
            blob = pickle.dumps((_dumps(task.plan), inputs_wire,
                                 task.shuffle_out, task.fault_key,
                                 task.attempt, task.trace_ctx))
            req = urllib.request.Request(self.address, data=blob,
                                         method="POST")
            from ..analysis import knobs
            timeout = knobs.env_float("DAFT_TPU_WORKER_TIMEOUT")
            t0_us = int(_time.time() * 1e6)
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    body = r.read()
            except urllib.error.HTTPError as exc:
                # the body carries the serialized worker-side exception:
                # re-raise the original object (retry classification and
                # lineage recovery see the true cause) or a
                # RemoteTaskError with the remote type + traceback
                raw = exc.read()
                try:
                    kind, payload, *rest = pickle.loads(raw)
                except Exception:
                    raise RuntimeError(
                        "remote worker failed:\n"
                        + raw.decode(errors="replace")) from exc
                self._merge_spans(rec, rest, t0_us,
                                  int(_time.time() * 1e6))
                if kind == "error":
                    _raise_remote(payload)
                raise RuntimeError(
                    f"remote worker failed: {payload!r}") from exc
            kind, payload, *rest = pickle.loads(body)
            self._merge_spans(rec, rest, t0_us, int(_time.time() * 1e6))
            if kind == "error":
                _raise_remote(payload)
            if kind == "shuffle":
                return payload
            return _parts_from_ipc(payload)

    def _merge_spans(self, rec, rest, t0_us: int, t1_us: int) -> None:
        """Fold the worker's shipped spans into the driver's recorder,
        correcting their wall clock by the measured offset (worker send
        time vs the RPC's midpoint on the driver clock)."""
        tp = rest[0] if rest else None
        if rec is None or not tp:
            return
        offset_us = (t0_us + t1_us) // 2 - tp["now_us"]
        rec.add_remote(tp["spans"], offset_us, worker=self.address)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


def main(argv=None) -> int:
    import argparse
    import time
    p = argparse.ArgumentParser(prog="daft-tpu-worker")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--host", default="0.0.0.0",
                   help="bind address (default all interfaces)")
    p.add_argument("--advertise-host", default="",
                   help="hostname peers should use to reach this worker")
    args = p.parse_args(argv)
    srv = WorkerServer(args.port, args.slots, host=args.host,
                       advertise_host=args.advertise_host)
    print(f"daft-tpu worker on {srv.address}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
