"""PySpark front door: a SparkSession served by the embedded Spark Connect
server.

Reference: ``/root/reference/daft/pyspark/__init__.py`` — a SparkSession
shim that boots the engine's Spark Connect endpoint and points the pyspark
client at it. Same shape here: ``SparkSession.builder.local().getOrCreate()``
starts ``daft_tpu.connect``'s server and returns a real
``pyspark.sql.SparkSession`` wired to ``sc://127.0.0.1:<port>``. Gated on
pyspark being importable (it is an optional client-side dependency; the
server itself is dependency-free and unit-tested over raw grpc in
``tests/test_connect.py``).
"""

from __future__ import annotations

from typing import Optional


class SparkSessionBuilder:
    def __init__(self):
        self._remote: Optional[str] = None
        self._server = None

    def local(self) -> "SparkSessionBuilder":
        """Serve from an in-process daft_tpu Spark Connect server."""
        from .connect import start_server
        if self._server is not None:
            self._server.stop()  # re-calling local() must not leak one
        self._server = start_server()
        self._remote = self._server.address
        return self

    def remote(self, address: str) -> "SparkSessionBuilder":
        """Point at an already-running daft_tpu connect endpoint
        (``sc://host:port``)."""
        self._remote = address
        return self

    def getOrCreate(self):
        try:
            from pyspark.sql import SparkSession as _PySparkSession
        except ImportError as exc:
            raise ImportError(
                "daft_tpu.pyspark needs the optional 'pyspark' client "
                "package; the server side (daft_tpu.connect) works without "
                "it") from exc
        if self._remote is None:
            self.local()
        spark = _PySparkSession.builder.remote(self._remote).getOrCreate()
        if self._server is not None:
            # stop the embedded server when the client session closes
            orig_stop = spark.stop
            server = self._server

            def stop():
                try:
                    orig_stop()
                finally:
                    server.stop()

            spark.stop = stop
        return spark


class _SessionMeta(type):
    @property
    def builder(cls) -> SparkSessionBuilder:
        # a fresh builder per access, like pyspark's classproperty
        return SparkSessionBuilder()


class SparkSession(metaclass=_SessionMeta):
    """``SparkSession.builder.local().getOrCreate()`` → pyspark session
    against an embedded daft_tpu Spark Connect server."""
