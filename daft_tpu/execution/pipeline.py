"""Push-based morsel pipeline: per-operator workers over bounded channels.

The reference's local engine ("Swordfish",
``src/daft-local-execution/src/pipeline.rs:100-830``) runs every operator
as concurrent tasks connected by bounded channels: a dispatcher task
distributes input morsels to N worker tasks
(``dispatcher.rs:24-60`` — RoundRobin preserves order, Unordered doesn't,
Partitioned fans by key), and blocking sinks consume their whole input
through the same channel machinery before emitting
(``sinks/blocking_sink.rs:32-55``).

This module is that dataflow for the TPU engine, built on Python threads
(Arrow C++ and XLA release the GIL, so operator workers genuinely overlap;
the reference reaches the same place with tokio tasks):

- :class:`Channel` — bounded MPMC queue with producer-refcounted close and
  cooperative cancellation.
- :class:`PipelineContext` — per-query thread registry, first-error
  capture, cancellation fan-out.
- :class:`PushExecutor` — a :class:`LocalExecutor` whose ``_exec`` returns
  an iterator over an ACTIVELY-PUSHED output channel instead of a lazy
  generator:

  * map-shaped operators (Project/Filter/Explode/…) become real worker
    stages: one RoundRobin dispatcher thread, N kernel workers, one
    collector thread that restores order — per-operator worker counts and
    observed morsel sizes land in ``explain_analyze``/traces.
  * everything else (sources, sorts, joins, exchanges, device tiers,
    limits) runs its inherited handler inside a dedicated driver thread;
    the handler's child pulls transparently become channel reads, so every
    operator in the plan is an always-running concurrent component with
    backpressure — the push topology — while the TPU-specialized handlers
    stay single-sourced in ``executor.py``.

Cancellation: dropping the output iterator (or an operator error) cancels
the context; blocked producers wake within ``_POLL_S`` and unwind. The
first error wins and re-raises at the consumer.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable, Iterator, List, Optional

from ..micropartition import MicroPartition
from ..physical import plan as pp
from .executor import LocalExecutor

_POLL_S = 0.05  # cancellation latency bound for blocked channel ops
_REAGG_ROWS = 1 << 17  # partitioned-agg reducer: merge state every N rows


class PipelineCancelled(Exception):
    """Internal unwind signal — never escapes to the user."""


class PipelineContext:
    """Per-query registry of stage threads + first-error capture."""

    def __init__(self):
        self.cancelled = threading.Event()
        self.error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self.threads: List[threading.Thread] = []
        # the owning query's RuntimeStatsContext: installed on every
        # stage thread so shared-plane counters attribute to this query
        self.stats_ctx = None

    def fail(self, exc: BaseException):
        with self._lock:
            if self.error is None:
                self.error = exc
        self.cancelled.set()

    def cancel(self):
        self.cancelled.set()

    def spawn(self, fn: Callable[[], None], name: str) -> threading.Thread:
        t = threading.Thread(target=self._guard, args=(fn,), name=name,
                             daemon=True)
        with self._lock:
            self.threads.append(t)
        t.start()
        return t

    def _guard(self, fn):
        from .. import observability as obs
        from .. import tracing
        try:
            with obs.attributed(self.stats_ctx):
                # one span per stage-thread lifetime; the thread name is
                # deterministic (plan-derived), so span ids replay
                name = threading.current_thread().name
                with tracing.span("pipeline:stage", key=f"stage:{name}",
                                  attrs={"thread": name},
                                  lane="pipeline"):
                    fn()
        except PipelineCancelled:
            pass
        except BaseException as exc:  # noqa: BLE001 — first error wins
            self.fail(exc)

    def join(self, timeout: float = 5.0):
        for t in self.threads:
            t.join(timeout=timeout)


_DONE = object()


class Channel:
    """Bounded channel with producer-refcounted close.

    ``producers`` producers must each call :meth:`close`; when the last
    one does, ``consumers`` DONE markers are enqueued so every consumer's
    iteration terminates. Blocked puts/gets poll the context's cancel
    event (there is no way to interrupt a raw ``queue`` wait)."""

    def __init__(self, ctx: PipelineContext, capacity: int = 4,
                 producers: int = 1, consumers: int = 1):
        self.ctx = ctx
        self._q: queue.Queue = queue.Queue(maxsize=max(capacity, 1))
        self._producers = producers
        self._consumers = consumers
        self._lock = threading.Lock()

    def put(self, item) -> None:
        while True:
            if self.ctx.cancelled.is_set():
                raise PipelineCancelled()
            try:
                self._q.put(item, timeout=_POLL_S)
                return
            except queue.Full:
                continue

    def close(self) -> None:
        with self._lock:
            self._producers -= 1
            if self._producers > 0:
                return
        for _ in range(self._consumers):
            try:
                self.put(_DONE)
            except PipelineCancelled:
                return

    def __iter__(self) -> Iterator:
        while True:
            if self.ctx.cancelled.is_set():
                raise PipelineCancelled()
            try:
                item = self._q.get(timeout=_POLL_S)
            except queue.Empty:
                continue
            if item is _DONE:
                return
            yield item


def _default_workers() -> int:
    return max(min((os.cpu_count() or 4), 8), 2)


# map-shaped operators: (node type name) -> kernel factory. Each returns a
# per-morsel function; the stage machinery provides dispatcher / workers /
# in-order collection. Per-partition semantics match executor.py's
# _ordered_parallel bodies (single-sourced there for the interpreter).
def _map_kernel(node) -> Optional[Callable[[MicroPartition], MicroPartition]]:
    name = type(node).__name__
    if name == "Project":
        return lambda p: p.eval_expression_list(node.exprs)
    if name == "UDFProject":
        return lambda p: p.eval_expression_list(node.exprs)
    if name == "Filter":
        return lambda p: p.filter(node.predicate)
    if name == "Explode":
        return lambda p: p.explode(node.exprs)
    if name == "Unpivot":
        return lambda p: p.unpivot(node.ids, node.values,
                                   node.variable_name, node.value_name)
    if name == "Dedup":
        return lambda p: p.distinct(node.on)
    if name == "Sample":
        if node.fraction is not None:
            return lambda p: p.sample(fraction=node.fraction, size=None,
                                      with_replacement=node.with_replacement,
                                      seed=node.seed)
        return lambda p: p.head(node.size)
    if name == "Window":
        from ..window_exec import run_window
        return lambda p: MicroPartition.from_recordbatch(
            run_window(p.combined(), node))
    if name == "Pivot":
        return lambda p: p.pivot(node.group_by, node.pivot_col,
                                 node.value_col,
                                 node.names).cast_to_schema(node.schema())
    if name == "Aggregate":
        # per-partition agg (partial stage, or final over hash buckets) is
        # map-shaped; the fused device tier (DeviceFragmentAgg) stays a
        # driver stage
        return lambda p: p.agg(node.aggs, node.group_by) \
            .cast_to_schema(node.schema())
    return None


def _map_workers(node) -> int:
    if type(node).__name__ == "UDFProject" and node.concurrency:
        return max(int(node.concurrency), 1)
    return _default_workers()


# The final-stage agg ops the fused reducer can merge are the associative
# self-merges single-sourced in ``aggs.AGG_DECOMPOSITION``: re-applying the
# op over its own output column merges two partial states correctly, which
# is what makes the reference's Partitioned dispatcher + grouped_aggregate
# sink sound (``dispatcher.rs:24-60``, ``sinks/grouped_aggregate.rs:54-151``).
# The merge expressions come from ``aggs.merge_exprs_for`` (shared with the
# distributed map-side shuffle combine and the streaming reduce-side merge
# agg).

#: decline the fused dispatcher when the evidence predicts more groups
#: than this: the spill-bounded exchange path aggregates each bucket
#: exactly once, while the fused reducer's LSM merges cost O(log n) passes
#: over a state it must also hold in RAM. Measured crossover on TPC-H:
#: 15M groups (SF10 Q18) fused wins 34.5s vs 46.5s; 150M groups (SF100
#: Q18) fused loses 528s vs 207s. Evidence, best-first: parquet-footer
#: NDV; else the planner's row estimate (an upper bound on groups — a
#: near-unique-key groupby on a huge in-memory source must not default
#: into the fused reducer's unbounded group state, the r5 OOM hole);
#: either way a configured DAFT_TPU_MEMORY_LIMIT additionally declines
#: predicted group state that cannot fit the budget.
_FUSE_MAX_GROUPS = 32_000_000

#: resident bytes one group row costs the fused reducer (key + agg state
#: columns at ~8B each plus Arrow overhead), times the ~2× LSM headroom —
#: deliberately coarse; only the order of magnitude gates anything
_FUSE_BYTES_PER_GROUP = 16


def _fused_groups_admissible(node) -> bool:
    """Decline-if-huge gate for the fused partitioned-agg dispatcher."""
    ndv = getattr(node, "group_ndv", None)
    if ndv is None:
        ndv = getattr(node, "group_rows_est", None)
    if ndv is None:
        return True
    if ndv > _FUSE_MAX_GROUPS:
        return False
    from .memory import memory_limit_bytes
    budget = memory_limit_bytes()
    if budget is not None:
        est = _est_state_bytes(node)
        if est is not None and est > budget:
            return False
    return True


def _est_state_bytes(node):
    """Predicted resident group-state bytes for this final agg (the
    fused reducer's working set): NDV evidence × row width × the coarse
    per-group cost — None without evidence."""
    ndv = getattr(node, "group_ndv", None)
    if ndv is None:
        ndv = getattr(node, "group_rows_est", None)
    if ndv is None:
        return None
    width = max(1 + len(getattr(node, "group_by", ())
                        ) + len(getattr(node, "aggs", ())), 2)
    return float(ndv) * width * _FUSE_BYTES_PER_GROUP


def _partitioned_agg_info(node, cfg=None):
    """When ``node`` is a final grouped Aggregate over an engine-inserted
    hash Exchange whose final aggs are associative self-merges, return
    (exchange_child, key_exprs, merge_aggs, spill, est_state_bytes) for
    the fused partitioned-agg stage; else None. ``merge_aggs`` re-merge
    two batches of FINAL-schema state: for a final agg
    ``op(col(p)).alias(out)``, the merge is ``op(col(out)).alias(out)``.

    ``spill`` selects the spill-partitioned reducer (round 19): a group
    state the budget can't hold streams through a rotated-radix spill
    store and merges per bucket on read (``AGG_DECOMPOSITION`` self-merge
    semantics) — peak RSS ≈ budget + one bucket — instead of declining
    the fusion (``DAFT_TPU_SPILL_AGG=0`` restores the decline)."""
    from ..aggs import merge_exprs_for
    from . import out_of_core as ooc
    if not (isinstance(node, pp.Aggregate) and node.mode == "final"
            and node.group_by):
        return None
    ch = node.children[0]
    if not (isinstance(ch, pp.Exchange) and ch.kind == "hash"
            and ch.engine_inserted):
        return None
    mode = ooc.spill_agg_mode(cfg)
    est_state = _est_state_bytes(node)
    if _fused_groups_admissible(node):
        spill = mode == "1"
    elif mode == "0":
        return None  # legacy decline → the spill-bounded exchange plan
    else:
        # the in-memory reducer's state would not fit (or NDV evidence
        # is past the fuse ceiling): spill-partitioned reducer
        spill = True
    # shared subplans stream through the executor's shared buffer — the
    # fusion would bypass it
    if getattr(ch, "shared_consumers", 1) > 1 \
            or getattr(node, "shared_consumers", 1) > 1:
        return None
    merge = merge_exprs_for(node.aggs, alias_to="out")
    if merge is None:
        return None
    return ch.children[0], list(ch.by), merge, spill, est_state


class PushExecutor(LocalExecutor):
    """Push-dataflow executor: every plan node is an always-running stage.

    Inherits every operator implementation from :class:`LocalExecutor`;
    only the wiring changes — ``_exec`` spawns the node's stage threads and
    returns an iterator over its bounded output channel, so a handler's
    ``self._exec(child)`` transparently becomes a channel subscription and
    the whole plan runs concurrently with backpressure."""

    #: channel capacity between stages, in morsels. Small: backpressure is
    #: the point; each buffered morsel is ~default_morsel_size rows.
    CHANNEL_CAPACITY = 4

    def __init__(self):
        super().__init__()
        self.pipe = PipelineContext()

    # ------------------------------------------------------------- entry
    def run(self, plan: pp.PhysicalPlan,
            stage_inputs=None) -> Iterator[MicroPartition]:
        if stage_inputs:
            self.stage_inputs = stage_inputs
        from .. import observability as obs
        from . import cancellation as _cxl
        self.stats = obs.new_query_stats()
        self.stats.plan = plan
        self.pipe.stats_ctx = self.stats
        xdir = obs.xplane_trace_dir()
        tok = self.cancel_token
        if tok is not None:
            # a fired token must unblock EVERY stage (channels poll the
            # pipeline's cancel event), not just the driver loop
            tok.add_callback(self.pipe.cancel)

        def gen():
            xtrace = obs._XplaneTrace(xdir) if xdir else None
            try:
                out = self._exec(plan)
                while True:
                    try:
                        with obs.attributed(self.stats):
                            mp = next(out)
                    except StopIteration:
                        break
                    except PipelineCancelled:
                        break
                    yield mp
                if tok is not None and tok.is_set():
                    raise _cxl.QueryCancelled(
                        tok.reason or "query cancelled")
                if self.pipe.error is not None:
                    raise self.pipe.error
            finally:
                self.pipe.cancel()
                if xtrace is not None:
                    xtrace.stop()
                self.stats.finish()
                obs.set_last_stats(self.stats)
                path = obs.chrome_trace_path()
                if path and self.stats.tracer is not None:
                    self.stats.tracer.dump(path)
        return gen()

    # ------------------------------------------------------------ stages
    # _exec (inherited) routes multi-consumer nodes through the shared
    # buffer; everything else lands here and becomes a stage
    def _exec_node(self, node: pp.PhysicalPlan) -> Iterator[MicroPartition]:
        pagg = _partitioned_agg_info(node, self.cfg)
        if pagg is not None:
            out = self._partitioned_agg_stage(node, *pagg)
        elif isinstance(node, pp.Aggregate) \
                and self._streamed_agg_input(node):
            # a streaming parallel-fetch stage input yields one morsel per
            # map SOURCE (not hash-disjoint) — the per-morsel map kernel
            # would duplicate groups; run the inherited streaming
            # merge-agg handler on a driver stage instead
            out = self._driver_stage(node)
        else:
            kernel = _map_kernel(node)
            if kernel is not None:
                out = self._map_stage(node, kernel)
            else:
                out = self._driver_stage(node)
        from ..analysis import plan_sanitizer
        wrapped = plan_sanitizer.wrap_node(node, iter(out))
        if self.stats is not None:
            return self.stats.instrument(node, wrapped)
        return wrapped

    def _driver_stage(self, node) -> Channel:
        """One dedicated thread runs the inherited handler generator and
        pushes its output — sources, sinks, joins, exchanges, device tiers
        and limits keep their single-sourced implementations while still
        living inside the push topology."""
        h = getattr(LocalExecutor, "_exec_" + type(node).__name__, None)
        if h is None:
            raise NotImplementedError(f"executor for {type(node).__name__}")
        out = Channel(self.pipe, self.CHANNEL_CAPACITY)

        def drive():
            # fail() BEFORE close(): close enqueues the DONE marker, and a
            # consumer that drains it must already see ctx.error — the
            # reverse order can end a failing query as a clean truncated
            # stream
            try:
                for mp in h(self, node):
                    out.put(mp)
            except PipelineCancelled:
                pass
            except BaseException as exc:  # noqa: BLE001
                self.pipe.fail(exc)
            finally:
                out.close()
        self.pipe.spawn(drive, name=f"drv-{type(node).__name__}")
        return out

    def _partitioned_agg_stage(self, node, exchange_child, by,
                               merge_aggs, spill: bool = False,
                               est_state=None) -> Channel:
        """Partitioned-by-hash dispatcher fused with the final grouped
        aggregation (reference ``dispatcher.rs:24-60`` Partitioned +
        ``sinks/grouped_aggregate.rs:54-151``): the dispatcher hashes each
        incoming partial-agg morsel into k slices, worker i streams
        partition i, incrementally merging its state every
        ``_REAGG_ROWS`` buffered rows, and emits its final state at
        close. Replaces Exchange(hash) + per-bucket map agg: no
        materialization barrier, k concurrent reducers, and the final agg
        starts before the child finishes.

        Memory: the un-merged buffer is bounded by
        ``max(_REAGG_ROWS, len(state))`` — the LSM-style amortization lets
        it grow to the current state size, so peak residency is ~2× the
        worker's group cardinality (proportional to the output this
        reducer must materialize anyway). With ``spill`` (round 19) the
        reducer never holds its state at all: every ``_REAGG_ROWS`` the
        buffer collapses to FINAL-schema partial states that radix-fan
        (rotated — the dispatcher already consumed ``h % k``) into a
        per-reducer spill store, and each bucket self-merges ON READ via
        the ``AGG_DECOMPOSITION`` merge expressions — an unbounded-NDV
        group-by streams in one pass at peak RSS ≈ budget + one bucket,
        recursing (bounded) on a bucket skew redominates."""
        k = _default_workers()
        if self.stats is not None:
            self.stats.register(node).workers = k
        if self.cfg.enable_aqe:
            self._aqe().record_replan(
                f"fused partitioned agg: hash shuffle elided → {k} reducers"
                + (" (spill-partitioned)" if spill else ""))
        child = self._exec(exchange_child)
        in_q = [Channel(self.pipe, 2) for _ in range(k)]
        out = Channel(self.pipe, self.CHANNEL_CAPACITY, producers=k)
        name = type(node).__name__

        def dispatch():
            try:
                for mp in child:
                    for i, part in enumerate(mp.partition_by_hash(by, k)):
                        if len(part):
                            in_q[i].put(part)
            except PipelineCancelled:
                pass
            except BaseException as exc:  # noqa: BLE001
                self.pipe.fail(exc)  # before close — see _driver_stage
            finally:
                for q in in_q:
                    q.close()

        def reducer(i):
            state: Optional[MicroPartition] = None
            buf: List[MicroPartition] = []
            rows = 0

            def merge():
                nonlocal state, buf, rows
                if not buf:
                    return
                fresh = buf[0].concat(buf[1:]) if len(buf) > 1 else buf[0]
                fresh = fresh.agg(node.aggs, node.group_by) \
                    .cast_to_schema(node.schema())
                state = fresh if state is None else \
                    state.concat([fresh]).agg(merge_aggs, node.group_by) \
                    .cast_to_schema(node.schema())
                buf, rows = [], 0

            try:
                for mp in in_q[i]:
                    buf.append(mp)
                    rows += len(mp)
                    # merge only once the buffer rivals the state (LSM-style
                    # amortization): every row then joins O(log n) merges.
                    # A fixed threshold is quadratic on near-unique keys —
                    # SF100 Q18 (groups ≈ rows) spent 5.6× host time
                    # re-merging a 100M-row state every 128k rows
                    if rows >= max(_REAGG_ROWS,
                                   0 if state is None else len(state)):
                        merge()
                merge()
                if state is not None and len(state):
                    out.put(state)
            except PipelineCancelled:
                pass
            except BaseException as exc:  # noqa: BLE001
                self.pipe.fail(exc)
            finally:
                out.close()

        def spill_reducer(i):
            from ..expressions import col as _col
            from . import memory, out_of_core as ooc, spill_io
            skeys = [_col(g.name()) for g in node.group_by]
            m = ooc.agg_state_fanout(est_state, k, self.cfg)
            depth_max = ooc.spill_max_depth(self.cfg)
            bucket_budget = max(ooc.pair_budget_bytes() // k, 16 << 10)
            store = memory.PartitionedSpillStore(
                m, budget=max(memory.breaker_budget_bytes() // k,
                              16 << 10))
            buf: List[MicroPartition] = []
            rows = 0

            def flush():
                nonlocal buf, rows
                if not buf:
                    return
                fresh = buf[0].concat(buf[1:]) if len(buf) > 1 else buf[0]
                fresh = fresh.agg(node.aggs, node.group_by) \
                    .cast_to_schema(node.schema())
                for j, piece in enumerate(ooc.radix_split(
                        fresh.combined(), skeys, m, 1)):
                    if len(piece):
                        store.push(j, piece)
                buf, rows = [], 0

            try:
                for mp in in_q[i]:
                    buf.append(mp)
                    rows += len(mp)
                    if rows >= _REAGG_ROWS:
                        flush()
                flush()
                store.finalize()
                # bucket reads prefetch-pipelined like the grace join's
                # pair reads: bucket j+1 decodes while j merges
                for batches in spill_io.prefetch_ordered(
                        (lambda j=j: store.bucket_batches(j)
                         for j in range(m)),
                        spill_io.read_prefetch_window(self.cfg)):
                    if not batches:
                        continue
                    for state in ooc.merge_spilled_agg_bucket(
                            batches, merge_aggs, node.group_by,
                            node.schema(), skeys, 1, depth_max,
                            bucket_budget):
                        if len(state):
                            out.put(state)
            except PipelineCancelled:
                pass
            except BaseException as exc:  # noqa: BLE001
                self.pipe.fail(exc)
            finally:
                store.close()
                out.close()

        self.pipe.spawn(dispatch, name=f"dsp-{name}")
        body = spill_reducer if spill else reducer
        for i in range(k):
            self.pipe.spawn(lambda i=i: body(i), name=f"red-{name}-{i}")
        return out

    def _map_stage(self, node, kernel) -> Channel:
        """RoundRobin dispatcher → N kernel workers → in-order collector
        (``dispatcher.rs:38-131``: RR to per-worker channels preserves
        global order when read back round-robin)."""
        k = _map_workers(node)
        if self.stats is not None:
            self.stats.register(node).workers = k
        child = self._exec(node.children[0])
        in_q = [Channel(self.pipe, 2) for _ in range(k)]
        out_q = [Channel(self.pipe, 2) for _ in range(k)]
        out = Channel(self.pipe, self.CHANNEL_CAPACITY)
        name = type(node).__name__

        def dispatch():
            try:
                i = 0
                for mp in child:
                    in_q[i % k].put(mp)
                    i += 1
            except PipelineCancelled:
                pass
            except BaseException as exc:  # noqa: BLE001
                self.pipe.fail(exc)  # before close — see _driver_stage
            finally:
                for q in in_q:
                    q.close()

        def worker(i):
            try:
                for mp in in_q[i]:
                    out_q[i].put(kernel(mp))
            except PipelineCancelled:
                pass
            except BaseException as exc:  # noqa: BLE001
                self.pipe.fail(exc)
            finally:
                out_q[i].close()

        def collect():
            try:
                iters = [iter(q) for q in out_q]
                alive = list(range(k))
                while alive:
                    nxt = []
                    for i in alive:
                        try:
                            out.put(next(iters[i]))
                            nxt.append(i)
                        except StopIteration:
                            pass
                    alive = nxt
            except PipelineCancelled:
                pass
            except BaseException as exc:  # noqa: BLE001
                self.pipe.fail(exc)
            finally:
                out.close()

        self.pipe.spawn(dispatch, name=f"dsp-{name}")
        for i in range(k):
            self.pipe.spawn(lambda i=i: worker(i), name=f"wrk-{name}-{i}")
        self.pipe.spawn(collect, name=f"col-{name}")
        return out
