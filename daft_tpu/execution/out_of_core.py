"""Out-of-core execution: grace hash join + spill-partitioned aggregation.

The breaker tier (``execution/memory.py``) bounds how much a blocking
sink *buffers*, but until this module the per-partition WORK units — one
join bucket pair, one final-agg bucket — were still loaded whole: a
bucket that outgrew the budget (skew, under-partitioned SF10 inputs) was
an OOM, not a price. This module makes partitioned execution recursive
(Exoshuffle's composition of out-of-core operators from shuffle
primitives):

- **grace hash join** — both sides radix-partition by the join-key hash
  chain into :class:`~.memory.PartitionedSpillStore` buckets, streamed
  straight off the child (for scans: straight off the read planner's
  byte-range batches — no whole-table materialize, the r9 contract);
  bucket PAIRS join one at a time, and a pair that still exceeds the
  pair budget re-partitions with a ROTATED radix (rehash of the hash —
  depth d is decorrelated from depth d-1's ``h % n`` residue) up to
  ``DAFT_TPU_SPILL_MAX_DEPTH``. Per-pair joins reuse the ordinary
  ``hash_join`` kernel stack, so the r12 device hash/sort kernels (and
  their overflow re-dispatch contract) apply per partition unchanged.
- **spill-partitioned aggregation** — the fused partitioned-agg reducer
  (``execution/pipeline.py``) spills overflowing group state as PARTIAL
  state rows into a rotated-radix store and merges each bucket on read
  with the ``AGG_DECOMPOSITION`` self-merge expressions, so an
  unbounded-NDV group-by streams in one pass at peak RSS ≈ budget + one
  bucket (recursing on a bucket that still doesn't fit).

``DAFT_TPU_SPILL_JOIN`` / ``DAFT_TPU_SPILL_AGG`` gate the two paths
(``auto`` prices via ``costmodel.spill_plan_wins``; ``1`` forces
partitioned execution; ``0`` restores the legacy materialize-then-refan
behavior). Null keys hash consistently on both sides and never match
inside a bucket, so all join types (inner/left/right/outer/semi/anti)
stay bucket-decomposable; group-by NULL keys co-locate the same way.
"""

from __future__ import annotations

import math
from typing import Iterator, List, Optional

import numpy as np

from ..micropartition import MicroPartition
from ..recordbatch import RecordBatch
from . import memory

#: default first-level fanout when no planner evidence sizes the input
_DEFAULT_PARTITIONS = 16
#: hard ceiling on any radix fanout (matches the breaker fanout cap)
_MAX_PARTITIONS = 1024
#: sub-partition ceiling per recursion step
_MAX_SUBPARTITIONS = 64


def _mode(raw: Optional[str], cfg_val: str) -> str:
    v = (raw if raw is not None else cfg_val or "auto").strip().lower()
    if v in ("0", "off", "false", "no"):
        return "0"
    if v in ("1", "on", "force", "true", "yes"):
        return "1"
    return "auto"


def spill_join_mode(cfg=None) -> str:
    """``DAFT_TPU_SPILL_JOIN`` → ``auto`` | ``1`` (force partitioned) |
    ``0`` (legacy materialize-then-refan). Env overrides the per-query
    ``ExecutionConfig.tpu_spill_join``."""
    from ..analysis import knobs
    return _mode(knobs.env_raw("DAFT_TPU_SPILL_JOIN"),
                 getattr(cfg, "tpu_spill_join", "auto") if cfg else "auto")


def spill_agg_mode(cfg=None) -> str:
    """``DAFT_TPU_SPILL_AGG`` → ``auto`` | ``1`` | ``0`` for the
    spill-partitioned aggregation reducer."""
    from ..analysis import knobs
    return _mode(knobs.env_raw("DAFT_TPU_SPILL_AGG"),
                 getattr(cfg, "tpu_spill_agg", "auto") if cfg else "auto")


def spill_max_depth(cfg=None) -> int:
    """Recursion bound for re-partitioning an oversized bucket. Depth
    exhaustion (an all-duplicate key no radix can split) falls through to
    an in-memory join/merge of the bucket, counted in
    ``depth_exhausted``."""
    from ..analysis import knobs
    v = knobs.env_int("DAFT_TPU_SPILL_MAX_DEPTH", default=None)
    if v is None:
        v = getattr(cfg, "tpu_spill_max_depth", 3) if cfg else 3
    return max(int(v), 0)


def forced_partitions(cfg=None) -> int:
    """``DAFT_TPU_SPILL_PARTITIONS``: non-zero forces the first-level
    radix fanout (tests / ops); 0 = planner evidence decides."""
    from ..analysis import knobs
    v = knobs.env_int("DAFT_TPU_SPILL_PARTITIONS", default=None)
    if v is None:
        v = getattr(cfg, "tpu_spill_partitions", 0) if cfg else 0
    return max(int(v), 0)


def pair_budget_bytes(budget: Optional[int] = None) -> int:
    """Bytes one resident work unit (a join bucket pair / one agg state
    bucket) may occupy: a quarter of the breaker budget — both sides plus
    the join output must coexist with the stores' own buffers. The floor
    is deliberately tiny so forced-small test budgets exercise real
    recursion. Under governor memory pressure the budget halves
    (``budget_scale``): smaller resident work units are exactly how the
    spill tier gives RSS back."""
    from . import governor
    b = budget if budget is not None else memory.breaker_budget_bytes()
    scale = governor.budget_scale()
    if scale != 1.0:
        b = int(b * scale)
    return max(b // 4, 16 << 10)


def plan_partitions(est_bytes: Optional[float], cfg=None,
                    budget: Optional[int] = None) -> int:
    """First-level radix fanout from planner evidence: enough buckets
    that each is expected to fit the pair budget, with headroom for
    estimate error (2x) — recursion is the safety net when the evidence
    was wrong, not the plan."""
    forced = forced_partitions(cfg)
    if forced:
        return min(forced, _MAX_PARTITIONS)
    if not est_bytes:
        return _DEFAULT_PARTITIONS
    target = pair_budget_bytes(budget)
    n = -(-int(2 * est_bytes) // target)
    return max(2, min(_MAX_PARTITIONS, n))


# ---------------------------------------------------------- rotated radix

def radix_split(rb: RecordBatch, by, n: int, depth: int
                ) -> List[RecordBatch]:
    """Hash-partition ``rb`` into ``n`` pieces on the ``by`` key chain.
    Depth 0 is bit-identical to ``RecordBatch.partition_by_hash`` (the
    xxh-style chain every exchange/co-partition path uses); depth d > 0
    re-hashes the hash d times, so a bucket that was uniform in
    ``h % n`` fans out again instead of landing whole in one sub-bucket
    (gcd(n, m) correlation)."""
    if len(rb) == 0:
        return [rb.slice(0, 0) for _ in range(n)]
    keys = [rb.eval_expression(e) for e in by]
    h = keys[0].hash()
    for k in keys[1:]:
        h = k.hash(seed=h)
    for _ in range(depth):
        h = h.hash()
    pid = (h.to_numpy() % np.uint64(n)).astype(np.int64)
    return rb._split_by_pid(pid, n)


def drain_to_store(stream: Iterator[MicroPartition], by, n: int,
                   depth: int = 0, poll=None,
                   budget: Optional[int] = None
                   ) -> memory.PartitionedSpillStore:
    """Stream morsels into an ``n``-bucket store by rotated radix — the
    out-of-core ingest: a scan child feeds this one planned-byte-range
    batch at a time, so no whole table is ever resident. The store
    closes itself if the drain fails; callers own it once returned."""
    store = memory.PartitionedSpillStore(n, budget=budget)
    try:
        for mp in stream:
            if poll is not None:
                poll()
            for j, piece in enumerate(radix_split(mp.combined(), by, n,
                                                  depth)):
                if len(piece):
                    store.push(j, piece)
        store.finalize()
    except BaseException:
        store.close()
        raise
    return store


def _batches_nbytes(batches: List[RecordBatch]) -> int:
    return sum(int(b.size_bytes() or 0) for b in batches)


def _concat_or_empty(batches: List[RecordBatch], schema) -> RecordBatch:
    batches = [b if b.schema == schema else b.cast_to_schema(schema)
               for b in batches if len(b)]
    if not batches:
        return RecordBatch.empty(schema)
    return RecordBatch.concat(batches)


# ---------------------------------------------------------- grace join

def _join_pair(mem, lbatches: List[RecordBatch],
               rbatches: List[RecordBatch], node, lschema, rschema,
               depth: int, depth_max: int, pair_budget: int,
               poll=None) -> List[MicroPartition]:
    """Join one co-hashed bucket pair, recursing with a rotated radix
    when the pair exceeds the pair budget. The in-memory leaf join
    admits its bytes against the executor's MemoryManager, so
    cancellation mid-partition (poll before each pair) and concurrent
    pairs stay inside the process budget."""
    if poll is not None:
        poll()
    nbytes = _batches_nbytes(lbatches) + _batches_nbytes(rbatches)
    if nbytes > pair_budget and depth < depth_max:
        memory.spill_count("recursions")
        memory.spill_count(f"recursions_d{depth + 1}")
        m = max(2, min(_MAX_SUBPARTITIONS, -(-int(nbytes) // pair_budget)))
        sub_budget = max(pair_budget, 1)
        with memory.PartitionedSpillStore(m, budget=sub_budget) as ls, \
                memory.PartitionedSpillStore(m, budget=sub_budget) as rs:
            for b in lbatches:
                for j, piece in enumerate(radix_split(
                        b, list(node.left_on), m, depth + 1)):
                    if len(piece):
                        ls.push(j, piece)
            for b in rbatches:
                for j, piece in enumerate(radix_split(
                        b, list(node.right_on), m, depth + 1)):
                    if len(piece):
                        rs.push(j, piece)
            ls.finalize()
            rs.finalize()
            out: List[MicroPartition] = []
            for j in range(m):
                out.extend(_join_pair(
                    mem, ls.bucket_batches(j), rs.bucket_batches(j),
                    node, lschema, rschema, depth + 1, depth_max,
                    pair_budget, poll))
            return out
    if nbytes > pair_budget:
        # bounded depth exhausted (all-duplicate key): join in memory
        # anyway — a price, not a failure — and make it visible
        memory.spill_count("depth_exhausted")
    lmp = _concat_or_empty(lbatches, lschema)
    rmp = _concat_or_empty(rbatches, rschema)
    mem.acquire(nbytes)
    try:
        joined = lmp.hash_join(rmp, node.left_on, node.right_on, node.how)
    finally:
        mem.release(nbytes)
    return [MicroPartition.from_recordbatch(joined)]


def grace_hash_join(ex, node) -> Iterator[MicroPartition]:
    """Spill-partitioned (grace) hash join for a HashJoin with no static
    co-partitioning evidence: stream BOTH children into rotated-radix
    stores (no intermediate whole-side materialize — the legacy path
    paid a second spill write+read), then either gather-join (the
    observed total fits one pair, priced by ``spill_plan_wins``) or join
    bucket pairs one at a time with bounded-depth recursion on any pair
    the first radix level left oversized."""
    from ..device import costmodel
    lnode, rnode = node.children
    cfg = ex.cfg
    budget = memory.breaker_budget_bytes()
    pair_b = pair_budget_bytes(budget)
    est = (getattr(node, "left_bytes_est", None) or 0) \
        + (getattr(node, "right_bytes_est", None) or 0)
    n = plan_partitions(est or None, cfg, budget)
    mode = spill_join_mode(cfg)
    depth_max = spill_max_depth(cfg)
    lstore = drain_to_store(ex._exec(lnode), list(node.left_on), n,
                            poll=ex._poll_cancel, budget=budget // 2)
    try:
        rstore = drain_to_store(ex._exec(rnode), list(node.right_on), n,
                                poll=ex._poll_cancel, budget=budget // 2)
    except BaseException:
        lstore.close()
        raise
    try:
        total = sum(lstore.nbytes) + sum(rstore.nbytes)
        if mode != "1" and not costmodel.spill_plan_wins(total, pair_b):
            # observed total fits one resident pair: a single gathered
            # join keeps the whole-input kernel vectorization
            memory.spill_count("joins_gathered")
            lbat = [b for i in range(n) for b in lstore.bucket_batches(i)]
            rbat = [b for i in range(n) for b in rstore.bucket_batches(i)]
            yield from _join_pair(ex.mem, lbat, rbat, node,
                                  lnode.schema(), rnode.schema(),
                                  depth_max, depth_max, pair_b,
                                  ex._poll_cancel)
            return
        memory.spill_count("joins_partitioned")

        # prefetch-pipelined bucket reads (r23): pair i+1's IPC decode
        # resolves on the spill pool while pair i joins — the read-side
        # half of the spill fast path; window 0 (chaos / serial knob)
        # degrades to in-line reads verbatim
        from . import spill_io

        def read_pair(i):
            def read():
                lb = lstore.bucket_batches(i)
                rb = rstore.bucket_batches(i)
                _grace_pair_check(i, n, node, lb, rb)
                return lb, rb
            return read

        pairs = spill_io.prefetch_ordered(
            (read_pair(i) for i in range(n)),
            spill_io.read_prefetch_window(cfg))

        from .executor import _ordered_parallel
        for outs in _ordered_parallel(
                pairs,
                lambda lr: _join_pair(ex.mem, lr[0], lr[1], node,
                                      lnode.schema(), rnode.schema(),
                                      0, depth_max, pair_b,
                                      ex._poll_cancel)):
            yield from outs
    finally:
        lstore.close()
        rstore.close()


def _grace_pair_check(i: int, n: int, node, lbat, rbat) -> None:
    """Plan-sanitizer hook (DAFT_TPU_SANITIZE_PLAN=1): a bucket pair read
    back from the rotated-radix stores must re-hash into its own bucket —
    depth 0 is contractually ``h % n``, bit-identical to
    ``partition_by_hash``; a spill/IPC dtype drift breaks exactly this."""
    from ..analysis import plan_sanitizer
    if not plan_sanitizer.is_enabled():
        return
    if lbat:
        plan_sanitizer.check_grace_pair(
            i, n, list(node.left_on),
            MicroPartition.from_recordbatch(lbat[0]))
    if rbat:
        plan_sanitizer.check_grace_pair(
            i, n, list(node.right_on),
            MicroPartition.from_recordbatch(rbat[0]))


def join_copartitioned_pair(ex, lmp: MicroPartition, rmp: MicroPartition,
                            node, lschema, rschema
                            ) -> List[MicroPartition]:
    """Skew guard for statically co-partitioned joins (both children are
    hash exchanges on the join keys): a partition PAIR that exceeds the
    pair budget re-partitions with the rotated radix (depth 1 — the pair
    came from depth 0's ``h % n``) instead of joining whole."""
    pair_b = pair_budget_bytes()
    nbytes = int(lmp.size_bytes() or 0) + int(rmp.size_bytes() or 0)
    if spill_join_mode(ex.cfg) == "0" or nbytes <= pair_b:
        return [lmp.hash_join(rmp, node.left_on, node.right_on, node.how)]
    return _join_pair(ex.mem, [lmp.combined()], [rmp.combined()], node,
                      lschema, rschema, 0, spill_max_depth(ex.cfg),
                      pair_b, ex._poll_cancel)


# ------------------------------------------------- spill-partitioned agg

def merge_spilled_agg_bucket(batches: List[RecordBatch], merge_aggs,
                             group_by, schema, key_exprs, depth: int,
                             depth_max: int, bucket_budget: int,
                             poll=None) -> List[MicroPartition]:
    """Merge-on-read for one spilled group-state bucket: the bucket's
    partial-state rows self-merge in ONE agg pass
    (``AGG_DECOMPOSITION``'s merge expressions). A bucket whose raw
    state exceeds the bucket budget re-partitions by a deeper rotated
    radix first — skewed keys that redominate one bucket keep splitting
    until the budget holds or the depth bound trips."""
    if poll is not None:
        poll()
    nbytes = _batches_nbytes(batches)
    if nbytes > bucket_budget and depth < depth_max:
        memory.spill_count("recursions")
        memory.spill_count(f"recursions_d{depth + 1}")
        m = max(2, min(_MAX_SUBPARTITIONS,
                       -(-int(nbytes) // bucket_budget)))
        with memory.PartitionedSpillStore(
                m, budget=max(bucket_budget, 1)) as store:
            for b in batches:
                for j, piece in enumerate(radix_split(b, key_exprs, m,
                                                      depth + 1)):
                    if len(piece):
                        store.push(j, piece)
            store.finalize()
            out: List[MicroPartition] = []
            for j in range(m):
                sub = store.bucket_batches(j)
                if sub:
                    out.extend(merge_spilled_agg_bucket(
                        sub, merge_aggs, group_by, schema, key_exprs,
                        depth + 1, depth_max, bucket_budget, poll))
            return out
    if nbytes > bucket_budget:
        memory.spill_count("depth_exhausted")
    merged = _concat_or_empty(batches, schema)
    if len(merged) == 0:
        return []
    state = merged.agg(merge_aggs, group_by).cast_to_schema(schema)
    memory.spill_count("agg_buckets_merged")
    return [MicroPartition.from_recordbatch(state)]


def agg_state_fanout(est_state_bytes: Optional[float], workers: int,
                     cfg=None) -> int:
    """Sub-bucket count per spilling reducer: enough that one bucket's
    merged state is expected to fit the per-reducer bucket budget."""
    forced = forced_partitions(cfg)
    if forced:
        return min(forced, _MAX_PARTITIONS)
    if not est_state_bytes or not math.isfinite(est_state_bytes):
        return _DEFAULT_PARTITIONS
    per_reducer = est_state_bytes / max(workers, 1)
    target = pair_budget_bytes() / max(workers, 1)
    n = -(-int(2 * per_reducer) // max(int(target), 1 << 20))
    return max(2, min(_MAX_PARTITIONS, n))
