"""Spill-plane IO fast path: bounded writer pool + prefetch-piped reads.

r19's out-of-core tier moved data through its Arrow IPC spill files
SERIALLY — every ``PartitionedSpillStore.push`` to a spilled bucket
converted and wrote the batch inline *under the store lock* (flagged by
daft-lint as blocking-under-lock and waived as a follow-up), and grace
join / spill-agg reads pulled each bucket back synchronously between
joins. This module is that follow-up, shaped like the scan plane's r9
fast path:

- **bounded writer pool** — spill writes run on a shared IO pool,
  serialized *per bucket* (futures chain key-ordered, so within-bucket
  push order — the read-side contract — is preserved) but concurrent
  *across* buckets; Arrow IPC serialization and the codec both release
  the GIL, so the radix-splitting producer keeps running while batches
  drain to disk. Pending (enqueued, unwritten) bytes are capped by the
  store budget so the queue can never become a second unbounded buffer:
  a pusher past the cap takes a bounded wait that the draining writers
  release (same single-huge-request rule as ``MemoryManager`` — one
  oversize batch is always admitted when nothing else is pending, so a
  giant morsel can't deadlock).
- **prefetch-piped reads** — :func:`prefetch_ordered` resolves up to a
  small window of bucket reads ahead of the consumer on the same pool,
  so pair N+1's IPC decode overlaps pair N's join.

``DAFT_TPU_SPILL_IO_PARALLELISM`` sizes the pool; ``0`` restores the
serial r19 write path and serial reads VERBATIM — which is also the
forced degradation under ``DAFT_TPU_CHAOS_SERIALIZE=1`` / an active
fault plan, so chaos replay stays bit-identical (the r9/r17 contract).
"""

from __future__ import annotations

import concurrent.futures as cf
import threading
from typing import Callable, Dict, Iterator, Optional

_SPILL_POOL: Optional[cf.ThreadPoolExecutor] = None
_pool_lock = threading.Lock()

#: pool thread ceiling — parallelism beyond this saturates one NVMe
_MAX_POOL = 8


def spill_io_parallelism(cfg=None) -> int:
    """``DAFT_TPU_SPILL_IO_PARALLELISM``: concurrent spill write/read
    tasks (default 4); ``0`` = the serial legacy path. Chaos serialize
    or an active fault plan force 0 — the fast path must degrade to the
    recorded serial behavior verbatim."""
    from ..analysis import knobs
    if knobs.env_bool("DAFT_TPU_CHAOS_SERIALIZE"):
        return 0
    try:
        from ..distributed.resilience import active_fault_plan
        if active_fault_plan() is not None:
            return 0
    except Exception:
        pass
    v = knobs.env_int("DAFT_TPU_SPILL_IO_PARALLELISM", default=None)
    if v is None and cfg is None:
        try:
            from ..context import get_context
            cfg = get_context().execution_config
        except Exception:
            cfg = None
    if v is None:
        v = getattr(cfg, "tpu_spill_io_parallelism", 4) if cfg else 4
    return max(min(int(v), _MAX_POOL), 0)


def _pool() -> cf.ThreadPoolExecutor:
    """Shared spill-IO pool. Dedicated (not the exec pool): a spill
    write blocked on disk must never hold an exec slot a downstream
    operator needs, and the scan pool's producers block on admission.
    Sized to the ceiling once; per-store concurrency is bounded by the
    per-bucket chains, not pool width."""
    global _SPILL_POOL
    if _SPILL_POOL is not None:
        return _SPILL_POOL
    with _pool_lock:
        if _SPILL_POOL is None:
            _SPILL_POOL = cf.ThreadPoolExecutor(
                max_workers=_MAX_POOL,
                thread_name_prefix="daft-tpu-spill-io")
        return _SPILL_POOL


class SpillWriterGroup:
    """Per-store async write front: ``submit(key, fn, nbytes)`` chains
    ``fn`` after the previous write of the same ``key`` (within-bucket
    order preserved) and runs chains of different keys concurrently on
    the shared pool. ``drain()`` blocks until every chained write
    landed and re-raises the first write error; ``close()`` is the
    no-raise cleanup variant. Pending bytes are capped at
    ``pending_cap``: over-cap submits wait (bounded by writer progress —
    writes always terminate) unless nothing is pending (the
    single-huge-request rule)."""

    def __init__(self, pending_cap: int):
        self.pending_cap = max(int(pending_cap), 1 << 20)
        self._cond = threading.Condition()
        self._pending_bytes = 0
        self._inflight = 0
        self._tails: Dict[object, cf.Future] = {}
        self._err: Optional[BaseException] = None

    def submit(self, key, fn: Callable[[], None], nbytes: int) -> None:
        from .. import observability as obs
        if self._err is not None:
            raise self._err
        nbytes = max(int(nbytes), 0)
        with self._cond:
            while self._pending_bytes > 0 and \
                    self._pending_bytes + nbytes > self.pending_cap:
                self._cond.wait(0.1)
                if self._err is not None:
                    raise self._err
            self._pending_bytes += nbytes
            self._inflight += 1
        attribution = obs.current_attribution()

        def run():
            try:
                obs.run_attributed(attribution, fn)
            except BaseException as exc:  # noqa: BLE001
                with self._cond:
                    if self._err is None:
                        self._err = exc
            finally:
                with self._cond:
                    self._pending_bytes -= nbytes
                    self._inflight -= 1
                    self._cond.notify_all()

        placeholder: cf.Future = cf.Future()

        def kick(_prev=None):
            real = _pool().submit(run)
            real.add_done_callback(
                lambda f: placeholder.set_result(None))

        with self._cond:
            prev = self._tails.get(key)
            self._tails[key] = placeholder
        if prev is None:
            kick()
        else:
            prev.add_done_callback(kick)

    def drain(self) -> None:
        """Wait for every chained write; raise the first write error
        (the store's ``finalize()`` calls this before sealing — a
        swallowed write error would read back truncated buckets)."""
        with self._cond:
            while self._inflight > 0:
                self._cond.wait(0.1)
            if self._err is not None:
                raise self._err

    def close(self) -> None:
        """No-raise drain for cleanup paths (store ``close()``): waits
        out in-flight writes so files aren't deleted under a writer."""
        try:
            with self._cond:
                while self._inflight > 0:
                    self._cond.wait(0.1)
        except Exception:
            pass


def prefetch_ordered(thunks: Iterator[Callable[[], object]],
                     window: int) -> Iterator[object]:
    """Resolve ``thunks`` on the spill pool up to ``window`` ahead of
    the consumer, yielding results in order — the bucket-read analogue
    of the scan plane's prefetch pipeline (pair N+1's IPC decode
    overlaps pair N's join). ``window <= 0`` degrades to the serial
    in-line path (chaos contract)."""
    if window <= 0:
        for t in thunks:
            yield t()
        return
    from .. import observability as obs
    pool = _pool()
    pending = []
    it = iter(thunks)
    done = False
    try:
        while True:
            while not done and len(pending) < window + 1:
                try:
                    t = next(it)
                except StopIteration:
                    done = True
                    break
                pending.append(pool.submit(
                    obs.run_attributed, obs.current_attribution(), t))
            if not pending:
                return
            yield pending.pop(0).result()
    finally:
        for f in pending:  # abandoned consumer: don't run queued reads
            f.cancel()


def read_prefetch_window(cfg=None) -> int:
    """Bucket-read lookahead: capped at 2 (a bucket pair is large), 0
    when the writer pool is serialized (chaos / parallelism 0), and
    governor-narrowed under memory pressure — prefetched buckets are
    resident bytes."""
    par = spill_io_parallelism(cfg)
    if par <= 0:
        return 0
    from . import governor
    return governor.prefetch_window(min(par, 2), cfg)
