"""Memory budget + host-spill tier for pipeline-breaking materialization.

Reference capabilities mirrored:
- ``MemoryManager`` admission semaphore with ``DAFT_MEMORY_LIMIT``
  (``src/daft-local-execution/src/resource_manager.rs:1-60``) →
  ``DAFT_TPU_MEMORY_LIMIT`` here
- spill-to-IPC-files out-of-core tier (``src/daft-shuffles/src/
  shuffle_cache.rs:14-80`` spills per-partition Arrow IPC files)

Blocking sinks (sort, exchange, join build) materialize whole input streams;
``SpillBuffer`` keeps them under the budget by flushing overflow partitions
to Arrow IPC files and re-streaming them on iteration. On TPU hosts this is
the "out-of-HBM, out-of-host-RAM" tier (SURVEY §7 hard part 4).
"""

from __future__ import annotations

import os
import tempfile
import threading
import uuid
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

import pyarrow as pa
import pyarrow.ipc as paipc

# --------------------------------------------------------- spill counters
# Process-wide spill-tier accounting, mirroring the shuffle data-plane
# counters: ``RuntimeStatsContext`` snapshots at query start and diffs at
# finish() for the per-query ``spill`` block (bytes written/read,
# partitions spilled, grace-join recursions, per-store peak residency).

_spill_counters_lock = threading.Lock()
_spill_counters: Dict[str, float] = {}


def spill_count(name: str, n: float = 1) -> None:
    with _spill_counters_lock:
        _spill_counters[name] = _spill_counters.get(name, 0) + n
    # context-local attribution for the serving plane (overlapping
    # queries each see only their own spill traffic)
    from .. import observability as obs
    obs.bump_plane("spill", name, n)


def spill_counters_snapshot() -> Dict[str, float]:
    with _spill_counters_lock:
        return dict(_spill_counters)


def spill_counters_delta(before: Dict[str, float],
                         after: Optional[Dict[str, float]] = None
                         ) -> Dict[str, float]:
    if after is None:
        after = spill_counters_snapshot()
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d:
            out[k] = d
    return out


# ------------------------------------------------------ spill compression
# Spill IPC writers reuse the shuffle tier's codec machinery (r8): Arrow
# IPC *buffer* compression is self-describing, so every reader
# (SpillBuffer reload, bucket reads) needs no configuration.

_spill_ipc_cache: Dict[str, Optional[object]] = {}


def spill_compression(cfg=None) -> str:
    """Resolved spill codec (``lz4`` | ``zstd`` | ``none``):
    ``DAFT_TPU_SPILL_COMPRESSION`` wins, else the per-query
    ``ExecutionConfig.tpu_spill_compression``, else the spill tier
    inherits the shuffle plane's ``DAFT_TPU_SHUFFLE_COMPRESSION``
    (default ``lz4``) — one compression story for every byte that
    leaves RAM."""
    from ..analysis import knobs
    pref = knobs.env_str("DAFT_TPU_SPILL_COMPRESSION")
    if not pref and cfg is None:
        try:
            from ..context import get_context
            cfg = get_context().execution_config
        except Exception:
            cfg = None
    if not pref:
        pref = getattr(cfg, "tpu_spill_compression", "") if cfg else ""
    if not pref:
        pref = knobs.env_str("DAFT_TPU_SHUFFLE_COMPRESSION") or "lz4"
    return pref.strip().lower()


def spill_ipc_options() -> Optional["paipc.IpcWriteOptions"]:
    """IPC write options for spill files per :func:`spill_compression` —
    out-of-core runs pay roughly half the disk bytes under ``lz4``;
    falls back to uncompressed when the codec is missing from this
    pyarrow build."""
    pref = spill_compression()
    if pref in ("none", "off", "0", ""):
        return None
    if pref in _spill_ipc_cache:
        return _spill_ipc_cache[pref]
    try:
        opts = paipc.IpcWriteOptions(compression=pref)
    except Exception:
        opts = None  # unknown codec / not built in → uncompressed
    _spill_ipc_cache[pref] = opts
    return opts


def parse_bytes(v: str) -> int:
    v = v.strip().upper()
    for suffix, mult in (("TIB", 1 << 40), ("GIB", 1 << 30), ("MIB", 1 << 20),
                         ("KIB", 1 << 10),
                         ("TB", 10 ** 12), ("GB", 10 ** 9), ("MB", 10 ** 6),
                         ("KB", 10 ** 3),
                         ("T", 1 << 40), ("G", 1 << 30), ("M", 1 << 20),
                         ("K", 1 << 10), ("B", 1)):
        if v.endswith(suffix):
            return int(float(v[:-len(suffix)]) * mult)
    return int(v)


def memory_limit_bytes() -> Optional[int]:
    """Budget from DAFT_TPU_MEMORY_LIMIT (e.g. "4GB", "512MiB"); None =
    unbounded (no spilling). A malformed value is a hard error — silently
    dropping a user-configured limit would trade an error message for an
    OOM."""
    from ..analysis import knobs
    v = knobs.env_raw("DAFT_TPU_MEMORY_LIMIT")
    if not v:
        return None
    try:
        return parse_bytes(v)
    except ValueError:
        raise ValueError(
            f"unparseable DAFT_TPU_MEMORY_LIMIT={v!r}; "
            f"expected e.g. '4GB', '512MiB', '1TiB', or a byte count")


class MemoryManager:
    """Byte-budget admission control (reference: ``resource_manager.rs`` —
    a request larger than the whole budget is admitted when nothing else is
    in flight, so a single huge morsel can't deadlock)."""

    def __init__(self, budget: Optional[int] = None):
        self.budget = budget if budget is not None else memory_limit_bytes()
        self._held = 0
        self._cond = threading.Condition()

    def acquire(self, nbytes: int):
        if self.budget is None:
            return
        with self._cond:
            while self._held > 0 and self._held + nbytes > self.budget:
                self._cond.wait()
            self._held += nbytes

    def try_acquire(self, nbytes: int, deadline: Optional[float] = None,
                    cancel=None) -> bool:
        """Admission-control variant: wait until the request fits, the
        monotonic ``deadline`` passes, or ``cancel`` (a CancelToken /
        Event-like with ``is_set``) fires. Returns True iff the bytes
        were admitted — the serving scheduler's admit/queue/reject
        decision rides on this, so unlike :meth:`acquire` it never waits
        forever. The single-huge-request rule is unchanged: a request
        larger than the whole budget is admitted when nothing else is in
        flight (it can spill), but only a *growing* wait is bounded."""
        import time as _time
        if self.budget is None:
            return True
        with self._cond:
            while self._held > 0 and self._held + nbytes > self.budget:
                if cancel is not None and cancel.is_set():
                    return False
                timeout = 0.1  # poll so a cancel fires within ~100ms
                if deadline is not None:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        return False
                    timeout = min(timeout, remaining)
                self._cond.wait(timeout)
            self._held += nbytes
            return True

    @property
    def outstanding(self) -> int:
        """Currently-admitted bytes (0 when unbudgeted) — the serving
        bench's leak invariant: this must return to zero after drain."""
        with self._cond:
            return self._held if self.budget is not None else 0

    def release(self, nbytes: int):
        if self.budget is None:
            return
        with self._cond:
            self._held = max(self._held - nbytes, 0)
            self._cond.notify_all()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_spill_lock = threading.Lock()
_spill_dir: Optional[str] = None


def spill_dir() -> str:
    global _spill_dir
    with _spill_lock:
        if _spill_dir is None:
            from ..analysis import knobs
            base = knobs.env_str("DAFT_TPU_SPILL_DIR")
            _spill_dir = base or tempfile.mkdtemp(prefix="daft_tpu_spill_")
            os.makedirs(_spill_dir, exist_ok=True)
        return _spill_dir


class SpillBuffer:
    """Multi-pass materialized partition buffer with a byte budget.

    Append partitions; once in-memory bytes exceed the budget, further
    partitions are written to Arrow IPC files (compressed per
    ``spill_ipc_options``). Iteration re-yields all partitions in append
    order (disk ones re-loaded lazily). ``close()`` (deterministic —
    breaker sites own it via try/finally or ``with``; ``__del__`` is only
    the last-resort GC net) deletes spill files.
    """

    def __init__(self, budget: Optional[int] = None):
        self.budget = budget if budget is not None else memory_limit_bytes()
        self._entries: List[Tuple[str, object]] = []  # ("mem", mp)|("disk", path)
        self._mem_bytes = 0
        self.peak_mem_bytes = 0
        self.bytes_spilled = 0
        self.total_rows = 0
        self._accounted = False

    def append(self, mp) -> None:
        self.total_rows += len(mp)
        sz = mp.size_bytes() or 0
        if self.budget is not None and self._mem_bytes + sz > self.budget:
            path = self._write_ipc(mp)
            self._entries.append(("disk", path))
            self.bytes_spilled += sz
            spill_count("bytes_written", sz)
            spill_count("partitions_spilled")
        else:
            self._entries.append(("mem", mp))
            self._mem_bytes += sz
            self.peak_mem_bytes = max(self.peak_mem_bytes, self._mem_bytes)

    def _write_ipc(self, mp) -> str:
        path = os.path.join(spill_dir(), f"{uuid.uuid4().hex}.arrow")
        table = mp.combined().to_arrow_table()
        with paipc.new_stream(path, table.schema,
                              options=spill_ipc_options()) as w:
            w.write_table(table)
        # disk_bytes_written is the POST-codec file size; bytes_written
        # (logical) stays the cross-PR comparable series — the ratio is
        # the codec's measured win
        try:
            spill_count("disk_bytes_written", os.path.getsize(path))
        except OSError:
            pass
        return path

    @staticmethod
    def _read_ipc(path: str):
        from ..micropartition import MicroPartition
        from ..recordbatch import RecordBatch
        try:
            spill_count("disk_bytes_read", os.path.getsize(path))
        except OSError:
            pass
        with paipc.open_stream(path) as r:
            table = r.read_all()
        spill_count("bytes_read", table.nbytes)
        return MicroPartition.from_recordbatch(
            RecordBatch.from_arrow_table(table))

    @property
    def total_bytes(self) -> int:
        """Materialized size across memory + spill (AQE's stage actuals)."""
        return self._mem_bytes + self.bytes_spilled

    def __len__(self) -> int:
        return len(self._entries)

    def __iter__(self) -> Iterator:
        for kind, v in self._entries:
            yield v if kind == "mem" else self._read_ipc(v)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self._entries)))]
        kind, v = self._entries[i]
        return v if kind == "mem" else self._read_ipc(v)

    def close(self):
        # only stores that really hit disk count toward the spill block:
        # a resident-only buffer is ordinary breaker memory, not spill
        # evidence (and would make zero-spill queries render the block)
        if not self._accounted and self.bytes_spilled:
            self._accounted = True
            spill_count("stores")
            spill_count("store_peak_bytes", self.peak_mem_bytes)
        for kind, v in self._entries:
            if kind == "disk":
                try:
                    os.unlink(v)
                except OSError:
                    pass
        self._entries = []
        self._mem_bytes = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def materialize(parts: Iterable, budget: Optional[int] = None) -> SpillBuffer:
    """Drain a partition stream into a (possibly spilling) buffer. The
    buffer closes itself if the DRAIN fails — the caller only owns it
    once it is returned whole."""
    buf = SpillBuffer(budget)
    try:
        for p in parts:
            buf.append(p)
    except BaseException:
        buf.close()
        raise
    return buf


def breaker_budget_bytes() -> int:
    """In-memory byte budget for pipeline-breaker buffers (sort input,
    bucket stores, gather). The user's DAFT_TPU_MEMORY_LIMIT wins; without
    one, a quarter of physical RAM — a breaker must never degenerate into
    an unbounded in-memory materialize just because no limit was set."""
    lim = memory_limit_bytes()
    if lim is not None:
        return lim
    try:
        total = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
        return max(total // 4, 256 << 20)
    except (ValueError, OSError, AttributeError):
        return 1 << 30


class PartitionedSpillStore:
    """n-bucket accumulator with one SHARED in-memory byte budget: pushes
    stay in RAM until the store exceeds the budget, then whole buckets
    (largest first) convert to per-bucket Arrow IPC spill files and any
    later push to a spilled bucket appends to its file — push order within
    a bucket is preserved. This is the blocking-sink store behind the
    streaming breakers (hash/random/range exchanges, external sort buckets,
    spill-partitioned joins): peak RSS ≈ budget + one bucket at read time
    (reference: ``sinks/blocking_sink.rs:32-55`` consume-all-then-emit with
    memory-pressure spilling; the distributed Flight path keeps its own
    always-on-disk ``ShuffleCache``)."""

    def __init__(self, n: int, budget: Optional[int] = None):
        import uuid as _uuid
        from . import spill_io
        self.n = n
        self.budget = budget if budget is not None else breaker_budget_bytes()
        self._mem: List[List] = [[] for _ in range(n)]  # pa.Table lists
        self._mem_bytes_per = [0] * n
        self._mem_bytes = 0
        self.peak_mem_bytes = 0
        self._writers: List[Optional[Tuple[object, object]]] = [None] * n
        self._spilled = [False] * n
        self.rows = [0] * n
        self.nbytes = [0] * n
        self.bytes_spilled = 0
        self._root = os.path.join(spill_dir(),
                                  f"pstore_{_uuid.uuid4().hex}")
        self._lock = threading.Lock()
        self._sealed = False
        self._accounted = False
        # spill-IO fast path (r23): writes to spilled buckets run on the
        # bounded writer pool, chained per bucket (push order preserved)
        # and capped at one budget of pending bytes — worst-case resident
        # overshoot is budget (resident) + budget (enqueued). Parallelism
        # 0 / chaos keeps the r19 serial write-under-lock path verbatim.
        self._io = (spill_io.SpillWriterGroup(self.budget)
                    if spill_io.spill_io_parallelism() > 0 else None)

    def _path(self, i: int) -> str:
        return os.path.join(self._root, f"bucket-{i}.arrow")

    def _write_table(self, i: int, table) -> None:
        """Append one Arrow table to bucket i's IPC file, creating the
        writer on first touch and counting post-codec disk bytes. Called
        either under the store lock (serial path) or from the writer
        pool with per-bucket exclusivity (async path) — never both for
        the same store, so writer slots need no extra lock."""
        w = self._writers[i]
        if w is None:
            os.makedirs(self._root, exist_ok=True)
            f = open(self._path(i), "ab")
            w = (paipc.new_stream(f, table.schema,
                                  options=spill_ipc_options()), f)
            self._writers[i] = w
        before = w[1].tell()
        w[0].write_table(table)
        spill_count("disk_bytes_written", w[1].tell() - before)

    def _make_write(self, j: int, batches: List):
        def write():
            for b in batches:
                self._write_table(j, b.to_arrow_table())
        return write

    def push(self, i: int, batch) -> None:
        """Append a RecordBatch to bucket i. Resident batches stay AS-IS
        (no Arrow conversion on the hot path); conversion happens only
        when a bucket spills — on the writer pool when the spill-IO fast
        path is on, inline (r19 verbatim) when serialized."""
        nb = batch.size_bytes()
        to_write: List[Tuple[int, List, int, bool]] = []
        with self._lock:
            self.rows[i] += len(batch)
            self.nbytes[i] += nb
            if self._spilled[i]:
                self.bytes_spilled += nb
                spill_count("bytes_written", nb)
                if self._io is not None:
                    to_write.append((i, [batch], nb, False))
                else:
                    t = batch.to_arrow_table()
                    # daft-lint: allow(blocking-under-lock) -- the
                    # serial (parallelism=0 / chaos) degradation keeps
                    # r19's verbatim behavior: writer state + budget
                    # accounting as one atomic unit
                    self._write_table(i, t)
            else:
                self._mem[i].append(batch)
                self._mem_bytes_per[i] += nb
                self._mem_bytes += nb
                self.peak_mem_bytes = max(self.peak_mem_bytes,
                                          self._mem_bytes)
                while self._mem_bytes > self.budget:
                    j = max(range(self.n),
                            key=lambda x: self._mem_bytes_per[x])
                    if self._mem_bytes_per[j] == 0:
                        break
                    if self._io is not None:
                        evicted = self._mem[j]
                        jb = self._mem_bytes_per[j]
                        self._mem[j] = []
                        self._mem_bytes -= jb
                        self._mem_bytes_per[j] = 0
                        self._spilled[j] = True
                        self.bytes_spilled += jb
                        spill_count("bytes_written", jb)
                        spill_count("partitions_spilled")
                        to_write.append((j, evicted, jb, True))
                    else:
                        self._spill_bucket(j)
        # enqueue OUTSIDE the lock: submit() may wait on the pending-byte
        # cap, and a blocked pusher must not hold the store lock the
        # draining writer's counters (or a concurrent pusher) need
        for j, batches, jb, _newly in to_write:
            self._io.submit(j, self._make_write(j, batches), jb)

    def _spill_bucket(self, j: int) -> None:
        for b in self._mem[j]:
            self._write_table(j, b.to_arrow_table())
        self.bytes_spilled += self._mem_bytes_per[j]
        spill_count("bytes_written", self._mem_bytes_per[j])
        spill_count("partitions_spilled")
        self._mem_bytes -= self._mem_bytes_per[j]
        self._mem_bytes_per[j] = 0
        self._mem[j] = []
        self._spilled[j] = True

    def finalize(self) -> None:
        if self._io is not None:
            # outside the lock: drain() blocks on writer progress, and
            # the writers never take the store lock
            self._io.drain()
        with self._lock:
            for w in self._writers:
                if w is not None:
                    w[0].close()
                    w[1].close()
            self._writers = [None] * self.n
            self._sealed = True

    def bucket_batches(self, i: int) -> List:
        """All of bucket i's RecordBatches, disk ones first then resident
        (push order: a bucket spills wholly before disk appends begin)."""
        from ..recordbatch import RecordBatch
        assert self._sealed, "finalize() before reading buckets"
        out = []
        if self._spilled[i] and os.path.exists(self._path(i)):
            read = 0
            try:
                spill_count("disk_bytes_read",
                            os.path.getsize(self._path(i)))
            except OSError:
                pass
            with open(self._path(i), "rb") as f:
                while True:
                    try:
                        r = paipc.open_stream(f)
                    except Exception:
                        break
                    t = r.read_all()
                    read += t.nbytes
                    out.append(RecordBatch.from_arrow_table(t))
            if read:
                spill_count("bytes_read", read)
        out.extend(self._mem[i])
        return out

    def close(self) -> None:
        # spilling stores only — see SpillBuffer.close
        if not self._accounted and self.bytes_spilled:
            self._accounted = True
            spill_count("stores")
            spill_count("store_peak_bytes", self.peak_mem_bytes)
        if self._io is not None:
            # wait out in-flight writes before deleting their files
            self._io.close()
        with self._lock:
            for w in self._writers:
                if w is not None:
                    try:
                        w[0].close()
                        w[1].close()
                    except Exception:
                        pass
            self._writers = [None] * self.n
            self._mem = [[] for _ in range(self.n)]
            self._mem_bytes = 0
            self._mem_bytes_per = [0] * self.n
        try:
            import shutil
            shutil.rmtree(self._root, ignore_errors=True)
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class SplitSpillBuffer:
    """Budgeted holder for fanout outputs: each input partition contributes a
    row of ``n`` split partitions; rows accumulate under the same spill
    budget so the exchange's peak (all fanout outputs live at once) is
    bounded, not just its input buffer."""

    def __init__(self, budget: Optional[int] = None):
        self._buf = SpillBuffer(budget)
        self._n: Optional[int] = None
        self.rows = 0

    def append_row(self, mps: List) -> None:
        if self._n is None:
            self._n = len(mps)
        assert len(mps) == self._n
        for mp in mps:
            self._buf.append(mp)
        self.rows += 1

    def get(self, row: int, i: int):
        return self._buf[row * self._n + i]

    def close(self):
        self._buf.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
