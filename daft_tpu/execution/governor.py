"""Process-wide memory governor: RSS-watermark backpressure.

The breaker/admission tier (``execution/memory.py``) bounds what each
*store* buffers, but nothing watched the PROCESS: a composed SF100 query
(scan prefetch × async device pipeline × grace-join stores × exchange
buffers) can sit under every per-store budget and still walk RSS past
``DAFT_TPU_MEMORY_LIMIT`` until the OS OOM-kills it. The governor closes
that loop the way the reference engine's memory manager does — observe
real RSS, act *before* the kernel does:

- **watermarks** — RSS is sampled (throttled, from ``/proc/self/statm``)
  against the memory limit; crossing ``DAFT_TPU_GOVERNOR_HIGH`` (default
  0.85 × limit) enters the pressured state, which only clears below
  ``DAFT_TPU_GOVERNOR_LOW`` (default 0.70) — hysteresis, so actions
  don't flap at the boundary;
- **actions under pressure** — spill budgets shrink
  (:func:`budget_scale` halves the pair/bucket budget, so grace
  joins/spilling reducers fan out into *smaller* resident work units),
  scan prefetch narrows to one task ahead (:func:`prefetch_window`),
  admission points take a bounded throttle wait (:func:`throttle` —
  never a hard gate: a governor that can block forever is a new
  deadlock, so waits are sliced and capped), and one ``gc.collect()``
  runs per pressure episode;
- **evidence** — every action lands in the ``governor`` counter plane
  (flight recorder / ``explain(analyze=True)`` / ``/metrics``), and the
  process peak RSS is tracked for the scale bench's bounded-RSS gate.

Chaos-determinism contract: like calibration/re-planning (r20), the
governor FREEZES under ``DAFT_TPU_CHAOS_SERIALIZE=1`` or an active fault
plan — replayed plans must not depend on the recording machine's RSS.
Without a memory limit the governor is inert (there is no watermark to
govern against); peak-RSS tracking still works for the bench.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

# ------------------------------------------------------------- counters
# Same snapshot/delta discipline as the spill plane: process-wide totals
# for /metrics, context-local attribution for per-query stat blocks.

_counters_lock = threading.Lock()
_counters: Dict[str, float] = {}


def governor_count(name: str, n: float = 1) -> None:
    with _counters_lock:
        _counters[name] = _counters.get(name, 0) + n
    from .. import observability as obs
    obs.bump_plane("governor", name, n)


def counters_snapshot() -> Dict[str, float]:
    with _counters_lock:
        return dict(_counters)


def counters_delta(before: Dict[str, float],
                   after: Optional[Dict[str, float]] = None
                   ) -> Dict[str, float]:
    if after is None:
        after = counters_snapshot()
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d:
            out[k] = d
    return out


# ------------------------------------------------------------ RSS probe

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096
#: sampling throttle: /proc reads are ~µs but the callers are hot loops
_SAMPLE_INTERVAL_S = 0.02

_state_lock = threading.Lock()
_last_sample_t = 0.0
_last_rss = 0
_peak_rss = 0
_pressured = False
_gc_pending = False


def _read_rss() -> int:
    """Resident set size in bytes. ``/proc/self/statm`` field 2 on
    Linux; the ru_maxrss fallback (macOS/CI containers without /proc)
    reports the high-water mark instead — monotone, which only makes the
    governor MORE conservative."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, IndexError, ValueError):
        try:
            import resource
            ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            return int(ru) * 1024  # Linux reports KiB
        except Exception:
            return 0


def rss_bytes(refresh: bool = False) -> int:
    """Throttled current RSS (a fresh read at most every 20ms process
    wide; ``refresh=True`` forces one — tests and the bench's per-query
    bookends use it)."""
    global _last_sample_t, _last_rss, _peak_rss
    now = time.monotonic()
    with _state_lock:
        if not refresh and now - _last_sample_t < _SAMPLE_INTERVAL_S:
            return _last_rss
        _last_sample_t = now
    rss = _read_rss()
    with _state_lock:
        _last_rss = rss
        if rss > _peak_rss:
            _peak_rss = rss
        return rss


def peak_rss_bytes() -> int:
    """High-water RSS since process start (or the last
    :func:`reset_peak`) as seen by the governor's samples."""
    rss_bytes()
    with _state_lock:
        return _peak_rss


def reset_peak() -> int:
    """Restart peak tracking from the current RSS (the bench's per-query
    peak bookend) and return the new baseline."""
    global _peak_rss
    rss = rss_bytes(refresh=True)
    with _state_lock:
        _peak_rss = rss
    return rss


# ----------------------------------------------------------- watermarks

def _cfg(name, default):
    try:
        from ..context import get_context
        return getattr(get_context().execution_config, name, default)
    except Exception:
        return default


def limit_bytes() -> Optional[int]:
    from . import memory
    try:
        return memory.memory_limit_bytes()
    except ValueError:
        return None


def watermarks(cfg=None) -> tuple:
    """(high, low) pressure fractions of the memory limit.
    ``DAFT_TPU_GOVERNOR_HIGH`` / ``_LOW`` env override the
    ``ExecutionConfig`` fields; low is clamped under high so the
    hysteresis band never inverts."""
    from ..analysis import knobs
    high = knobs.env_float("DAFT_TPU_GOVERNOR_HIGH", default=None)
    if high is None:
        high = getattr(cfg, "tpu_governor_high", None) if cfg else None
        if high is None:
            high = _cfg("tpu_governor_high", 0.85)
    low = knobs.env_float("DAFT_TPU_GOVERNOR_LOW", default=None)
    if low is None:
        low = getattr(cfg, "tpu_governor_low", None) if cfg else None
        if low is None:
            low = _cfg("tpu_governor_low", 0.70)
    high = max(float(high), 0.05)
    low = min(max(float(low), 0.0), high * 0.99)
    return high, low


def enabled(cfg=None) -> bool:
    """Governor active: a memory limit exists, ``DAFT_TPU_GOVERNOR``
    isn't off, and the chaos-determinism freeze isn't active (frozen
    replans must not depend on the recording machine's RSS)."""
    from ..analysis import knobs
    if not knobs.env_bool("DAFT_TPU_GOVERNOR", default=True):
        return False
    if limit_bytes() is None:
        return False
    try:
        from ..device.calibration import frozen
        if frozen():
            return False
    except Exception:
        pass
    return True


def pressure() -> float:
    """RSS / limit (0.0 when unlimited) — the /metrics gauge."""
    lim = limit_bytes()
    if not lim:
        return 0.0
    return rss_bytes() / lim


def under_pressure(cfg=None) -> bool:
    """Sample RSS and return the hysteresis state: True above the high
    watermark until RSS falls back under the low one. Rising edges count
    a ``pressure_episodes`` action and schedule one gc.collect()."""
    global _pressured, _gc_pending
    if not enabled(cfg):
        return False
    lim = limit_bytes()
    high, low = watermarks(cfg)
    rss = rss_bytes()
    run_gc = False
    with _state_lock:
        if not _pressured and rss >= high * lim:
            _pressured = True
            _gc_pending = True
        elif _pressured and rss <= low * lim:
            _pressured = False
        if _pressured and _gc_pending:
            _gc_pending = False
            run_gc = True
        pressured = _pressured
    if run_gc:
        # outside the lock: a collection can run finalizers that re-enter
        governor_count("pressure_episodes")
        import gc
        gc.collect()
        governor_count("gc_collects")
        rss_bytes(refresh=True)
    return pressured


def budget_scale(cfg=None) -> float:
    """Multiplier for spill pair/bucket budgets: 0.5 under pressure
    (smaller resident work units → more, smaller partitions), 1.0
    otherwise. Counted so fanout decisions taken under governor pressure
    are visible in the stats blocks."""
    if under_pressure(cfg):
        governor_count("budget_shrinks")
        return 0.5
    return 1.0


def prefetch_window(base: int, cfg=None) -> int:
    """Scan-prefetch window under governor control: collapses to 1 task
    ahead while pressured (prefetched bytes are exactly the RSS the
    governor is trying to claw back)."""
    if base > 1 and under_pressure(cfg):
        governor_count("prefetch_shrinks")
        return 1
    return base


#: bounded throttle: total wait cap and slice (never a hard gate)
_THROTTLE_MAX_S = 0.5
_THROTTLE_SLICE_S = 0.05


def throttle(kind: str = "admission", cfg=None) -> float:
    """Bounded backpressure at an admission point (scan-prefetch
    producer start, pipeline admission): while pressured, sleep in 50ms
    slices up to 0.5s total, re-sampling between slices so a drop below
    the low watermark releases early. Returns seconds actually waited.
    DEADLOCK-SAFE by construction: the wait is time-bounded and holds no
    locks, so even if every thread throttles at once the process keeps
    making progress at ≥2 steps/second."""
    if not under_pressure(cfg):
        return 0.0
    t0 = time.monotonic()
    waited = 0.0
    while waited < _THROTTLE_MAX_S:
        time.sleep(_THROTTLE_SLICE_S)
        waited = time.monotonic() - t0
        if not under_pressure(cfg):
            break
    governor_count("throttle_waits")
    governor_count("throttle_wait_us", waited * 1e6)
    governor_count(f"throttle_{kind}")
    return waited


def snapshot() -> Dict[str, float]:
    """Gauge snapshot for /metrics and the bench: current/peak RSS, the
    configured limit, and the live pressured flag."""
    lim = limit_bytes() or 0
    rss = rss_bytes()
    with _state_lock:
        peak = _peak_rss
        pressured = _pressured
    return {"rss_bytes": float(rss), "rss_peak_bytes": float(peak),
            "limit_bytes": float(lim),
            "pressured": 1.0 if pressured else 0.0}


def _reset_for_tests() -> None:
    """Test hook: clear hysteresis/peak state between cases."""
    global _pressured, _gc_pending, _last_sample_t, _peak_rss, _last_rss
    with _state_lock:
        _pressured = False
        _gc_pending = False
        _last_sample_t = 0.0
        _last_rss = 0
        _peak_rss = 0
