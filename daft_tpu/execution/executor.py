"""Streaming partition-parallel local executor.

The single-node engine (reference: "Swordfish",
``src/daft-local-execution``): operators stream MicroPartitions, pipelined
ops run on a shared thread pool (Arrow C++ and XLA both release the GIL, so
threads scale), pipeline breakers (sort / final agg / join build) materialize.
Ordering is preserved via bounded in-order future windows
(the RoundRobin dispatcher of ``dispatcher.rs:24-60``).

Global sort follows the reference's sample→boundaries→range-partition→merge
pipeline (``daft/execution/physical_plan.py:1632``).
"""

from __future__ import annotations

import concurrent.futures as cf
import os
from typing import Callable, Iterator, List, Optional

import numpy as np

from ..context import get_context
from ..expressions import Expression, col
from ..micropartition import MicroPartition
from ..physical import plan as pp
from ..recordbatch import RecordBatch
from ..series import Series

_POOL: Optional[cf.ThreadPoolExecutor] = None


def _pool() -> cf.ThreadPoolExecutor:
    global _POOL
    if _POOL is None:
        _POOL = cf.ThreadPoolExecutor(
            max_workers=max(os.cpu_count() or 4, 4),
            thread_name_prefix="daft-tpu-exec")
    return _POOL


def _ordered_parallel(inputs: Iterator, fn: Callable,
                      width: Optional[int] = None) -> Iterator:
    """Map fn over inputs on the pool, yielding results in order with a
    bounded in-flight window (backpressure)."""
    width = width or max((os.cpu_count() or 4), 4) * 2
    pool = _pool()
    pending: List[cf.Future] = []
    it = iter(inputs)
    done = False
    while True:
        while not done and len(pending) < width:
            try:
                x = next(it)
            except StopIteration:
                done = True
                break
            pending.append(pool.submit(fn, x))
        if not pending:
            return
        yield pending.pop(0).result()


class LocalExecutor:
    """Interprets a physical plan into a stream of MicroPartitions."""

    def __init__(self):
        from . import memory
        self.cfg = get_context().execution_config
        self.stats = None
        # bounds bytes of scan tasks materializing concurrently
        self.mem = memory.MemoryManager()

    def run(self, plan: pp.PhysicalPlan) -> Iterator[MicroPartition]:
        from .. import observability as obs
        self.stats = obs.new_query_stats()
        self.stats.plan = plan  # for explain_analyze rendering

        def gen():
            try:
                yield from obs.wrap_progress(self._exec(plan))
            finally:
                self.stats.finish()
                obs.set_last_stats(self.stats)
                path = obs.chrome_trace_path()
                if path and self.stats.tracer is not None:
                    self.stats.tracer.dump(path)
        return gen()

    # ------------------------------------------------------------------
    def _exec(self, node: pp.PhysicalPlan) -> Iterator[MicroPartition]:
        h = getattr(self, "_exec_" + type(node).__name__, None)
        if h is None:
            raise NotImplementedError(f"executor for {type(node).__name__}")
        it = h(node)
        if self.stats is not None:
            it = self.stats.instrument(node, it)
        return it

    # sources ----------------------------------------------------------
    def _exec_ScanSource(self, node: pp.ScanSource):
        def run(t):
            est = t.size_bytes() or 0
            self.mem.acquire(est)
            try:
                mp = MicroPartition.from_scan_task(t)
                mp._load()
                return mp
            finally:
                self.mem.release(est)
        if not node.tasks:
            yield MicroPartition.empty(node.schema())
            return
        yield from _ordered_parallel(iter(node.tasks), run)

    def _exec_InMemorySource(self, node: pp.InMemorySource):
        if not node.partitions:
            yield MicroPartition.empty(node.schema())
            return
        yield from iter(node.partitions)

    # pipelined maps ---------------------------------------------------
    def _exec_Project(self, node: pp.Project):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: p.eval_expression_list(node.exprs))

    def _exec_UDFProject(self, node: pp.UDFProject):
        child = self._exec(node.children[0])
        width = node.concurrency or None
        yield from _ordered_parallel(
            child, lambda p: p.eval_expression_list(node.exprs), width=width)

    def _exec_Filter(self, node: pp.Filter):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, lambda p: p.filter(node.predicate))

    def _exec_Explode(self, node: pp.Explode):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, lambda p: p.explode(node.exprs))

    def _exec_Unpivot(self, node: pp.Unpivot):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: p.unpivot(node.ids, node.values,
                                       node.variable_name, node.value_name))

    def _exec_Sample(self, node: pp.Sample):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: p.sample(fraction=node.fraction, size=None,
                                      with_replacement=node.with_replacement,
                                      seed=node.seed)
            if node.fraction is not None else p.head(node.size))

    def _exec_MonotonicallyIncreasingId(self, node):
        child = self._exec(node.children[0])
        for i, p in enumerate(child):
            yield p.add_monotonically_increasing_id(i, node.column_name)

    def _exec_Limit(self, node: pp.Limit):
        remaining = node.limit
        to_skip = node.offset
        for p in self._exec(node.children[0]):
            n = len(p)
            if to_skip:
                if n <= to_skip:
                    to_skip -= n
                    continue
                p = MicroPartition.from_recordbatch(
                    p.combined().slice(to_skip, n))
                to_skip = 0
            if remaining <= 0:
                break
            if len(p) > remaining:
                p = p.head(remaining)
            remaining -= len(p)
            yield p
            if remaining <= 0:
                break

    def _exec_Concat(self, node: pp.Concat):
        yield from self._exec(node.children[0])
        yield from self._exec(node.children[1])

    # aggregation ------------------------------------------------------
    def _exec_Aggregate(self, node: pp.Aggregate):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: p.agg(node.aggs, node.group_by)
            .cast_to_schema(node.schema()))

    def _exec_DeviceFragmentAgg(self, node: pp.DeviceFragmentAgg):
        from ..aggs import split_agg_expr
        from ..device import fragment, runtime as drt
        specs = [split_agg_expr(a) for a in node.aggs]
        child_exprs = [(c if c is not None else _lit_true()).alias(f"__v{i}__")
                       for i, (op, c, nm, pr) in enumerate(specs)]
        ops = tuple(s[0] for s in specs)
        agg_names = [s[2] for s in specs]

        def run(p: MicroPartition) -> MicroPartition:
            rb = p.combined()
            if drt.device_enabled() and len(rb) >= max(drt._min_rows(), 1):
                prog = fragment.get_fused_agg(node.group_by, child_exprs, ops,
                                              node.predicate, rb.schema)
                if prog is not None:
                    out = fragment.run_fused_agg(
                        prog, rb, node.group_by,
                        [col(nm) for nm in agg_names], node.schema())
                    if out is not None:
                        return MicroPartition.from_recordbatch(
                            out.cast_to_schema(node.schema()))
            # host fallback: equivalent unfused chain
            if node.predicate is not None:
                rb = rb.filter(node.predicate)
            return MicroPartition.from_recordbatch(
                rb.agg(node.aggs, node.group_by).cast_to_schema(node.schema()))

        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, run)

    def _exec_Dedup(self, node: pp.Dedup):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, lambda p: p.distinct(node.on))

    def _exec_Pivot(self, node: pp.Pivot):
        for p in self._exec(node.children[0]):
            yield p.pivot(node.group_by, node.pivot_col, node.value_col,
                          node.names).cast_to_schema(node.schema())

    def _exec_Window(self, node: pp.Window):
        from ..window_exec import run_window
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: MicroPartition.from_recordbatch(
                run_window(p.combined(), node)))

    # sort -------------------------------------------------------------
    def _exec_Sort(self, node: pp.Sort):
        from . import memory
        parts = memory.materialize(self._exec(node.children[0]))
        if len(parts) == 1:
            yield parts[0].sort(node.sort_by, node.descending, node.nulls_first)
            return
        ranged = self._range_partition(parts, list(node.sort_by),
                                       list(node.descending),
                                       list(node.nulls_first))
        yield from _ordered_parallel(
            iter(ranged),
            lambda p: p.sort(node.sort_by, node.descending, node.nulls_first))

    def _exec_TopN(self, node: pp.TopN):
        child = self._exec(node.children[0])
        tops = list(_ordered_parallel(
            child, lambda p: MicroPartition.from_recordbatch(
                p.combined().top_n(node.sort_by, node.limit, node.descending,
                                   node.nulls_first))))
        merged = tops[0].concat(tops[1:]) if len(tops) > 1 else tops[0]
        yield MicroPartition.from_recordbatch(
            merged.combined().top_n(node.sort_by, node.limit, node.descending,
                                    node.nulls_first))

    # exchanges --------------------------------------------------------
    def _exec_Exchange(self, node: pp.Exchange):
        from . import memory
        parts = memory.materialize(self._exec(node.children[0]))
        kind, n = node.kind, node.num_partitions
        if kind == "gather" or (kind == "split" and n == 1):
            yield parts[0].concat(parts[1:]) if len(parts) > 1 else parts[0]
            return
        if kind == "split":
            yield from self._split(parts, n)
            return
        if kind == "random":
            split = self._materialize_split(_ordered_parallel(
                enumerate(parts),
                lambda ip: ip[1].partition_by_random(n, seed=ip[0])))
            yield from self._regroup(split, n)
            return
        if kind == "hash":
            by = list(node.by)
            split = self._materialize_split(_ordered_parallel(
                iter(parts), lambda p: p.partition_by_hash(by, n)))
            yield from self._regroup(split, n)
            return
        if kind == "range":
            yield from self._range_partition(parts, list(node.by),
                                             list(node.descending) or
                                             [False] * len(node.by),
                                             None, n)
            return
        raise NotImplementedError(f"exchange kind {kind}")

    def _materialize_split(self, rows):
        """Fanout outputs → budgeted (possibly spilling) buffer, so the
        exchange peak — every input's n split parts live at once — honors
        the memory limit."""
        from . import memory
        split = memory.SplitSpillBuffer()
        for outs in rows:
            split.append_row(list(outs))
        return split

    def _regroup(self, split, n: int):
        from . import memory
        if isinstance(split, memory.SplitSpillBuffer):
            for i in range(n):
                subs = [split.get(s, i) for s in range(split.rows)]
                yield subs[0].concat(subs[1:]) if len(subs) > 1 else subs[0]
            split.close()
            return
        for i in range(n):
            subs = [s[i] for s in split]
            yield subs[0].concat(subs[1:]) if len(subs) > 1 else subs[0]

    def _split(self, parts: List[MicroPartition], n: int):
        """Split/coalesce to exactly n partitions, preserving order."""
        total = sum(len(p) for p in parts)
        target = max((total + n - 1) // max(n, 1), 1)
        combined = parts[0].concat(parts[1:]) if len(parts) > 1 else parts[0]
        rb = combined.combined()
        out = 0
        start = 0
        while out < n:
            end = min(start + target, len(rb)) if out < n - 1 else len(rb)
            yield MicroPartition.from_recordbatch(rb.slice(start, end))
            start = end
            out += 1

    def _range_partition(self, parts: List[MicroPartition],
                         by: List[Expression], descending: List[bool],
                         nulls_first: Optional[List[bool]] = None,
                         n: Optional[int] = None) -> List[MicroPartition]:
        """Sample → boundaries → partition_by_range → regroup."""
        n = n or len(parts)
        nulls_first = nulls_first or list(descending)
        if n == 1:
            combined = parts[0].concat(parts[1:]) if len(parts) > 1 else parts[0]
            return [combined]
        k = self.cfg.sample_size_for_sort
        samples = []
        for p in parts:
            rb = p.combined()
            s = rb.sample(size=min(k, len(rb))) if len(rb) else rb
            samples.append(s.eval_expression_list(by))
        merged = RecordBatch.concat(samples)
        merged = merged.filter(~_any_null(by, merged)) if len(merged) else merged
        if len(merged) == 0:
            combined = parts[0].concat(parts[1:]) if len(parts) > 1 else parts[0]
            return [combined] + [MicroPartition.empty(parts[0].schema)
                                 for _ in range(n - 1)]
        skeys = [col(e.name()) for e in by]
        merged_sorted = merged.sort(skeys, descending, nulls_first)
        idx = [int(len(merged_sorted) * (i + 1) / n)
               for i in range(n - 1)]
        idx = [min(i, len(merged_sorted) - 1) for i in idx]
        boundaries = merged_sorted.take(np.asarray(idx, dtype=np.int64))
        split = self._materialize_split(_ordered_parallel(
            iter(parts),
            lambda p: p.partition_by_range(by, boundaries, descending)))
        return self._regroup(split, n)

    # joins ------------------------------------------------------------
    def _exec_HashJoin(self, node: pp.HashJoin):
        how = node.how
        if node.strategy == "broadcast_right":
            right = _gather_all(self._exec(node.children[1]))
            child = self._exec(node.children[0])
            yield from _ordered_parallel(
                child, lambda p: p.hash_join(right, node.left_on,
                                             node.right_on, how))
            return
        if node.strategy == "broadcast_left":
            left = _gather_all(self._exec(node.children[0]))
            child = self._exec(node.children[1])
            yield from _ordered_parallel(
                child, lambda p: left.hash_join(p, node.left_on,
                                                node.right_on, how))
            return
        from . import memory
        lparts = memory.materialize(self._exec(node.children[0]))
        rparts = memory.materialize(self._exec(node.children[1]))
        if len(lparts) != len(rparts):
            # co-partition by concat-gather fallback
            lparts = [_gather_all(iter(lparts))]
            rparts = [_gather_all(iter(rparts))]
        pairs = list(zip(lparts, rparts))
        yield from _ordered_parallel(
            iter(pairs),
            lambda lr: lr[0].hash_join(lr[1], node.left_on, node.right_on, how))

    def _exec_CrossJoin(self, node: pp.CrossJoin):
        right = _gather_all(self._exec(node.children[1]))
        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, lambda p: p.cross_join(right))

    # writes -----------------------------------------------------------
    def _exec_Write(self, node: pp.Write):
        info = node.info
        if info.get("kind") == "sink":
            sink = info["sink"]
            sink.start()
            results = list(sink.write(self._exec(node.children[0])))
            yield sink.finalize(results)
            return
        from ..io import writers
        if info.get("mode") == "overwrite":
            writers.overwrite_dir(info["root_dir"])
        child = self._exec(node.children[0])
        outs = list(_ordered_parallel(
            child, lambda p: writers.write_micropartition(
                p, info["kind"], info["root_dir"],
                info.get("partition_cols"), info.get("options"))))
        outs = [o for o in outs if len(o)]
        if not outs:
            yield MicroPartition.empty(node.schema())
            return
        yield MicroPartition.from_recordbatch(
            RecordBatch.concat(outs).cast_to_schema(node.schema()))


def _lit_true() -> Expression:
    from ..expressions.expressions import lit
    return lit(True)


def _gather_all(parts: Iterator[MicroPartition]) -> MicroPartition:
    ps = list(parts)
    return ps[0].concat(ps[1:]) if len(ps) > 1 else ps[0]


def _any_null(by: List[Expression], rb: RecordBatch) -> Expression:
    e = col(by[0].name()).is_null()
    for b in by[1:]:
        e = e | col(b.name()).is_null()
    return e
