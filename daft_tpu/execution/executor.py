"""Streaming partition-parallel local executor.

The single-node engine (reference: "Swordfish",
``src/daft-local-execution``): operators stream MicroPartitions, pipelined
ops run on a shared thread pool (Arrow C++ and XLA both release the GIL, so
threads scale), pipeline breakers (sort / final agg / join build) materialize.
Ordering is preserved via bounded in-order future windows
(the RoundRobin dispatcher of ``dispatcher.rs:24-60``).

Global sort follows the reference's sample→boundaries→range-partition→merge
pipeline (``daft/execution/physical_plan.py:1632``).
"""

from __future__ import annotations

import concurrent.futures as cf
import os
import threading
from typing import Callable, Iterator, List, Optional

import numpy as np

from ..context import get_context
from ..expressions import Expression, col
from ..micropartition import MicroPartition
from ..physical import plan as pp
from ..recordbatch import RecordBatch
from ..series import Series

_POOL: Optional[cf.ThreadPoolExecutor] = None
_SCAN_POOL: Optional[cf.ThreadPoolExecutor] = None
# guards pool creation: two racing first callers used to each build a
# pool, leaking the loser's worker threads for the process lifetime
# (found by daft-lint's unguarded-global-mutation rule)
_pools_lock = threading.Lock()


def _pool() -> cf.ThreadPoolExecutor:
    global _POOL
    if _POOL is not None:   # hot path: no lock once built
        return _POOL
    with _pools_lock:
        if _POOL is None:
            _POOL = cf.ThreadPoolExecutor(
                max_workers=max(os.cpu_count() or 4, 4),
                thread_name_prefix="daft-tpu-exec")
        return _POOL


def _scan_pool() -> cf.ThreadPoolExecutor:
    """Dedicated pool for prefetch-pipelined scan producers. NOT the
    shared exec pool: a producer blocked on its bounded output queue
    would otherwise hold an exec slot that a downstream operator's future
    needs to drain that very queue (deadlock when window+1 ≥ pool size)."""
    global _SCAN_POOL
    if _SCAN_POOL is not None:
        return _SCAN_POOL
    with _pools_lock:
        if _SCAN_POOL is None:
            _SCAN_POOL = cf.ThreadPoolExecutor(
                max_workers=max((os.cpu_count() or 4) * 2, 8),
                thread_name_prefix="daft-tpu-scan")
        return _SCAN_POOL


def _ordered_parallel(inputs: Iterator, fn: Callable,
                      width: Optional[int] = None) -> Iterator:
    """Map fn over inputs on the pool, yielding results in order with a
    bounded in-flight window (backpressure)."""
    from .. import observability as obs
    width = width or max((os.cpu_count() or 4), 4) * 2
    pool = _pool()
    pending: List[cf.Future] = []
    it = iter(inputs)
    done = False
    while True:
        while not done and len(pending) < width:
            try:
                x = next(it)
            except StopIteration:
                done = True
                break
            # carry the submitting thread's stats attribution onto the
            # pool worker: shared-plane counters bumped inside fn must
            # credit the query this morsel belongs to
            pending.append(pool.submit(
                obs.run_attributed, obs.current_attribution(), fn, x))
        if not pending:
            return
        yield pending.pop(0).result()


class LocalExecutor:
    """Interprets a physical plan into a stream of MicroPartitions."""

    def __init__(self):
        from . import cancellation, memory
        self.cfg = get_context().execution_config
        self.stats = None
        # cooperative cancellation: the serving scheduler installs the
        # query's token on the submitting thread (cancel_scope); capture
        # it here so it rides the executor instance into stage threads
        self.cancel_token = cancellation.current_token()
        # bounds bytes of scan tasks materializing concurrently
        self.mem = memory.MemoryManager()
        # stage-input bindings for distributed stage fragments
        self.stage_inputs = {}
        self._aqe_planner = None
        # shared-subplan result buffers (multi-consumer physical nodes)
        import threading as _th
        self._shared = {}
        self._shared_lock = _th.Lock()

    def _aqe(self):
        if self._aqe_planner is None:
            from ..physical import adaptive
            self._aqe_planner = adaptive.new_planner(self.cfg)
        return self._aqe_planner

    def _poll_cancel(self) -> None:
        """Cancellation poll for blocking drain loops. The driver loop
        checks the token at every YIELD boundary, but a pipeline breaker
        (sort consume, exchange fanout, join bucket store) drains its
        whole child before yielding anything — without this poll,
        INTERRUPT on a breaker-heavy query ran it to completion while
        holding its admission (daft-lint: uncancellable-loop)."""
        tok = self.cancel_token
        if tok is not None:
            tok.check()

    def run(self, plan: pp.PhysicalPlan,
            stage_inputs=None) -> Iterator[MicroPartition]:
        if stage_inputs:
            self.stage_inputs = stage_inputs
        return self._run(plan)

    def _run(self, plan: pp.PhysicalPlan) -> Iterator[MicroPartition]:
        from .. import observability as obs
        self.stats = obs.new_query_stats()
        self.stats.plan = plan  # for explain_analyze rendering
        xdir = obs.xplane_trace_dir()

        def gen():
            xtrace = obs._XplaneTrace(xdir) if xdir else None
            tok = self.cancel_token
            it = None
            try:
                # every pull at this boundary runs with this query's
                # stats context attributed on the consumer thread, so
                # shared-plane counters (scan io, shuffle, recovery)
                # credit THIS query even when others run concurrently;
                # the token check bounds cancel latency to one morsel
                with obs.attributed(self.stats):
                    it = obs.wrap_progress(self._exec(plan))
                while True:
                    if tok is not None:
                        tok.check()
                    with obs.attributed(self.stats):
                        try:
                            item = next(it)
                        except StopIteration:
                            break
                    yield item
            finally:
                if it is not None and hasattr(it, "close"):
                    with obs.attributed(self.stats):
                        it.close()  # producer cleanup counts here too
                if xtrace is not None:
                    xtrace.stop()
                self.stats.finish()
                obs.set_last_stats(self.stats)
                path = obs.chrome_trace_path()
                if path and self.stats.tracer is not None:
                    self.stats.tracer.dump(path)
        return gen()

    # ------------------------------------------------------------------
    def _exec(self, node: pp.PhysicalPlan) -> Iterator[MicroPartition]:
        if getattr(node, "shared_consumers", 1) > 1:
            return self._shared_stream(node)
        return self._exec_node(node)

    def _exec_node(self, node: pp.PhysicalPlan) -> Iterator[MicroPartition]:
        h = getattr(self, "_exec_" + type(node).__name__, None)
        if h is None:
            raise NotImplementedError(f"executor for {type(node).__name__}")
        it = h(node)
        from ..analysis import plan_sanitizer
        it = plan_sanitizer.wrap_node(node, it)
        if self.stats is not None:
            it = self.stats.instrument(node, it)
        return it

    def _shared_stream(self, node) -> Iterator[MicroPartition]:
        """A subplan with multiple consumers executes ONCE into a
        breaker-budget buffer; every consumer streams the buffered
        partitions (reference: common-subplan reuse in the physical
        planner). Thread-safe: the push executor's consumer stages race
        here — the first builds, the rest wait on its completion."""
        import threading
        from . import memory
        with self._shared_lock:
            ent = self._shared.get(id(node))
            build = ent is None
            if build:
                ent = {"done": threading.Event(), "buf": None, "err": None,
                       "remaining": getattr(node, "shared_consumers", 2)}
                self._shared[id(node)] = ent
        if build:
            try:
                ent["buf"] = memory.materialize(
                    self._exec_node(node), memory.breaker_budget_bytes())
            except BaseException as exc:  # noqa: BLE001
                ent["err"] = exc
                raise
            finally:
                ent["done"].set()
        else:
            ent["done"].wait()
            if ent["err"] is not None:
                raise ent["err"]

        def serve():
            # each consumer decrements on completion (or abandonment —
            # GeneratorExit lands in the finally); the LAST one frees the
            # buffer's memory and spill files mid-query instead of at GC
            try:
                yield from iter(ent["buf"])
            finally:
                with self._shared_lock:
                    ent["remaining"] -= 1
                    last = ent["remaining"] <= 0
                    if last:
                        self._shared.pop(id(node), None)
                if last:
                    ent["buf"].close()
        return serve()

    # sources ----------------------------------------------------------
    def _morselize(self, stream: Iterator) -> Iterator:
        """Re-chunk a partition stream to ``default_morsel_size`` rows
        (the reference's dispatcher-side morsel re-chunking,
        ``src/daft-local-execution/src/buffer.rs``): oversized source
        partitions split so downstream operators pipeline at morsel
        granularity. Observed sizes land in the per-op trace stats."""
        morsel = int(self.cfg.default_morsel_size or 0)
        if morsel <= 0:
            yield from stream
            return
        for p in stream:
            n = len(p)
            if n <= morsel + morsel // 2:
                yield p
                continue
            rb = p.combined()
            for start in range(0, n, morsel):
                yield MicroPartition.from_recordbatch(
                    rb.slice(start, min(start + morsel, n)))

    def _exec_ScanSource(self, node: pp.ScanSource):
        from ..io import read_planner as rp
        if not node.tasks:
            yield MicroPartition.empty(node.schema())
            return
        prefetch = rp.scan_prefetch_tasks()
        if prefetch <= 0 or rp.scan_sequential_fallback():
            # pre-fast-path behavior: whole-task loads on the pool (kept
            # verbatim as the DAFT_TPU_CHAOS_SERIALIZE / active-fault-plan
            # degradation so PR 2's replay contract stays bit-identical)
            def run(t):
                est = t.size_bytes() or 0
                self.mem.acquire(est)
                try:
                    return _load_with_retry(t)
                finally:
                    self.mem.release(est)
            yield from self._morselize(_ordered_parallel(iter(node.tasks),
                                                         run))
            return
        yield from self._morselize(self._prefetch_scan(node.tasks, prefetch))

    def _prefetch_scan(self, tasks, window: int):
        """Prefetch-pipelined scan source: up to ``window`` upcoming
        ScanTasks resolve on the IO pool AHEAD of the one the consumer is
        draining, each admission-gated by the memory manager (prefetched
        bytes can't blow DAFT_TPU_MEMORY_LIMIT), and each task's batches
        stream out as its files decode — the first morsel lands at
        first-file completion, not task completion. Output stays in task
        order. Wall vs serial-equivalent time feeds the ``io`` stats
        block."""
        import collections
        import queue as _queue
        import threading
        import time as _time

        from ..io import read_planner as rp

        pool = _scan_pool()
        t_span0 = _time.perf_counter()

        class _Stream:
            """Per-task batch queue; ``dead`` makes an abandoned consumer
            (early limit, error upstream) stop the producer. UNBOUNDED on
            purpose: memory admission is the loading gate (as in the
            pre-PR path, which also released admission on load
            completion). A bounded queue would let a producer block on
            put() while HOLDING admission that the FIFO-head task's
            producer is waiting for — a deadlock the consumer, stuck on
            the head task's queue, could never break."""

            def __init__(self):
                self.q = _queue.Queue()
                self.dead = threading.Event()

            def put(self, item):
                if self.dead.is_set():
                    raise _ScanAbandoned()
                self.q.put(item)

        class _ScanAbandoned(Exception):
            pass

        def produce(task, st: _Stream, task_idx: int):
            if st.dead.is_set():  # consumer gone before we even started
                return
            from .. import tracing
            from . import governor
            t0 = _time.perf_counter()
            est = task.size_bytes() or 0
            # governor backpressure BEFORE admission: a bounded throttle
            # (never a gate — it times out) that slows the producers down
            # while process RSS sits above the high watermark, so
            # prefetched bytes stop arriving before the OS OOMs
            governor.throttle("scan_prefetch")
            # producer span keyed by the deterministic task index; the
            # producer thread carries the query's span context through
            # the same attribution the io counters ride
            sp = tracing.span("scan:prefetch", key=f"scan.t{task_idx}",
                              attrs={"est_bytes": est}, lane="scan")
            self.mem.acquire(est)
            try:
                with sp:
                    if st.dead.is_set():
                        return
                    schema = task.materialized_schema()
                    produced = False
                    try:
                        for rb in task.stream_batches():
                            st.put(("batch",
                                    MicroPartition.from_recordbatch(
                                        rb.cast_to_schema(schema))))
                            produced = True
                    except OSError:
                        if produced:
                            raise  # can't re-stream mid-task: dup rows
                        _time.sleep(0.2)  # transient IO: one clean retry
                        for rb in task.stream_batches():
                            st.put(("batch",
                                    MicroPartition.from_recordbatch(
                                        rb.cast_to_schema(schema))))
                            produced = True
                    if not produced:
                        st.put(("batch", MicroPartition.empty(schema)))
                    st.put(("done", None))
            except _ScanAbandoned:
                pass
            except BaseException as exc:  # noqa: BLE001
                try:
                    st.put(("err", exc))
                except _ScanAbandoned:
                    pass
            finally:
                self.mem.release(est)
                rp.scan_count("scan_task_us",
                              (_time.perf_counter() - t0) * 1e6)

        inflight = collections.deque()
        it = iter(tasks)
        submitted = [0]

        def submit() -> bool:
            try:
                t = next(it)
            except StopIteration:
                return False
            st = _Stream()
            from .. import observability as obs
            pool.submit(obs.run_attributed, obs.current_attribution(),
                        produce, t, st, submitted[0])
            submitted[0] += 1
            inflight.append(st)
            rp.scan_count("prefetch_tasks")
            return True

        from . import governor

        def refill():
            # fill to the governor's CURRENT window (≤ the configured
            # one): under memory pressure in-flight prefetch narrows to
            # one task ahead, and widens back out once RSS recovers
            while len(inflight) < governor.prefetch_window(window) + 1:
                if not submit():
                    return

        refill()
        current = None
        try:
            while inflight:
                current = inflight.popleft()
                while True:
                    kind, val = current.q.get()
                    if kind == "batch":
                        yield val
                    elif kind == "err":
                        raise val
                    else:
                        break
                current = None
                refill()
        finally:
            # an abandoned consumer (early limit, downstream error) must
            # unblock every producer — including the one being drained
            if current is not None:
                current.dead.set()
            for st in inflight:
                st.dead.set()
            rp.scan_count("scan_span_us",
                          (_time.perf_counter() - t_span0) * 1e6)

    def _exec_InMemorySource(self, node: pp.InMemorySource):
        if not node.partitions:
            yield MicroPartition.empty(node.schema())
            return
        yield from iter(node.partitions)

    def _exec_StageInput(self, node: pp.StageInput):
        # binding: a materialized partition list OR a lazy _ParallelFetch
        # (distributed reduce input — per-source tables stream in as the
        # bounded fetch pool completes them; emptiness is only known after
        # draining it)
        parts = self.stage_inputs.get(node.stage_id)
        if parts is None:
            yield MicroPartition.empty(node.schema())
            return
        got = False
        for p in parts:
            got = True
            yield p
        if not got:
            yield MicroPartition.empty(node.schema())

    # pipelined maps ---------------------------------------------------
    def _exec_Project(self, node: pp.Project):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: p.eval_expression_list(node.exprs))

    def _exec_UDFProject(self, node: pp.UDFProject):
        child = self._exec(node.children[0])
        width = node.concurrency or None
        yield from _ordered_parallel(
            child, lambda p: p.eval_expression_list(node.exprs), width=width)

    def _exec_Filter(self, node: pp.Filter):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, lambda p: p.filter(node.predicate))

    def _exec_Explode(self, node: pp.Explode):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, lambda p: p.explode(node.exprs))

    def _exec_Unpivot(self, node: pp.Unpivot):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: p.unpivot(node.ids, node.values,
                                       node.variable_name, node.value_name))

    def _exec_Sample(self, node: pp.Sample):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: p.sample(fraction=node.fraction, size=None,
                                      with_replacement=node.with_replacement,
                                      seed=node.seed)
            if node.fraction is not None else p.head(node.size))

    def _exec_MonotonicallyIncreasingId(self, node):
        child = self._exec(node.children[0])
        for i, p in enumerate(child):
            yield p.add_monotonically_increasing_id(i, node.column_name)

    def _exec_Limit(self, node: pp.Limit):
        remaining = node.limit
        to_skip = node.offset
        for p in self._exec(node.children[0]):
            n = len(p)
            if to_skip:
                if n <= to_skip:
                    to_skip -= n
                    continue
                p = MicroPartition.from_recordbatch(
                    p.combined().slice(to_skip, n))
                to_skip = 0
            if remaining <= 0:
                break
            if len(p) > remaining:
                p = p.head(remaining)
            remaining -= len(p)
            yield p
            if remaining <= 0:
                break

    def _exec_Concat(self, node: pp.Concat):
        yield from self._exec(node.children[0])
        yield from self._exec(node.children[1])

    # aggregation ------------------------------------------------------
    def _streamed_agg_input(self, node) -> bool:
        """True when this Aggregate's child is a StageInput bound to a
        STREAMING parallel fetch: the binding yields one morsel per map
        source (not hash-disjoint!), so per-morsel aggregation would
        duplicate groups — the streaming merge-agg below re-merges
        instead. ``worker._stream_safe`` only enables streaming when the
        aggs are self-merges, so the merge table always exists here."""
        ch = node.children[0] if node.children else None
        if not isinstance(ch, pp.StageInput):
            return False
        return getattr(self.stage_inputs.get(ch.stage_id),
                       "streaming", False)

    def _exec_Aggregate(self, node: pp.Aggregate):
        if self._streamed_agg_input(node):
            yield from self._merge_agg_stream(node,
                                              self._exec(node.children[0]))
            return
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: p.agg(node.aggs, node.group_by)
            .cast_to_schema(node.schema()))

    _MERGE_AGG_REAGG_ROWS = 1 << 17

    def _merge_agg_stream(self, node: pp.Aggregate, stream):
        """Streaming merge over a multi-morsel pipelined-fetch input:
        aggregate each arriving source morsel and LSM-merge the states
        with the self-merge table (``aggs.merge_exprs_for``) — reduce
        compute overlaps the remaining fetches instead of waiting on the
        full concat barrier, and emits ONE state morsel like the barrier
        path did."""
        from ..aggs import merge_exprs_for
        merge_aggs = merge_exprs_for(node.aggs, alias_to="out")
        state: Optional[MicroPartition] = None
        buf: List[MicroPartition] = []
        rows = 0

        def merge():
            nonlocal state, buf, rows
            if not buf:
                return
            fresh = buf[0].concat(buf[1:]) if len(buf) > 1 else buf[0]
            fresh = fresh.agg(node.aggs, node.group_by) \
                .cast_to_schema(node.schema())
            state = fresh if state is None else \
                state.concat([fresh]).agg(merge_aggs, node.group_by) \
                .cast_to_schema(node.schema())
            buf, rows = [], 0

        for mp in stream:
            self._poll_cancel()
            buf.append(mp)
            rows += len(mp)
            if rows >= max(self._MERGE_AGG_REAGG_ROWS,
                           0 if state is None else len(state)):
                merge()
        merge()
        if state is not None:
            yield state
        else:
            yield MicroPartition.empty(node.schema())

    def _exec_DeviceFragmentAgg(self, node: pp.DeviceFragmentAgg):
        from ..aggs import split_agg_expr
        from ..device import fragment, runtime as drt
        specs = [split_agg_expr(a) for a in node.aggs]
        child_exprs = [(c if c is not None else _lit_true()).alias(f"__v{i}__")
                       for i, (op, c, nm, pr) in enumerate(specs)]
        ops = tuple(s[0] for s in specs)
        agg_names = [s[2] for s in specs]
        agg_cols = [col(nm) for nm in agg_names]

        def host_agg(rb: RecordBatch) -> MicroPartition:
            if node.predicate is not None:
                rb = rb.filter(node.predicate)
            return MicroPartition.from_recordbatch(
                rb.agg(node.aggs, node.group_by).cast_to_schema(node.schema()))

        def morsel_gate(rb: RecordBatch, window: int = 0):
            """Cost-gate one morsel: the fused program when the device
            should take it, None → host. No device work happens here.
            ``window`` ≥ 2 prices the transfer at the pipeline's
            steady-state overlap instead of the full serial chain."""
            from ..device import costmodel
            if not (drt.device_enabled()
                    and len(rb) >= max(drt._min_rows(), 1)):
                return None
            prog = fragment.get_fused_agg(node.group_by, child_exprs, ops,
                                          node.predicate, rb.schema)
            if prog is None:
                return None
            # in-memory batch: the upload is one-shot, it must beat the
            # host outright (no HBM-cache identity to invest in)
            from ..device import column as dcol
            packed_out = fragment.packed_bytes_per_group(
                len(node.group_by), len(ops)) * fragment._OUT_CAP0
            if not costmodel.agg_upload_wins(
                    dcol.encoded_nbytes(rb, prog.compiled.needs_cols),
                    packed_out, cacheable=False,
                    host_bytes=drt._batch_cols_nbytes(
                        rb, prog.compiled.needs_cols),
                    strategy=fragment.gate_strategy(
                        prog, len(rb), getattr(node, "group_ndv", None)),
                    window=window):
                return None
            return prog

        def submit_morsel(prog, rb: RecordBatch):
            """Encode + async dispatch (no blocking fetch); None → the
            device declined at submit (pyobject / lowering failure)."""
            try:
                return fragment.submit_fused_agg(
                    prog, rb, node.group_by, agg_cols, node.schema(),
                    groups=getattr(node, "group_ndv", None))
            except Exception:  # device OOM / lowering failure → host tier
                return None

        def drain_device_agg(tok) -> Optional[MicroPartition]:
            try:
                out = fragment.drain_fused_agg_table(tok)
            except Exception:  # device failure mid-flight → host tier
                return None
            if out is None:
                return None
            return MicroPartition.from_recordbatch(
                out.cast_to_schema(node.schema()))

        def device_agg(rb: RecordBatch) -> Optional[MicroPartition]:
            prog = morsel_gate(rb)
            if prog is None:
                return None
            tok = submit_morsel(prog, rb)
            return None if tok is None else drain_device_agg(tok)

        src = node.children[0]
        if isinstance(src, pp.ScanSource) and src.tasks \
                and drt.device_enabled() \
                and _fragment_groups_affordable(node, src):
            # task-level path: consult the HBM column cache per scan task —
            # a hit runs the fused program on device-resident columns with
            # zero file IO and zero host→device transfer. All tasks' packed
            # results come back in ONE device→host transfer (the link is
            # RTT-bound, so per-task gets would serialize ~40 ms each).
            prog = fragment.get_fused_agg(node.group_by, child_exprs, ops,
                                          node.predicate, src.schema())
            if prog is not None:
                yield from self._fragment_scan_tasks(
                    node, prog, src, agg_cols, host_agg)
                return

        child = self._exec(node.children[0])
        from ..device import pipeline as dpipe
        window = dpipe.inflight_window()
        if window > 0 and drt.device_enabled():
            # round 17: async pipeline — morsel N+1's encode+upload runs
            # on the submit pool while morsel N computes on device and
            # morsel N−1 downloads/decodes here
            yield from self._pipelined_fragment_morsels(
                child, morsel_gate, submit_morsel, drain_device_agg,
                host_agg, window)
            return

        # synchronous per-morsel chain, kept verbatim as the
        # DAFT_TPU_CHAOS_SERIALIZE / active-fault-plan degradation so
        # chaos replay stays bit-identical
        def run(p: MicroPartition) -> MicroPartition:
            rb = p.combined()
            out = device_agg(rb)
            return out if out is not None else host_agg(rb)

        yield from _ordered_parallel(child, run)

    def _pipelined_fragment_morsels(self, child, morsel_gate,
                                    submit_morsel, drain_device_agg,
                                    host_agg, window: int):
        """Bounded-window async device pipeline over the morsel stream
        (device/pipeline.py). Each device morsel's slot admits its
        encoded host+HBM footprint on submit (before the dispatch) and
        releases on drain; host-routed morsels bypass the window so a
        host-heavy stream keeps full pool parallelism. Ordering is
        preserved."""
        import time as _time

        from ..device import column as dcol, pipeline as dpipe

        def submit(p, seq, gate):
            rb = p.combined()
            prog = morsel_gate(rb, window=window)
            if prog is None:
                return host_agg(rb)
            est = dcol.encoded_nbytes(rb, prog.compiled.needs_cols)
            slot = dpipe.acquire_slot(gate, seq, self.mem, est)
            try:
                t0 = _time.perf_counter()
                with dpipe.upload_span(seq, window):
                    tok = submit_morsel(prog, rb)
                sub_s = _time.perf_counter() - t0
            except BaseException:
                dpipe.release_slot(slot)
                raise
            if tok is None:
                dpipe.release_slot(slot)
                return host_agg(rb)
            return dpipe.InflightItem(slot, (tok, rb), sub_s=sub_s,
                                      t_dispatched_us=dpipe.now_us())

        def drain(ret, seq):
            if not isinstance(ret, dpipe.InflightItem):
                return ret  # host result, already computed on the pool
            tok, rb = ret.token
            dpipe.note_compute_span(seq, window, ret.t_dispatched_us)
            with dpipe.download_span(seq, window):
                out = drain_device_agg(tok)
            return out if out is not None else host_agg(rb)

        yield from dpipe.run_pipelined(child, submit, drain,
                                       window=window,
                                       poll=self._poll_cancel)

    def _fragment_scan_tasks(self, node, prog, src, agg_cols, host_agg):
        """Windowed streaming over scan tasks: resolve each task in the
        window to an encoded DeviceTable (HBM cache hit, or load+encode+
        insert) or a host batch, dispatch the window's fused programs, and
        fetch ALL its packed results in one transfer. The window bounds
        host RAM and non-cached HBM residency like the morsel pipeline's
        in-flight limit; fallbacks re-read the pristine task (never decode
        the lossy device encoding back)."""
        import itertools
        from ..device import cache as dcache, column as dcol, fragment
        from ..device import runtime as drt

        n_tasks = len(src.tasks)

        def load(t) -> RecordBatch:
            est = t.size_bytes() or 0
            self.mem.acquire(est)
            try:
                return _load_with_retry(t).combined()
            finally:
                self.mem.release(est)

        def classify(t):
            """Phase A: cache hits are committed device participants;
            too-small / pyobject batches are forced host; the rest are
            candidates for the cost gate (phase B)."""
            fp = dcache.task_fingerprint(t)
            if fp is not None:
                dt = dcache.get_cache().get_table(fp, prog.compiled.needs_cols)
                if dt is not None:
                    return ("dev", dt, t)
            rb = load(t)
            if len(rb) < max(drt._min_rows(), 1):
                return ("host", rb, t)
            for nm in prog.compiled.needs_cols:
                if rb.get_column(nm).is_pyobject():
                    return ("host", rb, t)
            return ("cand", rb, t, fp)

        def gate(cand, n_sharing):
            """Phase B: measured cost gate. A cacheable upload is an
            investment the HBM cache repays on every later scan of the
            same task — but only if the whole scan's working set actually
            FITS the budget (otherwise LRU thrash re-pays the upload every
            query and put_table would refuse oversized tables anyway)."""
            from ..device import costmodel
            from ..device import fragment as dfrag
            _, rb, t, fp = cand
            packed_out = dfrag.packed_bytes_per_group(
                prog.nk, len(prog.ops)) * dfrag._OUT_CAP0
            col_bytes = dcol.encoded_nbytes(rb, prog.compiled.needs_cols)
            fits = col_bytes * max(n_tasks, 1) <= dcache._budget()
            # the packed fetch's round trips amortize over the tasks that
            # actually SHARE the transfer: committed cache hits + gate
            # candidates (r4 advisor: dividing by the whole window length
            # under-charged device tasks in mixed windows where forced-host
            # tasks never join the fetch). Still optimistic by candidates
            # the gate itself rejects — the safe direction, since fewer
            # sharers only makes the gate stricter.
            if not costmodel.agg_upload_wins(
                    col_bytes, packed_out,
                    cacheable=fp is not None and fits,
                    round_trips=2.0 / max(1, n_sharing),
                    host_bytes=drt._batch_cols_nbytes(
                        rb, prog.compiled.needs_cols),
                    strategy=dfrag.gate_strategy(
                        prog, len(rb), getattr(node, "group_ndv", None)),
                    # overlap pricing when the windows really pipeline
                    # (pwin is assigned before any window resolves)
                    window=pwin):
                return ("host", rb, t)
            try:
                dt = dcol.encode_batch(rb, prog.compiled.needs_cols)
            except (ValueError, TypeError):
                return ("host", rb, t)
            if fp is not None and fits:
                # only cache working sets that FIT the budget: caching a
                # slice of an oversized scan just LRU-evicts entries other
                # queries still repay (SF10 thrash, r4) — the upload then
                # streams through as a one-shot morsel instead
                dcache.get_cache().put_table(fp, dt)
            return ("dev", dt, t)

        width = max((os.cpu_count() or 4), 4) * 2
        groups_ndv = getattr(node, "group_ndv", None)
        from ..device import pipeline as dpipe
        pwin = dpipe.inflight_window()
        if pwin > 0:
            # the async pipeline needs windows to overlap: one giant
            # window over a small scan starves it (fetches stay batched
            # per window either way). Aim for window+1 windows — enough
            # to fill the in-flight ladder without multiplying the
            # per-window fetch round-trips an RTT-bound query pays
            width = max(1, min(width, -(-n_tasks // max(pwin + 1, 1))))

        def windows():
            it = iter(src.tasks)
            while True:
                w = list(itertools.islice(it, width))
                if not w:
                    return
                yield w

        def resolve(window_tasks):
            classified = list(_ordered_parallel(iter(window_tasks),
                                                classify))
            n_sharing = sum(1 for c in classified if c[0] != "host")
            gated = _ordered_parallel(
                iter([c for c in classified if c[0] == "cand"]),
                lambda c: gate(c, n_sharing))
            gated_it = iter(list(gated))
            return [c if c[0] != "cand" else next(gated_it)
                    for c in classified]

        def emit(resolved, outs):
            di = 0
            for kind, val, t in resolved:
                if kind == "dev":
                    out = outs[di]
                    di += 1
                    if out is None:  # device failure → pristine host re-read
                        yield host_agg(load(t))
                    else:
                        yield MicroPartition.from_recordbatch(
                            out.cast_to_schema(node.schema()))
                else:
                    yield host_agg(val)

        if pwin <= 0:
            # synchronous window loop, kept verbatim as the chaos /
            # fault-plan degradation: window N+1's loads wait for
            # window N's fetch, exactly the pre-pipeline event order
            for w in windows():
                resolved = resolve(w)
                outs = fragment.run_fused_agg_tables(
                    prog,
                    [dt for kind, dt, _ in resolved if kind == "dev"],
                    src.schema(), node.group_by, agg_cols, node.schema(),
                    groups=groups_ndv)
                yield from emit(resolved, outs)
            return

        # round 17 async pipeline over windows: window N+1's classify /
        # load / encode / dispatch runs on the submit pool while window
        # N's packed results download and decode here. Each in-flight
        # window's slot admits the encoded HBM footprint it keeps
        # resident until its drain (the transient load bytes are
        # separately admitted inside load()).
        import time as _time

        def p_submit(window_tasks, seq, wgate):
            t0 = _time.perf_counter()
            resolved = resolve(window_tasks)
            tables = [dt for kind, dt, _ in resolved if kind == "dev"]
            est = sum(
                int(c.data.nbytes) + int(c.validity.nbytes)
                for dt in tables for c in dt.columns.values())
            pre_s = _time.perf_counter() - t0
            slot = dpipe.acquire_slot(wgate, seq, self.mem, est)
            try:
                t1 = _time.perf_counter()
                with dpipe.upload_span(seq, pwin):
                    tok = fragment.submit_fused_agg_tables(
                        prog, tables, src.schema(), node.group_by,
                        agg_cols, node.schema(), groups=groups_ndv)
                sub_s = pre_s + (_time.perf_counter() - t1)
            except BaseException:
                dpipe.release_slot(slot)
                raise
            return dpipe.InflightItem(slot, (resolved, tok), sub_s=sub_s,
                                      t_dispatched_us=dpipe.now_us())

        def p_drain(ret, seq):
            resolved, tok = ret.token
            dpipe.note_compute_span(seq, pwin, ret.t_dispatched_us)
            with dpipe.download_span(seq, pwin):
                outs = fragment.drain_fused_agg_tables(tok)
            # release BEFORE emitting: a device-failure fallback re-reads
            # its task through load()'s own admission, which must not
            # wait on this very slot's bytes (release_slot is idempotent
            # — the driver's release after drain becomes a no-op)
            dpipe.release_slot(ret.slot)
            return list(emit(resolved, outs))

        for outs in dpipe.run_pipelined(windows(), p_submit, p_drain,
                                        window=pwin, width=pwin + 1,
                                        poll=self._poll_cancel):
            yield from outs

    # fused regions (round 21 whole-query compilation) -----------------
    def _exec_FusedRegion(self, node: pp.FusedRegion):
        """Execute a planner-proposed fusion region: the region's whole
        operator chain runs as ONE device program per morsel (submit =
        encode+dispatch, drain = one packed fetch), riding the r17 async
        pipeline. Admission is priced per morsel by ``fusion_wins``
        (``DAFT_TPU_FUSION=1`` force-admits); every decline — cost gate,
        pyobject/encode failure, overflow past the ladder ceiling — runs
        the equivalent host chain per morsel, and a region whose program
        does not lower at all runs the untouched ``fallback`` subtree."""
        from ..device import runtime as drt
        from ..physical import fusion as pfusion
        mode = pfusion.fusion_mode(self.cfg)
        if mode == "0" or not drt.device_enabled():
            yield from self._exec(node.fallback)
            return
        if node.shape == "join_agg":
            yield from self._exec_region_join_agg(node, mode)
            return
        yield from self._exec_region_chain(node, mode)

    def _exec_region_chain(self, node: pp.FusedRegion, mode: str):
        """chain / topk shapes: predicate + projection (+ in-program
        argsort for topk) + compaction in one dispatch, packed survivors
        back in one transfer."""
        from ..device import column as dcol, costmodel, fragment
        from ..device import pipeline as dpipe, runtime as drt
        topk = node.shape == "topk"
        prog = fragment.get_fused_region(
            node.exprs, node.predicate, node.source.schema(),
            sort_by=node.sort_by, descending=node.descending,
            nulls_first=node.nulls_first, limit=node.limit,
            fused_ops=node.fused_ops)
        if prog is None:
            yield from self._exec(node.fallback)
            return
        n_ops = max(len(node.fused_ops) - 1, 2)

        def host_run(rb: RecordBatch) -> MicroPartition:
            if node.predicate is not None:
                rb = rb.filter(node.predicate)
            rb = rb.eval_expression_list(node.exprs) \
                .cast_to_schema(node.schema())
            if topk:
                # per-morsel top-k in the OUTPUT namespace (the TopN
                # fallback's sort keys live there); merged below
                rb = rb.top_n(node.fallback.sort_by, node.limit,
                              node.descending, node.nulls_first)
            return MicroPartition.from_recordbatch(rb)

        def gate(rb: RecordBatch, window: int = 0) -> bool:
            if len(rb) < max(drt._min_rows(), 1):
                return False
            if mode == "1":
                return True
            est_w = dcol.bucket_capacity(max(node.limit or 0, 1)) if topk \
                else dcol.bucket_capacity(max(len(rb), 1))
            return costmodel.fusion_wins(
                node.shape, len(rb),
                dcol.encoded_nbytes(rb, prog.compiled.needs_cols),
                (1 + 2 * prog.nout) * 8 * est_w, n_ops,
                host_bytes=drt._batch_cols_nbytes(
                    rb, prog.compiled.needs_cols),
                window=window)

        def device_submit(rb: RecordBatch):
            try:
                return fragment.submit_region(prog, rb, node.exprs,
                                              node.schema())
            except Exception:
                return None

        def device_drain(tok) -> Optional[MicroPartition]:
            try:
                out = fragment.drain_region(tok)
            except Exception:
                return None
            if out is None:
                return None
            out = out.cast_to_schema(node.schema())
            return MicroPartition.from_recordbatch(out)

        def emit():
            child = self._exec(node.source)
            window = dpipe.inflight_window()
            if window > 0:
                def submit(p, seq, wgate):
                    import time as _time
                    rb = p.combined()
                    if not gate(rb, window=window):
                        return host_run(rb)
                    est = dcol.encoded_nbytes(rb, prog.compiled.needs_cols)
                    slot = dpipe.acquire_slot(wgate, seq, self.mem, est)
                    try:
                        t0 = _time.perf_counter()
                        with dpipe.upload_span(seq, window):
                            tok = device_submit(rb)
                        sub_s = _time.perf_counter() - t0
                    except BaseException:
                        dpipe.release_slot(slot)
                        raise
                    if tok is None:
                        dpipe.release_slot(slot)
                        return host_run(rb)
                    return dpipe.InflightItem(
                        slot, (tok, rb), sub_s=sub_s,
                        t_dispatched_us=dpipe.now_us())

                def drain(ret, seq):
                    if not isinstance(ret, dpipe.InflightItem):
                        return ret
                    tok, rb = ret.token
                    dpipe.note_compute_span(seq, window, ret.t_dispatched_us)
                    with dpipe.download_span(seq, window):
                        out = device_drain(tok)
                    return out if out is not None else host_run(rb)

                yield from dpipe.run_pipelined(child, submit, drain,
                                               window=window,
                                               poll=self._poll_cancel)
                return

            def run(p: MicroPartition) -> MicroPartition:
                rb = p.combined()
                if not gate(rb):
                    return host_run(rb)
                tok = device_submit(rb)
                out = device_drain(tok) if tok is not None else None
                return out if out is not None else host_run(rb)

            yield from _ordered_parallel(child, run)

        if not topk:
            yield from emit()
            return
        # topk tail: each morsel arrives already reduced to its own top-k
        # bucket; one final host merge produces the query's k rows
        tops = list(emit())
        if not tops:
            yield MicroPartition.from_recordbatch(
                RecordBatch.empty(node.schema()))
            return
        merged = tops[0].concat(tops[1:]) if len(tops) > 1 else tops[0]
        yield MicroPartition.from_recordbatch(
            merged.combined().top_n(node.fallback.sort_by, node.limit,
                                    node.descending, node.nulls_first))

    def _exec_region_join_agg(self, node: pp.FusedRegion, mode: str):
        """join_agg shape: the broadcast build side materializes once
        (host) and is encoded + key-sorted once on device; every probe
        morsel then joins, projects, and partially aggregates in ONE
        dispatch. Output is partial group blocks — the parent final
        Aggregate merges them."""
        from ..aggs import split_agg_expr
        from ..device import column as dcol, costmodel, fragment
        from ..device import pipeline as dpipe, runtime as drt
        specs = [split_agg_expr(a) for a in node.aggs]
        child_exprs = [(c if c is not None else _lit_true())
                       .alias(f"__v{i}__")
                       for i, (op, c, nm, pr) in enumerate(specs)]
        ops = tuple(s[0] for s in specs)
        agg_cols = [col(s[2]) for s in specs]
        post_pred = getattr(node, "post_predicate", None)
        lkey = node.left_on[0].name()
        rkey = node.right_on[0].name()
        prog = fragment.get_fused_join_agg(
            node.group_by, child_exprs, ops, node.predicate, post_pred,
            lkey, rkey, node.source.schema(), node.build.schema(),
            fused_ops=node.fused_ops)
        if prog is None:
            yield from self._exec(node.fallback)
            return
        build_rb = _gather_all(self._exec(node.build)).combined()
        build = fragment.prepare_region_build(prog, build_rb)
        if build is None:
            yield from self._exec(node.fallback)
            return
        n_ops = max(len(node.fused_ops), 3)
        nk, nv = len(node.group_by), len(ops)
        # adaptive group-bucket start: seed the next morsel's ladder from
        # the last drained group count (q3-style high-NDV keys would pay
        # one overflow re-dispatch per morsel otherwise)
        g_hint = [fragment._OUT_CAP0]

        def host_run(rb: RecordBatch) -> MicroPartition:
            if node.predicate is not None:
                rb = rb.filter(node.predicate)
            joined = rb.hash_join(build_rb, list(node.left_on),
                                  list(node.right_on), "inner")
            if post_pred is not None:
                joined = joined.filter(post_pred)
            return MicroPartition.from_recordbatch(
                joined.agg(list(node.aggs), list(node.group_by))
                .cast_to_schema(node.schema()))

        def gate(rb: RecordBatch, window: int = 0) -> bool:
            if len(rb) < max(drt._min_rows(), 1):
                return False
            if mode == "1":
                return True
            need = list(dict.fromkeys(
                [lkey] + list(prog.probe_needs)
                + list(prog.c_pred.needs_cols
                       if prog.c_pred is not None else ())))
            return costmodel.fusion_wins(
                "join_agg", len(rb), dcol.encoded_nbytes(rb, need),
                (1 + 2 * (nk + nv)) * 8
                * dcol.bucket_capacity(max(g_hint[0], 1)),
                n_ops, host_bytes=drt._batch_cols_nbytes(rb, need),
                window=window)

        def device_submit(rb: RecordBatch):
            try:
                return fragment.submit_join_agg(
                    prog, rb, build, node.group_by, agg_cols,
                    node.schema(), start_out_cap=g_hint[0])
            except Exception:
                return None

        def device_drain(tok) -> Optional[MicroPartition]:
            try:
                res = fragment.drain_join_agg(tok)
            except Exception:
                return None
            if res is None:
                return None
            out, g = res
            g_hint[0] = max(g, fragment._OUT_CAP0)
            return MicroPartition.from_recordbatch(
                out.cast_to_schema(node.schema()))

        child = self._exec(node.source)
        window = dpipe.inflight_window()
        if window > 0:
            def submit(p, seq, wgate):
                import time as _time
                rb = p.combined()
                if not gate(rb, window=window):
                    return host_run(rb)
                need = list(dict.fromkeys([lkey] + list(prog.probe_needs)))
                est = dcol.encoded_nbytes(rb, need)
                slot = dpipe.acquire_slot(wgate, seq, self.mem, est)
                try:
                    t0 = _time.perf_counter()
                    with dpipe.upload_span(seq, window):
                        tok = device_submit(rb)
                    sub_s = _time.perf_counter() - t0
                except BaseException:
                    dpipe.release_slot(slot)
                    raise
                if tok is None:
                    dpipe.release_slot(slot)
                    return host_run(rb)
                return dpipe.InflightItem(slot, (tok, rb), sub_s=sub_s,
                                          t_dispatched_us=dpipe.now_us())

            def drain(ret, seq):
                if not isinstance(ret, dpipe.InflightItem):
                    return ret
                tok, rb = ret.token
                dpipe.note_compute_span(seq, window, ret.t_dispatched_us)
                with dpipe.download_span(seq, window):
                    out = device_drain(tok)
                return out if out is not None else host_run(rb)

            yield from dpipe.run_pipelined(child, submit, drain,
                                           window=window,
                                           poll=self._poll_cancel)
            return

        def run(p: MicroPartition) -> MicroPartition:
            rb = p.combined()
            if not gate(rb):
                return host_run(rb)
            tok = device_submit(rb)
            out = device_drain(tok) if tok is not None else None
            return out if out is not None else host_run(rb)

        yield from _ordered_parallel(child, run)

    def _exec_DeviceExchangeAgg(self, node: pp.DeviceExchangeAgg):
        """Shuffle+final-merge as ONE mesh program: shard the partial group
        blocks over the device mesh, all_to_all by key hash over ICI, merge,
        and decode one disjoint group block per shard."""
        from . import memory
        parts = memory.materialize(self._exec(node.children[0]),
                                   memory.breaker_budget_bytes())
        try:
            outs = self._mesh_exchange_agg(node, parts)
            if outs is not None:
                yield from outs
                return
            # host fallback: hash exchange + final aggregate (what
            # translate would have emitted without the mesh, including its
            # partition cap) — bucket-store backed
            n = max(min(len(parts),
                        self.cfg.shuffle_aggregation_default_partitions), 1)
            store = self._key_bucket_store(iter(parts),
                                           list(node.group_by), n)
            try:
                yield from _ordered_parallel(
                    self._emit_buckets(store, node.children[0].schema()),
                    lambda p: MicroPartition.from_recordbatch(
                        p.combined().agg(node.aggs, node.group_by)
                        .cast_to_schema(node.schema())))
            finally:
                store.close()
        finally:
            parts.close()

    def _mesh_exchange_agg(self, node, parts) -> Optional[List[MicroPartition]]:
        import jax
        import numpy as np
        from ..aggs import split_agg_expr
        from ..device import column as dcol, runtime as drt
        from ..parallel import exchange, mesh as pmesh
        if not drt.device_enabled():
            return None
        mesh = pmesh.get_mesh()
        if mesh is None or pmesh.mesh_size() < 2:
            return None
        rb = RecordBatch.concat([p.combined() for p in parts]) \
            if len(parts) > 1 else parts[0].combined()
        if len(rb) == 0:
            return [MicroPartition.from_recordbatch(
                RecordBatch.empty(node.schema()))]
        key_names = [g.name() for g in node.group_by]
        specs = [split_agg_expr(a) for a in node.aggs]
        ops = tuple(s[0] for s in specs)
        val_names = [s[1]._unalias().params[0] for s in specs]
        out_names = [s[2] for s in specs]
        n = pmesh.mesh_size()
        total = len(rb)
        # per-shard capacity padded to a size class so literal-different
        # row counts re-enter the memoized collective program instead of
        # tracing one program per row count (the r16 retrace budget)
        C = dcol.bucket_capacity((total + n - 1) // n)
        cap = n * C

        encode = _np_plane_encoder(rb, cap)
        kplanes = _encode_plane_lists(encode, key_names)
        vplanes = _encode_plane_lists(encode, val_names)
        if kplanes is None or vplanes is None:
            return None
        keys, kvalids, kdicts = kplanes
        vals, vvalids, vdicts = vplanes
        mask = np.arange(cap) < total
        try:
            sb = lambda a: exchange.shard_blocks(mesh, a)
            fk, fkv, fv, fvv, gmask = exchange.sharded_grouped_agg(
                mesh, tuple(sb(k) for k in keys),
                tuple(sb(k) for k in kvalids),
                tuple(sb(v) for v in vals),
                tuple(sb(v) for v in vvalids), sb(mask), ops)
            host = jax.device_get((fk, fkv, fv, fvv, gmask))
        except Exception:
            return None
        _count_ici_exchange(total, list(keys) + list(vals),
                            list(kvalids) + list(vvalids))
        fk, fkv, fv, fvv, gmask = [
            [np.asarray(a) for a in grp] if isinstance(grp, (list, tuple))
            else np.asarray(grp) for grp in host]
        spec = [(nm, node.schema()[nm].dtype, fk[i], fkv[i], kdicts[i])
                for i, nm in enumerate(key_names)]
        spec += [(nm, node.schema()[nm].dtype, fv[j], fvv[j], vdicts[j])
                 for j, nm in enumerate(out_names)]
        return _decode_mesh_shards(n, gmask, spec, node.schema())

    def _mesh_hash_repartition(self, parts, by, n: int
                               ) -> Optional[List[MicroPartition]]:
        """Hash repartition as one all_to_all over the device mesh — chosen
        when the target partition count equals the mesh width and every
        column either round-trips the device encoding bit-exactly or is
        string/binary (those ride shared-dictionary codes built from the
        single concatenated batch; see _np_plane_encoder)."""
        import jax
        from ..device import column as dcol, runtime as drt
        from ..parallel import exchange, mesh as pmesh
        if not drt.device_enabled():
            return None
        if pmesh.mesh_size() < 2 or n != pmesh.mesh_size():
            return None
        mesh = pmesh.get_mesh()
        rb = RecordBatch.concat([p.combined() for p in parts]) \
            if len(parts) > 1 else parts[0].combined()
        # tiny repartitions don't repay the collective program's dispatch
        # against the host fanout: the cost model prices the exact bytes
        # against the calibrated ICI rate (DAFT_TPU_MESH_MIN_ROWS
        # force-overrides; =0 forces the mesh)
        if not pmesh.mesh_admits(
                len(rb), rb.size_bytes() / max(len(rb), 1)):
            return None
        schema = rb.schema
        # pure data movement must be bit-exact: every column must round-trip
        # the device encoding losslessly (no decimals-as-floats, no f64→f32).
        # String/binary columns qualify: the whole input is concatenated
        # into one batch, so their dictionary codes are shared across every
        # output shard and decode back exactly (see _np_plane_encoder).
        for f in schema:
            if not (dcol.is_lossless_device_dtype(f.dtype)
                    or f.dtype.is_string() or f.dtype.is_binary()):
                return None
        if len(rb) == 0:
            return [MicroPartition.from_recordbatch(RecordBatch.empty(schema))
                    for _ in range(n)]
        total = len(rb)
        # size-class padded per-shard capacity: one collective program per
        # bucket, not per literal row count (r16 retrace discipline)
        C = dcol.bucket_capacity((total + n - 1) // n)
        cap = n * C
        # destination shard from the SAME xxh64 chain as the host exchange
        # (partition_by_hash) so co-partitioned joins agree across tiers
        try:
            key_s = [rb.eval_expression(e) for e in by]
            h = key_s[0].hash()
            for k in key_s[1:]:
                h = k.hash(seed=h)
            pid = (h.to_numpy() % np.uint64(n)).astype(np.int32)
        except Exception:
            return None
        pid = np.concatenate(
            [pid, np.zeros(cap - total, dtype=np.int32)])
        encode = _np_plane_encoder(rb, cap)
        names = schema.column_names
        enc = _encode_plane_lists(encode, names)
        if enc is None:
            return None
        planes, valids, dicts = enc
        mask = np.arange(cap) < total
        try:
            sb = lambda a: exchange.shard_blocks(mesh, a)
            op, ov, om = exchange.sharded_hash_repartition(
                mesh, tuple(sb(p) for p in planes),
                tuple(sb(v) for v in valids), sb(mask), sb(pid))
            host = jax.device_get((op, ov, om))
        except Exception:
            return None
        _count_ici_exchange(total, planes, valids)
        op, ov, om = [[np.asarray(a) for a in grp]
                      if isinstance(grp, (list, tuple)) else np.asarray(grp)
                      for grp in host]
        spec = [(nm, schema[nm].dtype, op[j], ov[j], dicts[j])
                for j, nm in enumerate(names)]
        return _decode_mesh_shards(n, om, spec, schema)

    def _exec_Dedup(self, node: pp.Dedup):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, lambda p: p.distinct(node.on))

    def _exec_Pivot(self, node: pp.Pivot):
        for p in self._exec(node.children[0]):
            yield p.pivot(node.group_by, node.pivot_col, node.value_col,
                          node.names).cast_to_schema(node.schema())

    def _exec_Window(self, node: pp.Window):
        from ..window_exec import run_window
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: MicroPartition.from_recordbatch(
                run_window(p.combined(), node)))

    # sort -------------------------------------------------------------
    def _exec_Sort(self, node: pp.Sort):
        """Streaming external sort (the blocking sink shape of
        ``sinks/blocking_sink.rs:32-55``): ONE pass over the child spills
        morsels under the breaker budget while reservoir-sampling keys;
        boundaries from the sample range-fan the spilled stream into
        per-bucket stores; each bucket then sorts independently — peak RSS
        ≈ breaker budget + one bucket, never the whole child."""
        by = list(node.sort_by)
        desc, nf = list(node.descending), list(node.nulls_first)
        buf, samples = self._consume_sampling(
            self._exec(node.children[0]), by)
        try:
            if len(buf) == 0:
                yield MicroPartition.empty(node.schema())
                return
            n = self._breaker_fanout(buf.total_bytes)
            boundaries = None
            if n > 1 and len(buf) > 1 and samples:
                boundaries = self._sample_boundaries(
                    samples, [e.name() for e in by], desc, nf, n)
            if boundaries is None:
                yield _gather_all(iter(buf)).sort(node.sort_by,
                                                  node.descending,
                                                  node.nulls_first)
                return
            yield from _ordered_parallel(
                self._stream_range_buckets(buf, by, boundaries, desc, n,
                                           node.schema()),
                lambda p: p.sort(node.sort_by, node.descending,
                                 node.nulls_first))
        finally:
            buf.close()

    def _consume_sampling(self, stream, by: List[Expression]):
        """Drain a child ONCE into a breaker-budget SpillBuffer while
        reservoir-sampling its key columns (the old path re-walked the
        materialized child to sample, re-reading spill files)."""
        from . import memory
        k = self.cfg.sample_size_for_sort
        buf = memory.SpillBuffer(memory.breaker_budget_bytes())
        samples: List[RecordBatch] = []
        try:
            for p in stream:
                self._poll_cancel()
                rb = p.combined()
                if len(rb):
                    s = rb.sample(size=min(k, len(rb)))
                    samples.append(s.eval_expression_list(by))
                buf.append(p)
        except BaseException:
            buf.close()  # a failed drain must not leak the spill files
            raise
        return buf, samples

    def _breaker_fanout(self, total_bytes: int) -> int:
        """Bucket count for a streaming breaker: each bucket must fit
        comfortably in the breaker budget (it is loaded whole at read
        time), and stay near the configured partition size."""
        from . import memory
        target = min(self.cfg.target_partition_size_bytes,
                     max(memory.breaker_budget_bytes() // 4, 1))
        return max(1, min(1024, -(-int(total_bytes) // max(target, 1))))

    def _stream_range_buckets(self, buf, by, boundaries, desc, n,
                              schema):
        """Re-stream a spilled buffer, range-fanning each morsel into an
        n-bucket PartitionedSpillStore; emit buckets in range order."""
        from . import memory
        store = memory.PartitionedSpillStore(n)
        try:
            for mp in buf:
                self._poll_cancel()
                for i, piece in enumerate(
                        mp.partition_by_range(by, boundaries, desc)):
                    if len(piece):
                        store.push(i, piece.combined())
            buf.close()  # input spill frees before bucket reads begin
            store.finalize()
            yield from self._emit_buckets(store, schema)
        finally:
            store.close()

    def _emit_buckets(self, store, schema, groups=None):
        """One MicroPartition per bucket (or per GROUP of consecutive
        buckets, for AQE-coalesced shuffles). Resident batches pass
        through without any Arrow round-trip; consumers combine lazily."""
        for grp in (groups if groups is not None
                    else [[i] for i in range(store.n)]):
            batches = []
            for i in grp:
                batches.extend(store.bucket_batches(i))
            # normalize dtype drift (a spilled batch round-trips through
            # Arrow IPC; Series.concat later casts everything to the FIRST
            # batch's dtype, so each batch must match the declared schema)
            batches = [b if b.schema == schema else b.cast_to_schema(schema)
                       for b in batches if len(b)]
            if batches:
                yield MicroPartition.from_recordbatches(batches, schema)
            else:
                yield MicroPartition.empty(schema)

    def _exec_TopN(self, node: pp.TopN):
        child = self._exec(node.children[0])
        tops = list(_ordered_parallel(
            child, lambda p: MicroPartition.from_recordbatch(
                p.combined().top_n(node.sort_by, node.limit, node.descending,
                                   node.nulls_first))))
        if not tops:  # an empty child STREAM (not just empty morsels)
            yield MicroPartition.from_recordbatch(
                RecordBatch.empty(node.schema()))
            return
        merged = tops[0].concat(tops[1:]) if len(tops) > 1 else tops[0]
        yield MicroPartition.from_recordbatch(
            merged.combined().top_n(node.sort_by, node.limit, node.descending,
                                    node.nulls_first))

    # exchanges --------------------------------------------------------
    def _exec_Exchange(self, node: pp.Exchange):
        """Streaming shuffles: hash/random/range fan every incoming morsel
        into an n-bucket :class:`memory.PartitionedSpillStore` (RAM under
        the breaker budget, whole-bucket spill past it) — the child is
        never materialized as a unit. gather/split reshape partition
        boundaries by global position, so they drain into a breaker-budget
        SpillBuffer (spill-bounded, inherent to their contract)."""
        from . import memory
        kind, n = node.kind, node.num_partitions
        algo = getattr(self.cfg, "shuffle_algorithm", "auto")
        if algo not in ("auto", "naive", "spill_cache"):
            raise ValueError(
                f"shuffle_algorithm {algo!r}: expected 'auto', 'naive' or "
                f"'spill_cache'")
        if kind == "hash" and n > 1:
            if algo == "spill_cache":
                yield from self._spill_cache_hash_exchange(node, n)
            else:
                yield from self._hash_exchange_streaming(node, n)
            return
        if kind == "random" and n > 1:
            yield from self._fan_exchange_streaming(
                node, n, lambda mp, i: mp.partition_by_random(n, seed=i))
            return
        if kind == "range":
            yield from self._range_exchange_streaming(node, n)
            return
        # gather / split: global-position reshapes
        parts = memory.materialize(self._exec(node.children[0]),
                                   memory.breaker_budget_bytes())
        try:
            if len(parts) == 0:
                yield MicroPartition.empty(node.schema())
            elif kind in ("gather", "hash", "random") or n == 1:
                # hash/random collapse to a concat at n == 1 (the n > 1
                # cases took the streaming-store paths above)
                yield _gather_all(iter(parts))
            elif kind == "split":
                yield from self._split(list(parts), n)
            else:
                raise NotImplementedError(f"exchange kind {kind}")
        finally:
            parts.close()

    def _hash_exchange_streaming(self, node, n: int):
        from . import memory
        from ..device import runtime as drt
        from ..parallel import mesh as pmesh
        by = list(node.by)
        child = self._exec(node.children[0])
        if drt.device_enabled() and pmesh.mesh_size() >= 2 \
                and n == pmesh.mesh_size():
            # the ICI collective repartition wants a partition list; fall
            # back to the streaming store with the same (spill-bounded)
            # buffer when it declines
            parts = memory.materialize(child, memory.breaker_budget_bytes())
            try:
                mesh_out = self._mesh_hash_repartition(list(parts), by, n)
                if mesh_out is not None:
                    yield from mesh_out
                    return
                yield from self._fan_exchange_streaming(
                    node, n, lambda mp, i: mp.partition_by_hash(by, n),
                    stream=iter(parts))
            finally:
                parts.close()
            return
        yield from self._fan_exchange_streaming(
            node, n, lambda mp, i: mp.partition_by_hash(by, n),
            stream=child)

    def _fan_exchange_streaming(self, node, n: int, fan, stream=None):
        """Shared streaming fanout: morsel → n pieces → bucket store; AQE
        may coalesce consecutive buckets from measured totals (growing
        beyond the planned n would need a re-hash of spilled buckets, so
        adaptation only shrinks — the common small-data correction)."""
        from . import memory
        store = memory.PartitionedSpillStore(n)
        try:
            for i, mp in enumerate(stream if stream is not None
                                   else self._exec(node.children[0])):
                self._poll_cancel()
                for j, piece in enumerate(fan(mp, i)):
                    if len(piece):
                        store.push(j, piece.combined())
            store.finalize()
            groups = None
            if self.cfg.enable_aqe \
                    and getattr(node, "engine_inserted", False):
                planner = self._aqe()
                n2 = min(planner.adapt_partition_count(
                    n, sum(store.nbytes), sum(store.rows)), n)
                if n2 < n:
                    bounds = [round(j * n / n2) for j in range(n2 + 1)]
                    groups = [list(range(bounds[j], bounds[j + 1]))
                              for j in range(n2)]
            yield from self._emit_buckets(store, node.schema(), groups)
        finally:
            store.close()

    def _range_exchange_streaming(self, node, n: int):
        by = list(node.by)
        desc = list(node.descending) or [False] * len(by)
        buf, samples = self._consume_sampling(
            self._exec(node.children[0]), by)
        try:
            boundaries = None
            if n > 1 and samples:
                boundaries = self._sample_boundaries(
                    samples, [e.name() for e in by], desc, desc, n)
            if boundaries is None:
                if len(buf) == 0:
                    yield MicroPartition.empty(node.schema())
                else:
                    yield _gather_all(iter(buf))
                for _ in range(max(n - 1, 0)):
                    yield MicroPartition.empty(node.schema())
                return
            yield from self._stream_range_buckets(buf, by, boundaries,
                                                  desc, n, node.schema())
        finally:
            buf.close()


    def _spill_cache_hash_exchange(self, node, n: int):
        """Streaming map-side shuffle: every incoming morsel is hash-
        partitioned and appended to a per-partition spill file; the reduce
        side then streams one partition at a time (reference:
        ``shuffle_cache.rs:14-80`` map/partition/spill → fetch)."""
        import pyarrow as pa

        from ..distributed.shuffle_service import (ShuffleCache,
                                                   _spill_file_batches)
        by = list(node.by)
        cache = ShuffleCache(dirs=list(self.cfg.flight_shuffle_dirs) or None)
        try:
            for mp in self._exec(node.children[0]):
                self._poll_cancel()
                for i, piece in enumerate(mp.partition_by_hash(by, n)):
                    if len(piece):
                        cache.push(i, piece.combined().to_arrow_table())
            cache.close()
            schema = node.schema().to_arrow()
            for i in range(n):
                # lazy per-batch read off the spill file: one partition's
                # batches in memory at a time, never the raw bytes too
                batches = [b for _, b in
                           _spill_file_batches(cache._path(i))]
                t = (pa.Table.from_batches(batches) if batches
                     else schema.empty_table())
                yield MicroPartition.from_recordbatch(
                    RecordBatch.from_arrow_table(t))
        finally:
            cache.cleanup()



    def _split(self, parts: List[MicroPartition], n: int):
        """Split/coalesce to exactly n partitions, preserving order."""
        total = sum(len(p) for p in parts)
        target = max((total + n - 1) // max(n, 1), 1)
        combined = parts[0].concat(parts[1:]) if len(parts) > 1 else parts[0]
        rb = combined.combined()
        out = 0
        start = 0
        while out < n:
            end = min(start + target, len(rb)) if out < n - 1 else len(rb)
            yield MicroPartition.from_recordbatch(rb.slice(start, end))
            start = end
            out += 1

    def _sample_boundaries(self, sampled_keys: List[RecordBatch],
                           key_names: List[str], descending: List[bool],
                           nulls_first: List[bool], n: int
                           ) -> Optional[RecordBatch]:
        return sample_boundaries(sampled_keys, key_names, descending,
                                 nulls_first, n)


    def _sort_merge_join(self, node: pp.HashJoin):
        """Distributed sort-merge join (reference: SortMergeJoin physical
        op with ``sort_merge_join_sort_with_aligned_boundaries``): sample
        BOTH sides' keys while spilling each under the breaker budget,
        derive ONE shared set of range boundaries, range-bucket both sides
        with them (co-ranged, not co-hashed), then join pairwise — one
        bucket pair resident at a time. Output comes out range-clustered
        by key."""
        how = node.how
        left_on, right_on = list(node.left_on), list(node.right_on)
        lbuf, lsamp = self._consume_sampling(self._exec(node.children[0]),
                                             left_on)
        rbuf, rsamp = self._consume_sampling(self._exec(node.children[1]),
                                             right_on)
        try:
            n = max(self._breaker_fanout(lbuf.total_bytes),
                    self._breaker_fanout(rbuf.total_bytes),
                    min(max(len(lbuf), len(rbuf)), 16))
            names = [e.name() for e in left_on]
            # right-side key names normalize to the left's so samples
            # concat into one boundary table (comparison is positional)
            samples = lsamp + [
                RecordBatch.from_series([c.rename(nm) for c, nm in
                                         zip(rb.columns(), names)])
                for rb in rsamp]
            desc = [False] * len(left_on)
            boundaries = self._sample_boundaries(samples, names, desc,
                                                 desc, n) \
                if n > 1 and samples else None
            if boundaries is None:
                lall = _gather_all_or_empty(iter(lbuf),
                                            node.children[0].schema())
                rall = _gather_all_or_empty(iter(rbuf),
                                            node.children[1].schema())
                yield lall.hash_join(rall, left_on, right_on, how)
                return
            yield from _ordered_parallel(
                zip(self._stream_range_buckets(
                        lbuf, left_on, boundaries, desc, n,
                        node.children[0].schema()),
                    self._stream_range_buckets(
                        rbuf, right_on, boundaries, desc, n,
                        node.children[1].schema())),
                lambda lr: lr[0].hash_join(lr[1], left_on, right_on, how))
        finally:
            lbuf.close()
            rbuf.close()

    def _exec_HashJoin(self, node: pp.HashJoin):
        how = node.how
        if node.strategy == "sort_merge":
            yield from self._sort_merge_join(node)
            return
        if node.strategy == "hash" and self.cfg.enable_aqe:
            lnode, rnode = node.children
            if getattr(lnode, "join_side", False) \
                    and getattr(rnode, "join_side", False):
                yield from self._adaptive_hash_join(node, lnode.children[0],
                                                    rnode.children[0])
                return
        if node.strategy == "broadcast_right":
            right = _gather_all(self._exec(node.children[1]))
            child = self._exec(node.children[0])
            yield from _ordered_parallel(
                child, lambda p: p.hash_join(right, node.left_on,
                                             node.right_on, how))
            return
        if node.strategy == "broadcast_left":
            left = _gather_all(self._exec(node.children[0]))
            child = self._exec(node.children[1])
            yield from _ordered_parallel(
                child, lambda p: left.hash_join(p, node.left_on,
                                                node.right_on, how))
            return
        from . import memory
        lnode, rnode = node.children
        copart = (isinstance(lnode, pp.Exchange) and lnode.kind == "hash"
                  and isinstance(rnode, pp.Exchange) and rnode.kind == "hash"
                  and lnode.num_partitions == rnode.num_partitions
                  # the exchanges must partition on the JOIN keys: index
                  # pairing is only valid when both sides were fanned by
                  # the same key chain (a future non-key hash Exchange
                  # under a join must not silently drop matches)
                  and [e._key() for e in lnode.by]
                  == [e._key() for e in node.left_on]
                  and [e._key() for e in rnode.by]
                  == [e._key() for e in node.right_on])
        from . import out_of_core as ooc
        if copart:
            # both exchanges emit exactly n partitions in index order and
            # partition on the join keys — zip the two streams and join
            # pairwise. Each side's exchange is a streaming bucket store,
            # so at most one partition PAIR (plus the stores' bounded
            # buffers) is resident; neither side materializes as a list
            # (reference: hash_join.rs build-then-stream-probe, with the
            # build side's state held by the exchange sink). A skewed
            # pair past the pair budget re-partitions with the rotated
            # radix instead of joining whole (out_of_core).
            for outs in _ordered_parallel(
                    zip(self._exec(lnode), self._exec(rnode)),
                    lambda lr: ooc.join_copartitioned_pair(
                        self, lr[0], lr[1], node, lnode.schema(),
                        rnode.schema())):
                yield from outs
            return
        # no static co-partitioning evidence: index pairing would join
        # unrelated partitions — grace hash join: stream BOTH sides into
        # rotated-radix spill stores (same xxh64 chain at depth 0 →
        # co-partitioned buckets), then join bucket pairs one at a time,
        # recursing on any pair that still exceeds the pair budget; peak
        # memory is one bucket pair, not both children
        if ooc.spill_join_mode(self.cfg) != "0":
            yield from ooc.grace_hash_join(self, node)
            return
        # DAFT_TPU_SPILL_JOIN=0: the legacy materialize-then-refan path
        # (no recursion; an oversized bucket pair loads whole)
        with memory.materialize(self._exec(lnode),
                                memory.breaker_budget_bytes()) as lbuf, \
                memory.materialize(self._exec(rnode),
                                   memory.breaker_budget_bytes()) as rbuf:
            # fanout sized from BOTH sides (a tiny left must not gather an
            # arbitrarily large right into RAM); both buffers are
            # spill-bounded, so sizing them first costs disk, not memory
            n = max(self._breaker_fanout(lbuf.total_bytes),
                    self._breaker_fanout(rbuf.total_bytes))
            if n <= 1:
                # both sides fit one bucket — direct in-memory join
                lall = _gather_all_or_empty(iter(lbuf), lnode.schema())
                rall = _gather_all_or_empty(iter(rbuf), rnode.schema())
                yield lall.hash_join(rall, node.left_on, node.right_on,
                                     how)
                return
            n = max(n, min(max(len(lbuf), len(rbuf)), 16))
            lstore = self._key_bucket_store(iter(lbuf),
                                            list(node.left_on), n)
            lbuf.close()
            try:
                rstore = self._key_bucket_store(iter(rbuf),
                                                list(node.right_on), n)
            except BaseException:
                lstore.close()
                raise
            rbuf.close()
            try:
                yield from _ordered_parallel(
                    zip(self._emit_buckets(lstore, lnode.schema()),
                        self._emit_buckets(rstore, rnode.schema())),
                    lambda lr: lr[0].hash_join(lr[1], node.left_on,
                                               node.right_on, how))
            finally:
                lstore.close()
                rstore.close()

    def _key_bucket_store(self, stream, by, n: int):
        """Drain a stream into an n-bucket store hashed on ``by``. The
        store closes itself when the drain fails; the caller owns it
        once it is returned whole."""
        from . import memory
        store = memory.PartitionedSpillStore(n)
        try:
            for mp in stream:
                self._poll_cancel()
                for j, piece in enumerate(mp.partition_by_hash(by, n)):
                    if len(piece):
                        store.push(j, piece.combined())
            store.finalize()
        except BaseException:
            store.close()
            raise
        return store

    def _adaptive_hash_join(self, node: pp.HashJoin, li, ri):
        """AQE join-strategy demotion (reference: AdaptivePlanner re-plans
        the remaining query from materialized stats, ``physical_planner/
        planner.rs:451-640``): materialize each join input BELOW its
        planned hash exchange, and if the measured bytes of an eligible
        side fit the broadcast threshold, skip both shuffles and broadcast
        it; otherwise fan both materialized sides out as planned."""
        from . import memory
        how = node.how
        threshold = self.cfg.broadcast_join_size_bytes_threshold
        with memory.materialize(self._exec(li),
                                memory.breaker_budget_bytes()) as lparts:
            if lparts.total_bytes <= threshold and how in ("inner",
                                                           "right"):
                self._aqe().record_join("hash→broadcast_left",
                                        lparts.total_bytes)
                left = _gather_all(iter(lparts))
                lparts.close()
                yield from _ordered_parallel(
                    self._exec(ri), lambda p: left.hash_join(
                        p, node.left_on, node.right_on, how))
                return
            with memory.materialize(
                    self._exec(ri),
                    memory.breaker_budget_bytes()) as rparts:
                if rparts.total_bytes <= threshold \
                        and how in ("inner", "left", "semi", "anti"):
                    self._aqe().record_join("hash→broadcast_right",
                                            rparts.total_bytes)
                    right = _gather_all(iter(rparts))
                    rparts.close()
                    yield from _ordered_parallel(
                        iter(lparts), lambda p: p.hash_join(
                            right, node.left_on, node.right_on, how))
                    return
                n = node.children[0].num_partitions
                self._aqe().record_join(
                    "hash", lparts.total_bytes + rparts.total_bytes)
                yield from _ordered_parallel(
                    zip(self._refan(lparts, list(node.left_on), n,
                                    li.schema()),
                        self._refan(rparts, list(node.right_on), n,
                                    ri.schema())),
                    lambda lr: lr[0].hash_join(lr[1], node.left_on,
                                               node.right_on, how))

    def _refan(self, parts, by: List[Expression], n: int, schema):
        """Key-hash a (possibly spilled) partition buffer into n buckets
        and emit them in order — bucket-store backed, one bucket resident
        at a time."""
        from . import memory
        store = self._key_bucket_store(iter(parts), by, n)
        if isinstance(parts, memory.SpillBuffer):
            parts.close()

        def emit():
            try:
                yield from self._emit_buckets(store, schema)
            finally:
                store.close()
        return emit()

    def _exec_CrossJoin(self, node: pp.CrossJoin):
        right = _gather_all(self._exec(node.children[1]))
        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, lambda p: p.cross_join(right))

    # writes -----------------------------------------------------------
    def _exec_Write(self, node: pp.Write):
        info = node.info
        if info.get("kind") == "sink":
            sink = info["sink"]
            sink.start()
            results = list(sink.write(self._exec(node.children[0])))
            yield sink.finalize(results)
            return
        from ..io import writers
        if info.get("mode") == "overwrite":
            writers.overwrite_dir(info["root_dir"])
        child = self._exec(node.children[0])
        outs = list(_ordered_parallel(
            child, lambda p: writers.write_micropartition(
                p, info["kind"], info["root_dir"],
                info.get("partition_cols"), info.get("options"))))
        outs = [o for o in outs if len(o)]
        if not outs:
            yield MicroPartition.empty(node.schema())
            return
        yield MicroPartition.from_recordbatch(
            RecordBatch.concat(outs).cast_to_schema(node.schema()))


def _task_column_ndv(tasks, name: str):
    """max-min+1 folded over ALL tasks' parquet footers for an int column
    (the scan-level twin of logical/stats.column_ndv). A single file's
    range would underestimate scans range-partitioned on the key and let
    a non-reductive grouping through the gate."""
    try:
        import pyarrow.parquet as pq
        lo = hi = None
        seen = set()
        for t in tasks:
            if t.file_format != "parquet" or not t.paths:
                return None
            md_cached = getattr(t, "pq_metadata", None)
            for path in t.paths:
                if path in seen:
                    continue
                seen.add(path)
                md = md_cached if md_cached is not None \
                    and len(t.paths) == 1 else pq.ParquetFile(path).metadata
                idx = {md.schema.column(i).name: i
                       for i in range(md.num_columns)}.get(name)
                if idx is None:
                    return None
                for rg in range(md.num_row_groups):
                    st = md.row_group(rg).column(idx).statistics
                    if st is None or not st.has_min_max \
                            or not isinstance(st.min, int) \
                            or isinstance(st.min, bool):
                        return None
                    lo = st.min if lo is None else min(lo, st.min)
                    hi = st.max if hi is None else max(hi, st.max)
        return None if lo is None else float(hi - lo + 1)
    except Exception:
        return None


def _fragment_groups_affordable(node, src) -> bool:
    """Upfront group-cardinality gate for the fused device aggregation:
    a NON-reductive grouping (TPC-H Q18's near-unique l_orderkey, Q20's
    partkey×suppkey) would ship a group block rivaling the input over the
    link — estimate groups from parquet footer NDVs and refuse the device
    path when the packed transfer would exceed the host's own aggregation
    time (the same parity rule ``fragment._max_out_cap`` enforces at run
    time, applied before any upload or probe happens)."""
    import math

    from ..device import costmodel
    p = costmodel.link_profile()
    if p.down_bps == math.inf:
        return True
    ndvs = []
    for g in node.group_by:
        u = g._unalias()
        if u.op != "col":
            return True  # computed key: unknown → assume reductive
        ndv = _task_column_ndv(src.tasks, u.params[0])
        if ndv is None:
            return True  # strings/no stats → assume reductive
        ndvs.append(ndv)
    if not ndvs:
        return True  # global aggregation: one packed scalar row
    est_groups = 1.0
    for n in ndvs:
        est_groups *= n
    rows = sum(t.num_rows() or 0 for t in src.tasks)
    if rows:
        est_groups = min(est_groups, float(rows))
    from ..device.fragment import packed_bytes_per_group
    # node.aggs is the PARTIAL agg list (_split_aggs already decomposed
    # mean→sum+count etc. before _try_fuse_partial built this node), so its
    # length equals len(prog.ops) and prices the same packed layout that
    # run_packed emits
    bytes_per_group = packed_bytes_per_group(len(node.group_by),
                                             len(node.aggs))
    size = sum(t.size_bytes() or 0 for t in src.tasks)
    host_s = max(size, 1) / costmodel.HOST_AGG_BPS
    return est_groups * bytes_per_group <= host_s * p.down_bps


def _lit_true() -> Expression:
    from ..expressions.expressions import lit
    return lit(True)


def _count_ici_exchange(rows: int, planes, valids) -> None:
    """Account one completed mesh collective exchange in the shuffle
    data plane: bytes that rode ICI instead of the Flight wire (the
    encoded plane payload entering the all_to_all) — surfaced per query
    in ``explain(analyze=True)`` and at ``/metrics``."""
    try:
        from ..distributed.shuffle_service import shuffle_count
        nbytes = sum(int(p.nbytes) for p in planes) \
            + sum(int(v.nbytes) for v in valids)
        shuffle_count("ici_exchanges")
        shuffle_count("ici_rows", rows)
        shuffle_count("ici_bytes", nbytes)
    except Exception:
        pass  # accounting must never take the exchange down


def _encode_plane_lists(encode, names):
    """Encode columns into parallel (values, valids, dictionaries) plane
    lists; None when any column lacks a plain device representation."""
    vals, valids, dicts = [], [], []
    for nm in names:
        enc = encode(nm)
        if enc is None:
            return None
        vals.append(enc[0])
        valids.append(enc[1])
        dicts.append(enc[2])
    return vals, valids, dicts


def _decode_mesh_shards(n: int, live_mask: np.ndarray, cols_spec, schema
                        ) -> List[MicroPartition]:
    """Slice exchanged [n*C'] blocks into per-shard MicroPartitions.
    cols_spec: ordered (name, dtype, values_plane, valids_plane, dictionary)
    tuples — dictionary non-None for string/binary columns riding shared
    dictionary codes."""
    from ..device import column as dcol
    shard_len = live_mask.shape[0] // n
    outs = []
    for i in range(n):
        sl = slice(i * shard_len, (i + 1) * shard_len)
        live = live_mask[sl]
        cnt = int(live.sum())
        cols = []
        for nm, dtype, v, m, d in cols_spec:
            dc = dcol.DeviceColumn(v[sl][live], m[sl][live], dtype, d)
            cols.append(dcol.decode_column(nm, dc, cnt))
        outs.append(MicroPartition.from_recordbatch(
            RecordBatch.from_series(cols).cast_to_schema(schema)))
    return outs


def _load_with_retry(task, tries: int = 2) -> MicroPartition:
    """Scan-task load with transient-IO retry (reference analogue: per-task
    lineage retry in the classic runner / flotilla max_task_retries —
    inputs are re-scannable from storage, so retrying the load is safe)."""
    tries = max(tries, 1)
    last = None
    for attempt in range(tries):
        mp = MicroPartition.from_scan_task(task)
        try:
            mp._load()
            return mp
        except OSError as exc:
            last = exc
            if attempt + 1 < tries:
                import time
                time.sleep(min(0.2 * (2 ** attempt), 2.0))
    raise last


def _np_plane_encoder(rb: RecordBatch, cap: int):
    """Column name → (values, validity, dictionary) numpy planes zero-padded
    to cap, or None when the column has no plain device representation.

    String/binary columns ride dictionary codes. That is SOUND here even
    across shards: every mesh path concatenates its partitions into ONE
    RecordBatch before encoding, so all shards share a single dictionary —
    and ``_np_encode`` assigns rank codes over the SORTED dictionary, so
    code order is lexicographic order (min/max on codes is correct)."""
    import pyarrow as pa
    from ..device import column as dcol

    def encode(name):
        try:
            vals, valid, dictionary = dcol._np_encode(rb.get_column(name))
        except (ValueError, TypeError, pa.ArrowInvalid):
            return None
        if len(vals) < cap:
            vals = np.concatenate(
                [vals, np.zeros(cap - len(vals), dtype=vals.dtype)])
            valid = np.concatenate(
                [valid, np.zeros(cap - len(valid), dtype=np.bool_)])
        return vals, valid, dictionary

    return encode


def _gather_all(parts: Iterator[MicroPartition]) -> MicroPartition:
    ps = list(parts)
    return ps[0].concat(ps[1:]) if len(ps) > 1 else ps[0]


def _gather_all_or_empty(parts: Iterator[MicroPartition],
                         schema) -> MicroPartition:
    ps = list(parts)
    if not ps:
        return MicroPartition.empty(schema)
    return ps[0].concat(ps[1:]) if len(ps) > 1 else ps[0]


def sample_boundaries(sampled_keys: List[RecordBatch],
                      key_names: List[str], descending: List[bool],
                      nulls_first: List[bool], n: int
                      ) -> Optional[RecordBatch]:
    """Concatenated key samples → n-1 range boundaries (sorted,
    null-free), or None when there is nothing to sample. Shared by the
    local range exchange and the distributed worker-side sort protocol
    (the driver computes boundaries from samples only)."""
    merged = RecordBatch.concat(sampled_keys)
    by = [col(nm) for nm in key_names]
    merged = merged.filter(~_any_null(by, merged)) if len(merged) \
        else merged
    if len(merged) == 0:
        return None
    merged_sorted = merged.sort(by, descending, nulls_first)
    idx = [min(int(len(merged_sorted) * (i + 1) / n),
               len(merged_sorted) - 1) for i in range(n - 1)]
    return merged_sorted.take(np.asarray(idx, dtype=np.int64))


def _any_null(by: List[Expression], rb: RecordBatch) -> Expression:
    e = col(by[0].name()).is_null()
    for b in by[1:]:
        e = e | col(b.name()).is_null()
    return e
