"""Streaming partition-parallel local executor.

The single-node engine (reference: "Swordfish",
``src/daft-local-execution``): operators stream MicroPartitions, pipelined
ops run on a shared thread pool (Arrow C++ and XLA both release the GIL, so
threads scale), pipeline breakers (sort / final agg / join build) materialize.
Ordering is preserved via bounded in-order future windows
(the RoundRobin dispatcher of ``dispatcher.rs:24-60``).

Global sort follows the reference's sample→boundaries→range-partition→merge
pipeline (``daft/execution/physical_plan.py:1632``).
"""

from __future__ import annotations

import concurrent.futures as cf
import os
from typing import Callable, Iterator, List, Optional

import numpy as np

from ..context import get_context
from ..expressions import Expression, col
from ..micropartition import MicroPartition
from ..physical import plan as pp
from ..recordbatch import RecordBatch
from ..series import Series

_POOL: Optional[cf.ThreadPoolExecutor] = None


def _pool() -> cf.ThreadPoolExecutor:
    global _POOL
    if _POOL is None:
        _POOL = cf.ThreadPoolExecutor(
            max_workers=max(os.cpu_count() or 4, 4),
            thread_name_prefix="daft-tpu-exec")
    return _POOL


def _ordered_parallel(inputs: Iterator, fn: Callable,
                      width: Optional[int] = None) -> Iterator:
    """Map fn over inputs on the pool, yielding results in order with a
    bounded in-flight window (backpressure)."""
    width = width or max((os.cpu_count() or 4), 4) * 2
    pool = _pool()
    pending: List[cf.Future] = []
    it = iter(inputs)
    done = False
    while True:
        while not done and len(pending) < width:
            try:
                x = next(it)
            except StopIteration:
                done = True
                break
            pending.append(pool.submit(fn, x))
        if not pending:
            return
        yield pending.pop(0).result()


class LocalExecutor:
    """Interprets a physical plan into a stream of MicroPartitions."""

    def __init__(self):
        from . import memory
        self.cfg = get_context().execution_config
        self.stats = None
        # bounds bytes of scan tasks materializing concurrently
        self.mem = memory.MemoryManager()
        # stage-input bindings for distributed stage fragments
        self.stage_inputs = {}
        self._aqe_planner = None

    def _aqe(self):
        if self._aqe_planner is None:
            from ..physical import adaptive
            self._aqe_planner = adaptive.new_planner(self.cfg)
        return self._aqe_planner

    def run(self, plan: pp.PhysicalPlan,
            stage_inputs=None) -> Iterator[MicroPartition]:
        if stage_inputs:
            self.stage_inputs = stage_inputs
        return self._run(plan)

    def _run(self, plan: pp.PhysicalPlan) -> Iterator[MicroPartition]:
        from .. import observability as obs
        self.stats = obs.new_query_stats()
        self.stats.plan = plan  # for explain_analyze rendering
        xdir = obs.xplane_trace_dir()

        def gen():
            xtrace = obs._XplaneTrace(xdir) if xdir else None
            try:
                yield from obs.wrap_progress(self._exec(plan))
            finally:
                if xtrace is not None:
                    xtrace.stop()
                self.stats.finish()
                obs.set_last_stats(self.stats)
                path = obs.chrome_trace_path()
                if path and self.stats.tracer is not None:
                    self.stats.tracer.dump(path)
        return gen()

    # ------------------------------------------------------------------
    def _exec(self, node: pp.PhysicalPlan) -> Iterator[MicroPartition]:
        h = getattr(self, "_exec_" + type(node).__name__, None)
        if h is None:
            raise NotImplementedError(f"executor for {type(node).__name__}")
        it = h(node)
        if self.stats is not None:
            it = self.stats.instrument(node, it)
        return it

    # sources ----------------------------------------------------------
    def _morselize(self, stream: Iterator) -> Iterator:
        """Re-chunk a partition stream to ``default_morsel_size`` rows
        (the reference's dispatcher-side morsel re-chunking,
        ``src/daft-local-execution/src/buffer.rs``): oversized source
        partitions split so downstream operators pipeline at morsel
        granularity. Observed sizes land in the per-op trace stats."""
        morsel = int(self.cfg.default_morsel_size or 0)
        if morsel <= 0:
            yield from stream
            return
        for p in stream:
            n = len(p)
            if n <= morsel + morsel // 2:
                yield p
                continue
            rb = p.combined()
            for start in range(0, n, morsel):
                yield MicroPartition.from_recordbatch(
                    rb.slice(start, min(start + morsel, n)))

    def _exec_ScanSource(self, node: pp.ScanSource):
        def run(t):
            est = t.size_bytes() or 0
            self.mem.acquire(est)
            try:
                return _load_with_retry(t)
            finally:
                self.mem.release(est)
        if not node.tasks:
            yield MicroPartition.empty(node.schema())
            return
        yield from self._morselize(_ordered_parallel(iter(node.tasks), run))

    def _exec_InMemorySource(self, node: pp.InMemorySource):
        if not node.partitions:
            yield MicroPartition.empty(node.schema())
            return
        yield from iter(node.partitions)

    def _exec_StageInput(self, node: pp.StageInput):
        parts = self.stage_inputs.get(node.stage_id)
        if not parts:
            yield MicroPartition.empty(node.schema())
            return
        yield from iter(parts)

    # pipelined maps ---------------------------------------------------
    def _exec_Project(self, node: pp.Project):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: p.eval_expression_list(node.exprs))

    def _exec_UDFProject(self, node: pp.UDFProject):
        child = self._exec(node.children[0])
        width = node.concurrency or None
        yield from _ordered_parallel(
            child, lambda p: p.eval_expression_list(node.exprs), width=width)

    def _exec_Filter(self, node: pp.Filter):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, lambda p: p.filter(node.predicate))

    def _exec_Explode(self, node: pp.Explode):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, lambda p: p.explode(node.exprs))

    def _exec_Unpivot(self, node: pp.Unpivot):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: p.unpivot(node.ids, node.values,
                                       node.variable_name, node.value_name))

    def _exec_Sample(self, node: pp.Sample):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: p.sample(fraction=node.fraction, size=None,
                                      with_replacement=node.with_replacement,
                                      seed=node.seed)
            if node.fraction is not None else p.head(node.size))

    def _exec_MonotonicallyIncreasingId(self, node):
        child = self._exec(node.children[0])
        for i, p in enumerate(child):
            yield p.add_monotonically_increasing_id(i, node.column_name)

    def _exec_Limit(self, node: pp.Limit):
        remaining = node.limit
        to_skip = node.offset
        for p in self._exec(node.children[0]):
            n = len(p)
            if to_skip:
                if n <= to_skip:
                    to_skip -= n
                    continue
                p = MicroPartition.from_recordbatch(
                    p.combined().slice(to_skip, n))
                to_skip = 0
            if remaining <= 0:
                break
            if len(p) > remaining:
                p = p.head(remaining)
            remaining -= len(p)
            yield p
            if remaining <= 0:
                break

    def _exec_Concat(self, node: pp.Concat):
        yield from self._exec(node.children[0])
        yield from self._exec(node.children[1])

    # aggregation ------------------------------------------------------
    def _exec_Aggregate(self, node: pp.Aggregate):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: p.agg(node.aggs, node.group_by)
            .cast_to_schema(node.schema()))

    def _exec_DeviceFragmentAgg(self, node: pp.DeviceFragmentAgg):
        from ..aggs import split_agg_expr
        from ..device import fragment, runtime as drt
        specs = [split_agg_expr(a) for a in node.aggs]
        child_exprs = [(c if c is not None else _lit_true()).alias(f"__v{i}__")
                       for i, (op, c, nm, pr) in enumerate(specs)]
        ops = tuple(s[0] for s in specs)
        agg_names = [s[2] for s in specs]
        agg_cols = [col(nm) for nm in agg_names]

        def host_agg(rb: RecordBatch) -> MicroPartition:
            if node.predicate is not None:
                rb = rb.filter(node.predicate)
            return MicroPartition.from_recordbatch(
                rb.agg(node.aggs, node.group_by).cast_to_schema(node.schema()))

        def device_agg(rb: RecordBatch) -> Optional[MicroPartition]:
            from ..device import costmodel
            if not (drt.device_enabled()
                    and len(rb) >= max(drt._min_rows(), 1)):
                return None
            prog = fragment.get_fused_agg(node.group_by, child_exprs, ops,
                                          node.predicate, rb.schema)
            if prog is None:
                return None
            # in-memory batch: the upload is one-shot, it must beat the
            # host outright (no HBM-cache identity to invest in)
            packed_out = fragment.packed_bytes_per_group(
                len(node.group_by), len(ops)) * fragment._OUT_CAP0
            if not costmodel.agg_upload_wins(
                    drt._batch_cols_nbytes(rb, prog.compiled.needs_cols),
                    packed_out, cacheable=False):
                return None
            try:
                out = fragment.run_fused_agg(prog, rb, node.group_by,
                                             agg_cols, node.schema())
            except Exception:  # device OOM / lowering failure → host tier
                return None
            if out is None:
                return None
            return MicroPartition.from_recordbatch(
                out.cast_to_schema(node.schema()))

        src = node.children[0]
        if isinstance(src, pp.ScanSource) and src.tasks \
                and drt.device_enabled() \
                and _fragment_groups_affordable(node, src):
            # task-level path: consult the HBM column cache per scan task —
            # a hit runs the fused program on device-resident columns with
            # zero file IO and zero host→device transfer. All tasks' packed
            # results come back in ONE device→host transfer (the link is
            # RTT-bound, so per-task gets would serialize ~40 ms each).
            prog = fragment.get_fused_agg(node.group_by, child_exprs, ops,
                                          node.predicate, src.schema())
            if prog is not None:
                yield from self._fragment_scan_tasks(
                    node, prog, src, agg_cols, host_agg)
                return

        def run(p: MicroPartition) -> MicroPartition:
            rb = p.combined()
            out = device_agg(rb)
            return out if out is not None else host_agg(rb)

        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, run)

    def _fragment_scan_tasks(self, node, prog, src, agg_cols, host_agg):
        """Windowed streaming over scan tasks: resolve each task in the
        window to an encoded DeviceTable (HBM cache hit, or load+encode+
        insert) or a host batch, dispatch the window's fused programs, and
        fetch ALL its packed results in one transfer. The window bounds
        host RAM and non-cached HBM residency like the morsel pipeline's
        in-flight limit; fallbacks re-read the pristine task (never decode
        the lossy device encoding back)."""
        import itertools
        from ..device import cache as dcache, column as dcol, fragment
        from ..device import runtime as drt

        n_tasks = len(src.tasks)

        def load(t) -> RecordBatch:
            est = t.size_bytes() or 0
            self.mem.acquire(est)
            try:
                return _load_with_retry(t).combined()
            finally:
                self.mem.release(est)

        def resolve(t):
            from ..device import costmodel
            fp = dcache.task_fingerprint(t)
            if fp is not None:
                dt = dcache.get_cache().get_table(fp, prog.compiled.needs_cols)
                if dt is not None:
                    return ("dev", dt, t)
            rb = load(t)
            if len(rb) < max(drt._min_rows(), 1):
                return ("host", rb, t)
            for nm in prog.compiled.needs_cols:
                if rb.get_column(nm).is_pyobject():
                    return ("host", rb, t)
            # measured cost gate: a cacheable upload is an investment the
            # HBM cache repays on every later scan of the same task — but
            # only if the whole scan's working set actually FITS the budget
            # (otherwise LRU thrash re-pays the upload every query and
            # put_table would refuse oversized tables anyway)
            from ..device import fragment as dfrag
            packed_out = dfrag.packed_bytes_per_group(
                prog.nk, len(prog.ops)) * dfrag._OUT_CAP0
            col_bytes = drt._batch_cols_nbytes(rb, prog.compiled.needs_cols)
            est_encoded = 2 * col_bytes  # capacity bucketing ≤ doubles
            fits = est_encoded * max(n_tasks, 1) <= dcache._budget()
            if not costmodel.agg_upload_wins(
                    col_bytes, packed_out,
                    cacheable=fp is not None and fits):
                return ("host", rb, t)
            try:
                dt = dcol.encode_batch(rb, prog.compiled.needs_cols)
            except (ValueError, TypeError):
                return ("host", rb, t)
            if fp is not None:
                dcache.get_cache().put_table(fp, dt)
            return ("dev", dt, t)

        width = max((os.cpu_count() or 4), 4) * 2
        it = iter(src.tasks)
        while True:
            window = list(itertools.islice(it, width))
            if not window:
                return
            resolved = list(_ordered_parallel(iter(window), resolve))
            outs = fragment.run_fused_agg_tables(
                prog, [dt for kind, dt, _ in resolved if kind == "dev"],
                src.schema(), node.group_by, agg_cols, node.schema())
            di = 0
            for kind, val, t in resolved:
                if kind == "dev":
                    out = outs[di]
                    di += 1
                    if out is None:  # device failure → pristine host re-read
                        yield host_agg(load(t))
                    else:
                        yield MicroPartition.from_recordbatch(
                            out.cast_to_schema(node.schema()))
                else:
                    yield host_agg(val)

    def _exec_DeviceExchangeAgg(self, node: pp.DeviceExchangeAgg):
        """Shuffle+final-merge as ONE mesh program: shard the partial group
        blocks over the device mesh, all_to_all by key hash over ICI, merge,
        and decode one disjoint group block per shard."""
        from . import memory
        parts = memory.materialize(self._exec(node.children[0]))
        outs = self._mesh_exchange_agg(node, parts)
        if outs is not None:
            yield from outs
            return
        # host fallback: hash exchange + final aggregate (what translate
        # would have emitted without the mesh, including its partition cap)
        n = max(min(len(parts),
                    self.cfg.shuffle_aggregation_default_partitions), 1)
        split = self._materialize_split(_ordered_parallel(
            iter(parts),
            lambda p: p.partition_by_hash(list(node.group_by), n)))
        regrouped = self._regroup(split, n)
        yield from _ordered_parallel(
            regrouped, lambda p: MicroPartition.from_recordbatch(
                p.combined().agg(node.aggs, node.group_by)
                .cast_to_schema(node.schema())))

    def _mesh_exchange_agg(self, node, parts) -> Optional[List[MicroPartition]]:
        import jax
        import numpy as np
        from ..aggs import split_agg_expr
        from ..device import column as dcol, runtime as drt
        from ..parallel import exchange, mesh as pmesh
        if not drt.device_enabled():
            return None
        mesh = pmesh.get_mesh()
        if mesh is None or pmesh.mesh_size() < 2:
            return None
        rb = RecordBatch.concat([p.combined() for p in parts]) \
            if len(parts) > 1 else parts[0].combined()
        if len(rb) == 0:
            return [MicroPartition.from_recordbatch(
                RecordBatch.empty(node.schema()))]
        key_names = [g.name() for g in node.group_by]
        specs = [split_agg_expr(a) for a in node.aggs]
        ops = tuple(s[0] for s in specs)
        val_names = [s[1]._unalias().params[0] for s in specs]
        out_names = [s[2] for s in specs]
        n = pmesh.mesh_size()
        total = len(rb)
        C = (total + n - 1) // n
        cap = n * C

        encode = _np_plane_encoder(rb, cap)
        kplanes = _encode_plane_lists(encode, key_names)
        vplanes = _encode_plane_lists(encode, val_names)
        if kplanes is None or vplanes is None:
            return None
        keys, kvalids = kplanes
        vals, vvalids = vplanes
        mask = np.arange(cap) < total
        try:
            sb = lambda a: exchange.shard_blocks(mesh, a)
            fk, fkv, fv, fvv, gmask = exchange.sharded_grouped_agg(
                mesh, tuple(sb(k) for k in keys),
                tuple(sb(k) for k in kvalids),
                tuple(sb(v) for v in vals),
                tuple(sb(v) for v in vvalids), sb(mask), ops)
            host = jax.device_get((fk, fkv, fv, fvv, gmask))
        except Exception:
            return None
        fk, fkv, fv, fvv, gmask = [
            [np.asarray(a) for a in grp] if isinstance(grp, (list, tuple))
            else np.asarray(grp) for grp in host]
        spec = [(nm, node.schema()[nm].dtype, fk[i], fkv[i])
                for i, nm in enumerate(key_names)]
        spec += [(nm, node.schema()[nm].dtype, fv[j], fvv[j])
                 for j, nm in enumerate(out_names)]
        return _decode_mesh_shards(n, gmask, spec, node.schema())

    def _mesh_hash_repartition(self, parts, by, n: int
                               ) -> Optional[List[MicroPartition]]:
        """Hash repartition as one all_to_all over the device mesh — chosen
        when the target partition count equals the mesh width and every
        column is plain device-representable (no variable-width payloads:
        those ride the host exchange, SURVEY.md §7 hard-part #2)."""
        import jax
        from ..device import column as dcol, runtime as drt
        from ..parallel import exchange, mesh as pmesh
        if not drt.device_enabled():
            return None
        if pmesh.mesh_size() < 2 or n != pmesh.mesh_size():
            return None
        mesh = pmesh.get_mesh()
        rb = RecordBatch.concat([p.combined() for p in parts]) \
            if len(parts) > 1 else parts[0].combined()
        schema = rb.schema
        # pure data movement must be bit-exact: every column must round-trip
        # the device encoding losslessly (no decimals-as-floats, no f64→f32)
        for f in schema:
            if not dcol.is_lossless_device_dtype(f.dtype):
                return None
        if len(rb) == 0:
            return [MicroPartition.from_recordbatch(RecordBatch.empty(schema))
                    for _ in range(n)]
        total = len(rb)
        C = (total + n - 1) // n
        cap = n * C
        # destination shard from the SAME xxh64 chain as the host exchange
        # (partition_by_hash) so co-partitioned joins agree across tiers
        try:
            key_s = [rb.eval_expression(e) for e in by]
            h = key_s[0].hash()
            for k in key_s[1:]:
                h = k.hash(seed=h)
            pid = (h.to_numpy() % np.uint64(n)).astype(np.int32)
        except Exception:
            return None
        pid = np.concatenate(
            [pid, np.zeros(cap - total, dtype=np.int32)])
        encode = _np_plane_encoder(rb, cap)
        names = schema.column_names
        enc = _encode_plane_lists(encode, names)
        if enc is None:
            return None
        planes, valids = enc
        mask = np.arange(cap) < total
        try:
            sb = lambda a: exchange.shard_blocks(mesh, a)
            op, ov, om = exchange.sharded_hash_repartition(
                mesh, tuple(sb(p) for p in planes),
                tuple(sb(v) for v in valids), sb(mask), sb(pid))
            host = jax.device_get((op, ov, om))
        except Exception:
            return None
        op, ov, om = [[np.asarray(a) for a in grp]
                      if isinstance(grp, (list, tuple)) else np.asarray(grp)
                      for grp in host]
        spec = [(nm, schema[nm].dtype, op[j], ov[j])
                for j, nm in enumerate(names)]
        return _decode_mesh_shards(n, om, spec, schema)

    def _exec_Dedup(self, node: pp.Dedup):
        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, lambda p: p.distinct(node.on))

    def _exec_Pivot(self, node: pp.Pivot):
        for p in self._exec(node.children[0]):
            yield p.pivot(node.group_by, node.pivot_col, node.value_col,
                          node.names).cast_to_schema(node.schema())

    def _exec_Window(self, node: pp.Window):
        from ..window_exec import run_window
        child = self._exec(node.children[0])
        yield from _ordered_parallel(
            child, lambda p: MicroPartition.from_recordbatch(
                run_window(p.combined(), node)))

    # sort -------------------------------------------------------------
    def _exec_Sort(self, node: pp.Sort):
        from . import memory
        parts = memory.materialize(self._exec(node.children[0]))
        if len(parts) == 1:
            yield parts[0].sort(node.sort_by, node.descending, node.nulls_first)
            return
        ranged = self._range_partition(parts, list(node.sort_by),
                                       list(node.descending),
                                       list(node.nulls_first))
        yield from _ordered_parallel(
            iter(ranged),
            lambda p: p.sort(node.sort_by, node.descending, node.nulls_first))

    def _exec_TopN(self, node: pp.TopN):
        child = self._exec(node.children[0])
        tops = list(_ordered_parallel(
            child, lambda p: MicroPartition.from_recordbatch(
                p.combined().top_n(node.sort_by, node.limit, node.descending,
                                   node.nulls_first))))
        merged = tops[0].concat(tops[1:]) if len(tops) > 1 else tops[0]
        yield MicroPartition.from_recordbatch(
            merged.combined().top_n(node.sort_by, node.limit, node.descending,
                                    node.nulls_first))

    # exchanges --------------------------------------------------------
    def _exec_Exchange(self, node: pp.Exchange):
        from . import memory
        kind, n = node.kind, node.num_partitions
        if kind == "hash" and n > 1 and self._use_spill_cache_shuffle(node):
            yield from self._spill_cache_hash_exchange(node, n)
            return
        parts = memory.materialize(self._exec(node.children[0]))
        if self.cfg.enable_aqe and getattr(node, "engine_inserted", False) \
                and kind in ("hash", "random") and n > 1:
            # AQE: the child is materialized — re-size the shuffle from
            # ACTUAL bytes instead of the planner's estimate
            planner = self._aqe()
            total_bytes = sum(p.size_bytes() or 0 for p in parts)
            total_rows = sum(len(p) for p in parts)
            n = planner.adapt_partition_count(n, total_bytes, total_rows)
            if n == 1:  # coalesced shuffle = plain concat, skip hashing
                yield parts[0].concat(parts[1:]) if len(parts) > 1 \
                    else parts[0]
                return
        if kind == "gather" or (kind == "split" and n == 1):
            yield parts[0].concat(parts[1:]) if len(parts) > 1 else parts[0]
            return
        if kind == "split":
            yield from self._split(parts, n)
            return
        if kind == "random":
            split = self._materialize_split(_ordered_parallel(
                enumerate(parts),
                lambda ip: ip[1].partition_by_random(n, seed=ip[0])))
            yield from self._regroup(split, n)
            return
        if kind == "hash":
            by = list(node.by)
            mesh_out = self._mesh_hash_repartition(parts, by, n)
            if mesh_out is not None:
                yield from mesh_out
                return
            split = self._materialize_split(_ordered_parallel(
                iter(parts), lambda p: p.partition_by_hash(by, n)))
            yield from self._regroup(split, n)
            return
        if kind == "range":
            yield from self._range_partition(parts, list(node.by),
                                             list(node.descending) or
                                             [False] * len(node.by),
                                             None, n)
            return
        raise NotImplementedError(f"exchange kind {kind}")

    def _use_spill_cache_shuffle(self, node) -> bool:
        """Strategy pick (reference: ShuffleExchange strategy enum,
        ``ops/shuffle_exchange.rs:41-58``): the streaming spill-cache path
        skips materializing the exchange child entirely, but cedes to the
        AQE partition-resizing path and the device-mesh collective path."""
        from . import memory
        from ..device import runtime as drt
        from ..parallel import mesh as pmesh
        algo = getattr(self.cfg, "shuffle_algorithm", "auto")
        if algo not in ("auto", "naive", "spill_cache"):
            raise ValueError(
                f"shuffle_algorithm {algo!r}: expected 'auto', 'naive' or "
                f"'spill_cache'")
        if algo == "naive":
            return False
        if drt.device_enabled() and pmesh.mesh_size() >= 2 \
                and node.num_partitions == pmesh.mesh_size():
            return False  # the mesh collective repartition may apply
        if algo == "spill_cache":
            return True
        # auto: bounded-memory mode prefers the streaming cache (one
        # partition in memory at a time)
        return memory.memory_limit_bytes() is not None

    def _spill_cache_hash_exchange(self, node, n: int):
        """Streaming map-side shuffle: every incoming morsel is hash-
        partitioned and appended to a per-partition spill file; the reduce
        side then streams one partition at a time (reference:
        ``shuffle_cache.rs:14-80`` map/partition/spill → fetch)."""
        import pyarrow as pa

        from ..distributed.shuffle_service import (ShuffleCache,
                                                   _spill_file_batches)
        by = list(node.by)
        cache = ShuffleCache(dirs=list(self.cfg.flight_shuffle_dirs) or None)
        try:
            for mp in self._exec(node.children[0]):
                for i, piece in enumerate(mp.partition_by_hash(by, n)):
                    if len(piece):
                        cache.push(i, piece.combined().to_arrow_table())
            cache.close()
            schema = node.schema().to_arrow()
            for i in range(n):
                # lazy per-batch read off the spill file: one partition's
                # batches in memory at a time, never the raw bytes too
                batches = [b for _, b in
                           _spill_file_batches(cache._path(i))]
                t = (pa.Table.from_batches(batches) if batches
                     else schema.empty_table())
                yield MicroPartition.from_recordbatch(
                    RecordBatch.from_arrow_table(t))
        finally:
            cache.cleanup()

    def _materialize_split(self, rows):
        """Fanout outputs → budgeted (possibly spilling) buffer, so the
        exchange peak — every input's n split parts live at once — honors
        the memory limit."""
        from . import memory
        split = memory.SplitSpillBuffer()
        for outs in rows:
            split.append_row(list(outs))
        return split

    def _regroup(self, split, n: int):
        from . import memory
        if isinstance(split, memory.SplitSpillBuffer):
            for i in range(n):
                subs = [split.get(s, i) for s in range(split.rows)]
                yield subs[0].concat(subs[1:]) if len(subs) > 1 else subs[0]
            split.close()
            return
        for i in range(n):
            subs = [s[i] for s in split]
            yield subs[0].concat(subs[1:]) if len(subs) > 1 else subs[0]

    def _split(self, parts: List[MicroPartition], n: int):
        """Split/coalesce to exactly n partitions, preserving order."""
        total = sum(len(p) for p in parts)
        target = max((total + n - 1) // max(n, 1), 1)
        combined = parts[0].concat(parts[1:]) if len(parts) > 1 else parts[0]
        rb = combined.combined()
        out = 0
        start = 0
        while out < n:
            end = min(start + target, len(rb)) if out < n - 1 else len(rb)
            yield MicroPartition.from_recordbatch(rb.slice(start, end))
            start = end
            out += 1

    def _sample_boundaries(self, sampled_keys: List[RecordBatch],
                           key_names: List[str], descending: List[bool],
                           nulls_first: List[bool], n: int
                           ) -> Optional[RecordBatch]:
        return sample_boundaries(sampled_keys, key_names, descending,
                                 nulls_first, n)

    def _sample_keys(self, parts, by: List[Expression]) -> List[RecordBatch]:
        k = self.cfg.sample_size_for_sort
        out = []
        for p in parts:
            rb = p.combined()
            s = rb.sample(size=min(k, len(rb))) if len(rb) else rb
            out.append(s.eval_expression_list(by))
        return out

    def _range_fanout(self, parts, by: List[Expression],
                      boundaries: RecordBatch, descending: List[bool],
                      n: int):
        split = self._materialize_split(_ordered_parallel(
            iter(parts),
            lambda p: p.partition_by_range(by, boundaries, descending)))
        return self._regroup(split, n)

    def _range_partition(self, parts: List[MicroPartition],
                         by: List[Expression], descending: List[bool],
                         nulls_first: Optional[List[bool]] = None,
                         n: Optional[int] = None) -> List[MicroPartition]:
        """Sample → boundaries → partition_by_range → regroup."""
        n = n or len(parts)
        nulls_first = nulls_first or list(descending)
        if n == 1:
            combined = parts[0].concat(parts[1:]) if len(parts) > 1 else parts[0]
            return [combined]
        boundaries = self._sample_boundaries(
            self._sample_keys(parts, by), [e.name() for e in by],
            descending, nulls_first, n)
        if boundaries is None:
            combined = parts[0].concat(parts[1:]) if len(parts) > 1 else parts[0]
            return [combined] + [MicroPartition.empty(parts[0].schema)
                                 for _ in range(n - 1)]
        return self._range_fanout(parts, by, boundaries, descending, n)

    # joins ------------------------------------------------------------
    def _sort_merge_join(self, node: pp.HashJoin):
        """Distributed sort-merge join (reference: SortMergeJoin physical
        op with ``sort_merge_join_sort_with_aligned_boundaries``): sample
        BOTH sides' keys once, derive one shared set of range boundaries,
        range-partition both sides with them (co-ranged, not co-hashed),
        then merge-join pairwise. Output comes out range-clustered by key."""
        from . import memory
        how = node.how
        left_on, right_on = list(node.left_on), list(node.right_on)
        lparts = memory.materialize(self._exec(node.children[0]))
        rparts = memory.materialize(self._exec(node.children[1]))
        n = max(len(lparts), len(rparts), 1)
        if n == 1:
            lall = _gather_all(iter(lparts))
            rall = _gather_all(iter(rparts))
            yield lall.hash_join(rall, left_on, right_on, how)
            return
        names = [e.name() for e in left_on]
        # right-side key names normalize to the left's so samples concat
        # into one boundary table (boundary comparison is positional)
        samples = self._sample_keys(lparts, left_on) + [
            RecordBatch.from_series([c.rename(nm) for c, nm in
                                     zip(rb.columns(), names)])
            for rb in self._sample_keys(rparts, right_on)]
        desc = [False] * len(left_on)
        boundaries = self._sample_boundaries(samples, names, desc, desc, n)
        if boundaries is None:
            lall = _gather_all(iter(lparts))
            rall = _gather_all(iter(rparts))
            yield lall.hash_join(rall, left_on, right_on, how)
            return
        lregrouped = memory.materialize(
            self._range_fanout(lparts, left_on, boundaries, desc, n))
        rregrouped = memory.materialize(
            self._range_fanout(rparts, right_on, boundaries, desc, n))
        yield from _ordered_parallel(
            zip(lregrouped, rregrouped),
            lambda lr: lr[0].hash_join(lr[1], left_on, right_on, how))

    def _exec_HashJoin(self, node: pp.HashJoin):
        how = node.how
        if node.strategy == "sort_merge":
            yield from self._sort_merge_join(node)
            return
        if node.strategy == "hash" and self.cfg.enable_aqe:
            lnode, rnode = node.children
            if getattr(lnode, "join_side", False) \
                    and getattr(rnode, "join_side", False):
                yield from self._adaptive_hash_join(node, lnode.children[0],
                                                    rnode.children[0])
                return
        if node.strategy == "broadcast_right":
            right = _gather_all(self._exec(node.children[1]))
            child = self._exec(node.children[0])
            yield from _ordered_parallel(
                child, lambda p: p.hash_join(right, node.left_on,
                                             node.right_on, how))
            return
        if node.strategy == "broadcast_left":
            left = _gather_all(self._exec(node.children[0]))
            child = self._exec(node.children[1])
            yield from _ordered_parallel(
                child, lambda p: left.hash_join(p, node.left_on,
                                                node.right_on, how))
            return
        from . import memory
        lnode, rnode = node.children
        copart = (isinstance(lnode, pp.Exchange) and lnode.kind == "hash"
                  and isinstance(rnode, pp.Exchange) and rnode.kind == "hash"
                  and lnode.num_partitions == rnode.num_partitions
                  # the exchanges must partition on the JOIN keys: index
                  # pairing is only valid when both sides were fanned by
                  # the same key chain (a future non-key hash Exchange
                  # under a join must not silently drop matches)
                  and [e._key() for e in lnode.by]
                  == [e._key() for e in node.left_on]
                  and [e._key() for e in rnode.by]
                  == [e._key() for e in node.right_on])
        if copart:
            # streaming probe: the build side is the blocking sink
            # (spill-bounded SpillBuffer); probe partitions stream straight
            # from the exchange one at a time — never materialized as a
            # list (reference: hash_join.rs build-then-stream-probe)
            rparts = memory.materialize(self._exec(rnode))
            try:
                yield from _ordered_parallel(
                    enumerate(self._exec(lnode)),
                    lambda ip: ip[1].hash_join(
                        rparts[ip[0]], node.left_on, node.right_on, how))
            finally:
                rparts.close()
            return
        lparts = memory.materialize(self._exec(lnode))
        rparts = memory.materialize(self._exec(rnode))
        if len(lparts) == len(rparts) == 1:
            yield from _ordered_parallel(
                zip(lparts, rparts),
                lambda lr: lr[0].hash_join(lr[1], node.left_on,
                                           node.right_on, how))
            return
        # no static co-partitioning evidence: index pairing would join
        # unrelated partitions — re-fan BOTH sides by key hash (same xxh64
        # chain on both → co-partitioned)
        n = max(len(lparts), len(rparts), 1)
        lparts = self._refan(lparts, list(node.left_on), n)
        rparts = self._refan(rparts, list(node.right_on), n)
        yield from _ordered_parallel(
            zip(lparts, rparts),
            lambda lr: lr[0].hash_join(lr[1], node.left_on, node.right_on,
                                       how))

    def _adaptive_hash_join(self, node: pp.HashJoin, li, ri):
        """AQE join-strategy demotion (reference: AdaptivePlanner re-plans
        the remaining query from materialized stats, ``physical_planner/
        planner.rs:451-640``): materialize each join input BELOW its
        planned hash exchange, and if the measured bytes of an eligible
        side fit the broadcast threshold, skip both shuffles and broadcast
        it; otherwise fan both materialized sides out as planned."""
        from . import memory
        how = node.how
        threshold = self.cfg.broadcast_join_size_bytes_threshold
        lparts = memory.materialize(self._exec(li))
        if lparts.total_bytes <= threshold and how in ("inner", "right"):
            self._aqe().record_join("hash→broadcast_left",
                                    lparts.total_bytes)
            left = _gather_all(iter(lparts))
            lparts.close()
            yield from _ordered_parallel(
                self._exec(ri), lambda p: left.hash_join(
                    p, node.left_on, node.right_on, how))
            return
        rparts = memory.materialize(self._exec(ri))
        if rparts.total_bytes <= threshold and how in ("inner", "left",
                                                       "semi", "anti"):
            self._aqe().record_join("hash→broadcast_right",
                                    rparts.total_bytes)
            right = _gather_all(iter(rparts))
            rparts.close()
            yield from _ordered_parallel(
                iter(lparts), lambda p: p.hash_join(
                    right, node.left_on, node.right_on, how))
            return
        n = node.children[0].num_partitions
        self._aqe().record_join("hash",
                                lparts.total_bytes + rparts.total_bytes)
        lparts = self._refan(lparts, list(node.left_on), n)
        rparts = self._refan(rparts, list(node.right_on), n)
        yield from _ordered_parallel(
            zip(lparts, rparts),
            lambda lr: lr[0].hash_join(lr[1], node.left_on, node.right_on,
                                       how))

    def _refan(self, parts, by: List[Expression], n: int):
        from . import memory
        split = self._materialize_split(_ordered_parallel(
            iter(parts), lambda p: p.partition_by_hash(by, n)))
        out = memory.materialize(self._regroup(split, n))
        if isinstance(parts, memory.SpillBuffer):
            parts.close()
        return out

    def _exec_CrossJoin(self, node: pp.CrossJoin):
        right = _gather_all(self._exec(node.children[1]))
        child = self._exec(node.children[0])
        yield from _ordered_parallel(child, lambda p: p.cross_join(right))

    # writes -----------------------------------------------------------
    def _exec_Write(self, node: pp.Write):
        info = node.info
        if info.get("kind") == "sink":
            sink = info["sink"]
            sink.start()
            results = list(sink.write(self._exec(node.children[0])))
            yield sink.finalize(results)
            return
        from ..io import writers
        if info.get("mode") == "overwrite":
            writers.overwrite_dir(info["root_dir"])
        child = self._exec(node.children[0])
        outs = list(_ordered_parallel(
            child, lambda p: writers.write_micropartition(
                p, info["kind"], info["root_dir"],
                info.get("partition_cols"), info.get("options"))))
        outs = [o for o in outs if len(o)]
        if not outs:
            yield MicroPartition.empty(node.schema())
            return
        yield MicroPartition.from_recordbatch(
            RecordBatch.concat(outs).cast_to_schema(node.schema()))


def _task_column_ndv(tasks, name: str):
    """max-min+1 folded over ALL tasks' parquet footers for an int column
    (the scan-level twin of logical/stats.column_ndv). A single file's
    range would underestimate scans range-partitioned on the key and let
    a non-reductive grouping through the gate."""
    try:
        import pyarrow.parquet as pq
        lo = hi = None
        seen = set()
        for t in tasks:
            if t.file_format != "parquet" or not t.paths:
                return None
            md_cached = getattr(t, "pq_metadata", None)
            for path in t.paths:
                if path in seen:
                    continue
                seen.add(path)
                md = md_cached if md_cached is not None \
                    and len(t.paths) == 1 else pq.ParquetFile(path).metadata
                idx = {md.schema.column(i).name: i
                       for i in range(md.num_columns)}.get(name)
                if idx is None:
                    return None
                for rg in range(md.num_row_groups):
                    st = md.row_group(rg).column(idx).statistics
                    if st is None or not st.has_min_max \
                            or not isinstance(st.min, int) \
                            or isinstance(st.min, bool):
                        return None
                    lo = st.min if lo is None else min(lo, st.min)
                    hi = st.max if hi is None else max(hi, st.max)
        return None if lo is None else float(hi - lo + 1)
    except Exception:
        return None


def _fragment_groups_affordable(node, src) -> bool:
    """Upfront group-cardinality gate for the fused device aggregation:
    a NON-reductive grouping (TPC-H Q18's near-unique l_orderkey, Q20's
    partkey×suppkey) would ship a group block rivaling the input over the
    link — estimate groups from parquet footer NDVs and refuse the device
    path when the packed transfer would exceed the host's own aggregation
    time (the same parity rule ``fragment._max_out_cap`` enforces at run
    time, applied before any upload or probe happens)."""
    import math

    from ..device import costmodel
    p = costmodel.link_profile()
    if p.down_bps == math.inf:
        return True
    ndvs = []
    for g in node.group_by:
        u = g._unalias()
        if u.op != "col":
            return True  # computed key: unknown → assume reductive
        ndv = _task_column_ndv(src.tasks, u.params[0])
        if ndv is None:
            return True  # strings/no stats → assume reductive
        ndvs.append(ndv)
    if not ndvs:
        return True  # global aggregation: one packed scalar row
    est_groups = 1.0
    for n in ndvs:
        est_groups *= n
    rows = sum(t.num_rows() or 0 for t in src.tasks)
    if rows:
        est_groups = min(est_groups, float(rows))
    from ..device.fragment import packed_bytes_per_group
    # node.aggs is the PARTIAL agg list (_split_aggs already decomposed
    # mean→sum+count etc. before _try_fuse_partial built this node), so its
    # length equals len(prog.ops) and prices the same packed layout that
    # run_packed emits
    bytes_per_group = packed_bytes_per_group(len(node.group_by),
                                             len(node.aggs))
    size = sum(t.size_bytes() or 0 for t in src.tasks)
    host_s = max(size, 1) / costmodel.HOST_AGG_BPS
    return est_groups * bytes_per_group <= host_s * p.down_bps


def _lit_true() -> Expression:
    from ..expressions.expressions import lit
    return lit(True)


def _encode_plane_lists(encode, names):
    """Encode columns into parallel (values, valids) plane lists; None when
    any column lacks a plain device representation."""
    vals, valids = [], []
    for nm in names:
        enc = encode(nm)
        if enc is None:
            return None
        vals.append(enc[0])
        valids.append(enc[1])
    return vals, valids


def _decode_mesh_shards(n: int, live_mask: np.ndarray, cols_spec, schema
                        ) -> List[MicroPartition]:
    """Slice exchanged [n*C'] blocks into per-shard MicroPartitions.
    cols_spec: ordered (name, dtype, values_plane, valids_plane) tuples."""
    from ..device import column as dcol
    shard_len = live_mask.shape[0] // n
    outs = []
    for i in range(n):
        sl = slice(i * shard_len, (i + 1) * shard_len)
        live = live_mask[sl]
        cnt = int(live.sum())
        cols = []
        for nm, dtype, v, m in cols_spec:
            dc = dcol.DeviceColumn(v[sl][live], m[sl][live], dtype, None)
            cols.append(dcol.decode_column(nm, dc, cnt))
        outs.append(MicroPartition.from_recordbatch(
            RecordBatch.from_series(cols).cast_to_schema(schema)))
    return outs


def _load_with_retry(task, tries: int = 2) -> MicroPartition:
    """Scan-task load with transient-IO retry (reference analogue: per-task
    lineage retry in the classic runner / flotilla max_task_retries —
    inputs are re-scannable from storage, so retrying the load is safe)."""
    tries = max(tries, 1)
    last = None
    for attempt in range(tries):
        mp = MicroPartition.from_scan_task(task)
        try:
            mp._load()
            return mp
        except OSError as exc:
            last = exc
            if attempt + 1 < tries:
                import time
                time.sleep(min(0.2 * (2 ** attempt), 2.0))
    raise last


def _np_plane_encoder(rb: RecordBatch, cap: int):
    """Column name → (values, validity) numpy planes zero-padded to cap, or
    None when the column has no plain device representation."""
    import pyarrow as pa
    from ..device import column as dcol

    def encode(name):
        try:
            vals, valid, dictionary = dcol._np_encode(rb.get_column(name))
        except (ValueError, TypeError, pa.ArrowInvalid):
            return None
        if dictionary is not None:
            return None
        if len(vals) < cap:
            vals = np.concatenate(
                [vals, np.zeros(cap - len(vals), dtype=vals.dtype)])
            valid = np.concatenate(
                [valid, np.zeros(cap - len(valid), dtype=np.bool_)])
        return vals, valid

    return encode


def _gather_all(parts: Iterator[MicroPartition]) -> MicroPartition:
    ps = list(parts)
    return ps[0].concat(ps[1:]) if len(ps) > 1 else ps[0]


def sample_boundaries(sampled_keys: List[RecordBatch],
                      key_names: List[str], descending: List[bool],
                      nulls_first: List[bool], n: int
                      ) -> Optional[RecordBatch]:
    """Concatenated key samples → n-1 range boundaries (sorted,
    null-free), or None when there is nothing to sample. Shared by the
    local range exchange and the distributed worker-side sort protocol
    (the driver computes boundaries from samples only)."""
    merged = RecordBatch.concat(sampled_keys)
    by = [col(nm) for nm in key_names]
    merged = merged.filter(~_any_null(by, merged)) if len(merged) \
        else merged
    if len(merged) == 0:
        return None
    merged_sorted = merged.sort(by, descending, nulls_first)
    idx = [min(int(len(merged_sorted) * (i + 1) / n),
               len(merged_sorted) - 1) for i in range(n - 1)]
    return merged_sorted.take(np.asarray(idx, dtype=np.int64))


def _any_null(by: List[Expression], rb: RecordBatch) -> Expression:
    e = col(by[0].name()).is_null()
    for b in by[1:]:
        e = e | col(b.name()).is_null()
    return e
