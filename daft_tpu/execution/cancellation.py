"""Cooperative query cancellation.

The serving plane (``daft_tpu/serving``) admits N concurrent queries; any
of them can be cancelled — by a client INTERRUPT through the Spark Connect
server, a queue timeout, or an explicit ``QueryHandle.cancel()``. The
token is *cooperative*: executors check it at morsel boundaries (a batch
mid-kernel finishes), which bounds cancellation latency to one morsel
without unwinding device dispatches mid-flight.

Propagation is scope-based: the scheduler worker installs the query's
token with :func:`cancel_scope` before entering the runner, and the
executors capture :func:`current_token` at construction — the token rides
the plan, not the thread, so pipeline stage threads spawned later still
observe it through the executor instance.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, List, Optional


class QueryCancelled(RuntimeError):
    """Raised inside an executing query when its cancel token fires."""


class CancelToken:
    """One query's cancel flag + listener list.

    ``set()`` is idempotent; callbacks registered after the token fired
    run immediately (a late-registering executor must still unwind)."""

    def __init__(self) -> None:
        self._event = threading.Event()
        self._cb_lock = threading.Lock()
        self._callbacks: List[Callable[[], None]] = []
        self.reason: Optional[str] = None

    def set(self, reason: Optional[str] = None) -> None:
        with self._cb_lock:
            if self._event.is_set():
                return
            if reason is not None:
                self.reason = reason
            self._event.set()
            cbs = list(self._callbacks)
        for cb in cbs:
            try:
                cb()
            except Exception:
                pass  # a listener must never block the cancel itself

    def is_set(self) -> bool:
        return self._event.is_set()

    def check(self) -> None:
        """Raise :class:`QueryCancelled` if the token fired."""
        if self._event.is_set():
            raise QueryCancelled(self.reason or "query cancelled")

    def add_callback(self, fn: Callable[[], None]) -> None:
        fire_now = False
        with self._cb_lock:
            if self._event.is_set():
                fire_now = True
            else:
                self._callbacks.append(fn)
        if fire_now:
            try:
                fn()
            except Exception:
                pass


_tl = threading.local()


def current_token() -> Optional[CancelToken]:
    """The cancel token installed on this thread's active scope, if any."""
    return getattr(_tl, "token", None)


@contextlib.contextmanager
def cancel_scope(token: Optional[CancelToken]):
    """Install ``token`` as the thread's current cancellation scope."""
    prev = getattr(_tl, "token", None)
    _tl.token = token
    try:
        yield token
    finally:
        _tl.token = prev
