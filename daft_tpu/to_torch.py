"""Torch dataset bridges (reference: ``daft/dataframe/to_torch.py``)."""

from __future__ import annotations


class TorchMapDataset:
    def __init__(self, df):
        import torch.utils.data
        self._rows = df.to_pylist()

        class _DS(torch.utils.data.Dataset):
            def __init__(s):
                pass

            def __len__(s):
                return len(self._rows)

            def __getitem__(s, i):
                return self._rows[i]
        self._ds = _DS()

    def __len__(self):
        return len(self._ds)

    def __getitem__(self, i):
        return self._ds[i]


class TorchIterDataset:
    def __init__(self, df):
        import torch.utils.data

        class _DS(torch.utils.data.IterableDataset):
            def __iter__(s):
                return df.iter_rows()
        self._ds = _DS()

    def __iter__(self):
        return iter(self._ds)
