"""Process-wide device mesh singleton.

The engine's collective exchanges run over one 1-D ``data`` mesh spanning
every visible device (virtual CPU devices under
``xla_force_host_platform_device_count`` in tests, real chips on a pod).
``DAFT_TPU_MESH_DEVICES`` caps the axis length; mesh construction is guarded
behind the watchdog-probed backend (device/backend.py) so a wedged plugin
can't hang planning.
"""

from __future__ import annotations

import threading
from typing import Optional

_lock = threading.RLock()   # re-entrant: get_mesh holds it across mesh_size
_mesh = None
_size: Optional[int] = None


def mesh_size() -> int:
    """Number of devices the exchange mesh would span (0 = no device)."""
    global _size
    if _size is not None:
        return _size
    with _lock:
        if _size is not None:
            return _size
        from ..device import backend
        if backend.backend_name() is None:
            _size = 0
            return 0
        import jax

        n = len(jax.devices())
        from ..analysis import knobs
        cap = knobs.env_int("DAFT_TPU_MESH_DEVICES")
        if cap is not None:
            n = min(n, cap)
        _size = n
        return n


#: legacy static admission floor, now only the FALLBACK when the cost
#: model cannot price a collective (no calibrated rates at all);
#: ``DAFT_TPU_MESH_MIN_ROWS`` (when set) force-overrides the cost model
#: entirely — ``0`` forces the mesh (the knob the mesh-correctness tests
#: and the multichip dryrun set), ``N`` requires at least N rows
_MESH_MIN_ROWS = 65536


def mesh_min_rows() -> int:
    from ..analysis import knobs
    v = knobs.env_int("DAFT_TPU_MESH_MIN_ROWS", default=None)
    return v if v is not None else _MESH_MIN_ROWS


def mesh_admits(rows: Optional[int], row_bytes: float = 32.0) -> bool:
    """Admission for a mesh collective (exchange agg, hash repartition).

    ``DAFT_TPU_MESH_MIN_ROWS`` set → force-override: the static row floor
    decides exactly as before (``0`` forces the mesh). Unset → the cost
    model prices the collective (dispatch + amortized compile + bytes
    over the calibrated ICI rate, ``costmodel.ici_bps``) against one
    host hash-partition pass — so tiny aggs stop paying collective
    compile+dispatch while medium, wide-row ones stop being wrongly
    declined by a width-blind row count."""
    from ..analysis import knobs
    v = knobs.env_int("DAFT_TPU_MESH_MIN_ROWS", default=None)
    if v is not None:
        return rows is None or rows >= v
    try:
        from ..device import costmodel
        return costmodel.mesh_exchange_wins(rows, row_bytes, mesh_size())
    except Exception:
        return rows is None or rows >= _MESH_MIN_ROWS


def get_mesh():
    global _mesh
    with _lock:
        if _mesh is None:
            from . import exchange
            n = mesh_size()
            if n < 1:
                return None
            _mesh = exchange.make_mesh(n)
        return _mesh


def reset_for_tests() -> None:
    global _mesh, _size
    with _lock:
        _mesh = None
        _size = None
