"""Mesh-aware stage runner for the distributed runner.

Splits the physical plan at Exchange boundaries into stages (the flotilla
StagePlan model, ``src/daft-distributed/src/stage/mod.rs:54-80``) and runs
hash-exchange + aggregate stages through the fused mesh collective programs in
``exchange.py`` when the data is device-representable; everything else reuses
the local streaming executor (per-host work in a real pod deployment).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

from ..execution.executor import LocalExecutor
from ..micropartition import MicroPartition
from ..physical import plan as pp


class MeshStageRunner:
    def __init__(self, num_workers: Optional[int] = None):
        self.num_workers = num_workers

    def run(self, plan: pp.PhysicalPlan) -> Iterator[MicroPartition]:
        # Current revision: stage boundaries follow the local executor's
        # materialization points; collective offload is engaged per-stage by
        # the executor's device dispatch. Multi-host orchestration (one
        # runner per TPU host) reuses this same splitting.
        executor = LocalExecutor()
        yield from executor.run(plan)
