"""Mesh-collective exchange kernels: repartition as ICI collectives.

The TPU-native replacement for the reference's shuffle service
(``src/daft-shuffles``: map-side hash partitioning + Arrow Flight transport):
device shards hold padded column blocks; a jit+shard_map program hash-buckets
rows locally and exchanges buckets with ``lax.all_to_all`` over the mesh's ICI
links; a fused partial→exchange→final grouped aggregation keeps the whole
map/shuffle/reduce in one XLA program (SURVEY.md §2.6 "TPU mapping").

All programs here are SPMD over a 1-D ``data`` mesh axis and compile for any
device count — the multichip dry-run drives them on a virtual CPU mesh.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..device import kernels


def make_mesh(n_devices: Optional[int] = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


def _hash_u32(x: jnp.ndarray) -> jnp.ndarray:
    h = x.astype(jnp.uint32)
    h = (h ^ (h >> 16)) * jnp.uint32(0x85EBCA6B)
    h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def all_to_all_by_hash(keys: jnp.ndarray, payload: Tuple[jnp.ndarray, ...],
                       row_mask: jnp.ndarray, n_shards: int, axis: str):
    """Inside shard_map: bucket local rows by key hash and exchange so shard i
    receives every row with ``hash(key) % n == i``.

    Per-shard block size is static (= local capacity); buckets are padded.
    Returns (keys, payload..., row_mask) blocks of shape [n*cap_per_bucket]
    on each shard.
    """
    C = keys.shape[0]
    pid = (_hash_u32(keys) % jnp.uint32(n_shards)).astype(jnp.int32)
    pid = jnp.where(row_mask, pid, n_shards)  # dead rows bucket to the end
    # stable sort rows by destination bucket
    order = jnp.argsort(pid, stable=True)
    sorted_pid = jnp.take(pid, order)
    # each bucket gets a fixed C-slot frame: scatter rows to bucket-local
    # slots; dead rows (pid == n_shards) get out-of-range slots → dropped
    in_bucket_pos = jnp.arange(C) - jnp.searchsorted(
        sorted_pid, sorted_pid, side="left")
    slots = jnp.where(sorted_pid < n_shards,
                      sorted_pid * C + in_bucket_pos, n_shards * C)
    frame = jnp.zeros((n_shards * C,), keys.dtype)
    live_sorted = jnp.take(row_mask, order)
    frame_mask = jnp.zeros((n_shards * C,), jnp.bool_)
    frame = frame.at[slots].set(jnp.take(keys, order), mode="drop")
    frame_mask = frame_mask.at[slots].set(live_sorted, mode="drop")
    out_payload = []
    for p in payload:
        fp = jnp.zeros((n_shards * C,), p.dtype)
        fp = fp.at[slots].set(jnp.take(p, order), mode="drop")
        out_payload.append(fp)
    # [n_shards, C] frames → all_to_all over the mesh axis
    k2 = frame.reshape(n_shards, C)
    m2 = frame_mask.reshape(n_shards, C)
    k2 = lax.all_to_all(k2, axis, 0, 0, tiled=False)
    m2 = lax.all_to_all(m2, axis, 0, 0, tiled=False)
    out2 = []
    for fp in out_payload:
        out2.append(lax.all_to_all(fp.reshape(n_shards, C), axis, 0, 0,
                                   tiled=False).reshape(-1))
    return k2.reshape(-1), tuple(out2), m2.reshape(-1)


def sharded_grouped_sum(mesh: Mesh, keys_sharded, vals_sharded,
                        mask_sharded, axis: str = "data"):
    """Fused map→all_to_all→reduce grouped sum over the mesh.

    keys/vals/mask: [n_shards * C] arrays sharded on dim 0. Each device:
    (1) partial grouped-sum of its block, (2) all_to_all partials by key hash,
    (3) final grouped-sum. Output: per-shard padded group blocks.
    """
    n = mesh.shape[axis]

    from jax import shard_map

    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P(axis), P(axis)),
             out_specs=(P(axis), P(axis), P(axis), P(axis)),
             check_vma=False)
    def run(k, v, m):
        k, v, m = k.reshape(-1), v.reshape(-1), m.reshape(-1)
        # (1) local partial aggregation (shrinks data before the exchange)
        (pk,), (pkv,), (ps,), (psv,), cnt = kernels.grouped_agg_kernel(
            (k,), (m,), (v,), (m,), m, ("sum",))
        pmask = jnp.arange(pk.shape[0]) < cnt
        # (2) exchange partials so equal keys land on one shard
        k2, (v2,), m2 = all_to_all_by_hash(pk, (ps,), pmask & pkv, n, axis)
        # (3) final aggregation of received partials
        (fk,), (fkv,), (fs,), (fsv,), fcnt = kernels.grouped_agg_kernel(
            (k2,), (m2,), (v2,), (m2,), m2, ("sum",))
        fmask = jnp.arange(fk.shape[0]) < fcnt
        return fk, fs, fmask, jnp.broadcast_to(fcnt, (fk.shape[0],))

    return run(keys_sharded, vals_sharded, mask_sharded)


def shard_blocks(mesh: Mesh, arr: np.ndarray, axis: str = "data"):
    """Host ndarray → device array sharded along dim 0 of the mesh axis."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.device_put(arr, sharding)
