"""Mesh-collective exchange kernels: repartition as ICI collectives.

The TPU-native replacement for the reference's shuffle service
(``src/daft-shuffles``: map-side hash partitioning + Arrow Flight transport):
device shards hold padded column blocks; a jit+shard_map program hash-buckets
rows locally and exchanges buckets with ``lax.all_to_all`` over the mesh's ICI
links; a fused partial→exchange→final grouped aggregation keeps the whole
map/shuffle/reduce in one XLA program (SURVEY.md §2.6 "TPU mapping").

All programs here are SPMD over a 1-D ``data`` mesh axis and compile for any
device count — the multichip dry-run drives them on a virtual CPU mesh.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..device import kernels


#: (fn code + closure values, mesh, specs) → jitted collective program.
#: The callers below build their mapped fns as per-call closures, so the
#: function OBJECT differs every call while the program it traces to is
#: identical — keying on the code object plus the closure's cell values
#: (the shard count, op tuple, plane counts the closure captured) makes
#: repeated mesh exchanges re-enter jax's trace cache instead of paying
#: a fresh trace + compile per exchange (the round-16 retrace tax:
#: ~70 s eager vs milliseconds compiled was already fixed in r6; this
#: removes the remaining per-call re-trace of the SAME collective).
_program_cache: dict = {}
_program_counters = {"hits": 0, "misses": 0, "uncacheable": 0}


def exchange_cache_counters() -> dict:
    """Collective-program cache counters (the regression test's evidence
    that two same-shape exchanges share one trace)."""
    out = dict(_program_counters)
    out["entries"] = len(_program_cache)
    return out


def _program_key(f, mesh, in_specs, out_specs, check_vma):
    """Hashable identity of the collective program, or None when a
    closure cell holds something unhashable (those fall back to a fresh
    jit, exactly the old behavior)."""
    try:
        cells = tuple(c.cell_contents for c in (f.__closure__ or ()))
        # defaults are the THIRD identity channel besides code + cells:
        # two fns differing only in a default-argument value must not
        # share one compiled program
        defaults = (f.__defaults__ or (),
                    tuple(sorted((f.__kwdefaults__ or {}).items())))
        key = (f.__code__, cells, defaults, mesh, tuple(in_specs),
               tuple(out_specs), check_vma)
        return hash(key), key
    except (TypeError, ValueError):
        return None


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax versions: new jax exports it top-level
    with ``check_vma``; older releases ship ``jax.experimental.shard_map``
    whose equivalent knob is ``check_rep``.

    The program is returned JITTED and MEMOIZED on (fn identity, mesh,
    in/out specs): un-jitted shard_map executes eagerly (per-op dispatch
    over every mesh shard — measured ~70 s for one tiny mesh-exchanged
    Q1 on the 8-device CPU mesh, vs milliseconds compiled), and a fresh
    ``jax.jit`` wrapper per call could never hit jax's trace cache, so
    every exchange re-traced the same collective."""
    keyed = _program_key(f, mesh, in_specs, out_specs, check_vma)
    if keyed is not None:
        hit = _program_cache.get(keyed[1])
        if hit is not None:
            _program_counters["hits"] += 1  # GIL-atomic; approx. on race
            return hit
        _program_counters["misses"] += 1
    else:
        _program_counters["uncacheable"] += 1
    try:
        from jax import shard_map as sm
        mapped = sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_vma=check_vma)
    except (ImportError, TypeError):
        # TypeError: intermediate jax versions export top-level shard_map
        # but still spell the knob check_rep — fall through to the
        # experimental path, which takes it under that name
        from jax.experimental.shard_map import shard_map as sm
        mapped = sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_rep=check_vma)
    from ..analysis import retrace_sanitizer
    program = jax.jit(mapped)
    # uncacheable programs (unhashable closure cell) each get a UNIQUE
    # scope key: they legitimately trace once apiece, and sharing one
    # key would spuriously trip the per-signature retrace budget
    scope_key = keyed[1] if keyed is not None \
        else ("uncacheable", id(program))
    jitted = retrace_sanitizer.scoped_callable(
        "exchange.shard_map", scope_key, program)
    if keyed is not None:
        _program_cache[keyed[1]] = jitted
    return jitted


def make_mesh(n_devices: Optional[int] = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


def _hash_u32(x: jnp.ndarray) -> jnp.ndarray:
    h = x.astype(jnp.uint32)
    h = (h ^ (h >> 16)) * jnp.uint32(0x85EBCA6B)
    h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def all_to_all_by_hash(keys: jnp.ndarray, payload: Tuple[jnp.ndarray, ...],
                       row_mask: jnp.ndarray, n_shards: int, axis: str):
    """Inside shard_map: bucket local rows by key hash and exchange so shard i
    receives every row with ``hash(key) % n == i``.

    Per-shard block size is static (= local capacity); buckets are padded.
    Returns (keys, payload..., row_mask) blocks of shape [n*cap_per_bucket]
    on each shard.
    """
    pid = (_hash_u32(keys) % jnp.uint32(n_shards)).astype(jnp.int32)
    k2, out, m2 = all_to_all_by_pid(pid, (keys,) + payload, row_mask,
                                    n_shards, axis)
    return out[0], out[1:], m2


def all_to_all_by_pid(pid: jnp.ndarray, payload: Tuple[jnp.ndarray, ...],
                      row_mask: jnp.ndarray, n_shards: int, axis: str):
    """all_to_all routing by a precomputed destination-shard plane. Used when
    the partition assignment must agree with the host tier's hash (join
    co-partitioning: both sides of a hash join must route identically, so
    the pid is computed once with the engine-wide xxh64 chain and the mesh
    merely moves the rows)."""
    C = pid.shape[0]
    pid = jnp.where(row_mask, pid, n_shards)  # dead rows bucket to the end
    # stable sort rows by destination bucket
    order = jnp.argsort(pid, stable=True)
    sorted_pid = jnp.take(pid, order)
    # each bucket gets a fixed C-slot frame: scatter rows to bucket-local
    # slots; dead rows (pid == n_shards) get out-of-range slots → dropped
    in_bucket_pos = jnp.arange(C) - jnp.searchsorted(
        sorted_pid, sorted_pid, side="left")
    slots = jnp.where(sorted_pid < n_shards,
                      sorted_pid * C + in_bucket_pos, n_shards * C)
    live_sorted = jnp.take(row_mask, order)
    frame_mask = jnp.zeros((n_shards * C,), jnp.bool_)
    frame_mask = frame_mask.at[slots].set(live_sorted, mode="drop")
    out_payload = []
    for p in payload:
        fp = jnp.zeros((n_shards * C,), p.dtype)
        fp = fp.at[slots].set(jnp.take(p, order), mode="drop")
        out_payload.append(fp)
    # [n_shards, C] frames → all_to_all over the mesh axis
    m2 = lax.all_to_all(frame_mask.reshape(n_shards, C), axis, 0, 0,
                        tiled=False)
    out2 = []
    for fp in out_payload:
        out2.append(lax.all_to_all(fp.reshape(n_shards, C), axis, 0, 0,
                                   tiled=False).reshape(-1))
    return pid, tuple(out2), m2.reshape(-1)


def sharded_grouped_sum(mesh: Mesh, keys_sharded, vals_sharded,
                        mask_sharded, axis: str = "data"):
    """Fused map→all_to_all→reduce grouped sum over the mesh.

    keys/vals/mask: [n_shards * C] arrays sharded on dim 0. Each device:
    (1) partial grouped-sum of its block, (2) all_to_all partials by key hash,
    (3) final grouped-sum. Output: per-shard padded group blocks.
    """
    n = mesh.shape[axis]

    @partial(shard_map_compat, mesh=mesh, in_specs=(P(axis), P(axis), P(axis)),
             out_specs=(P(axis), P(axis), P(axis), P(axis)),
             check_vma=False)
    def run(k, v, m):
        k, v, m = k.reshape(-1), v.reshape(-1), m.reshape(-1)
        # (1) local partial aggregation (shrinks data before the exchange)
        (pk,), (pkv,), (ps,), (psv,), cnt = kernels.grouped_agg_kernel(
            (k,), (m,), (v,), (m,), m, ("sum",))
        pmask = jnp.arange(pk.shape[0]) < cnt
        # (2) exchange partials so equal keys land on one shard
        k2, (v2,), m2 = all_to_all_by_hash(pk, (ps,), pmask & pkv, n, axis)
        # (3) final aggregation of received partials
        (fk,), (fkv,), (fs,), (fsv,), fcnt = kernels.grouped_agg_kernel(
            (k2,), (m2,), (v2,), (m2,), m2, ("sum",))
        fmask = jnp.arange(fk.shape[0]) < fcnt
        return fk, fs, fmask, jnp.broadcast_to(fcnt, (fk.shape[0],))

    return run(keys_sharded, vals_sharded, mask_sharded)


def shard_blocks(mesh: Mesh, arr: np.ndarray, axis: str = "data"):
    """Host ndarray → device array sharded along dim 0 of the mesh axis."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.device_put(arr, sharding)


def _combine_hashes(keys, kvalids) -> jnp.ndarray:
    """Multi-key → one u32 hash plane (boost-style hash_combine)."""
    h = jnp.zeros(keys[0].shape, jnp.uint32)
    for k, kv in zip(keys, kvalids):
        x = k
        if x.dtype == jnp.bool_:
            x = x.astype(jnp.uint32)
        elif jnp.issubdtype(x.dtype, jnp.floating):
            x = lax.bitcast_convert_type(
                x.astype(jnp.float32), jnp.uint32)
        elif x.dtype in (jnp.int64, jnp.uint64):
            lo = (x & 0xFFFFFFFF).astype(jnp.uint32)
            hi = ((x >> 32) & 0xFFFFFFFF).astype(jnp.uint32)
            x = lo ^ (hi * jnp.uint32(0x9E3779B9))
        else:
            x = x.astype(jnp.uint32)
        hk = _hash_u32(x ^ kv.astype(jnp.uint32))
        h = h ^ (hk + jnp.uint32(0x9E3779B9) + (h << 6) + (h >> 2))
    return h


# final-merge ops that combine with themselves (x ⊕ x is the correct merge of
# two partials): the partial/final agg split upstream reduces count/mean/var
# to sums before this layer.
MERGEABLE_OPS = ("sum", "min", "max", "any_value", "bool_and", "bool_or")


def sharded_grouped_agg(mesh: Mesh, keys, kvalids, vals, vvalids, mask,
                        ops: Tuple[str, ...], axis: str = "data"):
    """Fused map→all_to_all→reduce grouped aggregation over the mesh, for any
    number of key/value planes. The general engine path behind
    ``DeviceExchangeAgg`` (reference seam: the ShuffleExchange strategy enum,
    ``src/daft-physical-plan/src/ops/shuffle_exchange.rs:41-58`` — here the
    strategy *is* an ICI collective inside one XLA program).

    keys/vals: tuples of [n*C] arrays sharded on dim 0; ops must all be in
    MERGEABLE_OPS. Returns (keys, kvalids, vals, vvalids, group_mask) blocks,
    one [C']-sized group block per shard with disjoint key sets.
    """
    n = mesh.shape[axis]
    nk, nv = len(keys), len(vals)
    assert all(op in MERGEABLE_OPS for op in ops), ops

    spec_in = (P(axis),) * (2 * nk + 2 * nv + 1)
    spec_out = (P(axis),) * (2 * nk + 2 * nv + 1)

    @partial(shard_map_compat, mesh=mesh, in_specs=spec_in,
             out_specs=spec_out,
             check_vma=False)
    def run(*args):
        ks = tuple(a.reshape(-1) for a in args[:nk])
        kvs = tuple(a.reshape(-1) for a in args[nk:2 * nk])
        vs = tuple(a.reshape(-1) for a in args[2 * nk:2 * nk + nv])
        vvs = tuple(a.reshape(-1) for a in args[2 * nk + nv:2 * nk + 2 * nv])
        m = args[-1].reshape(-1)
        # (1) local partial merge (shrinks data before the exchange)
        ok, okv, ov, ovv, cnt = kernels.grouped_agg_impl(ks, kvs, vs, vvs,
                                                         m, ops)
        pmask = jnp.arange(ok[0].shape[0]) < cnt
        # (2) exchange group blocks so equal keys land on one shard
        h = _combine_hashes(ok, okv)
        payload = tuple(ok) + tuple(okv) + tuple(ov) + tuple(ovv)
        _, payload2, m2 = all_to_all_by_hash(h.astype(jnp.int32), payload,
                                             pmask, n, axis)
        ks2 = payload2[:nk]
        kvs2 = payload2[nk:2 * nk]
        vs2 = payload2[2 * nk:2 * nk + nv]
        vvs2 = payload2[2 * nk + nv:]
        # (3) final merge of received partials
        fk, fkv, fv, fvv, fcnt = kernels.grouped_agg_impl(
            ks2, kvs2, vs2, vvs2, m2, ops)
        fmask = jnp.arange(fk[0].shape[0]) < fcnt
        return fk + fkv + fv + fvv + (fmask,)

    flat = run(*(tuple(keys) + tuple(kvalids) + tuple(vals) + tuple(vvalids)
                 + (mask,)))
    fk = flat[:nk]
    fkv = flat[nk:2 * nk]
    fv = flat[2 * nk:2 * nk + nv]
    fvv = flat[2 * nk + nv:2 * nk + 2 * nv]
    return fk, fkv, fv, fvv, flat[-1]


def sharded_broadcast_join(mesh: Mesh, l_key, l_valid, l_mask,
                           r_key, r_valid, r_mask,
                           out_capacity_per_shard: int, axis: str = "data"):
    """Broadcast equi-join over the mesh: the left key plane is sharded on
    the mesh axis; the small right side is REPLICATED to every device (the
    strategy the planner picks when one side is under the broadcast
    threshold — no all_to_all at all, the build side rides one broadcast).
    Each shard sort-merges its local block against the replicated build
    side in one XLA program (``kernels.join_*_impl``).

    Returns per-shard (left_idx, right_idx, valid) gather-index blocks
    stacked to [n_shards * out_capacity_per_shard]; left indices are
    SHARD-LOCAL (caller adds ``shard * C`` to globalize).
    """
    @partial(shard_map_compat, mesh=mesh,
             in_specs=(P(axis), P(axis), P(axis), P(), P(), P()),
             out_specs=(P(axis), P(axis), P(axis)), check_vma=False)
    def run(lk, lv, lm, rk, rv, rm):
        lk = lk.reshape(-1)
        lv = lv.reshape(-1)
        lm = lm.reshape(-1)
        rs, rperm, rcnt = kernels.join_sort_impl(rk, rv, rm)
        counts, starts, _ = kernels.join_count_impl(lk, lv, lm, rs, rcnt)
        return kernels.join_expand_impl(counts, starts, rperm,
                                        out_capacity_per_shard)

    return run(l_key, l_valid, l_mask, r_key, r_valid, r_mask)


def sharded_hash_repartition(mesh: Mesh, planes, valids, mask, pid,
                             axis: str = "data"):
    """Hash-repartition row blocks across the mesh with one all_to_all: shard
    i ends up holding every row whose ``pid`` plane says i. The pid is
    computed HOST-side with the engine-wide xxh64 chain
    (``recordbatch.py partition_by_hash``) so mesh- and host-exchanged
    partitions of the same key agree — a hash join may co-partition one side
    on the mesh and the other on the host. planes: tuple of [n*C] column
    arrays. Returns (planes, valids, row_mask) received blocks per shard."""
    n = mesh.shape[axis]
    np_ = len(planes)

    spec_in = (P(axis),) * (2 * np_ + 2)
    spec_out = (P(axis),) * (2 * np_ + 1)

    @partial(shard_map_compat, mesh=mesh, in_specs=spec_in,
             out_specs=spec_out,
             check_vma=False)
    def run(*args):
        ps = tuple(a.reshape(-1) for a in args[:np_])
        vs = tuple(a.reshape(-1) for a in args[np_:2 * np_])
        m = args[-2].reshape(-1)
        p = args[-1].reshape(-1)
        _, payload2, m2 = all_to_all_by_pid(p, ps + vs, m, n, axis)
        return tuple(payload2) + (m2,)

    flat = run(*(tuple(planes) + tuple(valids) + (mask, pid)))
    return flat[:np_], flat[np_:2 * np_], flat[-1]
