"""Per-operator runtime stats, chrome tracing, and progress reporting.

Capability mirror of the reference's observability stack:
- per-operator rows/cpu counters (``daft-local-execution/src/runtime_stats.rs:23-75``)
- chrome-trace layer gated by an env flag
  (``DAFT_DEV_ENABLE_CHROME_TRACE``, ``src/common/tracing/src/lib.rs:16-17``)
- progress bars (``progress_bar.rs`` / ``daft/runners/progress_bar.py``)
- ``explain_analyze`` plan annotation
  (``physical_planner/planner.rs:451-640``)

Env flags (same spirit as the reference's ``DAFT_DEV_*``):
- ``DAFT_TPU_CHROME_TRACE`` — ``1`` or a path; writes a chrome://tracing
  JSON for the last execution (default ``/tmp/daft_tpu_trace_<pid>.json``)
- ``DAFT_TPU_PROGRESS`` — ``1`` enables a tqdm partition-progress bar
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

_START_TS = time.perf_counter()


# ------------------------------------------------------------ attribution
#
# Process-wide planes (shuffle / scan-io / recovery) are shared counters;
# diffing them per query breaks the moment two queries overlap (both diffs
# see the union). The serving plane needs per-query numbers, so counter
# chokepoints ALSO bump the thread's *attributed* RuntimeStatsContext:
# executors install their stats context on every thread that does work for
# the query (driver generators, pool workers, pipeline stages, IO fan-out),
# and `finish()` prefers the context-local tally over the process diff
# whenever the context was attributed at all.

_attr_tl = threading.local()


def current_attribution() -> Optional["RuntimeStatsContext"]:
    return getattr(_attr_tl, "ctx", None)


@contextlib.contextmanager
def attributed(ctx: Optional["RuntimeStatsContext"]):
    """Install ``ctx`` as this thread's stats-attribution target (and,
    when the context belongs to a traced query, its span context — the
    tracing plane rides the same propagation: pool workers, pipeline
    stage threads and prefetch producers all come through here)."""
    from . import tracing  # hot path: resolved from sys.modules
    prev = getattr(_attr_tl, "ctx", None)
    _attr_tl.ctx = ctx
    tctx = ctx.trace_ctx if ctx is not None else None
    tprev = tracing._set_current(tctx) if tctx is not None else None
    if ctx is not None:
        ctx._attributed = True
    try:
        yield
    finally:
        if tctx is not None:
            tracing._set_current(tprev)
        _attr_tl.ctx = prev


# nested-execution marker: worker-side stage fragments and
# coordinator-deferred executions run their own executors (each with its
# own RuntimeStatsContext + set_last_stats); per-query EXPORTS (otlp,
# trace files, flight recorder) must fire once per top-level query, so
# nested scopes suppress them and the outermost owner finalizes.

_nested_tl = threading.local()


@contextlib.contextmanager
def nested_scope():
    prev = getattr(_nested_tl, "n", 0)
    _nested_tl.n = prev + 1
    try:
        yield
    finally:
        _nested_tl.n = prev


def in_nested_scope() -> bool:
    return getattr(_nested_tl, "n", 0) > 0


def run_attributed(ctx, fn, *args, **kwargs):
    """Run ``fn`` with ``ctx`` attributed — the shape pool-submit sites
    use to carry the submitting thread's attribution onto the worker."""
    with attributed(ctx):
        return fn(*args, **kwargs)


def bump_plane(plane: str, key: str, n: float = 1) -> None:
    """Credit ``n`` to the attributed context's plane tally (no-op when
    the thread is unattributed — the process-wide counter the caller
    already bumped remains the only record, as before)."""
    ctx = current_attribution()
    if ctx is not None:
        ctx._bump(plane, key, n)


def _now_us() -> int:
    return int((time.perf_counter() - _START_TS) * 1_000_000)


def _ledger_raw() -> Dict[str, dict]:
    """Raw snapshot of the device-kernel dispatch ledger (never raises —
    observability must not take a query down over a device import)."""
    try:
        from .device import costmodel
        return costmodel.ledger_snapshot(raw=True)
    except Exception:
        return {}


def _recovery_raw() -> Dict[str, int]:
    """Raw snapshot of the distributed resilience counters (retries,
    quarantines, recomputed map tasks, speculative wins/losses …) —
    never raises, like the device ledger."""
    try:
        from .distributed import resilience
        return resilience.counters_snapshot()
    except Exception:
        return {}


def _shuffle_raw() -> Dict[str, float]:
    """Raw snapshot of the shuffle data-plane counters (bytes written/
    fetched, compression ratio inputs, combine row reduction, fetch wall
    vs serial-equivalent time) — never raises, like the device ledger."""
    try:
        from .distributed import shuffle_service
        return shuffle_service.shuffle_counters_snapshot()
    except Exception:
        return {}


def _spill_raw() -> Dict[str, float]:
    """Raw snapshot of the out-of-core spill-tier counters (bytes
    written/read, partitions spilled, grace-join/agg recursions, store
    peak residency) — never raises, like the device ledger."""
    try:
        from .execution import memory
        return memory.spill_counters_snapshot()
    except Exception:
        return {}


def _scan_io_raw() -> Dict[str, float]:
    """Raw snapshot of the scan-plane IO counters (object GETs, planned
    ranges vs coalesced requests, bytes fetched vs used, prefetch wall vs
    serial-equivalent) — never raises, like the device ledger."""
    try:
        from .io import read_planner
        return read_planner.scan_counters_snapshot()
    except Exception:
        return {}


def _exchange_raw() -> Dict[str, float]:
    """Raw snapshot of the collective-exchange program-cache counters
    (hit/miss/uncacheable traces of the memoized mesh programs,
    ``parallel/exchange.py``) — never raises, like the device ledger."""
    try:
        from .parallel import exchange
        c = exchange.exchange_cache_counters()
        return {k: float(v) for k, v in c.items() if k != "entries"}
    except Exception:
        return {}


def _adaptive_raw() -> Dict[str, float]:
    """Raw snapshot of the self-tuning counters (calibration
    observations, re-plan decisions: combine flips, broadcast
    demotions, exchange re-picks, estimate rewrites) — never raises,
    like the device ledger."""
    try:
        from .physical import adaptive
        return adaptive.counters_snapshot()
    except Exception:
        return {}


def _governor_raw() -> Dict[str, float]:
    """Raw snapshot of the memory-governor action counters (pressure
    episodes, throttle waits, budget/prefetch shrinks, gc collections)
    — never raises, like the device ledger."""
    try:
        from .execution import governor
        return governor.counters_snapshot()
    except Exception:
        return {}


def _sanitizer_raw() -> Dict[str, float]:
    """Raw snapshot of the lock-order sanitizer counters (acquisitions,
    contended acquisitions, blocking-while-held events) — empty unless
    DAFT_TPU_SANITIZE=1; never raises, like the device ledger."""
    try:
        from .analysis import lock_sanitizer
        return lock_sanitizer.counters_snapshot()
    except Exception:
        return {}


def _retrace_raw() -> Dict[str, float]:
    """Raw snapshot of the retrace-sanitizer counters (trace events, XLA
    compiles + seconds, budget violations) — empty unless the retrace
    sanitizer is armed; never raises, like the device ledger."""
    try:
        from .analysis import retrace_sanitizer
        return retrace_sanitizer.counters_snapshot()
    except Exception:
        return {}


def _plansan_raw() -> Dict[str, float]:
    """Raw snapshot of the plan-sanitizer counters (rule checks,
    membership/order samples, conservation checks, violations) — empty
    unless the plan sanitizer is armed; never raises."""
    try:
        from .analysis import plan_sanitizer
        return plan_sanitizer.counters_snapshot()
    except Exception:
        return {}


def device_kernel_ledger() -> Dict[str, dict]:
    """Process-wide per-dispatch achieved-bytes/flops ledger with derived
    roofline/MFU percentages (``costmodel.ledger_record`` feeds it at
    every real argsort / join / grouped-agg / projection dispatch)."""
    try:
        from .device import costmodel
        return costmodel.ledger_snapshot()
    except Exception:
        return {}


class OperatorStats:
    """Counters for one physical operator (reference:
    ``RuntimeStatsContext`` counters)."""

    __slots__ = ("name", "rows_out", "batches_out", "inclusive_us",
                 "morsel_rows_min", "morsel_rows_max", "workers", "lock")

    def __init__(self, name: str):
        self.name = name
        self.rows_out = 0
        self.batches_out = 0
        self.inclusive_us = 0
        # observed morsel sizes: shows the re-chunking buffer honoring
        # execution_config.default_morsel_size in explain_analyze/traces
        self.morsel_rows_min = None
        self.morsel_rows_max = None
        # worker-thread count of this operator's pipeline stage (push
        # executor map stages; None = single driver thread)
        self.workers = None
        self.lock = threading.Lock()

    def record(self, nrows: int, dur_us: int):
        with self.lock:
            self.rows_out += nrows
            self.batches_out += 1
            self.inclusive_us += dur_us
            if self.morsel_rows_min is None or nrows < self.morsel_rows_min:
                self.morsel_rows_min = nrows
            if self.morsel_rows_max is None or nrows > self.morsel_rows_max:
                self.morsel_rows_max = nrows

    def record_time(self, dur_us: int):
        with self.lock:
            self.inclusive_us += dur_us


class ChromeTracer:
    """Collects chrome://tracing 'X' (complete) events; flushed per query."""

    def __init__(self):
        self._events: List[dict] = []
        self._lock = threading.Lock()

    def add(self, name: str, ts_us: int, dur_us: int):
        tid = threading.get_ident() & 0xFFFF
        with self._lock:
            self._events.append({"name": name, "ph": "X", "ts": ts_us,
                                 "dur": dur_us, "pid": os.getpid(), "tid": tid})

    def dump(self, path: str):
        with self._lock:
            events = list(self._events)
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)


class RuntimeStatsContext:
    """Per-query stats: one ``OperatorStats`` per physical-plan node.

    Timing semantics: ``inclusive_us`` is wall time spent producing each
    batch at that operator's output boundary (includes upstream pull in this
    pull-based pipeline); ``exclusive_us`` subtracts the children's inclusive
    time at render. With pipelined thread-pool ops this is an approximation —
    the reference's push model has the same per-operator granularity.
    """

    def __init__(self, tracer: Optional[ChromeTracer] = None):
        from . import tracing
        self._ops: Dict[int, OperatorStats] = {}
        self._children: Dict[int, List[int]] = {}
        self._lock = threading.Lock()
        self.tracer = tracer
        self.wall_us: Optional[int] = None
        self.plan = None  # physical plan root, set by the executor
        self._t0 = time.perf_counter()
        self._t0_unix_us = int(time.time() * 1e6)
        # tracing plane: adopt the thread's current span context (the
        # runner / serving scheduler started the trace before building
        # this context); None = this query is untraced — every span
        # site guard-checks that and stays allocation-free
        self.trace_ctx = tracing.current()
        self.trace_summary: Dict[str, object] = {}
        # per-dispatch device-kernel MFU/roofline accounting: snapshot the
        # process-wide ledger now, diff at finish() → this query's share
        self._ledger0 = _ledger_raw()
        self.device_kernels: Dict[str, dict] = {}
        # same pattern for the resilience plane's recovery events
        self._recovery0 = _recovery_raw()
        self.recovery: Dict[str, int] = {}
        # …and for the shuffle data plane (bytes written/fetched,
        # compression, combine reduction, fetch overlap)
        self._shuffle0 = _shuffle_raw()
        self.shuffle: Dict[str, float] = {}
        # …and for the scan-side IO plane (requests vs planned ranges,
        # bytes fetched vs used, prefetch overlap)
        self._io0 = _scan_io_raw()
        self.io: Dict[str, float] = {}
        # …and the out-of-core spill tier (bytes written/read, grace
        # recursions, per-store peak residency)
        self._spill0 = _spill_raw()
        self.spill: Dict[str, float] = {}
        # …and the collective-exchange program cache (hit/miss/
        # uncacheable): the evidence that same-shape mesh exchanges
        # re-enter one trace instead of re-tracing per call
        self._exchange0 = _exchange_raw()
        self.exchange: Dict[str, float] = {}
        # …and the self-tuning feedback plane (round 20): calibration
        # observations + runtime re-plan decisions this query made
        self._adaptive0 = _adaptive_raw()
        self.adaptive: Dict[str, float] = {}
        # …and the memory governor (round 23): pressure actions taken
        # while this query ran, plus the process peak RSS at finish —
        # the bounded-RSS evidence the scale bench commits per query
        self._governor0 = _governor_raw()
        self.governor: Dict[str, float] = {}
        # …and for the lock-order sanitizer (DAFT_TPU_SANITIZE=1):
        # per-query acquisition/contention deltas + current graph size
        self._sanitizer0 = _sanitizer_raw()
        self.sanitizer: Dict[str, float] = {}
        # …and the retrace sanitizer (DAFT_TPU_SANITIZE_RETRACE): this
        # query's trace/recompile events — the per-query recompile tax
        self._retrace0 = _retrace_raw()
        self.retrace: Dict[str, float] = {}
        # …and the plan sanitizer (DAFT_TPU_SANITIZE_PLAN): this query's
        # plan-contract checks — rule schema equality, re-hashed
        # membership samples, sort-order and row-conservation proofs
        self._plansan0 = _plansan_raw()
        self.plansan: Dict[str, float] = {}
        # context-local plane tallies (shuffle/io/recovery): counter
        # chokepoints bump these through the thread attribution installed
        # by the executors; finish() prefers them over the process diffs
        # so two overlapping queries don't read each other's counters
        self._plane_lock = threading.Lock()
        self._planes: Dict[str, Dict[str, float]] = {}
        self._attributed = False
        # serving-plane block (queue wait, admission, cache hits) — set
        # by the query scheduler for queries it ran; empty otherwise
        self.serving: Dict[str, object] = {}

    def _bump(self, plane: str, key: str, n: float) -> None:
        with self._plane_lock:
            d = self._planes.setdefault(plane, {})
            d[key] = d.get(key, 0) + n

    def _plane(self, plane: str) -> Dict[str, float]:
        with self._plane_lock:
            return dict(self._planes.get(plane, {}))

    def register(self, node) -> OperatorStats:
        key = id(node)
        with self._lock:
            st = self._ops.get(key)
            if st is None:
                st = OperatorStats(type(node).__name__)
                self._ops[key] = st
                self._children[key] = [id(c) for c in node.children]
            return st

    def instrument(self, node, it):
        """Wrap a node's output iterator with rows/time accounting."""
        st = self.register(node)
        tracer = self.tracer

        def gen():
            while True:
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    return
                dur = int((time.perf_counter() - t0) * 1_000_000)
                st.record(len(item), dur)
                if tracer is not None:
                    tracer.add(st.name, _now_us() - dur, dur)
                yield item
        return gen()

    def finish(self):
        self.wall_us = int((time.perf_counter() - self._t0) * 1_000_000)
        # scoped attribution beats the process-wide diff: an attributed
        # context's tallies contain exactly this query's events even when
        # other queries ran concurrently. Unattributed contexts (e.g. the
        # distributed runner's driver-level context, whose counters come
        # from worker/fetch threads) keep the legacy diff semantics.
        try:
            from .device import costmodel
            if self._attributed:
                self.device_kernels = costmodel.ledger_from_tallies(
                    self._plane("device_kernels"))
            else:
                self.device_kernels = costmodel.ledger_delta(
                    self._ledger0, _ledger_raw())
        except Exception:
            self.device_kernels = {}
        if self._attributed:
            self.recovery = {k: int(v)
                             for k, v in self._plane("recovery").items()}
            self.shuffle = self._plane("shuffle")
            self.io = self._plane("io")
            self.spill = self._plane("spill")
            self.adaptive = self._plane("adaptive")
            self.governor = self._plane("governor")
        else:
            try:
                from .distributed import resilience
                self.recovery = resilience.counters_delta(
                    self._recovery0, _recovery_raw())
            except Exception:
                self.recovery = {}
            try:
                from .distributed import shuffle_service
                self.shuffle = shuffle_service.shuffle_counters_delta(
                    self._shuffle0, _shuffle_raw())
            except Exception:
                self.shuffle = {}
            try:
                from .io import read_planner
                self.io = read_planner.scan_counters_delta(
                    self._io0, _scan_io_raw())
            except Exception:
                self.io = {}
            try:
                from .execution import memory
                self.spill = memory.spill_counters_delta(
                    self._spill0, _spill_raw())
            except Exception:
                self.spill = {}
            try:
                from .physical import adaptive
                self.adaptive = adaptive.counters_delta(
                    self._adaptive0, _adaptive_raw())
            except Exception:
                self.adaptive = {}
            try:
                from .execution import governor
                self.governor = governor.counters_delta(
                    self._governor0, _governor_raw())
            except Exception:
                self.governor = {}
        # RSS gauges ride the governor block regardless of attribution:
        # peak RSS is process state (like the sanitizers), not traffic —
        # the scale bench's bounded-RSS gate reads it per query
        try:
            from .execution import governor
            self.governor["rss_peak_bytes"] = float(
                governor.peak_rss_bytes())
            lim = governor.limit_bytes()
            if lim:
                self.governor["rss_limit_bytes"] = float(lim)
        except Exception:
            pass
        # process-wide diff regardless of attribution: the program cache
        # is shared engine state (like the sanitizers), not per-thread
        # traffic — concurrent queries legitimately share its hits
        after_ex = _exchange_raw()
        self.exchange = {k: v - self._exchange0.get(k, 0)
                         for k, v in after_ex.items()
                         if v - self._exchange0.get(k, 0)}
        try:
            from .analysis import lock_sanitizer
            self.sanitizer = lock_sanitizer.counters_delta(
                self._sanitizer0, _sanitizer_raw())
        except Exception:
            self.sanitizer = {}
        try:
            from .analysis import retrace_sanitizer
            self.retrace = retrace_sanitizer.counters_delta(
                self._retrace0, _retrace_raw())
        except Exception:
            self.retrace = {}
        try:
            from .analysis import plan_sanitizer
            self.plansan = plan_sanitizer.counters_delta(
                self._plansan0, _plansan_raw())
        except Exception:
            self.plansan = {}
        self._emit_trace_spans()

    def _emit_trace_spans(self) -> None:
        """Fold this executor's per-operator timings into the query
        trace as one span per physical operator (children of the span
        context this executor ran under — the task:run span for worker
        fragments, the query root locally)."""
        ctx = self.trace_ctx
        if ctx is None:
            return
        rec = ctx.recorder
        try:
            for key, st in list(self._ops.items()):
                rec.add(f"op:{st.name}",
                        rec.unique_span_id(f"op:{st.name}"),
                        ctx.span_id, self._t0_unix_us, st.inclusive_us,
                        attrs={"rows_out": st.rows_out,
                               "batches": st.batches_out,
                               "self_us": self.exclusive_us(key)},
                        lane="pipeline")
            self.trace_summary = rec.summary()
        except Exception:
            pass  # observability must never take the query down

    # ---- reporting ---------------------------------------------------
    def exclusive_us(self, key: int) -> int:
        st = self._ops[key]
        child_incl = sum(self._ops[c].inclusive_us
                         for c in self._children.get(key, [])
                         if c in self._ops)
        return max(st.inclusive_us - child_incl, 0)

    def render(self, plan=None) -> str:
        """ASCII explain-analyze tree (annotated like the reference's
        ``explain_analyze``)."""
        if plan is None:
            plan = self.plan
        lines = []
        if self.wall_us is not None:
            lines.append(f"query wall time: {self.wall_us / 1e6:.3f}s")

        def walk(node, depth):
            key = id(node)
            st = self._ops.get(key)
            pad = "  " * depth
            if st is None:
                lines.append(f"{pad}{type(node).__name__}")
            else:
                wk = f" workers={st.workers}" if st.workers else ""
                lines.append(
                    f"{pad}{st.name}: rows_out={st.rows_out} "
                    f"batches={st.batches_out} "
                    f"total={st.inclusive_us / 1e6:.3f}s "
                    f"self={self.exclusive_us(key) / 1e6:.3f}s{wk}")
            for c in node.children:
                walk(c, depth + 1)

        if plan is not None:
            walk(plan, 0)
        else:
            for st in self._ops.values():
                lines.append(f"{st.name}: rows_out={st.rows_out} "
                             f"batches={st.batches_out} "
                             f"total={st.inclusive_us / 1e6:.3f}s")
        if self.device_kernels:
            lines.append("device kernels (per-dispatch ledger, "
                         "end-to-end incl. link):")
            for kind, d in sorted(self.device_kernels.items()):
                extra = ""
                if "achieved_gbps" in d:
                    extra = (f" {d['achieved_gbps']} GB/s"
                             f" ({d.get('roofline_pct', 0)}% roofline)")
                if "mfu_pct" in d:
                    extra += f" {d['mfu_pct']}% MFU"
                if "strategy" in d:
                    extra += f" strategy={d['strategy']}"
                    if "load_factor" in d:
                        extra += f" load={d['load_factor']}"
                if "overlap_x" in d:
                    # r17 async pipeline: serial-equivalent stage seconds
                    # vs pipelined wall (>1 = overlap really hid work)
                    extra += f" overlap={d['overlap_x']}x"
                if "fused_ops" in d:
                    # r21 whole-query compilation: operators fused into
                    # region programs + host round-trips that eliminated
                    extra += (f" fused_ops={d['fused_ops']}"
                              f" rt_saved={d.get('round_trips_saved', 0)}")
                if "fusion_x" in d:
                    extra += f" fusion={d['fusion_x']}x"
                lines.append(
                    f"  {kind}: dispatches={d['dispatches']} "
                    f"rows={d['rows']} time={d['seconds']:.3f}s{extra}")
        if self.recovery:
            lines.append("resilience (recovery events):")
            for k, v in sorted(self.recovery.items()):
                lines.append(f"  {k}: {v}")
        lines.extend(render_shuffle_block(self.shuffle))
        lines.extend(render_exchange_block(self.exchange))
        lines.extend(render_adaptive_block(self.adaptive))
        lines.extend(render_io_block(self.io))
        lines.extend(render_spill_block(self.spill))
        lines.extend(render_governor_block(self.governor))
        lines.extend(render_sanitizer_block(self.sanitizer))
        lines.extend(render_retrace_block(self.retrace))
        lines.extend(render_plansan_block(self.plansan))
        lines.extend(render_serving_block(self.serving))
        if self.trace_summary:
            t = self.trace_summary
            lines.append(f"trace: id={t.get('trace_id')} "
                         f"spans={t.get('spans')} "
                         f"dropped={t.get('dropped', 0)}")
        return "\n".join(lines)

    def as_dict(self) -> Dict[str, dict]:
        out = {}
        for key, st in self._ops.items():
            name = st.name
            i = 2
            while name in out:
                name = f"{st.name}#{i}"
                i += 1
            out[name] = {"rows_out": st.rows_out,
                         "morsel_rows_min": st.morsel_rows_min,
                         "morsel_rows_max": st.morsel_rows_max,
                         "workers": st.workers,
                         "batches_out": st.batches_out,
                         "inclusive_us": st.inclusive_us,
                         "exclusive_us": self.exclusive_us(key)}
        return out


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} GB"


def render_shuffle_block(sh: Dict[str, float]) -> List[str]:
    """Human lines for one query's shuffle data-plane delta (shared by
    ``explain(analyze=True)`` and the dashboard). Shows each fast-path
    layer's evidence: wire-vs-raw bytes (compression ratio), combine row
    reduction, and parallel-fetch wall vs the serial-equivalent sum."""
    if not sh:
        return []
    lines = ["shuffle (data plane):"]
    written = sh.get("bytes_written", 0)
    raw = sh.get("bytes_pushed_raw", 0)
    if written or raw:
        ratio = f", {raw / written:.2f}x compression" if written else ""
        lines.append(f"  written: {_fmt_bytes(written)} wire "
                     f"({_fmt_bytes(raw)} raw{ratio}), "
                     f"rows={int(sh.get('rows_pushed', 0))}")
    cin, cout = sh.get("combine_rows_in", 0), sh.get("combine_rows_out", 0)
    if cin:
        red = f" ({cin / cout:.1f}x reduction)" if cout else ""
        lines.append(f"  combine: {int(cin)} -> {int(cout)} rows{red}")
    fetched = sh.get("bytes_fetched", 0)
    if fetched or sh.get("fetches"):
        wall = sh.get("fetch_span_us", 0) / 1e6
        serial = sh.get("fetch_wall_us", 0) / 1e6
        overlap = f", wall {wall:.3f}s vs serial-equivalent " \
                  f"{serial:.3f}s" if wall else \
                  f", serial {serial:.3f}s"
        lines.append(f"  fetched: {_fmt_bytes(fetched)} in "
                     f"{int(sh.get('fetches', 0))} fetches{overlap}")
    paths = {p: int(sh.get(f"exchange_path_{p}", 0))
             for p in ("collective", "hierarchical", "flight")}
    if any(paths.values()):
        lines.append("  exchange paths: " + ", ".join(
            f"{p}={n}" for p, n in paths.items() if n))
    if sh.get("ici_exchanges"):
        lines.append(
            f"  ici: {_fmt_bytes(sh.get('ici_bytes', 0))} in "
            f"{int(sh.get('ici_exchanges', 0))} collective exchanges "
            f"({int(sh.get('ici_rows', 0))} rows over the mesh, "
            f"not the wire)")
    if sh.get("hierarchical_streams"):
        lines.append(f"  hierarchical: "
                     f"{int(sh.get('hierarchical_streams', 0))} "
                     f"per-mesh stream(s)")
    return lines


def render_exchange_block(ex: Dict[str, float]) -> List[str]:
    """Human lines for one query's collective-exchange program-cache
    delta (shared by ``explain(analyze=True)`` and the dashboard): the
    evidence that repeated same-shape mesh exchanges re-entered one
    memoized trace instead of re-tracing per call."""
    if not ex:
        return []
    lines = ["exchange programs (collective cache):"]
    lines.append("  " + ", ".join(
        f"{k}={int(v)}" for k, v in sorted(ex.items())))
    return lines


def render_adaptive_block(d: Dict[str, float]) -> List[str]:
    """Human lines for one query's self-tuning delta (shared by
    ``explain(analyze=True)`` and the dashboard): the re-plan decisions
    it made, the calibration observations it fed, plus the live
    calibrated-vs-default state of the cost-model constants (which
    learned values are overriding the hard-coded defaults right now)."""
    cal_names: List[str] = []
    try:
        from .device import calibration
        if calibration.enabled():
            cal_names = calibration.calibrated_names()
    except Exception:
        pass
    if not d and not cal_names:
        return []
    lines = ["adaptive (self-tuning):"]
    decisions = {k: int(v) for k, v in sorted(d.items())
                 if k != "calibration_observations" and v}
    if decisions:
        lines.append("  re-plan: " + ", ".join(
            f"{k}={v}" for k, v in decisions.items()))
    obs_n = int(d.get("calibration_observations", 0))
    if obs_n:
        lines.append(f"  calibration: {obs_n} observations fed")
    if cal_names:
        lines.append("  calibrated constants (overriding defaults): "
                     + ", ".join(cal_names))
    return lines


def render_spill_block(d: Dict[str, float]) -> List[str]:
    """Human lines for one query's out-of-core spill delta (shared by
    ``explain(analyze=True)`` and the dashboard): disk bytes the spill
    tier wrote/read, partitions that left RAM, grace-join/agg recursion
    evidence (deepest rotated-radix level reached, depth-bound
    exhaustions on unsplittable keys), and the summed per-store peak
    residency of the stores that spilled (an upper bound on what the
    spill tier held resident)."""
    if not d:
        return []
    lines = ["spill (out-of-core tier):"]
    written = d.get("bytes_written", 0)
    read = d.get("bytes_read", 0)
    if written or read:
        lines.append(f"  disk: {_fmt_bytes(written)} written / "
                     f"{_fmt_bytes(read)} read, "
                     f"{int(d.get('partitions_spilled', 0))} partitions "
                     f"spilled")
    jp, jg = int(d.get("joins_partitioned", 0)), \
        int(d.get("joins_gathered", 0))
    if jp or jg:
        lines.append(f"  grace join: {jp} partitioned, {jg} gathered")
    rec = int(d.get("recursions", 0))
    if rec or d.get("depth_exhausted"):
        deepest = max((int(k.rsplit("_d", 1)[1]) for k in d
                       if k.startswith("recursions_d")), default=0)
        lines.append(
            f"  recursion: {rec} re-partitions (deepest level {deepest}),"
            f" {int(d.get('depth_exhausted', 0))} depth-bound exhaustions")
    if d.get("agg_buckets_merged"):
        lines.append(f"  agg: {int(d.get('agg_buckets_merged', 0))} "
                     f"state buckets merged on read")
    if d.get("stores"):
        ns = int(d.get("stores", 0))
        # summed per-store peaks: an upper bound on what the spilling
        # stores held resident (stores are often sequential, so the true
        # instantaneous peak is usually far lower)
        lines.append(
            f"  resident: ≤{_fmt_bytes(d.get('store_peak_bytes', 0))} "
            f"summed peak across {ns} spilling store(s)")
    disk_w = d.get("disk_bytes_written", 0)
    if disk_w and written:
        # post-codec file bytes vs logical bytes: the spill codec's
        # measured on-disk win (r23 fast path)
        lines.append(
            f"  codec: {_fmt_bytes(disk_w)} on disk "
            f"({written / disk_w:.2f}x compression)")
    return lines


def render_governor_block(d: Dict[str, float]) -> List[str]:
    """Human lines for one query's memory-governor delta (shared by
    ``explain(analyze=True)`` and the dashboard): the backpressure
    actions taken while the query ran (pressure episodes, bounded
    throttle waits, budget/prefetch shrinks, gc passes) and the process
    peak RSS against the configured limit — the bounded-RSS evidence
    the scale bench commits per query."""
    peak = d.get("rss_peak_bytes", 0)
    lim = d.get("rss_limit_bytes", 0)
    actions = {k: v for k, v in d.items()
               if k not in ("rss_peak_bytes", "rss_limit_bytes") and v}
    if not actions and not (peak and lim):
        return []
    lines = ["memory governor:"]
    if peak:
        vs = f" vs limit {_fmt_bytes(lim)}" if lim else ""
        lines.append(f"  rss: peak {_fmt_bytes(peak)}{vs}")
    if actions:
        waits = int(actions.pop("throttle_waits", 0))
        wait_us = actions.pop("throttle_wait_us", 0)
        if waits:
            lines.append(f"  throttle: {waits} bounded wait(s), "
                         f"{wait_us / 1e6:.2f}s total")
        rest = {k: int(v) for k, v in sorted(actions.items())
                if not k.startswith("throttle_")}
        if rest:
            lines.append("  actions: " + ", ".join(
                f"{k}={v}" for k, v in rest.items()))
    return lines


def render_io_block(d: Dict[str, float]) -> List[str]:
    """Human lines for one query's scan-plane IO delta (shared by
    ``explain(analyze=True)`` and the dashboard). Each fast-path layer's
    evidence: requests issued vs byte ranges needed pre-coalesce, bytes
    fetched vs bytes actually decoded, and prefetch-pipelined wall vs the
    serial-equivalent sum of per-task load times."""
    if not d:
        return []
    lines = ["io (scan plane):"]
    gets = int(d.get("gets", 0))
    planned = int(d.get("ranges_planned", 0))
    reqs = int(d.get("range_requests", 0))
    if gets or planned:
        coal = f", {planned / reqs:.1f}x coalesced" if reqs else ""
        lines.append(f"  requests: {gets} GETs "
                     f"({planned} ranges needed -> {reqs} range "
                     f"requests{coal})")
    fetched = d.get("bytes_fetched", 0)
    used = d.get("bytes_used", 0)
    if fetched:
        eff = f" ({100.0 * used / fetched:.1f}% used)" if used else ""
        lines.append(f"  bytes: {_fmt_bytes(fetched)} fetched / "
                     f"{_fmt_bytes(used)} decoded{eff}")
    span = d.get("scan_span_us", 0) / 1e6
    serial = d.get("scan_task_us", 0) / 1e6
    if span or serial:
        tasks = int(d.get("prefetch_tasks", 0))
        overlap = f" ({serial / span:.1f}x overlap)" if span else ""
        lines.append(f"  prefetch: {tasks} tasks, wall {span:.3f}s vs "
                     f"serial-equivalent {serial:.3f}s{overlap}")
    misses = int(d.get("planner_miss_gets", 0))
    falls = int(d.get("planned_read_fallbacks", 0))
    if misses or falls:
        lines.append(f"  planner: {misses} miss GETs, "
                     f"{falls} whole-file fallbacks")
    return lines


def render_serving_block(s: Dict[str, object]) -> List[str]:
    """Human lines for one query's serving-plane record (shared by
    ``explain(analyze=True)`` and the dashboard; set only for queries run
    through the query scheduler): which session/priority it ran as, how
    long it queued, what the admission controller charged it, and whether
    the plan/result caches served it."""
    if not s:
        return []
    lines = ["serving (query scheduler):"]
    lines.append(
        f"  session={s.get('session')} priority={s.get('priority', 0)} "
        f"queue_wait={float(s.get('queue_wait_us', 0)) / 1e3:.1f}ms "
        f"admitted={_fmt_bytes(float(s.get('admitted_bytes', 0)))} "
        f"(running={int(s.get('running_at_admit', 0))} at admit)")
    lines.append(
        f"  plan cache: {s.get('plan_cache', 'off')}, "
        f"result cache: {s.get('result_cache', 'off')}")
    return lines


def render_sanitizer_block(s: Dict[str, float]) -> List[str]:
    """Human lines for one query's lock-sanitizer delta (shared by
    ``explain(analyze=True)`` and the dashboard; empty unless
    ``DAFT_TPU_SANITIZE=1``): current lock-order graph size + cycle
    count, and this query's acquisition/contention/blocking events."""
    if not s:
        return []
    cycles = int(s.get("graph_cycles", 0))
    lines = ["concurrency (lock sanitizer):"]
    lines.append(f"  graph: {int(s.get('graph_locks', 0))} lock sites, "
                 f"{int(s.get('graph_edges', 0))} order edges, "
                 f"{cycles} cycle{'s' if cycles != 1 else ''}"
                 + (" (POTENTIAL DEADLOCK)" if cycles else ""))
    lines.append(f"  this query: {int(s.get('acquisitions', 0))} "
                 f"acquisitions, {int(s.get('contended', 0))} contended, "
                 f"{int(s.get('blocking_while_held', 0))} "
                 f"blocking-while-held")
    return lines


def render_retrace_block(s: Dict[str, float]) -> List[str]:
    """Human lines for one query's retrace-sanitizer delta (shared by
    ``explain(analyze=True)`` and the dashboard; empty unless the
    retrace sanitizer is armed): trace events + XLA compiles this query
    paid — a hot query's line should read all zeros."""
    if not s:
        return []
    viol = int(s.get("violations", 0))
    lines = ["shape discipline (retrace sanitizer):"]
    lines.append(
        f"  this query: {int(s.get('traces', 0))} trace events, "
        f"{int(s.get('compiles', 0))} XLA compiles "
        f"({float(s.get('compile_seconds', 0.0)):.3f}s compiling), "
        f"{int(s.get('unscoped_traces', 0))} unscoped")
    lines.append(
        f"  budget violations: {viol} this query, "
        f"{int(s.get('total_violations', 0))} total"
        + (" (RETRACE TAX — see retrace_sanitizer.report())"
           if viol else ""))
    return lines


def render_plansan_block(s: Dict[str, float]) -> List[str]:
    """Human lines for one query's plan-sanitizer delta (shared by
    ``explain(analyze=True)`` and the dashboard; empty unless the plan
    sanitizer is armed): contract checks this query paid and whether
    any plan invariant broke — a healthy query reads violations 0."""
    if not s:
        return []
    viol = int(s.get("violations", 0))
    lines = ["plan discipline (plan sanitizer):"]
    lines.append(
        f"  this query: {int(s.get('rule_checks', 0))} rule schema "
        f"checks, {int(s.get('membership_parts', 0))} partitions "
        f"({int(s.get('membership_rows', 0))} rows) membership-sampled, "
        f"{int(s.get('order_parts', 0))} order-checked, "
        f"{int(s.get('conservation_checks', 0))} conservation proofs")
    lines.append(
        f"  contract violations: {viol} this query, "
        f"{int(s.get('total_violations', 0))} total"
        + (" (PLAN CONTRACT BROKEN — see plan_sanitizer.report())"
           if viol else ""))
    return lines


# ---------------------------------------------------------------------------
# per-process "last query" registry


_last_stats: Optional[RuntimeStatsContext] = None
_last_lock = threading.Lock()


def xplane_trace_dir() -> Optional[str]:
    """``DAFT_TPU_XPLANE_DIR=<dir>`` captures a jax profiler (xplane/
    TensorBoard) trace per query — the TPU-native analogue of the
    reference's chrome-trace layer (``src/common/tracing``): device kernel
    timelines, HBM transfers and XLA compilation spans land in
    ``<dir>/plugins/profile``."""
    from .analysis import knobs
    return knobs.env_str("DAFT_TPU_XPLANE_DIR") or None


_xplane_lock = threading.Lock()
_xplane_owner: Optional[object] = None


class _XplaneTrace:
    """Per-query jax profiler session. The jax profiler is process-global,
    so only the OUTERMOST executor owns the capture — nested/concurrent
    executors (exchanges, worker tasks) no-op instead of truncating the
    query-level trace. Never takes the query down on failure."""

    def __init__(self, out_dir: str):
        global _xplane_owner
        self._active = False
        with _xplane_lock:
            if _xplane_owner is not None:
                return  # someone else is tracing this process
            _xplane_owner = self
        try:
            import jax
            jax.profiler.start_trace(out_dir)
            self._active = True
        except Exception:
            with _xplane_lock:
                _xplane_owner = None

    def stop(self) -> None:
        global _xplane_owner
        if not self._active:
            return
        self._active = False
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:
            pass
        with _xplane_lock:
            if _xplane_owner is self:
                _xplane_owner = None


def chrome_trace_path() -> Optional[str]:
    from .analysis import knobs
    v = knobs.env_str("DAFT_TPU_CHROME_TRACE")
    if not v:
        return None
    low = v.strip().lower()
    if low in ("", "0", "false", "no", "off"):
        return None
    if low in ("1", "true", "yes", "on"):
        return f"/tmp/daft_tpu_trace_{os.getpid()}.json"
    return v


def progress_enabled() -> bool:
    from .analysis import knobs
    return bool(knobs.env_bool("DAFT_TPU_PROGRESS"))


def new_query_stats() -> RuntimeStatsContext:
    from . import tracing
    tracer = ChromeTracer() if chrome_trace_path() else None
    ctx = RuntimeStatsContext(tracer)
    # fallback trace start for executors driven without a runner (the
    # runners/serving scheduler normally start the trace earlier, so the
    # planner spans land too); nested scopes never start traces — the
    # query-wide sampling decision was the top level's to make
    if ctx.trace_ctx is None and not in_nested_scope() \
            and tracing.trace_enabled():
        ctx.trace_ctx = tracing.maybe_start_trace("query")
    return ctx


_tl_last = threading.local()


def set_last_stats(ctx: RuntimeStatsContext):
    global _last_stats
    with _last_lock:
        _last_stats = ctx
    # per-thread record too: under the serving plane N queries finish
    # concurrently and the GLOBAL last-stats slot is whichever finished
    # last — each scheduler worker reads its own query's context back via
    # last_query_stats_local() (the executor's finish runs on the thread
    # that drained it)
    _tl_last.stats = ctx
    # feed the dashboard when it's up (reference: broadcast_query_plan hook)
    from . import dashboard
    if dashboard._server is not None:
        dashboard.broadcast_query(ctx)
    # per-query exports fire once per TOP-LEVEL query: nested scopes
    # (worker stage fragments, scheduler-deferred executions) suppress
    # them and the outermost coordinator calls finalize_query itself
    if not in_nested_scope():
        finalize_query(ctx)


# ------------------------------------------------- observability counters
# Export-plane accounting (otlp_export_errors & co): process-wide like
# the shuffle/recovery counters, surfaced through the /metrics scrape.

_obs_counters_lock = threading.Lock()
_obs_counters: Dict[str, float] = {}


def obs_count(name: str, n: float = 1) -> None:
    with _obs_counters_lock:
        _obs_counters[name] = _obs_counters.get(name, 0) + n


def obs_counters_snapshot() -> Dict[str, float]:
    with _obs_counters_lock:
        return dict(_obs_counters)


def finalize_query(ctx: RuntimeStatsContext) -> None:
    """One top-level query's export hooks: OTLP metrics (+spans for
    traced queries), the merged Chrome trace file, and the flight
    recorder. Idempotent per trace; never raises into the query path."""
    from . import tracing
    from .analysis import knobs
    endpoint = knobs.env_str("DAFT_TPU_OTLP_ENDPOINT")
    if endpoint:
        export_otlp(ctx, endpoint)
    try:
        tctx = ctx.trace_ctx
        rec = tctx.recorder if tctx is not None else None
        if rec is not None and not rec.exported:
            rec.exported = True
            rec.finish()
            ctx.trace_summary = rec.summary()
            tracing.unregister_recorder(rec.trace_id)
            out_dir = knobs.env_str("DAFT_TPU_TRACE_DIR")
            if out_dir:
                try:
                    os.makedirs(out_dir, exist_ok=True)
                    path = os.path.join(out_dir,
                                        f"trace_{rec.trace_id}.json")
                    with open(path, "w") as f:
                        json.dump(tracing.chrome_trace_json(rec), f)
                except Exception:
                    obs_count("trace_export_errors")
            if endpoint:
                _post_otlp_async(endpoint, "/v1/traces",
                                 tracing.otlp_spans_payload(rec))
        if tracing._flight_path():  # don't build entries nobody records
            tracing.flight_record(flight_entry(ctx))
    except Exception:
        obs_count("finalize_errors")


def flight_entry(ctx: RuntimeStatsContext) -> dict:
    """One flight-recorder record: the query's stat blocks, trace
    summary and slow-query flag."""
    from . import tracing
    wall_us = ctx.wall_us or 0
    slow_ms = tracing.slow_query_ms()
    entry = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "wall_us": wall_us,
        "slow": bool(slow_ms and slow_ms > 0
                     and wall_us / 1e3 > slow_ms),
        "operators": ctx.as_dict(),
    }
    for block in ("recovery", "shuffle", "exchange", "io", "spill",
                  "governor", "adaptive", "device_kernels", "serving",
                  "sanitizer", "retrace", "plansan"):
        v = getattr(ctx, block, None)
        if v:
            entry[block] = dict(v)
    if ctx.trace_summary:
        entry["trace"] = dict(ctx.trace_summary)
    return entry


# ------------------------------------------------------------------ OTLP

def otlp_payload(ctx: RuntimeStatsContext) -> dict:
    """Per-operator counters as an OTLP/HTTP JSON ExportMetricsServiceRequest
    (the reference exports the same counters over OTLP:
    ``src/common/tracing/src/lib.rs:29-90``, ``runtime_stats.rs:23-66``).
    DELTA temporality: each export carries one query's contribution, keyed
    only by operator name — bounded series cardinality, and collectors sum
    deltas across queries without reset semantics."""
    now_ns = int(time.time() * 1e9)
    start_ns = now_ns - (ctx.wall_us or 0) * 1000

    def sum_metric(name: str, unit: str, points):
        return {"name": name, "unit": unit, "sum": {
            "aggregationTemporality": 1,  # DELTA
            "isMonotonic": True,
            "dataPoints": points}}

    def point(value: int, op_name: str):
        return {"asInt": str(int(value)),
                "startTimeUnixNano": str(start_ns),
                "timeUnixNano": str(now_ns),
                "attributes": [
                    {"key": "operator",
                     "value": {"stringValue": op_name}}]}

    per_op = ctx.as_dict()
    metrics = [
        sum_metric("daft_tpu.operator.rows_out", "{row}",
                   [point(st["rows_out"], nm)
                    for nm, st in per_op.items()]),
        sum_metric("daft_tpu.operator.batches_out", "{batch}",
                   [point(st["batches_out"], nm)
                    for nm, st in per_op.items()]),
        sum_metric("daft_tpu.operator.cpu_us", "us",
                   [point(st["exclusive_us"], nm)
                    for nm, st in per_op.items()]),
    ]
    return {"resourceMetrics": [{
        "resource": {"attributes": [
            {"key": "service.name",
             "value": {"stringValue": "daft_tpu"}}]},
        "scopeMetrics": [{
            "scope": {"name": "daft_tpu.observability"},
            "metrics": metrics}]}]}


def _post_otlp_async(endpoint: str, route: str, payload_obj: dict) -> None:
    """Fire-and-forget OTLP/HTTP POST on a daemon thread with a bounded
    timeout (``DAFT_TPU_OTLP_TIMEOUT``). A hung or erroring collector
    can neither stall nor fail the query — every failure (including a
    non-2xx status, a read that outlives the timeout, or a thread spawn
    at interpreter shutdown) is swallowed and counted in
    ``otlp_export_errors``."""
    import urllib.request

    try:
        from .analysis import knobs
        timeout = knobs.env_float("DAFT_TPU_OTLP_TIMEOUT")
        payload = json.dumps(payload_obj).encode()
        url = endpoint.rstrip("/") + route

        def post():
            try:
                req = urllib.request.Request(
                    url, data=payload,
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=timeout).read()
            except Exception:
                obs_count("otlp_export_errors")

        threading.Thread(target=post, name="daft-tpu-otlp",
                         daemon=True).start()
    except Exception:
        obs_count("otlp_export_errors")


def export_otlp(ctx: RuntimeStatsContext, endpoint: str) -> None:
    """Fire-and-forget POST of the query's operator counters to an
    OTLP/HTTP collector (``<endpoint>/v1/metrics``); traced queries
    additionally export their span tree to ``/v1/traces`` (see
    ``finalize_query``). Never fails or blocks the query."""
    try:
        _post_otlp_async(endpoint, "/v1/metrics", otlp_payload(ctx))
    except Exception:
        obs_count("otlp_export_errors")


def last_query_stats() -> Optional[RuntimeStatsContext]:
    """Stats of the most recent execution in this process."""
    with _last_lock:
        return _last_stats


def last_query_stats_local() -> Optional[RuntimeStatsContext]:
    """Stats of the most recent execution drained on THIS thread (nested
    executions overwrite it in completion order, so after a top-level
    drain this is the outermost query's context)."""
    return getattr(_tl_last, "stats", None)


def wrap_progress(it, desc: str = "partitions"):
    """tqdm progress over a partition stream when DAFT_TPU_PROGRESS=1."""
    if not progress_enabled():
        return it
    try:
        from tqdm import tqdm
    except ImportError:
        return it

    def gen():
        rows = 0
        with tqdm(desc=desc, unit="part") as bar:
            for p in it:
                rows += len(p)
                bar.set_postfix_str(f"{rows} rows")
                bar.update(1)
                yield p
    return gen()
