"""Aggregation kernels (host tier).

Reference capability: ``src/daft-recordbatch/src/ops/agg.rs:12-29``
(agg/agg_global/agg_groupby). Grouped aggregation rides Arrow C++
``TableGroupBy`` (native hash aggregation); the TPU tier
(``daft_tpu.device.kernels.grouped_agg``) takes precedence when the executor
dispatches device-representable batches.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .datatype import DataType
from .expressions import Expression, col
from .schema import Field, Schema
from .series import Series


def split_agg_expr(e: Expression) -> Tuple[str, Expression, str, Tuple]:
    """alias(agg(child)) -> (agg_op, child_expr, out_name, agg_params)."""
    name = e.name()
    inner = e._unalias()
    if not inner.op.startswith("agg."):
        raise ValueError(f"expected aggregation expression, got {inner.op}")
    child = inner.args[0] if inner.args else None
    return inner.op[4:], child, name, inner.params


_PA_AGGS = {
    "sum": "sum", "mean": "mean", "min": "min", "max": "max",
    "count_distinct": "count_distinct", "stddev": "stddev", "var": "variance",
    "list": "list", "any_value": "first", "bool_and": "all", "bool_or": "any",
    "approx_count_distinct": "count_distinct", "set": "distinct",
}


# ----------------------------------------------- partial/merge decomposition

#: How each aggregation decomposes across a shuffle/pipeline boundary:
#: ``op -> (partial-state ops over the input, merge op over each state
#: column)``. Single-sourced on purpose — three layers read it:
#: the planner's partial/final split (``physical/translate._split_aggs``),
#: the local fused partitioned-agg reducer (``execution/pipeline``), and
#: the distributed map-side shuffle combine
#: (``distributed/stages.combine_for_boundary`` → ``worker.run_task``).
#: An op absent here (see :data:`NON_DECOMPOSABLE_AGGS`) aggregates in a
#: single stage over gathered/co-partitioned rows.
AGG_DECOMPOSITION: Dict[str, Tuple[Tuple[str, ...], str]] = {
    "sum": (("sum",), "sum"),
    "count": (("count",), "sum"),
    "min": (("min",), "min"),
    "max": (("max",), "max"),
    "any_value": (("any_value",), "any_value"),
    "bool_and": (("bool_and",), "bool_and"),
    "bool_or": (("bool_or",), "bool_or"),
    "list": (("list",), "concat"),
    "concat": (("concat",), "concat"),
    "mean": (("sum", "count"), "sum"),
    "stddev": (("sum", "count", "sumsq"), "sum"),
    "var": (("sum", "count", "sumsq"), "sum"),
}

#: aggregations with no partial/merge split — their whole input must meet
#: in one place (the planner gathers or co-partitions the raw rows)
NON_DECOMPOSABLE_AGGS = frozenset({
    "count_distinct", "approx_count_distinct", "approx_percentiles",
    "skew", "set"})

#: merge-stage ops that are associative SELF-merges: re-applying the op
#: over its own output column correctly merges two batches of state
#: (derived from the table above — every merge op is one)
SELF_MERGE_OPS = frozenset(m for _, m in AGG_DECOMPOSITION.values())


def merge_exprs_for(aggs: List[Expression], alias_to: str = "out"
                    ) -> Optional[List[Expression]]:
    """For merge/final-stage aggs shaped ``op(col(p)).alias(out)`` whose
    ops are all self-merges, the expressions that merge two batches of
    aggregated state:

    - ``alias_to="out"`` — merge batches of FINAL-schema state:
      ``op(col(out)).alias(out)`` (the fused partitioned-agg reducer's
      shape in ``execution/pipeline.py``).
    - ``alias_to="source"`` — merge batches of WIRE-schema partial
      columns: ``op(col(p)).alias(p)`` (the map-side shuffle combine's
      shape: the combined output keeps the exact map-output schema, so
      the reduce side is unchanged).

    Returns None when any agg is not a single-column self-merge — the
    caller falls back to its unmerged path."""
    out: List[Expression] = []
    seen: Dict[str, str] = {}
    for a in aggs:
        u = a._unalias()
        if not u.op.startswith("agg.") or u.op[4:] not in SELF_MERGE_OPS \
                or len(u.args) != 1:
            return None
        arg = u.args[0]._unalias()
        if arg.op != "col":
            return None
        if alias_to == "out":
            out.append(Expression(u.op, (col(a.name()),), u.params)
                       .alias(a.name()))
        else:
            src = arg.name()
            prev = seen.get(src)
            if prev is not None:
                if prev != u.op:
                    return None  # conflicting merges of one wire column
                continue
            seen[src] = u.op
            out.append(Expression(u.op, (col(src),), u.params).alias(src))
    return out


def agg_recordbatch(batch, to_agg: List[Expression], group_by: List[Expression]):
    from .recordbatch import RecordBatch

    from .device import runtime as device_runtime
    out = device_runtime.try_agg(batch, to_agg, group_by)
    if out is not None:
        return out

    specs = [split_agg_expr(e) for e in to_agg]
    if not group_by:
        return _agg_global(batch, specs)
    return _agg_groupby(batch, specs, group_by)


def _eval_child(batch, child: Optional[Expression], i: int) -> Series:
    if child is None:
        return Series.from_pylist([True] * len(batch), f"__in{i}__")
    return batch.eval_expression(child).rename(f"__in{i}__")


def _agg_global(batch, specs):
    from .recordbatch import RecordBatch
    out_cols = []
    for i, (op, child, name, params) in enumerate(specs):
        s = _eval_child(batch, child, i)
        out_cols.append(_global_one(op, s, name, params))
    return RecordBatch.from_series(out_cols)


def _global_one(op: str, s: Series, name: str, params) -> Series:
    in_dtype = s.datatype()
    if op == "count":
        mode = params[0] if params else "valid"
        if mode == "all" or s.is_pyobject():
            v = len(s) if mode == "all" else \
                sum(1 for x in s.to_pylist() if x is not None)
        elif mode == "null":
            v = s.null_count()
        else:
            v = len(s) - s.null_count()
        return Series.from_pylist([v], name, dtype=DataType.uint64())
    arr = s.to_arrow()
    if op == "sum":
        out_dt = _sum_dtype(in_dtype)
        v = pc.sum(arr).as_py()
        return Series.from_pylist([v], name, dtype=out_dt)
    if op == "mean":
        v = pc.mean(arr).as_py() if len(arr) else None
        return Series.from_pylist([v], name, dtype=DataType.float64())
    if op in ("min", "max"):
        v = (pc.min if op == "min" else pc.max)(arr).as_py() if len(arr) else None
        return Series.from_pylist([v], name, dtype=in_dtype)
    if op in ("count_distinct", "approx_count_distinct"):
        if op == "approx_count_distinct":
            from . import native
            if native.AVAILABLE and not s.is_pyobject():
                # HyperLogLog over native row hashes (reference: hyperloglog
                # crate feeding approx_count_distinct in daft-core agg ops)
                hashes = s.filter(s.not_null()).hash().to_numpy()
                est = native.HyperLogLog().add_hashes(hashes).estimate()
                return Series.from_pylist([int(round(est))], name,
                                          dtype=DataType.uint64())
        v = pc.count_distinct(arr, mode="only_valid").as_py()
        return Series.from_pylist([v], name, dtype=DataType.uint64())
    if op == "any_value":
        vals = [x for x in arr.to_pylist() if x is not None] or [None]
        return Series.from_pylist([vals[0]], name, dtype=in_dtype)
    if op == "list":
        return Series.from_pylist([arr.to_pylist()], name,
                                  dtype=DataType.list(in_dtype))
    if op == "set":
        seen, out = set(), []
        for x in arr.to_pylist():
            if x is not None and x not in seen:
                seen.add(x)
                out.append(x)
        return Series.from_pylist([out], name, dtype=DataType.list(in_dtype))
    if op == "concat":
        if in_dtype.is_string():
            vals = [x for x in arr.to_pylist() if x is not None]
            return Series.from_pylist(["".join(vals) if vals else None], name)
        out = []
        for v in arr.to_pylist():
            if v is not None:
                out.extend(v)
        return Series.from_pylist([out], name, dtype=in_dtype)
    if op == "stddev":
        v = pc.stddev(arr, ddof=0).as_py() if len(arr) else None
        return Series.from_pylist([v], name, dtype=DataType.float64())
    if op == "var":
        v = pc.variance(arr, ddof=0).as_py() if len(arr) else None
        return Series.from_pylist([v], name, dtype=DataType.float64())
    if op == "skew":
        v = _skew(arr.to_numpy(zero_copy_only=False))
        return Series.from_pylist([v], name, dtype=DataType.float64())
    if op in ("bool_and", "bool_or"):
        fn = pc.all if op == "bool_and" else pc.any
        v = fn(arr.cast(pa.bool_())).as_py()
        return Series.from_pylist([v], name, dtype=DataType.bool())
    if op == "approx_percentiles":
        ps = list(params[0])
        v = pc.tdigest(arr, q=ps).to_pylist()
        return Series.from_pylist(
            [v], name, dtype=DataType.fixed_size_list(DataType.float64(), len(ps)))
    raise NotImplementedError(f"global agg {op}")


def _skew(v: np.ndarray) -> Optional[float]:
    v = v[~np.isnan(v.astype(np.float64))].astype(np.float64)
    if len(v) == 0:
        return None
    m = v.mean()
    s2 = ((v - m) ** 2).mean()
    if s2 == 0:
        return 0.0
    return float(((v - m) ** 3).mean() / s2 ** 1.5)


def _sum_dtype(d: DataType) -> DataType:
    if d.is_signed_integer() or d.is_boolean():
        return DataType.int64()
    if d.is_unsigned_integer():
        return DataType.uint64()
    return d


def _agg_groupby(batch, specs, group_by: List[Expression]):
    from .recordbatch import RecordBatch

    key_series = [batch.eval_expression(e) for e in group_by]
    key_names = [f"__k{i}__" for i in range(len(key_series))]
    cols = {kn: ks.to_arrow() for kn, ks in zip(key_names, key_series)}

    pa_aggs = []
    post: List[Tuple[str, str, DataType, str]] = []  # (pa_out_name, out_name, dtype, op)
    py_specs = []
    for i, (op, child, name, params) in enumerate(specs):
        s = _eval_child(batch, child, i)
        in_name = f"__in{i}__"
        if op == "count":
            mode = params[0] if params else "valid"
            cols[in_name] = s.not_null().to_arrow() if not s.is_pyobject() else \
                pa.array([x is not None for x in s.to_pylist()])
            pa_mode = {"valid": "sum", "all": "count", "null": None}.get(mode, "sum")
            if mode == "null":
                cols[in_name] = pc.invert(cols[in_name])
                pa_mode = "sum"
            pa_aggs.append((in_name, pa_mode))
            post.append((f"{in_name}_{pa_mode}", name, DataType.uint64(), op))
        elif op in _PA_AGGS and not s.is_pyobject():
            cols[in_name] = s.to_arrow()
            pa_op = _PA_AGGS[op]
            opts = None
            if op in ("stddev", "var"):
                opts = pc.VarianceOptions(ddof=0)
            pa_aggs.append((in_name, pa_op, opts) if opts else (in_name, pa_op))
            out_dt = _agg_out_dtype(op, s.datatype())
            post.append((f"{in_name}_{pa_op}", name, out_dt, op))
        else:
            py_specs.append((i, op, s, name, params))
            post.append((None, name, None, op))

    tbl = pa.table(cols)
    g = tbl.group_by(key_names, use_threads=False)
    aggd = g.aggregate(pa_aggs)

    # row indices per group for python-side aggs (NaN-safe group keys)
    def _norm_key(x):
        if isinstance(x, float) and x != x:
            return "__nan__"
        return x

    if py_specs:
        idx_tbl = pa.table({**{k: cols[k] for k in key_names},
                            "__row__": pa.array(np.arange(len(batch)))})
        rows = idx_tbl.group_by(key_names, use_threads=False) \
            .aggregate([("__row__", "list")])
        row_lists = {tuple(_norm_key(rows.column(k)[i].as_py())
                           for k in key_names):
                     rows.column("__row___list")[i].as_py()
                     for i in range(rows.num_rows)}

    out_cols: List[Series] = []
    for ki, (kn, ke) in enumerate(zip(key_names, group_by)):
        out_cols.append(Series.from_arrow(aggd.column(kn), ke.name())
                        .cast(key_series[ki].datatype()))
    for (pa_out, name, out_dt, op) in post:
        if pa_out is not None:
            s_out = Series.from_arrow(aggd.column(pa_out), name)
            if op == "concat":
                pass
            out_cols.append(s_out.cast(out_dt) if out_dt is not None else s_out)
        else:
            i, op2, s, name2, params = next(p for p in py_specs if p[3] == name)
            group_keys = [tuple(_norm_key(aggd.column(k)[r].as_py())
                                for k in key_names)
                          for r in range(aggd.num_rows)]
            vals = []
            for gk in group_keys:
                ridx = row_lists[gk]
                sub = s.take(np.asarray(ridx))
                vals.append(_global_one(op2, sub, name2, params).to_pylist()[0])
            dt = _agg_out_dtype(op2, s.datatype())
            out_cols.append(Series.from_pylist(vals, name2, dtype=dt))
    return RecordBatch.from_series(out_cols)


def _agg_out_dtype(op: str, in_dtype: DataType) -> DataType:
    if op == "sum":
        return _sum_dtype(in_dtype)
    if op in ("mean", "stddev", "var", "skew"):
        return DataType.float64()
    if op in ("count", "count_distinct", "approx_count_distinct"):
        return DataType.uint64()
    if op in ("min", "max", "any_value"):
        return in_dtype
    if op in ("list", "set"):
        return DataType.list(in_dtype)
    if op == "concat":
        return in_dtype if in_dtype.is_list() or in_dtype.is_string() \
            else DataType.list(in_dtype)
    if op in ("bool_and", "bool_or"):
        return DataType.bool()
    if op == "approx_percentiles":
        return None  # set by caller
    return in_dtype


def pivot_recordbatch(batch, group_by: List[Expression], pivot_col: Expression,
                      value_col: Expression, names: List[str]):
    """Reference: ``src/daft-recordbatch/src/ops/pivot.rs``."""
    from .recordbatch import RecordBatch
    keys = [batch.eval_expression(e) for e in group_by]
    pv = batch.eval_expression(pivot_col)
    vv = batch.eval_expression(value_col)
    tbl = pa.table({**{f"__k{i}__": k.to_arrow() for i, k in enumerate(keys)},
                    "__p__": pv.to_arrow(), "__v__": vv.to_arrow()})
    knames = [f"__k{i}__" for i in range(len(keys))]
    g = tbl.group_by(knames + ["__p__"], use_threads=False) \
        .aggregate([("__v__", "first")])
    # gather group keys
    group_rows: Dict[Tuple, Dict] = {}
    order: List[Tuple] = []
    for r in range(g.num_rows):
        gk = tuple(g.column(k)[r].as_py() for k in knames)
        if gk not in group_rows:
            group_rows[gk] = {}
            order.append(gk)
        group_rows[gk][g.column("__p__")[r].as_py()] = \
            g.column("__v___first")[r].as_py()
    out_cols = []
    for i, (k, e) in enumerate(zip(keys, group_by)):
        out_cols.append(Series.from_pylist([gk[i] for gk in order], e.name(),
                                           dtype=k.datatype()))
    for nm in names:
        key = nm
        pv_dt = pv.datatype()
        if pv_dt.is_integer():
            try:
                key = int(nm)
            except ValueError:
                key = nm
        out_cols.append(Series.from_pylist(
            [group_rows[gk].get(key) for gk in order], str(nm),
            dtype=vv.datatype()))
    return RecordBatch.from_series(out_cols)
