"""Distributed runner: stage plan → scheduler → workers.

Reference architecture: the flotilla engine (``src/daft-distributed``): the
logical/physical plan splits into exchange-free stages
(``stage/mod.rs:54-80``), a scheduler actor places stage tasks on workers
through a pluggable policy (``scheduling/scheduler/mod.rs:18-23``), and each
worker runs the local streaming engine on its fragment. Here workers are
in-process per-host executors (one per CPU slice / mesh device group; a
multi-host deployment swaps in gRPC workers behind the same ``Worker``
seam), exchanges between stages run on the driver, and mesh-collective
exchanges (DeviceExchangeAgg) stay fused inside stages.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

from ..distributed import (InProcessWorker, LeastLoadedScheduler, StagePlan,
                           StageRunner, WorkerManager)
from ..micropartition import MicroPartition
from ..physical.translate import translate
from .runner import Runner


class DistributedRunner(Runner):
    name = "tpu_distributed"

    def __init__(self, num_workers: Optional[int] = None, scheduler=None):
        super().__init__()
        from ..analysis import knobs
        self.num_workers = num_workers or max(
            knobs.env_int("DAFT_TPU_NUM_WORKERS")
            or min((os.cpu_count() or 4) // 2, 8), 2)
        self._scheduler = scheduler
        self._manager: Optional[WorkerManager] = None

    def _get_manager(self) -> WorkerManager:
        if self._manager is None:
            slots = max((os.cpu_count() or 4) // self.num_workers, 1)
            self._manager = WorkerManager(
                [InProcessWorker(f"worker-{i}", num_slots=slots)
                 for i in range(self.num_workers)])
        return self._manager

    def run_iter(self, builder, results_buffer_size: Optional[int] = None
                 ) -> Iterator[MicroPartition]:
        from .. import observability as obs
        from .. import tracing
        from ..context import get_context
        cfg = get_context().execution_config
        tctx = tracing.maybe_start_trace("distributed")
        # a planning failure strikes before the driver stats context
        # below takes ownership of the recorder — close and unregister it
        # on that path or it leaks (daft-lint: trace-recorder-leak)
        try:
            with tracing.attach(tctx):
                aqe_planner = None
                if cfg.enable_aqe:
                    # the native runner's AQE loop, distributed (round
                    # 20): join inputs materialize THROUGH the stage
                    # runner, their actual rows/bytes replace the
                    # subtree, and the optimizer re-runs — join ORDER
                    # and broadcast decisions in this tier come from
                    # measurements too
                    with tracing.span("plan:optimize", lane="planner"):
                        plan, aqe_planner = self._adaptive_logical(
                            builder, cfg)
                    with tracing.span("plan:translate", lane="planner"):
                        pplan = translate(plan)
                else:
                    with tracing.span("plan:optimize", lane="planner"):
                        optimized = builder.optimize()
                    with tracing.span("plan:translate", lane="planner"):
                        pplan = translate(optimized.plan)
                stage_plan = StagePlan.from_physical(pplan)
                runner = StageRunner(
                    self._get_manager(),
                    self._scheduler or LeastLoadedScheduler())
                runner._aqe_planner = aqe_planner
                # driver-level query stats: each stage task runs its own
                # local executor (whose stats only cover that fragment);
                # this context spans the whole query, so its
                # resilience-counter delta carries every recovery event
                # of the run into explain_analyze and the dashboard
                stats = obs.new_query_stats()
                stats.plan = pplan
            it = runner.run(stage_plan)
        except BaseException:
            tracing.abort_trace(tctx)
            raise
        try:
            # each pull runs under (a) the query's span context, so the
            # stage runner / task supervisor / driver-side exchange spans
            # join the merged trace, and (b) a nested scope, so fragment
            # executors' set_last_stats never fire the per-query exports
            while True:
                with obs.nested_scope(), tracing.attach(stats.trace_ctx):
                    try:
                        p = next(it)
                    except StopIteration:
                        break
                yield p
        finally:
            # the export chain (set_last_stats → finalize_query) must
            # run even when the stage runner's generator cleanup — or
            # finish() itself — raises; otherwise the trace recorder
            # outlives the query (daft-lint: trace-recorder-leak)
            try:
                with obs.nested_scope(), tracing.attach(stats.trace_ctx):
                    it.close()
                stats.finish()
            finally:
                obs.set_last_stats(stats)

    # ------------------------------------------------------------- AQE
    def _adaptive_logical(self, builder, cfg):
        """Distributed port of ``NativeRunner._run_adaptive``'s planning
        loop (the reference's next_stage/update_stats): the cheapest
        unmeasured join input materializes through the DISTRIBUTED stage
        runner (workers, shuffle plane, resilience included), an
        in-memory source carrying its ACTUAL rows/bytes replaces the
        subtree, and the whole optimizer re-runs — repeated until every
        join input is measured. → (final logical plan, the AdaptivePlanner
        holding the re-plan history, shared with the stage runner's
        boundary-level re-planner)."""
        from .. import observability as obs
        from ..logical import plan as lp
        from ..logical.optimizer import Optimizer
        from ..physical import adaptive
        from .native_runner import _pick_join_input, _replace_subtree

        planner = adaptive.new_planner(cfg)
        plan = Optimizer().optimize(builder._plan)
        for _round in range(32):  # bound the loop defensively
            target = _pick_join_input(plan)
            if target is None:
                break
            sub_runner = StageRunner(
                self._get_manager(),
                self._scheduler or LeastLoadedScheduler())
            sub_runner._aqe_planner = planner
            with obs.nested_scope():  # no per-query exports mid-loop
                parts = [p for p in sub_runner.run(
                    StagePlan.from_physical(translate(target)))
                    if len(p)]
            rows = sum(len(p) for p in parts)
            size = sum(int(p.size_bytes() or 0) for p in parts)
            src = lp.Source(partitions=parts, schema=target.schema(),
                            num_partitions=max(len(parts), 1))
            planner.record_replan(
                f"materialized join input distributed ({rows} rows, "
                f"{size} bytes actual) → re-optimized remainder",
                rows, size)
            plan = _replace_subtree(plan, target, src)
            plan = Optimizer().optimize(plan)
        return plan, planner
