"""Distributed runner over a jax device Mesh (single- or multi-host SPMD).

Reference architecture: the flotilla engine (``src/daft-distributed``) — a
stage planner splitting at exchanges, per-worker local execution, a scheduler
with pluggable policy. TPU mapping: partitions are sharded across mesh
devices; exchange ops run as ICI collectives (``daft_tpu.parallel``); each
host runs the local streaming executor for its shard of scan tasks.
"""

from __future__ import annotations

from typing import Iterator, Optional

from ..execution.executor import LocalExecutor
from ..micropartition import MicroPartition
from ..physical.translate import translate
from .runner import Runner


class DistributedRunner(Runner):
    """Runs the physical plan with device-mesh-aware exchanges.

    On one process this is the local executor plus mesh-collective exchange
    kernels for repartitions (see ``daft_tpu.parallel.exchange``); stage
    orchestration across hosts reuses the same plan splitting.
    """

    name = "tpu_distributed"

    def __init__(self, num_workers: Optional[int] = None):
        super().__init__()
        self.num_workers = num_workers

    def run_iter(self, builder, results_buffer_size: Optional[int] = None
                 ) -> Iterator[MicroPartition]:
        from ..parallel.stage_runner import MeshStageRunner
        optimized = builder.optimize()
        pplan = translate(optimized.plan)
        runner = MeshStageRunner(self.num_workers)
        yield from runner.run(pplan)
