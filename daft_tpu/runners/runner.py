"""Runner abstraction + partition sets.

Reference: ``daft/runners/runner.py:25-70`` (Runner ABC: run / run_iter /
run_iter_tables + partition-set cache) and ``daft/runners/partitioning.py``
(PartitionSet / MaterializedResult / PartitionSetCache).
"""

from __future__ import annotations

import threading
import uuid
import weakref
from typing import Dict, Iterator, List, Optional

from ..micropartition import MicroPartition
from ..recordbatch import RecordBatch
from ..schema import Schema


class PartitionSet:
    """Materialized query result: an ordered list of MicroPartitions."""

    def __init__(self, partitions: List[MicroPartition], schema: Schema):
        self.partitions = partitions
        self.schema = schema

    def num_partitions(self) -> int:
        return len(self.partitions)

    def __len__(self) -> int:
        return sum(len(p) for p in self.partitions)

    def size_bytes(self) -> int:
        return sum(p.size_bytes() for p in self.partitions)

    def to_recordbatch(self) -> RecordBatch:
        batches = []
        for p in self.partitions:
            batches.extend(p.batches())
        batches = [b for b in batches if len(b)] or batches[:1]
        if not batches:
            return RecordBatch.empty(self.schema)
        return RecordBatch.concat(batches).cast_to_schema(self.schema)


class PartitionSetCache:
    """Keeps collected results alive for downstream queries
    (reference: ``runner.py:22-35``, InMemoryPartitionSetCache)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sets: Dict[str, PartitionSet] = {}

    def put(self, ps: PartitionSet) -> str:
        key = uuid.uuid4().hex
        with self._lock:
            self._sets[key] = ps
        return key

    def get(self, key: str) -> Optional[PartitionSet]:
        with self._lock:
            return self._sets.get(key)

    def rm(self, key: str):
        with self._lock:
            self._sets.pop(key, None)

    def clear(self):
        with self._lock:
            self._sets.clear()


class Runner:
    def __init__(self):
        self.partition_set_cache = PartitionSetCache()

    def run(self, builder) -> PartitionSet:
        parts = list(self.run_iter(builder))
        return PartitionSet(parts, builder.schema())

    def run_iter(self, builder,
                 results_buffer_size: Optional[int] = None
                 ) -> Iterator[MicroPartition]:
        raise NotImplementedError

    def run_iter_tables(self, builder,
                        results_buffer_size: Optional[int] = None
                        ) -> Iterator[RecordBatch]:
        for p in self.run_iter(builder, results_buffer_size):
            for b in p.batches():
                if len(b):
                    yield b
