"""Planning-time materialization helpers (e.g. pivot distinct-values probe)."""

from __future__ import annotations

from typing import List


def materialize_for_planning(builder) -> List:
    """Run a small plan eagerly and return the single column as a pylist."""
    from ..context import get_context
    runner = get_context().get_or_create_runner()
    ps = runner.run(builder)
    rb = ps.to_recordbatch()
    return rb.get_column(rb.column_names()[0]).to_pylist()
