"""NativeRunner: optimize → translate → local streaming executor.

Reference: ``daft/runners/native_runner.py:49-99``.
"""

from __future__ import annotations

from typing import Iterator, Optional

from ..execution.executor import LocalExecutor
from ..micropartition import MicroPartition
from ..physical.translate import translate
from .runner import Runner


class NativeRunner(Runner):
    name = "native"

    def run_iter(self, builder, results_buffer_size: Optional[int] = None
                 ) -> Iterator[MicroPartition]:
        optimized = builder.optimize()
        pplan = translate(optimized.plan)
        executor = LocalExecutor()
        yield from executor.run(pplan)
