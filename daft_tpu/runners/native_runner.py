"""NativeRunner: optimize → translate → local streaming executor.

Reference: ``daft/runners/native_runner.py:49-99``. With
``enable_aqe=True`` the runner becomes the reference's AdaptivePlanner
loop (``physical_planner/planner.rs:451-640`` next_stage/update_stats):
join inputs materialize stage by stage, their ACTUAL cardinalities are
folded back into the logical plan as in-memory sources, and the whole
optimizer re-runs over the remainder — join order and broadcast
decisions are made from measurements, not estimates.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

from ..execution.executor import LocalExecutor
from ..micropartition import MicroPartition
from ..physical.translate import translate
from .runner import Runner


def make_local_executor(cfg) -> LocalExecutor:
    """Engine pick: the push-based morsel pipeline (default), or the
    pull-generator interpreter via ``local_executor="interp"`` /
    ``DAFT_LOCAL_EXECUTOR=interp``."""
    if getattr(cfg, "local_executor", "push") == "interp":
        return LocalExecutor()
    from ..execution.pipeline import PushExecutor
    return PushExecutor()


class NativeRunner(Runner):
    name = "native"

    def run_iter(self, builder, results_buffer_size: Optional[int] = None
                 ) -> Iterator[MicroPartition]:
        from .. import tracing
        from ..context import get_context
        cfg = get_context().execution_config
        if cfg.enable_aqe:
            yield from self._run_adaptive(builder, cfg)
            return
        # the trace (when sampled in) starts HERE so the planner spans
        # land on it; the executor's stats context adopts it and the
        # export fires at set_last_stats. Until that adoption the
        # recorder has no owner: a planner failure must close and
        # unregister it here or it leaks in the registry with the trace
        # silently lost (found by daft-lint's trace-recorder-leak check)
        tctx = tracing.maybe_start_trace("query")
        try:
            with tracing.attach(tctx):
                with tracing.span("plan:optimize", lane="planner"):
                    optimized = builder.optimize()
                with tracing.span("plan:translate", lane="planner"):
                    pplan = translate(optimized.plan)
                executor = make_local_executor(cfg)
                it = executor.run(pplan)
        except BaseException:
            tracing.abort_trace(tctx)
            raise
        yield from it

    # ------------------------------------------------------------- AQE
    def _run_adaptive(self, builder, cfg) -> Iterator[MicroPartition]:
        """Stage-by-stage adaptive loop: materialize the cheapest
        unresolved join input, substitute an in-memory source carrying its
        ACTUAL rows/bytes, re-optimize the remainder, repeat. The final
        translate sees only measured sizes, so broadcast-vs-hash and join
        order are decided from actuals (re-plans are visible in
        ``explain_analyze``)."""
        from ..execution import memory
        from ..logical import plan as lp
        from ..logical.optimizer import Optimizer
        from ..physical import adaptive

        planner = adaptive.new_planner(cfg)
        plan = Optimizer().optimize(builder._plan)
        for _round in range(32):  # bound the loop defensively
            target = _pick_join_input(plan)
            if target is None:
                break
            ex = make_local_executor(cfg)
            ex._aqe_planner = planner
            # spill-bounded, like the normal join-build path: the loop
            # eventually materializes the largest fact side, which must not
            # bypass the memory budget (it streams to disk past it)
            buf = memory.materialize(ex.run(translate(target)))
            rows, size = buf.total_rows, buf.total_bytes
            src = lp.Source(partitions=buf, schema=target.schema(),
                            num_partitions=max(len(buf), 1))
            planner.record_replan(
                f"materialized join input ({rows} rows, {size} bytes "
                f"actual) → re-optimized remainder", rows, size)
            plan = _replace_subtree(plan, target, src)
            plan = Optimizer().optimize(plan)
        ex = make_local_executor(cfg)
        ex._aqe_planner = planner
        planner.final_plan = translate(plan)
        yield from ex.run(planner.final_plan)


def _is_measured(node) -> bool:
    """Only a bare in-memory source carries EXACT stats — anything above
    it (Filter/Aggregate/Join/scan) still runs on estimates and is worth
    materializing before the join decision. The optimizer's own derived
    null-key filters (FilterNullJoinKey re-adds them every pass) don't
    count: treating them as unmeasured would re-materialize the same
    source forever."""
    from ..logical import plan as lp
    from ..logical.optimizer import split_conjuncts
    while isinstance(node, lp.Filter) and all(
            c._unalias().op == "not_null"
            and c._unalias().args[0].op == "col"
            for c in split_conjuncts(node.predicate)):
        node = node.children[0]
    return isinstance(node, lp.Source) and node.partitions is not None


def _pick_join_input(plan):
    """The cheapest-estimated unmeasured input of the bottom-most join
    that still has one, or None when every join input is a measured
    in-memory source. Joins whose inputs are all measured stop blocking
    their ancestors, so the loop works its way up the join tree."""
    from ..logical import plan as lp
    from ..logical import stats as lstats

    best: Optional[Tuple[float, object]] = None

    def visit(node) -> bool:
        """True iff the subtree contains a join with unmeasured inputs."""
        nonlocal best
        kid_flags = [visit(c) for c in node.children]  # no short-circuit
        has_inner = any(kid_flags)
        if isinstance(node, lp.Join):
            pending = [c for c in node.children if not _is_measured(c)]
            if not pending:
                return has_inner
            if not has_inner:
                for c in pending:
                    est = lstats.estimate(c).size_bytes
                    key = est if est is not None else float("inf")
                    if best is None or key < best[0]:
                        best = (key, c)
            return True
        return has_inner

    visit(plan)
    return None if best is None else best[1]


def _replace_subtree(plan, target, replacement):
    if plan is target:
        return replacement
    kids = [_replace_subtree(c, target, replacement)
            for c in plan.children]
    return plan.with_children(kids)
