"""Horizontal serving plane: N driver replicas behind one front door.

Pieces (each submodule's docstring carries the design):

- ``router``      — consistent-hash session affinity, drain/kill
                    lifecycle, gauge aggregation + scale signal;
- ``cache_tier``  — cross-replica plan/result cache layer keyed by the
                    plan fingerprints (sidecar store or in-process hub);
- ``state_sync``  — gossiped learned state (calibration profiles +
                    admission history) with gen-stamped idempotent
                    merges, plus the fleet counters plane;
- ``replica``     — the subprocess replica entrypoint: Spark Connect
                    server + control HTTP plane + gossip loop.

This package root only hosts the process-level router install point the
Spark Connect server consults; everything else is imported on demand so
``import daft_tpu`` stays fleet-free.
"""

from __future__ import annotations

import threading
from typing import Optional

_router_lock = threading.Lock()
_router = None


def install_router(router) -> None:
    """Install the process's fleet router: the Spark Connect server
    routes session submissions through it when present. None uninstalls
    (tests)."""
    global _router
    with _router_lock:
        _router = router


def installed_router():
    with _router_lock:
        return _router


def __getattr__(name: str):
    if name in ("router", "cache_tier", "state_sync", "replica"):
        import importlib
        return importlib.import_module(f".{name}", __name__)
    if name in ("FleetRouter", "InProcessReplica", "SubprocessReplica",
                "ReplicaUnavailable"):
        from . import router as _r
        return getattr(_r, name)
    if name == "StateStore":
        from . import state_sync as _s
        return _s.StateStore
    raise AttributeError(name)
