"""Cross-replica cache tier: warm state regardless of landing replica.

The serving caches (``serving/caches.py``) are process-local LRUs; with
N replicas behind the router, a repeat query that lands on a different
replica than its first run pays full execution again. This tier adds a
SECOND cache layer the scheduler consults on a local miss, keyed by the
same ``logical/fingerprint.py`` fingerprints — which means the existing
invalidation rules carry over wholesale: source ``(size, mtime_ns)``
version tokens, the ExecutionConfig hash, and the calibration-generation
token are all baked into the key, so a stale entry is simply never
looked up again (no cross-process invalidation protocol needed).

Two deployments:

- :class:`InProcessCacheTier` — a shared hub for in-process replicas
  (tests, the embedded fleet): plans AND results, shared by reference.
- :class:`SidecarCacheTier` — an HTTP client to a :class:`CacheSidecar`
  store process (``python -m daft_tpu.fleet.cache_tier --port N``).
  Results cross the wire as Arrow IPC streams; plans stay per-replica
  (a physical plan holds live scan tasks and closures — not portable),
  which session-affinity routing already keeps warm where they're used.

Every path degrades to a miss on any failure — the tier can slow a
repeat query down to normal execution, never break it. No locks are held
across serialization or network calls.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, Optional, Tuple

from ..serving.caches import _LRUCache
from . import state_sync

_DEFAULT_TIMEOUT_S = 2.0


def _fp_token(fp) -> str:
    """Process-portable cache token: fingerprint keys are tuples of
    strings/ints whose repr is deterministic across processes."""
    return hashlib.sha256(repr(fp.key).encode()).hexdigest()


# ------------------------------------------------------- serialization

def _result_to_ipc(ps) -> bytes:
    import pyarrow as pa
    t = ps.to_recordbatch().to_arrow_table()
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, t.schema) as w:
        w.write_table(t)
    return sink.getvalue().to_pybytes()


def _result_from_ipc(data: bytes):
    import pyarrow as pa

    from ..micropartition import MicroPartition
    from ..runners.runner import PartitionSet
    from ..schema import Schema
    t = pa.ipc.open_stream(pa.py_buffer(data)).read_all()
    mp = MicroPartition.from_arrow_table(t)
    return PartitionSet([mp], Schema.from_arrow(t.schema))


# ------------------------------------------------------------- in-process

class InProcessCacheTier:
    """Shared hub for in-process replicas: each replica's scheduler keeps
    its own local caches and falls through to this one, so the fleet
    tests exercise the exact local-miss → tier-hit → local-promote flow
    the sidecar deployment uses — minus the wire."""

    def __init__(self, result_budget_bytes: int = 256 << 20,
                 plan_budget_bytes: int = 64 << 20):
        self._results = _LRUCache(result_budget_bytes)
        self._plans = _LRUCache(plan_budget_bytes)

    def get_result(self, fp):
        got = self._results.get(fp.key)
        state_sync.count("cache_tier_hits" if got is not None
                         else "cache_tier_misses")
        return got

    def put_result(self, fp, ps) -> None:
        try:
            nbytes = int(ps.size_bytes() or 0)
        except Exception:
            return
        self._results.put(fp.key, ps, nbytes)
        state_sync.count("cache_tier_puts")

    def get_plan(self, fp) -> Optional[Tuple]:
        return self._plans.get(fp.key)

    def put_plan(self, fp, optimized_plan, physical_plan) -> None:
        from ..serving.caches import PlanCache
        nbytes = PlanCache._NODE_COST * (
            PlanCache._tree_size(optimized_plan)
            + PlanCache._tree_size(physical_plan))
        self._plans.put(fp.key, (optimized_plan, physical_plan), nbytes)

    def stats(self) -> Dict[str, Dict[str, float]]:
        return {"results": self._results.stats(),
                "plans": self._plans.stats()}


# ---------------------------------------------------------------- sidecar

class SidecarCacheTier:
    """HTTP client to a :class:`CacheSidecar` store. Result-only (see
    module docstring); every failure counts and degrades to a miss."""

    def __init__(self, address: str, timeout_s: float = _DEFAULT_TIMEOUT_S):
        self.address = address.rstrip("/")
        if "://" not in self.address:
            self.address = "http://" + self.address
        self.timeout_s = float(timeout_s)

    def _url(self, fp) -> str:
        return f"{self.address}/result/{_fp_token(fp)}"

    def get_result(self, fp):
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen(self._url(fp),
                                        timeout=self.timeout_s) as r:
                data = r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                state_sync.count("cache_tier_misses")
            else:
                state_sync.count("cache_tier_errors")
            return None
        except Exception:
            state_sync.count("cache_tier_errors")
            return None
        try:
            ps = _result_from_ipc(data)
        except Exception:
            state_sync.count("cache_tier_errors")
            return None
        state_sync.count("cache_tier_hits")
        return ps

    def put_result(self, fp, ps) -> None:
        import urllib.request
        try:
            data = _result_to_ipc(ps)
        except Exception:
            state_sync.count("cache_tier_errors")
            return
        try:
            req = urllib.request.Request(
                self._url(fp), data=data, method="PUT",
                headers={"Content-Type": "application/octet-stream"})
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
            state_sync.count("cache_tier_puts")
        except Exception:
            state_sync.count("cache_tier_errors")

    def get_plan(self, fp):
        return None  # plans are not portable across processes

    def put_plan(self, fp, optimized_plan, physical_plan) -> None:
        pass

    def stats(self) -> Dict[str, object]:
        import json
        import urllib.request
        try:
            with urllib.request.urlopen(f"{self.address}/stats",
                                        timeout=self.timeout_s) as r:
                return json.loads(r.read().decode())
        except Exception:
            return {}


class CacheSidecar:
    """The store process: a byte-budgeted LRU of opaque result blobs
    behind a tiny HTTP surface (GET/PUT ``/result/<token>``, GET
    ``/stats``). Single-writer semantics are irrelevant — entries are
    immutable (the fingerprint token pins content), so last-put-wins."""

    def __init__(self, budget_bytes: int = 256 << 20, port: int = 0,
                 host: str = "127.0.0.1"):
        self._blobs = _LRUCache(budget_bytes)
        self._host = host
        self._port = port
        self._httpd = None
        self._thread = None

    @property
    def address(self) -> str:
        return f"{self._host}:{self._port}"

    def start(self) -> str:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        blobs = self._blobs

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _token(self):
                parts = self.path.strip("/").split("/")
                if len(parts) == 2 and parts[0] == "result":
                    return parts[1]
                return None

            def do_GET(self):
                if self.path == "/stats":
                    import json
                    body = json.dumps(blobs.stats()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                tok = self._token()
                blob = blobs.get((tok,)) if tok else None
                if blob is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def do_PUT(self):
                tok = self._token()
                n = int(self.headers.get("Content-Length", 0) or 0)
                data = self.rfile.read(n) if n else b""
                if tok and data:
                    blobs.put((tok,), data, len(data))
                self.send_response(204)
                self.end_headers()

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="daft-tpu-cache-sidecar", daemon=True)
        self._thread.start()
        return self.address

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


# ------------------------------------------------------- process install

_installed_lock = threading.Lock()
_installed = None


def install(tier) -> None:
    """Install the process's cache tier — what a scheduler built without
    an explicit ``cache_tier`` falls back to. None uninstalls (tests)."""
    global _installed
    with _installed_lock:
        _installed = tier


def installed():
    with _installed_lock:
        return _installed


def tier_from_env():
    """Build the tier the environment asks for: a sidecar client when
    ``DAFT_TPU_FLEET_SIDECAR`` names a store, else None."""
    from ..analysis import knobs
    addr = knobs.env_str("DAFT_TPU_FLEET_SIDECAR")
    if addr:
        return SidecarCacheTier(addr)
    return None


def _main() -> int:
    """Sidecar store entrypoint:
    ``python -m daft_tpu.fleet.cache_tier [--port N]``."""
    import argparse
    import time

    from ..analysis import knobs
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args()
    budget = knobs.env_bytes("DAFT_TPU_FLEET_SIDECAR_BYTES",
                             default=256 << 20)
    sc = CacheSidecar(budget_bytes=budget, port=args.port, host=args.host)
    addr = sc.start()
    print(f"FLEET_SIDECAR_READY {addr}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        sc.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
