"""Replicated learned state: what one replica learns, the fleet knows.

Everything the engine learns is process-local by birth — the calibration
profile (``device/calibration.py``) and the per-fingerprint admission /
result-byte history (``serving/scheduler.py``) both start empty in a
fresh replica, which means a scale-up event serves its first minutes of
traffic priced from hard-coded defaults. This module makes learned state
a first-class replicated artifact (the Exoshuffle lineage-as-shared-
metadata idea applied to cost-model evidence): each replica owns ONE
origin slot, stamps it with a monotonic generation counter, and gossips
full origin snapshots; peers keep the newest snapshot per origin.

Merge semantics (the properties the fleet tests assert):

- **idempotent** — re-ingesting a snapshot whose ``(origin, gen)`` is
  already held is a no-op (last-writer-wins per origin by generation);
- **commutative** — ingest order cannot matter: the held state is a
  per-origin map keyed by generation, and every *read* recomputes the
  merged view from it, so any ingest ordering that delivers the same
  snapshots yields bit-identical merged views;
- **sample-count-weighted** — merged views average origin values
  weighted by their EWMA sample counts, so a replica with 500
  observations outweighs one with 3.

Consumers:

- ``device/calibration.const`` falls back to :meth:`merged_calibration`
  when the local profile is below the sample floor — a cold replica's
  first query prices device dispatches from fleet history;
- ``serving/scheduler._fleet_history_estimate`` falls back to
  :meth:`merged_admission` when both the cost model and the local
  admission history are blind (counter ``est_seeded_fleet``);
- admission-history keys are ``PlanFingerprint.history_structure``-based
  (no calibration token), so the same workload hashes identically on
  every replica regardless of each one's learned profile.

The module also hosts the fleet-wide counters (routes, drains, gossip
merges) exported as the ``daft_fleet_*`` plane on ``/metrics``.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------- counters

_counts_lock = threading.Lock()
_counters: Dict[str, float] = {}


def count(name: str, n: float = 1) -> None:
    """Bump a fleet-plane counter (``fleet:route``/``fleet:drain``
    events, gossip merges, fleet cache/calibration reads)."""
    with _counts_lock:
        _counters[name] = _counters.get(name, 0) + n


def counters_snapshot() -> Dict[str, float]:
    with _counts_lock:
        return dict(_counters)


# ----------------------------------------------------------- sanitization

def _clean_calib(calib) -> Dict[str, Dict[str, float]]:
    out: Dict[str, Dict[str, float]] = {}
    for name, e in (calib or {}).items():
        try:
            v, n = float(e["value"]), float(e["samples"])
        except (TypeError, ValueError, KeyError):
            continue
        if v > 0 and n > 0:
            out[str(name)] = {"value": v, "samples": n}
    return out


def _clean_admission(adm) -> Dict[str, Dict[str, float]]:
    out: Dict[str, Dict[str, float]] = {}
    for key, e in (adm or {}).items():
        try:
            if isinstance(e, dict):
                b = float(e["bytes"])
                w = float(e.get("wall_us", 0.0))
                n = float(e.get("samples", 1.0))
            else:  # the scheduler's native (bytes, wall_us, samples)
                b, w, n = float(e[0]), float(e[1]), float(e[2])
        except (TypeError, ValueError, KeyError, IndexError):
            continue
        if b >= 0 and n > 0:
            out[str(key)] = {"bytes": b, "wall_us": max(w, 0.0),
                             "samples": n}
    return out


def _copy_snap(s: dict) -> dict:
    return {"origin": s["origin"], "gen": s["gen"],
            "calib": {k: dict(v) for k, v in s["calib"].items()},
            "admission": {k: dict(v) for k, v in s["admission"].items()}}


# ------------------------------------------------------------------ store

class StateStore:
    """One replica's view of the fleet's learned state: its own origin
    slot (re-published with a bumped generation on every gossip round)
    plus the newest known snapshot of every peer origin."""

    def __init__(self, origin: str):
        self.origin = str(origin)
        self._lock = threading.Lock()
        self._gen = 0
        self._per_origin: Dict[str, dict] = {}

    # -- publish -------------------------------------------------------
    def publish_local(self, calibration=None, admission=None) -> dict:
        """Replace this replica's own origin snapshot with the given
        learned state and bump the generation. Returns a copy of the
        published snapshot (what a gossip push sends)."""
        calib = _clean_calib(calibration)
        adm = _clean_admission(admission)
        with self._lock:
            self._gen += 1
            snap = {"origin": self.origin, "gen": self._gen,
                    "calib": calib, "admission": adm}
            self._per_origin[self.origin] = snap
            out = _copy_snap(snap)
        count("publish")
        return out

    def publish_from_engine(self, scheduler=None) -> dict:
        """Convenience publish: export the process's LIVE learned state —
        the calibration profile plus the (given or process-shared)
        scheduler's admission history."""
        calib = {}
        try:
            from ..device import calibration
            calib = calibration.profile_entries()
        except Exception:
            calib = {}
        adm = {}
        if scheduler is None:
            try:
                from ..serving import shared_scheduler_if_running
                scheduler = shared_scheduler_if_running()
            except Exception:
                scheduler = None
        if scheduler is not None:
            try:
                adm = scheduler.admission_history_snapshot()
            except Exception:
                adm = {}
        return self.publish_local(calibration=calib, admission=adm)

    # -- ingest --------------------------------------------------------
    def ingest(self, snapshot: dict) -> bool:
        """Accept a peer origin snapshot iff its generation is strictly
        newer than what we hold for that origin. Re-delivery and
        reordering are both safe: last-writer-wins per origin by
        generation is exactly idempotent, and merged views are computed
        from the held per-origin map on every read."""
        try:
            origin = str(snapshot["origin"])
            gen = int(snapshot["gen"])
        except (TypeError, ValueError, KeyError):
            count("ingest_malformed")
            return False
        if origin == self.origin:
            # we are authoritative for our own slot: a peer echoing our
            # old snapshot back must not regress the generation
            count("ingest_self")
            return False
        calib = _clean_calib(snapshot.get("calib"))
        adm = _clean_admission(snapshot.get("admission"))
        with self._lock:
            cur = self._per_origin.get(origin)
            if cur is not None and cur["gen"] >= gen:
                applied = False
            else:
                self._per_origin[origin] = {
                    "origin": origin, "gen": gen,
                    "calib": calib, "admission": adm}
                applied = True
        count("ingest_applied" if applied else "ingest_stale")
        return applied

    def snapshot_all(self) -> dict:
        """Full-state export for anti-entropy exchange: every origin
        snapshot this store holds (its own included)."""
        with self._lock:
            return {"origins": {o: _copy_snap(s)
                                for o, s in self._per_origin.items()}}

    def ingest_all(self, state: dict) -> int:
        """Merge a peer's full-state export; returns snapshots applied."""
        n = 0
        for snap in (state.get("origins") or {}).values():
            if isinstance(snap, dict) and self.ingest(snap):
                n += 1
        return n

    # -- merged views --------------------------------------------------
    def merged_admission(self, key: str
                         ) -> Optional[Tuple[float, float, float]]:
        """Sample-count-weighted fleet view of one admission-history
        key → ``(bytes, wall_us, samples)``, or None when no origin has
        observed it."""
        with self._lock:
            entries = [s["admission"].get(str(key))
                       for s in self._per_origin.values()]
        entries = [e for e in entries if e]
        if not entries:
            return None
        n = sum(e["samples"] for e in entries)
        b = sum(e["bytes"] * e["samples"] for e in entries) / n
        w = sum(e["wall_us"] * e["samples"] for e in entries) / n
        return (b, w, n)

    def merged_calibration(self, name: str
                           ) -> Optional[Tuple[float, float]]:
        """Sample-count-weighted fleet view of one calibrated constant
        → ``(value, samples)``, or None when the fleet is blind on it."""
        with self._lock:
            entries = [s["calib"].get(str(name))
                       for s in self._per_origin.values()]
        entries = [e for e in entries if e]
        if not entries:
            return None
        n = sum(e["samples"] for e in entries)
        v = sum(e["value"] * e["samples"] for e in entries) / n
        return (v, n)

    def merged_calibration_all(self) -> Dict[str, Tuple[float, float]]:
        with self._lock:
            names = {n for s in self._per_origin.values()
                     for n in s["calib"]}
        out: Dict[str, Tuple[float, float]] = {}
        for name in names:
            got = self.merged_calibration(name)
            if got is not None:
                out[name] = got
        return out

    # -- introspection -------------------------------------------------
    def origins(self) -> List[str]:
        with self._lock:
            return sorted(self._per_origin)

    def generation(self, origin: Optional[str] = None) -> int:
        with self._lock:
            s = self._per_origin.get(origin or self.origin)
            return int(s["gen"]) if s else 0

    def view(self) -> Dict[str, object]:
        """Dashboard/debug summary: per-origin generations + sizes."""
        with self._lock:
            return {o: {"gen": s["gen"], "calib": len(s["calib"]),
                        "admission": len(s["admission"])}
                    for o, s in self._per_origin.items()}


# ------------------------------------------------------- process install

_installed_lock = threading.Lock()
_installed: Optional[StateStore] = None


def install(store: Optional[StateStore]) -> None:
    """Install the process's fleet state store — the provider
    ``calibration.const`` and the scheduler's admission estimator fall
    back to. Pass None to uninstall (tests)."""
    global _installed
    with _installed_lock:
        _installed = store


def installed() -> Optional[StateStore]:
    with _installed_lock:
        return _installed


def reset_for_tests() -> None:
    global _installed
    with _installed_lock:
        _installed = None
    with _counts_lock:
        _counters.clear()
