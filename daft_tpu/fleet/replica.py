"""Replica subprocess entrypoint: one driver process of the fleet.

``python -m daft_tpu.fleet.replica --replica-id r0`` boots:

- the process-shared :class:`~daft_tpu.serving.QueryScheduler` wired to
  this replica's :class:`~daft_tpu.fleet.state_sync.StateStore` (its
  gossip origin) and, when ``DAFT_TPU_FLEET_SIDECAR`` names a store, the
  sidecar cache tier;
- the embedded Spark Connect server (query traffic; skipped cleanly
  when grpc is unavailable — the control plane still runs);
- a control HTTP plane the router drives: ``/health``, ``/gauges``,
  ``/counters``, ``/sessions``, ``/fleet/state`` (GET = export, POST =
  anti-entropy exchange: ingest the peer's snapshots, answer with ours),
  ``/drain``, ``/release_session``, ``/metrics`` (prometheus text);
- a gossip loop (``DAFT_TPU_FLEET_GOSSIP_S``) that republishes this
  replica's learned state and exchanges with every peer in
  ``DAFT_TPU_FLEET_PEERS`` (comma-separated control addresses).

On readiness it prints ``FLEET_REPLICA_READY control=<addr>
connect=<addr>`` on stdout — the line :meth:`SubprocessReplica.spawn`
waits for. SIGTERM triggers a graceful drain before exit.
"""

from __future__ import annotations

import json
import threading
from typing import List, Optional

from . import cache_tier, state_sync


def _gossip_interval_s() -> float:
    from ..analysis import knobs
    v = knobs.env_float("DAFT_TPU_FLEET_GOSSIP_S", default=None)
    if v is None:
        try:
            from ..context import get_context
            v = get_context().execution_config.tpu_fleet_gossip_s
        except Exception:
            v = 2.0
    return max(float(v), 0.05)


def _peers() -> List[str]:
    from ..analysis import knobs
    raw = knobs.env_str("DAFT_TPU_FLEET_PEERS") or ""
    return [p.strip() for p in raw.split(",") if p.strip()]


class ReplicaProcess:
    """The in-process composition of one fleet replica (also usable
    from tests without a subprocess)."""

    def __init__(self, replica_id: str, control_port: int = 0,
                 connect_port: int = 0, with_connect: bool = True):
        from .. import serving
        self.replica_id = replica_id
        self.store = state_sync.StateStore(origin=replica_id)
        state_sync.install(self.store)
        tier = cache_tier.tier_from_env()
        if tier is not None:
            cache_tier.install(tier)
        self.scheduler = serving.shared_scheduler()
        self.connect_server = None
        if with_connect:
            try:
                from ..connect import start_server
                self.connect_server = start_server(port=connect_port)
            except Exception:
                self.connect_server = None
        self._httpd = None
        self._control_port = control_port
        self._stop = threading.Event()
        self._gossip_thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- control
    @property
    def control_address(self) -> str:
        return f"127.0.0.1:{self._control_port}"

    @property
    def connect_address(self) -> str:
        if self.connect_server is None:
            return ""
        return f"127.0.0.1:{self.connect_server.port}"

    def start_control(self) -> str:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        replica = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, obj, code: int = 200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                raw = self.rfile.read(n) if n else b""
                try:
                    return json.loads(raw.decode()) if raw else {}
                except ValueError:
                    return {}

            def do_GET(self):
                try:
                    if self.path == "/health":
                        self._json(replica.health())
                    elif self.path == "/gauges":
                        self._json(replica.scheduler.gauges())
                    elif self.path == "/counters":
                        self._json(replica.counters())
                    elif self.path == "/sessions":
                        self._json({"sessions": replica.sessions()})
                    elif self.path == "/fleet/state":
                        replica.store.publish_from_engine(
                            replica.scheduler)
                        self._json(replica.store.snapshot_all())
                    elif self.path == "/metrics":
                        from .. import tracing
                        body = tracing.prometheus_text().encode()
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "text/plain; version=0.0.4")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self._json({"error": "not found"}, 404)
                except Exception as exc:  # control plane must not die
                    try:
                        self._json({"error": str(exc)}, 500)
                    except Exception:
                        pass

            def do_POST(self):
                try:
                    body = self._body()
                    if self.path == "/sql":
                        # grpc-free query path: SQL in, pydict out. The
                        # fleet smoke/bench drive subprocess replicas
                        # through this on runners without grpcio.
                        from ..serving import AdmissionRejected
                        try:
                            self._json(replica.run_sql(
                                str(body.get("sql", "")),
                                session=str(body.get("session", "http")),
                                timeout_s=float(
                                    body.get("timeout_s", 120.0))))
                        except AdmissionRejected as exc:
                            self._json({"rejected": exc.kind,
                                        "error": str(exc)}, 503)
                    elif self.path == "/fleet/state":
                        applied = replica.store.ingest_all(body)
                        replica.store.publish_from_engine(
                            replica.scheduler)
                        out = replica.store.snapshot_all()
                        out["applied"] = applied
                        self._json(out)
                    elif self.path == "/drain":
                        stats = replica.scheduler.drain(
                            float(body.get("timeout_s", 10.0)))
                        self._json(stats)
                    elif self.path == "/release_session":
                        self._json({"released": replica.release_session(
                            str(body.get("session", "")))})
                    else:
                        self._json({"error": "not found"}, 404)
                except Exception as exc:
                    try:
                        self._json({"error": str(exc)}, 500)
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer(
            ("127.0.0.1", self._control_port), Handler)
        self._httpd.daemon_threads = True
        self._control_port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever,
                             name=f"daft-tpu-fleet-ctl-{self.replica_id}",
                             daemon=True)
        t.start()
        return self.control_address

    # ------------------------------------------------------------- views
    def health(self) -> dict:
        return {"ok": True, "replica": self.replica_id,
                "draining": self.scheduler.draining}

    def sessions(self) -> List[str]:
        out = set()
        with self.scheduler._cond:
            out.update(self.scheduler._sessions)
        if self.connect_server is not None:
            out.update(self.connect_server.sessions())
        return sorted(out)

    def counters(self) -> dict:
        out = dict(self.scheduler.counters_snapshot())
        out["session_count"] = len(self.sessions())
        out["state_gen"] = self.store.generation()
        try:
            from ..analysis import lock_sanitizer
            out["lock_graph_cycles"] = \
                lock_sanitizer.counters_snapshot().get("graph_cycles", 0)
        except Exception:
            pass
        for k, v in state_sync.counters_snapshot().items():
            out[f"fleet_{k}"] = v
        return out

    def run_sql(self, sql: str, session: str = "http",
                timeout_s: float = 120.0) -> dict:
        """Plan + schedule one SQL statement through this replica's
        scheduler; returns the materialized result as a pydict plus the
        serving block (cache outcomes, admitted bytes)."""
        import daft_tpu as dt
        df = dt.sql(sql)
        h = self.scheduler.submit(df, session=session)
        ps = h.result(timeout=timeout_s)
        out = {"data": ps.to_recordbatch().to_pydict()}
        serving = getattr(h.stats, "serving", None) if h.stats else None
        if serving:
            out["serving"] = {
                k: serving[k] for k in
                ("plan_cache", "result_cache", "admitted_bytes")
                if k in serving}
        return out

    def release_session(self, session: str) -> bool:
        released = False
        if self.connect_server is not None:
            # also releases the scheduler's session queue via the
            # process-shared scheduler
            released = self.connect_server.release_session(session)
        else:
            released = self.scheduler.release_session(session)
        return released

    # ------------------------------------------------------------ gossip
    def start_gossip(self) -> None:
        peers = _peers()
        if not peers:
            return
        interval = _gossip_interval_s()

        def loop():
            import urllib.request
            while not self._stop.wait(interval):
                self.store.publish_from_engine(self.scheduler)
                own = self.store.snapshot_all()
                data = json.dumps(own).encode()
                for peer in peers:
                    if peer == self.control_address:
                        continue
                    try:
                        req = urllib.request.Request(
                            f"http://{peer}/fleet/state", data=data,
                            method="POST",
                            headers={"Content-Type": "application/json"})
                        with urllib.request.urlopen(req, timeout=2.0) as r:
                            theirs = json.loads(r.read().decode())
                        self.store.ingest_all(theirs)
                    except Exception:
                        state_sync.count("gossip_errors")
                state_sync.count("gossip_rounds")

        self._gossip_thread = threading.Thread(
            target=loop, name=f"daft-tpu-fleet-gossip-{self.replica_id}",
            daemon=True)
        self._gossip_thread.start()

    # ---------------------------------------------------------- lifecycle
    def stop(self, drain_timeout_s: float = 5.0) -> None:
        self._stop.set()
        try:
            self.scheduler.drain(drain_timeout_s)
        except Exception:
            pass
        if self.connect_server is not None:
            try:
                self.connect_server.stop(grace=1.0)
            except Exception:
                pass
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def _main() -> int:
    import argparse
    import signal

    from ..analysis import knobs
    ap = argparse.ArgumentParser()
    ap.add_argument("--replica-id",
                    default=knobs.env_str("DAFT_TPU_FLEET_REPLICA_ID")
                    or "replica-0")
    ap.add_argument("--control-port", type=int, default=0)
    ap.add_argument("--connect-port", type=int, default=0)
    ap.add_argument("--no-connect", action="store_true")
    args = ap.parse_args()

    rp = ReplicaProcess(args.replica_id, control_port=args.control_port,
                        connect_port=args.connect_port,
                        with_connect=not args.no_connect)
    rp.start_control()
    rp.start_gossip()
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: done.set())
    signal.signal(signal.SIGINT, lambda *a: done.set())
    print(f"FLEET_REPLICA_READY control={rp.control_address} "
          f"connect={rp.connect_address}", flush=True)
    done.wait()
    rp.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
