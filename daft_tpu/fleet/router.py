"""Front-door router: session-affinity placement of Connect sessions.

One consistent-hash ring (sha256 hashpoints, ``DAFT_TPU_FLEET_VNODES``
virtual nodes per replica) maps session ids onto replicas; the first
route for a session is STICKY — an assignment map pins it so ring
changes (replicas joining) never migrate a live session, which is what
keeps its plan-cache / jitted-fragment warmth on one replica. A session
moves only when its replica stops admitting:

- **death** (``kill`` or a crashed subprocess): the session re-routes to
  the next admitting replica on the ring (counter ``reroute``), and the
  raised :class:`ReplicaUnavailable` carries ``retry_after_s`` so the
  Connect front door can return structured retryable UNAVAILABLE;
- **drain** (``drain``): the replica stops admitting (its scheduler
  rejects with kind ``draining``), finishes or cooperatively cancels
  in-flight queries via their ``CancelToken``s, and every session it
  held is handed off — the router re-pins them and fires
  ``release_session`` on the old replica so the 60s idle-TTL sweep's
  work happens NOW instead of leaking re-homed queues.

The router also aggregates per-replica queue-depth / admitted-bytes
gauges into a worker-pool scale signal (``scale_signal``), the
autoscaling hook the fleet bench reports.

Replica flavors: :class:`InProcessReplica` (own scheduler + state store,
shared process — tests and the embedded fleet) and
:class:`SubprocessReplica` (a real ``fleet/replica.py`` process with its
own Connect server and control HTTP plane — the bench/CI deployment).
All router state lives under one lock; every replica call (submit,
drain, HTTP control) happens outside it.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Dict, List, Optional

from . import state_sync


class ReplicaUnavailable(RuntimeError):
    """A routed replica is dead/unreachable and no peer could take the
    query. Carries retry-info for the Connect front door's structured
    UNAVAILABLE mapping."""

    def __init__(self, message: str, replica: Optional[str] = None,
                 retry_after_s: float = 1.0):
        super().__init__(message)
        self.replica = replica
        self.retry_after_s = retry_after_s


def _hashpoint(s: str) -> int:
    return int(hashlib.sha256(s.encode()).hexdigest()[:16], 16)


class _Ring:
    """Consistent-hash ring with virtual nodes."""

    def __init__(self, vnodes: int = 64):
        self.vnodes = max(int(vnodes), 1)
        self._points: List[int] = []       # sorted hashpoints
        self._owners: Dict[int, str] = {}  # hashpoint → replica name

    def add(self, name: str) -> None:
        for i in range(self.vnodes):
            hp = _hashpoint(f"{name}#{i}")
            if hp in self._owners:
                continue
            bisect.insort(self._points, hp)
            self._owners[hp] = name

    def remove(self, name: str) -> None:
        for i in range(self.vnodes):
            hp = _hashpoint(f"{name}#{i}")
            if self._owners.get(hp) == name:
                del self._owners[hp]
                idx = bisect.bisect_left(self._points, hp)
                if idx < len(self._points) and self._points[idx] == hp:
                    del self._points[idx]

    def route(self, session: str, eligible) -> Optional[str]:
        """First vnode clockwise of the session's hashpoint owned by an
        eligible replica; walks the whole ring before giving up."""
        if not self._points:
            return None
        start = bisect.bisect_right(self._points, _hashpoint(session))
        n = len(self._points)
        for off in range(n):
            owner = self._owners[self._points[(start + off) % n]]
            if owner in eligible:
                return owner
        return None


# ---------------------------------------------------------------- replicas

class InProcessReplica:
    """One replica inside this process: its own QueryScheduler and
    StateStore (optionally a shared cache tier). GIL-bound — the unit
    the fleet tests exercise; real scale-out is SubprocessReplica."""

    def __init__(self, name: str, cache_tier=None, **scheduler_kwargs):
        from ..serving.scheduler import QueryScheduler
        self.name = name
        self.store = state_sync.StateStore(origin=name)
        self.scheduler = QueryScheduler(
            fleet_state=self.store, cache_tier=cache_tier, name=name,
            **scheduler_kwargs)
        self._alive = True

    def alive(self) -> bool:
        return self._alive

    def admitting(self) -> bool:
        return self._alive and not self.scheduler.draining

    def submit(self, query, session: str, **kw):
        if not self._alive:
            raise ReplicaUnavailable(
                f"replica {self.name!r} is dead", replica=self.name)
        return self.scheduler.submit(query, session=session, **kw)

    def sql(self, sql: str, session: str = "default",
            timeout_s: float = 120.0) -> dict:
        """SQL round-trip through this replica's scheduler — the same
        shape ``SubprocessReplica.sql`` answers over HTTP."""
        if not self._alive:
            raise ReplicaUnavailable(
                f"replica {self.name!r} is dead", replica=self.name)
        import daft_tpu as dt
        h = self.scheduler.submit(dt.sql(sql), session=session)
        ps = h.result(timeout=timeout_s)
        out = {"data": ps.to_recordbatch().to_pydict()}
        serving = getattr(h.stats, "serving", None) if h.stats else None
        if serving:
            out["serving"] = {
                k: serving[k] for k in
                ("plan_cache", "result_cache", "admitted_bytes")
                if k in serving}
        return out

    def kill(self) -> int:
        """Simulated crash: stop admitting, cooperatively cancel every
        queued and in-flight query. Returns handles signalled."""
        self._alive = False
        return self.scheduler.cancel_all("replica killed")

    def drain(self, timeout_s: float = 10.0) -> Dict[str, object]:
        return self.scheduler.drain(timeout_s)

    def release_session(self, session: str) -> bool:
        return self.scheduler.release_session(session)

    def sessions(self) -> List[str]:
        with self.scheduler._cond:
            return list(self.scheduler._sessions)

    def state_snapshot(self) -> dict:
        self.store.publish_from_engine(self.scheduler)
        return self.store.snapshot_all()

    def ingest_state(self, state: dict) -> int:
        return self.store.ingest_all(state)

    def gauges(self) -> Dict[str, float]:
        return self.scheduler.gauges()

    def counters(self) -> Dict[str, float]:
        return self.scheduler.counters_snapshot()

    def shutdown(self) -> None:
        self._alive = False
        self.scheduler.shutdown()


class SubprocessReplica:
    """A real replica process (``python -m daft_tpu.fleet.replica``):
    own interpreter, scheduler, Spark Connect server, control HTTP
    plane. The router drives control (drain / release / gossip / gauges)
    over HTTP; query traffic goes straight to ``connect_address`` via
    the Connect client — the router only picks WHICH address."""

    def __init__(self, name: str, proc, control_address: str,
                 connect_address: str, timeout_s: float = 5.0):
        self.name = name
        self.proc = proc
        self.control_address = control_address
        self.connect_address = connect_address
        self.timeout_s = timeout_s
        self._killed = False

    @classmethod
    def spawn(cls, name: str, env: Optional[Dict[str, str]] = None,
              timeout_s: float = 60.0) -> "SubprocessReplica":
        import os
        import subprocess
        import sys
        import time
        cmd = [sys.executable, "-m", "daft_tpu.fleet.replica",
               "--replica-id", name]
        e = dict(os.environ)
        e.update(env or {})
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True, env=e)
        deadline = time.monotonic() + timeout_s
        control = connect = None
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"replica {name!r} exited rc={proc.returncode} "
                        "before READY")
                continue
            if line.startswith("FLEET_REPLICA_READY"):
                for tok in line.split():
                    if tok.startswith("control="):
                        control = tok.split("=", 1)[1]
                    elif tok.startswith("connect="):
                        connect = tok.split("=", 1)[1]
                break
        if not control:
            proc.kill()
            raise RuntimeError(f"replica {name!r} never became ready")
        return cls(name, proc, control, connect or "")

    # -- control-plane HTTP -------------------------------------------
    def _url(self, path: str) -> str:
        return f"http://{self.control_address}{path}"

    def _get(self, path: str):
        import json
        import urllib.request
        with urllib.request.urlopen(self._url(path),
                                    timeout=self.timeout_s) as r:
            return json.loads(r.read().decode())

    def _post(self, path: str, obj=None):
        import json
        import urllib.request
        data = json.dumps(obj or {}).encode()
        req = urllib.request.Request(
            self._url(path), data=data, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            body = r.read().decode()
            return json.loads(body) if body else None

    def alive(self) -> bool:
        if self._killed or self.proc.poll() is not None:
            return False
        try:
            return bool(self._get("/health").get("ok"))
        except Exception:
            return False

    def admitting(self) -> bool:
        if self._killed or self.proc.poll() is not None:
            return False
        try:
            h = self._get("/health")
            return bool(h.get("ok")) and not h.get("draining")
        except Exception:
            return False

    def submit(self, query, session: str, **kw):
        raise ReplicaUnavailable(
            "subprocess replicas take traffic over Spark Connect "
            f"(address {self.connect_address!r}) or ``.sql()``, not "
            "router.submit", replica=self.name)

    def sql(self, sql: str, session: str = "default",
            timeout_s: float = 120.0) -> dict:
        """Run one SQL statement on the replica over the (grpc-free)
        control plane. ``draining``/``shutdown`` rejections and transport
        failures surface as :class:`ReplicaUnavailable` so the router
        re-routes; other admission rejections stay structured."""
        import json as _json
        import urllib.error
        import urllib.request
        if self._killed or self.proc.poll() is not None:
            raise ReplicaUnavailable(
                f"replica {self.name!r} is dead", replica=self.name)
        data = _json.dumps({"sql": sql, "session": session,
                            "timeout_s": timeout_s}).encode()
        req = urllib.request.Request(
            self._url("/sql"), data=data, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout_s + self.timeout_s) as r:
                return _json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            kind = "unavailable"
            try:
                kind = _json.loads(e.read().decode()) \
                    .get("rejected", kind)
            except Exception:
                pass
            if e.code == 503 and kind in ("draining", "shutdown"):
                raise ReplicaUnavailable(
                    f"replica {self.name!r} rejected: {kind}",
                    replica=self.name) from None
            from ..serving.scheduler import AdmissionRejected
            if e.code == 503:
                raise AdmissionRejected(
                    kind, f"replica {self.name!r} rejected: {kind}") \
                    from None
            raise
        except (urllib.error.URLError, OSError) as e:
            raise ReplicaUnavailable(
                f"replica {self.name!r} unreachable: {e}",
                replica=self.name) from None

    def kill(self) -> int:
        self._killed = True
        try:
            self.proc.kill()
        except Exception:
            pass
        return 0

    def drain(self, timeout_s: float = 10.0) -> Dict[str, object]:
        return self._post("/drain", {"timeout_s": timeout_s}) or {}

    def release_session(self, session: str) -> bool:
        try:
            r = self._post("/release_session", {"session": session})
            return bool(r and r.get("released"))
        except Exception:
            return False

    def sessions(self) -> List[str]:
        try:
            return list(self._get("/sessions").get("sessions") or [])
        except Exception:
            return []

    def state_snapshot(self) -> dict:
        return self._get("/fleet/state")

    def ingest_state(self, state: dict) -> int:
        r = self._post("/fleet/state", state)
        return int((r or {}).get("applied", 0))

    def gauges(self) -> Dict[str, float]:
        try:
            return self._get("/gauges")
        except Exception:
            return {}

    def counters(self) -> Dict[str, float]:
        try:
            return self._get("/counters")
        except Exception:
            return {}

    def shutdown(self) -> None:
        self._killed = True
        try:
            self.proc.terminate()
            self.proc.wait(timeout=10)
        except Exception:
            try:
                self.proc.kill()
            except Exception:
                pass


# ------------------------------------------------------------------ router

class FleetRouter:
    """Session-affinity router over N replicas (see module docstring)."""

    def __init__(self, replicas=None, vnodes: Optional[int] = None):
        if vnodes is None:
            from ..analysis import knobs
            vnodes = knobs.env_int("DAFT_TPU_FLEET_VNODES", default=None)
            if vnodes is None:
                try:
                    from ..context import get_context
                    vnodes = get_context().execution_config.tpu_fleet_vnodes
                except Exception:
                    vnodes = 64
        self._lock = threading.Lock()
        self._ring = _Ring(vnodes=max(int(vnodes), 1))
        self._replicas: Dict[str, object] = {}
        self._assignments: Dict[str, str] = {}  # session → replica name
        for r in (replicas or []):
            self.add_replica(r)

    # -- membership ----------------------------------------------------
    def add_replica(self, replica) -> None:
        with self._lock:
            self._replicas[replica.name] = replica
            self._ring.add(replica.name)

    def remove_replica(self, name: str) -> None:
        with self._lock:
            self._replicas.pop(name, None)
            self._ring.remove(name)
            for sess, owner in list(self._assignments.items()):
                if owner == name:
                    del self._assignments[sess]

    def replicas(self) -> List[object]:
        with self._lock:
            return list(self._replicas.values())

    def replica(self, name: str):
        with self._lock:
            return self._replicas.get(name)

    # -- routing -------------------------------------------------------
    def _admitting_names(self) -> set:
        # liveness probes may do IO (subprocess health checks) — never
        # under the router lock
        with self._lock:
            reps = list(self._replicas.values())
        return {r.name for r in reps if r.admitting()}

    def route(self, session: str):
        """The replica owning ``session`` — sticky while its replica
        admits, re-pinned (counter ``reroute``) when it doesn't."""
        eligible = self._admitting_names()
        with self._lock:
            owner = self._assignments.get(session)
            if owner is not None and owner in eligible:
                return self._replicas[owner]
            target = self._ring.route(session, eligible)
            if target is None:
                raise ReplicaUnavailable(
                    "no admitting replica in the fleet",
                    replica=owner, retry_after_s=1.0)
            self._assignments[session] = target
            rep = self._replicas[target]
        state_sync.count("route")
        if owner is not None and owner != target:
            state_sync.count("reroute")
        return rep

    def submit(self, query, session: str = "default", **kw):
        """Route + submit with one re-route retry: a replica that died
        or began draining between the route and the submit hands the
        query to the next admitting peer."""
        from .. import tracing
        from ..serving.scheduler import AdmissionRejected
        last: Optional[BaseException] = None
        for _attempt in range(2):
            rep = self.route(session)  # raises when the fleet is empty
            try:
                with tracing.span("fleet:route", lane="serving"):
                    h = rep.submit(query, session=session, **kw)
            except ReplicaUnavailable as exc:
                last = exc
                self._forget(session, rep.name)
                continue
            err = h._error if h.done() and h.state == "rejected" else None
            if isinstance(err, AdmissionRejected) \
                    and err.kind in ("draining", "shutdown"):
                last = err
                self._forget(session, rep.name)
                continue
            return h
        raise last if isinstance(last, ReplicaUnavailable) else \
            ReplicaUnavailable(f"submit failed after re-route: {last}",
                               retry_after_s=1.0)

    def sql(self, sql: str, session: str = "default",
            timeout_s: float = 120.0) -> dict:
        """Route + run one SQL statement (the grpc-free traffic path the
        fleet bench/smoke drive), with the same one-retry re-route as
        :meth:`submit` on a replica that died or began draining."""
        from .. import tracing
        last: Optional[BaseException] = None
        for _attempt in range(2):
            rep = self.route(session)
            try:
                with tracing.span("fleet:route", lane="serving"):
                    return rep.sql(sql, session=session,
                                   timeout_s=timeout_s)
            except ReplicaUnavailable as exc:
                last = exc
                self._forget(session, rep.name)
        raise last if last is not None else ReplicaUnavailable(
            "sql failed after re-route", retry_after_s=1.0)

    def _forget(self, session: str, owner: str) -> None:
        with self._lock:
            if self._assignments.get(session) == owner:
                del self._assignments[session]
        state_sync.count("reroute")

    def assignments(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._assignments)

    # -- lifecycle -----------------------------------------------------
    def kill(self, name: str) -> Dict[str, object]:
        """Replica death: cancel its in-flight queries, re-home its
        sessions (they re-route on their next submit)."""
        rep = self.replica(name)
        if rep is None:
            return {"killed": False}
        cancelled = rep.kill()
        moved = self._handoff(name)
        state_sync.count("kill")
        return {"killed": True, "cancelled": cancelled,
                "sessions_moved": moved}

    def drain(self, name: str, timeout_s: Optional[float] = None
              ) -> Dict[str, object]:
        """Graceful drain: the replica stops admitting, finishes or
        cancels in-flight work, and hands its sessions off — with an
        immediate ``release_session`` on the old replica so re-homed
        sessions don't wait out the 60s idle TTL."""
        from .. import tracing
        if timeout_s is None:
            from ..analysis import knobs
            timeout_s = knobs.env_float("DAFT_TPU_FLEET_DRAIN_TIMEOUT",
                                        default=None)
            if timeout_s is None:
                try:
                    from ..context import get_context
                    timeout_s = get_context() \
                        .execution_config.tpu_fleet_drain_timeout
                except Exception:
                    timeout_s = 10.0
        rep = self.replica(name)
        if rep is None:
            return {"drained": False}
        with tracing.span("fleet:drain", lane="serving"):
            sessions = rep.sessions()
            stats = rep.drain(float(timeout_s))
            moved = self._handoff(name, sessions=sessions, release=rep)
        state_sync.count("drain")
        out = {"drained": True, "sessions_moved": moved}
        out.update(stats or {})
        return out

    def _handoff(self, name: str, sessions: Optional[List[str]] = None,
                 release=None) -> int:
        """Unpin every session assigned to ``name`` (next submit
        re-routes); optionally fire release_session on the old replica."""
        with self._lock:
            doomed = [s for s, o in self._assignments.items() if o == name]
            for s in doomed:
                del self._assignments[s]
        for s in set(doomed) | set(sessions or []):
            if release is not None:
                try:
                    release.release_session(s)
                except Exception:
                    pass
            state_sync.count("handoff_sessions")
        return len(doomed)

    # -- learned-state gossip ------------------------------------------
    def gossip_round(self) -> int:
        """One anti-entropy round: pull every live replica's full state,
        keep the newest snapshot per origin, push the union back.
        Returns origin snapshots applied across the fleet."""
        with self._lock:
            reps = list(self._replicas.values())
        reps = [r for r in reps if r.alive()]
        merged: Dict[str, dict] = {}
        for r in reps:
            try:
                snaps = (r.state_snapshot() or {}).get("origins") or {}
            except Exception:
                state_sync.count("gossip_errors")
                continue
            for origin, snap in snaps.items():
                cur = merged.get(origin)
                if cur is None or int(snap.get("gen", 0)) \
                        > int(cur.get("gen", 0)):
                    merged[origin] = snap
        applied = 0
        for r in reps:
            try:
                applied += r.ingest_state({"origins": merged})
            except Exception:
                state_sync.count("gossip_errors")
        state_sync.count("gossip_rounds")
        return applied

    # -- observability + autoscaling hooks -----------------------------
    def gauges(self) -> Dict[str, object]:
        """Per-replica gauges + fleet aggregates (the /api/fleet view)."""
        with self._lock:
            reps = list(self._replicas.values())
        per: Dict[str, Dict[str, float]] = {}
        for r in reps:
            try:
                per[r.name] = dict(r.gauges() or {})
            except Exception:
                per[r.name] = {}
            per[r.name]["alive"] = 1.0 if r.alive() else 0.0
        agg = {k: sum(g.get(k, 0.0) for g in per.values())
               for k in ("queued", "running", "admitted_bytes",
                         "concurrency", "sessions")}
        agg["replicas"] = float(len(per))
        agg["replicas_admitting"] = float(
            sum(1 for g in per.values()
                if g.get("alive") and not g.get("draining")))
        return {"replicas": per, "aggregate": agg,
                "assignments": len(self.assignments()),
                "scale_signal": self._scale_signal(agg)}

    @staticmethod
    def _scale_signal(agg: Dict[str, float]) -> Dict[str, float]:
        """Worker-pool scale signal: desired replica count from demand
        (queued + running) vs per-replica concurrency, with a ±1
        hysteresis band so a transient queue blip doesn't flap the pool."""
        admitting = max(agg.get("replicas_admitting", 0.0), 1.0)
        per_replica = max(
            agg.get("concurrency", 0.0) / max(agg.get("replicas", 1.0), 1.0),
            1.0)
        demand = agg.get("queued", 0.0) + agg.get("running", 0.0)
        desired = max(1.0, float(-(-demand // per_replica)))  # ceil
        if abs(desired - admitting) <= 1.0:
            desired = admitting
        return {"demand": demand, "per_replica_slots": per_replica,
                "desired_replicas": desired,
                "utilization": demand / (admitting * per_replica)}

    def scale_signal(self) -> Dict[str, float]:
        return self.gauges()["scale_signal"]

    def shutdown(self) -> None:
        with self._lock:
            reps = list(self._replicas.values())
        for r in reps:
            try:
                r.shutdown()
            except Exception:
                pass
