"""Calibrated cost-model profile: measured history beats footer estimates.

The engine records everything — per-dispatch achieved rates in the MFU
ledger, shuffle wire rates at every fetch, per-query stat blocks in the
flight recorder — and until round 20 used none of it for the next query:
every ``costmodel.*_wins`` decision was priced from hard-coded dev-box
constants (``DEV_*_BPS``). This module closes loop (a) of the self-tuning
plan (ROADMAP item 4): a per-backend profile of OBSERVED constants,
learned with an EWMA update rule and persisted across processes
(``DAFT_TPU_CALIBRATION_DIR``), that overrides the hard-coded defaults
once a sample-count floor is met.

Calibrated names (one entry each, same units as the costmodel constant):

- ``DEV_VECTOR_BPS`` / ``DEV_AGG_BPS`` / ``DEV_AGG_HASH_BPS`` — achieved
  device bytes/s per kernel family+strategy, observed at every real
  dispatch through ``costmodel.ledger_record``;
- ``DEV_SORT_ROWS_PER_S`` / ``DEV_JOIN_ROWS_PER_S`` /
  ``DEV_JOIN_HASH_ROWS_PER_S`` — achieved rows/s, same chokepoint;
- ``SHUFFLE_WIRE_BPS`` — achieved shuffle-fetch bytes/s, observed at
  ``shuffle_service.fetch_partition`` (sizable fetches only: tiny
  partitions measure RTT, not bandwidth);
- ``ICI_BPS`` — the marginal collective-exchange rate, observed whenever
  ``costmodel._measure_ici`` runs;
- ``NDV_FOOTER_RATIO`` — observed actual-groups / footer-NDV ratio
  (parquet min/max range NDV systematically OVER-predicts: a sparse key
  set reads as near-unique). ``shuffle_combine_wins`` and
  ``groupby_strategy`` damp footer NDV evidence by this ratio.

Contract with the chaos-determinism rules (r10/r14): under
``DAFT_TPU_CHAOS_SERIALIZE=1`` or an active fault plan the profile is
FROZEN — ``const()`` returns the hard-coded default and ``observe()``
drops the sample — so a chaos replay prices every decision exactly like
the recorded run, bit-identically.

Everything is gated on ``DAFT_TPU_CALIBRATION`` (default off; the
``ExecutionConfig.tpu_calibration`` mirror is the per-query spelling):
with the knob off this module is a handful of dict lookups returning
defaults, and the observation chokepoints are no-ops.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Dict, Optional

#: observations between opportunistic persists (plus a time throttle) —
#: a hot query must not fsync the profile per dispatch
_PERSIST_EVERY = 32
_PERSIST_MIN_INTERVAL_S = 5.0

#: calibrated-NDV damping is clamped: a ratio below this would let one
#: freak observation erase footer evidence entirely, above it would
#: inflate footer NDV past the row count the caller already clamps to
_NDV_RATIO_MIN = 1.0 / 64.0
_NDV_RATIO_MAX = 4.0

_lock = threading.Lock()
_profile: Optional[Dict[str, Dict[str, float]]] = None  # name → entry
_obs_since_persist = 0
_last_persist = 0.0
_history_ingested = False
_atexit_registered = False


# ------------------------------------------------------------------ knobs

def _cfg(field: str, default):
    try:
        from ..context import get_context
        return getattr(get_context().execution_config, field)
    except Exception:
        return default


def enabled() -> bool:
    """Master gate: env ``DAFT_TPU_CALIBRATION`` overrides the per-query
    ``ExecutionConfig.tpu_calibration`` mirror; default off."""
    from ..analysis import knobs
    raw = knobs.env_raw("DAFT_TPU_CALIBRATION")
    if raw is not None:
        return bool(knobs.env_bool("DAFT_TPU_CALIBRATION"))
    return bool(_cfg("tpu_calibration", False))


def frozen() -> bool:
    """Feedback state is frozen (reads return defaults, observations are
    dropped) whenever the chaos-determinism contract is active: replay
    must price every decision exactly like the recorded run."""
    from ..analysis import knobs
    if knobs.env_bool("DAFT_TPU_CHAOS_SERIALIZE"):
        return True
    try:
        from ..distributed.resilience import active_fault_plan
        return active_fault_plan() is not None
    except Exception:
        return False


def alpha() -> float:
    from ..analysis import knobs
    a = knobs.env_float("DAFT_TPU_CALIBRATION_ALPHA", default=None)
    if a is None:
        a = _cfg("tpu_calibration_alpha", 0.2)
    return min(max(float(a), 1e-3), 1.0)


def min_samples() -> int:
    from ..analysis import knobs
    n = knobs.env_int("DAFT_TPU_CALIBRATION_MIN_SAMPLES", default=None)
    if n is None:
        n = _cfg("tpu_calibration_min_samples", 8)
    return max(int(n), 1)


def profile_dir() -> Optional[str]:
    from ..analysis import knobs
    d = knobs.env_str("DAFT_TPU_CALIBRATION_DIR")
    if not d:
        d = _cfg("tpu_calibration_dir", "") or None
    return d or None


def _backend_name() -> str:
    try:
        from . import backend
        return backend.backend_name() or "cpu"
    except Exception:
        return "cpu"


def _path() -> Optional[str]:
    d = profile_dir()
    if not d:
        return None
    return os.path.join(d, f"calibration_{_backend_name()}.json")


# ------------------------------------------------------------- load/store

def _read_profile_file() -> Dict[str, Dict[str, float]]:
    """Parse the persisted profile (no locks held — pure file read)."""
    out: Dict[str, Dict[str, float]] = {}
    path = _path()
    if path:
        try:
            with open(path) as f:
                d = json.load(f)
            for name, e in (d.get("entries") or {}).items():
                v, n = float(e["value"]), float(e["samples"])
                if math.isfinite(v) and v > 0 and n > 0:
                    out[name] = {"value": v, "samples": n}
        except (OSError, ValueError, KeyError, TypeError):
            pass
    return out


def _ensure_loaded() -> None:
    """Lazy one-time profile load. The file read happens OUTSIDE the
    lock (a duplicate read in a race is harmless; first install wins).
    After the install, flight-recorder history seeds the profile once —
    the 'fresh processes start calibrated' channel (the nested
    ``observe``/``const`` calls the ingest makes re-enter here and
    return immediately on the installed profile)."""
    global _profile
    if _profile is None:
        loaded = _read_profile_file()
        with _lock:
            if _profile is None:
                _profile = loaded
    # not tied to the install above: a load that happened while
    # calibration was disabled must not skip the ingest forever (the
    # latch is set inside ingest_flight_history, before it observes,
    # so the nested re-entry from its own observe() calls is a no-op)
    if not _history_ingested and enabled() and not frozen():
        ingest_flight_history()


def _load_locked() -> Dict[str, Dict[str, float]]:
    """The live profile dict; callers hold ``_lock`` and have called
    :func:`_ensure_loaded` first."""
    global _profile
    if _profile is None:
        # daft-lint: allow(unguarded-global-mutation) -- inside _lock at
        # every call site; the empty-dict install is a benign fallback
        # for callers that skipped _ensure_loaded
        _profile = {}
    return _profile


def _persist(snapshot: Dict[str, Dict[str, float]]) -> None:
    """Atomic profile write (outside the lock: the caller passes a
    snapshot). Best-effort — calibration must never fail a query."""
    path = _path()
    if not path:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"backend": _backend_name(), "ts": time.time(),
                       "entries": snapshot}, f)
        os.replace(tmp, path)
    except OSError:
        pass


def ingest_flight_history(limit: int = 200) -> int:
    """Seed the profile from flight-recorder history
    (``DAFT_TPU_QUERY_LOG``): each persisted query's ``device_kernels``
    block carries per-family achieved bytes/rows/seconds — the same
    evidence ``ledger_record`` observes live, recovered from disk so a
    fresh process starts calibrated. Returns observations ingested."""
    global _history_ingested
    if not enabled() or frozen():
        # do NOT latch: a call while disabled/frozen must not burn the
        # one-time ingest a later enabled process would want
        return 0
    with _lock:
        if _history_ingested:
            return 0
        _history_ingested = True
    try:
        from .. import tracing
        entries = tracing.flight_history(limit=limit)
    except Exception:
        return 0
    n = 0
    for entry in entries:
        dk = entry.get("device_kernels")
        if not isinstance(dk, dict):
            continue
        for kind, d in dk.items():
            if not isinstance(d, dict):
                continue
            try:
                n += _observe_family(
                    kind, d.get("strategy"),
                    rows=float(d.get("rows", 0) or 0),
                    nbytes=float(d.get("bytes", 0) or 0),
                    seconds=float(d.get("seconds", 0) or 0),
                    dispatches=float(d.get("dispatches", 1) or 1))
            except (TypeError, ValueError):
                continue
    return n


# ---------------------------------------------------------------- updates

def observe(name: str, value: float, weight: float = 1.0) -> None:
    """Fold one observed constant sample into the profile (EWMA with an
    effective weight: ``w`` repeated samples collapse to one update).
    No-op when calibration is off or frozen."""
    global _obs_since_persist, _last_persist
    if not enabled() or frozen():
        return
    try:
        value = float(value)
        weight = float(weight)
    except (TypeError, ValueError):
        return
    if not math.isfinite(value) or value <= 0 or weight <= 0:
        return
    global _atexit_registered
    persist_snap = None
    _ensure_loaded()
    with _lock:
        if not _atexit_registered and profile_dir():
            # short-lived processes must not lose the tail of their
            # observations to the persist throttle
            import atexit
            atexit.register(flush)
            _atexit_registered = True
        prof = _load_locked()
        e = prof.get(name)
        if e is None:
            prof[name] = {"value": value, "samples": weight}
        else:
            a = 1.0 - (1.0 - alpha()) ** weight
            e["value"] += a * (value - e["value"])
            e["samples"] += weight
        _obs_since_persist += 1
        now = time.monotonic()
        # BOTH throttles must clear: enough new observations AND a
        # minimum interval elapsed — a hot query must not rewrite the
        # profile file many times per second, and the atexit flush
        # covers whatever a short-lived process accumulates under it
        if _obs_since_persist >= _PERSIST_EVERY \
                and now - _last_persist > _PERSIST_MIN_INTERVAL_S:
            _obs_since_persist = 0
            _last_persist = now
            persist_snap = {k: dict(v) for k, v in prof.items()}
    from ..physical import adaptive
    adaptive.count("calibration_observations")
    if persist_snap is not None:
        _persist(persist_snap)


def flush() -> None:
    """Persist the current profile now (atexit hook / tests / ops)."""
    global _obs_since_persist
    with _lock:
        if _profile is None:
            return
        _obs_since_persist = 0
        snap = {k: dict(v) for k, v in _profile.items()}
    _persist(snap)


_FAMILY_BYTES = {("grouped_agg", "hash"): "DEV_AGG_HASH_BPS",
                 ("grouped_agg", "sort"): "DEV_AGG_BPS",
                 ("grouped_agg", None): "DEV_AGG_BPS",
                 ("projection", None): "DEV_VECTOR_BPS"}
_FAMILY_ROWS = {("argsort", None): "DEV_SORT_ROWS_PER_S",
                ("join", "hash"): "DEV_JOIN_HASH_ROWS_PER_S",
                ("join", "sort"): "DEV_JOIN_ROWS_PER_S",
                ("join", None): "DEV_JOIN_ROWS_PER_S"}

#: dispatches below these floors measure launch overhead / RTT, not the
#: kernel rate the constants model — skip them
_MIN_OBS_BYTES = 1 << 16
_MIN_OBS_ROWS = 1 << 12
_MIN_OBS_SECONDS = 1e-5


def _observe_family(kind: str, strategy: Optional[str], rows: float,
                    nbytes: float, seconds: float,
                    dispatches: float = 1.0) -> int:
    """One ledger-shaped observation → the matching calibrated constant
    (per-dispatch achieved rate, dispatch overhead subtracted so a small
    batch doesn't read as a slow kernel). Returns 1 when recorded."""
    if seconds <= _MIN_OBS_SECONDS or dispatches <= 0:
        return 0
    skey = strategy if strategy in ("hash", "sort") else None
    from . import costmodel
    eff_s = max(seconds - costmodel.DEV_DISPATCH_S * dispatches,
                seconds * 0.1)
    name = _FAMILY_BYTES.get((kind, skey)) or _FAMILY_BYTES.get((kind, None))
    if name is not None and nbytes >= _MIN_OBS_BYTES:
        observe(name, nbytes / eff_s, weight=dispatches)
        return 1
    name = _FAMILY_ROWS.get((kind, skey)) or _FAMILY_ROWS.get((kind, None))
    if name is not None and rows >= _MIN_OBS_ROWS:
        observe(name, rows / eff_s, weight=dispatches)
        return 1
    return 0


def observe_dispatch(kind: str, strategy: Optional[str], rows: float,
                     nbytes: float, seconds: float,
                     dispatches: float = 1.0) -> None:
    """Live chokepoint, called by ``costmodel.ledger_record`` at every
    real dispatch. Cheap gate first: the common (calibration-off) path
    is one function call and a dict read."""
    if not enabled():
        return
    _observe_family(kind, strategy, rows=rows, nbytes=nbytes,
                    seconds=seconds, dispatches=dispatches)


# ------------------------------------------------------------------ reads

def const(name: str, default: float) -> float:
    """The calibrated value for ``name`` when the profile has one past
    the sample floor (and calibration is on and not frozen); else the
    caller's hard-coded default. This is THE read every costmodel
    decision site routes through. When the local profile is blind a
    gossiped fleet view (sample-weighted over replica origins,
    ``fleet/state_sync``) beats the hard-coded default — this is how a
    cold replica's first query prices like a warm one."""
    if not enabled() or frozen():
        return default
    _ensure_loaded()
    with _lock:
        e = _load_locked().get(name)
        if e is not None and e["samples"] >= min_samples():
            return e["value"]
    # outside _lock: the fleet store has its own lock and must not nest
    # under the profile lock
    fleet = _fleet_const(name)
    return default if fleet is None else fleet


def _fleet_const(name: str) -> Optional[float]:
    """Merged fleet-history value for ``name`` past the sample floor, or
    None when no fleet state store is installed / the fleet is blind."""
    try:
        from ..fleet import state_sync
        st = state_sync.installed()
        if st is None:
            return None
        got = st.merged_calibration(name)
        if got is None:
            return None
        value, samples = got
        if samples < min_samples():
            return None
        state_sync.count("calibration_fleet_reads")
        return float(value)
    except Exception:
        return None


def profile_entries() -> Dict[str, Dict[str, float]]:
    """Copy of the learned profile ``{name: {value, samples}}`` — the
    gossip export consumed by ``fleet/state_sync``."""
    _ensure_loaded()
    with _lock:
        return {k: dict(v) for k, v in _load_locked().items()}


def _quantize(v: float) -> str:
    # 2 significant digits: EWMA nudges within a few percent keep the
    # plan token (and therefore the plan cache) stable
    try:
        return f"{float(v):.1e}"
    except (TypeError, ValueError):
        return "?"


def plan_token() -> str:
    """Calibration-generation token folded into plan fingerprints
    (``logical/fingerprint.py``): a quantized digest of every constant
    ACTIVELY overriding its default right now. When a calibrated value
    crosses the sample floor or moves materially, the token changes and
    cached plans priced under the old constants are invalidated —
    without it, r20's calibrated flips (combine gating, kernel strategy,
    fusion pricing) kept serving stale pre-calibration plans. Empty when
    calibration is off/frozen or nothing is active, so the common path
    leaves fingerprints untouched."""
    if not enabled() or frozen():
        return ""
    floor = min_samples()
    _ensure_loaded()
    with _lock:
        prof = {k: dict(v) for k, v in _load_locked().items()}
    active = {n: _quantize(e["value"]) for n, e in prof.items()
              if e["samples"] >= floor}
    # fleet-inherited constants flip the same decisions local ones do
    try:
        from ..fleet import state_sync
        st = state_sync.installed()
    except Exception:
        st = None
    if st is not None:
        for n, (v, samples) in st.merged_calibration_all().items():
            if n not in active and samples >= floor:
                active[n] = _quantize(v)
    if not active:
        return ""
    import hashlib
    blob = ",".join(f"{n}={active[n]}" for n in sorted(active))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def ndv_ratio() -> float:
    """Clamped damping factor for parquet-footer NDV evidence (1.0 =
    trust the footer; the observed actual/footer ratio once calibrated)."""
    r = const("NDV_FOOTER_RATIO", 1.0)
    return min(max(r, _NDV_RATIO_MIN), _NDV_RATIO_MAX)


def summary(defaults: Optional[Dict[str, float]] = None
            ) -> Dict[str, Dict[str, object]]:
    """Profile snapshot for explain/tests: per constant the learned
    value, sample count, and whether it is ACTIVE (overriding the
    default) right now."""
    if defaults is None:
        defaults = costmodel_defaults()
    on = enabled() and not frozen()
    floor = min_samples()
    _ensure_loaded()
    with _lock:
        prof = {k: dict(v) for k, v in _load_locked().items()}
    out: Dict[str, Dict[str, object]] = {}
    for name, default in defaults.items():
        e = prof.pop(name, None)
        out[name] = {
            "default": default,
            "value": e["value"] if e else None,
            "samples": e["samples"] if e else 0,
            "active": bool(on and e and e["samples"] >= floor),
        }
    for name, e in prof.items():  # learned names outside the default map
        out[name] = {"default": None, "value": e["value"],
                     "samples": e["samples"],
                     "active": bool(on and e["samples"] >= floor)}
    return out


def costmodel_defaults() -> Dict[str, float]:
    """The hard-coded constants the profile can override, single-sourced
    from the costmodel module attributes."""
    from ..analysis import knobs
    from . import costmodel as cm
    return {
        "DEV_VECTOR_BPS": cm.DEV_VECTOR_BPS,
        "DEV_AGG_BPS": cm.DEV_AGG_BPS,
        "DEV_AGG_HASH_BPS": cm.DEV_AGG_HASH_BPS,
        "DEV_SORT_ROWS_PER_S": cm.DEV_SORT_ROWS_PER_S,
        "DEV_JOIN_ROWS_PER_S": cm.DEV_JOIN_ROWS_PER_S,
        "DEV_JOIN_HASH_ROWS_PER_S": cm.DEV_JOIN_HASH_ROWS_PER_S,
        "SHUFFLE_WIRE_BPS":
            (knobs.REGISTRY["DAFT_TPU_SHUFFLE_WIRE_MBPS"].default or 1000.0)
            * 1e6,
        "ICI_BPS": cm._ICI_FALLBACK_BPS,
        "NDV_FOOTER_RATIO": 1.0,
    }


def calibrated_names() -> list:
    """Names currently overriding their defaults (sorted) — what
    ``explain(analyze=True)`` shows as calibrated-vs-default."""
    return sorted(n for n, d in summary().items() if d["active"])


def reset_for_tests() -> None:
    global _profile, _obs_since_persist, _last_persist, _history_ingested
    with _lock:
        _profile = None
        _obs_since_persist = 0
        _last_persist = 0.0
        _history_ingested = False
