"""Achieved-utilization measurement for the device kernel families.

Rows/s says nothing about how close a kernel runs to the silicon, so this
module reports the two currencies that do (BASELINE's "TPU-efficient"
criterion; the public scaling-book framing):

- **MFU** for the MXU-shaped grouped-agg kernel: its one-hot matmul has
  statically known dims (``[C, out_cap]`` accumulation), so FLOPs are
  exact: ``2 * C * out_cap`` per reduced value plane.
- **Roofline %** (achieved bytes/s vs HBM bandwidth) for the
  memory-bound families: sort-based join phases and multi-key argsort —
  their arithmetic is negligible; the ceiling is HBM traffic.

Timing methodology on a (possibly tunneled) chip: inputs are made
device-resident first, K dispatches are issued back-to-back and ONE final
``block_until_ready`` fences — dispatch is async, so tunnel RTT amortizes
to ~1/K per run. The first (compile) pass is excluded.

Peaks default to TPU v5e public specs and are env-overridable for other
chips: ``DAFT_TPU_PEAK_FLOPS`` (bf16-class peak, 197e12) and
``DAFT_TPU_HBM_BPS`` (819e9).
"""

from __future__ import annotations

import os
import time
from typing import Dict

import numpy as np

import jax
import jax.numpy as jnp

from . import kernels


def _peak_flops() -> float:
    return float(os.environ.get("DAFT_TPU_PEAK_FLOPS", 197e12))


def _hbm_bps() -> float:
    return float(os.environ.get("DAFT_TPU_HBM_BPS", 819e9))


def _timed(fn, args, iters: int = 8) -> float:
    """Median-free amortized timing: one warm (compile) pass, then
    ``iters`` async dispatches fenced once. Returns seconds per run."""
    out = fn(*args)
    jax.tree_util.tree_map(
        lambda x: x.block_until_ready() if hasattr(x, "block_until_ready")
        else x, out)
    t0 = time.perf_counter()
    last = None
    for _ in range(iters):
        last = fn(*args)
    jax.tree_util.tree_map(
        lambda x: x.block_until_ready() if hasattr(x, "block_until_ready")
        else x, last)
    return (time.perf_counter() - t0) / iters


def measure_grouped_agg(n: int = 1 << 20, groups: int = 256,
                        n_vals: int = 2) -> Dict:
    """MFU of the one-hot-matmul grouped aggregation (the TPC-H Q1 shape:
    few groups, several reduced value planes)."""
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, groups, n).astype(np.int64))
    valid = jnp.ones(n, dtype=bool)
    vals = tuple(jnp.asarray(rng.uniform(0, 100, n).astype(np.float32))
                 for _ in range(n_vals))
    mask = jnp.ones(n, dtype=bool)
    out_cap = max(256, groups)
    ops = ("sum",) * n_vals

    import functools
    fn = jax.jit(functools.partial(
        kernels.grouped_agg_block_impl, ops=ops, out_cap=out_cap))
    t = _timed(lambda k, kv, v, vv, m: fn((k,), (kv,), v, vv, m),
               (keys, valid, vals, (valid,) * n_vals, mask))
    # one-hot matmul: 2*C*out_cap FLOPs per accumulated plane (values +
    # the count plane the kernel always reduces). At TPC-H-like shapes
    # (many rows, few groups) the kernel is SORT/bandwidth-bound, not
    # FLOP-bound — so the bytes-based roofline is reported alongside MFU
    # (key sort ~2 passes over key+index planes, one read of each value
    # plane; the one-hot matrix is fused by XLA, never materialized).
    flops = 2.0 * n * out_cap * (n_vals + 1)
    bytes_touched = 2 * n * (8 + 4) + (n_vals + 1) * n * 4
    return {"kernel": "grouped_agg_matmul", "rows": n, "groups": groups,
            "time_s": round(t, 6), "flops": flops,
            "achieved_tflops": round(flops / t / 1e12, 3),
            "mfu_pct": round(100.0 * flops / t / _peak_flops(), 3),
            "achieved_gbps": round(bytes_touched / t / 1e9, 2),
            "roofline_pct": round(
                100.0 * bytes_touched / t / _hbm_bps(), 3)}


def measure_join_phases(n: int = 1 << 20) -> Dict:
    """Roofline % of the sort-merge join pipeline (sort + searchsorted +
    expand). Bytes model: the dominant traffic is the right-side key sort
    (~2 passes over key+index planes), the two searchsorted probes, and
    the expansion gathers — counted once each, a LOWER bound on true
    traffic (so the reported roofline is conservative)."""
    rng = np.random.default_rng(1)
    r_key = jnp.asarray(rng.integers(0, n // 2, n).astype(np.int64))
    l_key = jnp.asarray(rng.integers(0, n // 2, n).astype(np.int64))
    ones = jnp.ones(n, dtype=bool)

    def pipeline(lk, lv, lm, rk, rv, rm):
        rs, rperm, rcnt = kernels.join_phase_sort(rk, rv, rm)
        counts, starts, total = kernels.join_phase_count(lk, lv, lm, rs,
                                                         rcnt)
        return kernels.join_phase_expand(counts, starts, rperm, rk.shape[0])

    t = _timed(pipeline, (l_key, ones, ones, r_key, ones, ones))
    bytes_touched = (
        2 * (n * 8 + n * 4)        # sort: ~2 passes over key + perm
        + 2 * n * 8                # two searchsorted probes of the keys
        + 3 * n * 4)               # expand: counts/starts/idx planes
    return {"kernel": "join_phases", "rows": n, "time_s": round(t, 6),
            "bytes": bytes_touched,
            "achieved_gbps": round(bytes_touched / t / 1e9, 2),
            "roofline_pct": round(
                100.0 * bytes_touched / t / _hbm_bps(), 3)}


def measure_argsort(n: int = 1 << 20, n_keys: int = 2) -> Dict:
    """Roofline % of the multi-key argsort behind ORDER BY / window
    partitioning. Bytes model: log2(n) merge passes are internal to XLA's
    bitonic sort; we count the documented-minimum 2 passes per operand
    (read + write) times the operand planes — conservative."""
    rng = np.random.default_rng(2)
    keys = tuple(jnp.asarray(rng.uniform(0, 1e6, n).astype(np.float32))
                 for _ in range(n_keys))
    ones = jnp.ones(n, dtype=bool)

    def fn(*ks):
        return kernels.argsort_kernel(
            ks, (ones,) * n_keys, ones,
            tuple(False for _ in range(n_keys)),
            tuple(False for _ in range(n_keys)))

    t = _timed(fn, keys)
    bytes_touched = 2 * n * (4 * n_keys + 4)
    return {"kernel": "argsort_multikey", "rows": n,
            "time_s": round(t, 6), "bytes": bytes_touched,
            "achieved_gbps": round(bytes_touched / t / 1e9, 2),
            "roofline_pct": round(
                100.0 * bytes_touched / t / _hbm_bps(), 3)}


def report(n: int = 1 << 20) -> Dict:
    """All kernel families; the bench device child embeds this in its
    detail and the compact summary carries the two headline numbers."""
    out = {"peak_flops": _peak_flops(), "hbm_bps": _hbm_bps()}
    try:
        out["grouped_agg"] = measure_grouped_agg(n)
        out["join"] = measure_join_phases(n)
        out["argsort"] = measure_argsort(n)
    except Exception as exc:  # a wedged backend must not kill the bench
        out["error"] = str(exc)[:200]
    return out
