"""Achieved-utilization measurement for the device kernel families.

Rows/s says nothing about how close a kernel runs to the silicon, so this
module reports the two currencies that do (BASELINE's "TPU-efficient"
criterion; the public scaling-book framing):

- **MFU** for the MXU-shaped grouped-agg kernel: its one-hot matmul has
  statically known dims (``[C, out_cap]`` accumulation), so FLOPs are
  exact: ``2 * C * out_cap`` per reduced value plane.
- **Roofline %** (achieved bytes/s vs HBM bandwidth) for the
  memory-bound families: the fused sort-merge join and the packed-key
  multi-key argsort — their arithmetic is negligible; the ceiling is HBM
  traffic.

Timing methodology (round 6, after the r5 postmortem: back-to-back async
dispatches did NOT amortize a tunneled chip's RTT, and the recorded
0.23%-of-roofline "argsort" number was measuring the wire): repetition
now runs INSIDE one jit program — ``lax.fori_loop`` over K kernel
iterations with a loop-carried input perturbation so XLA's while-loop
invariant code motion cannot hoist the kernel out of the loop. One
dispatch + one fence covers K iterations; per-iteration time is silicon
plus 1/K of one round trip.

Byte models are conservative LOWER bounds (≥2 passes per sorted operand
plane; one read per input plane), so reported roofline percentages are
under-, never over-stated.

This module also carries the **byte/flop models** the per-dispatch MFU
ledger (``costmodel.ledger_record``) prices real engine dispatches with —
single-sourced here so the synthetic benchmarks and the production ledger
can never disagree on the model.

Peaks default to TPU v5e public specs and are env-overridable for other
chips: ``DAFT_TPU_PEAK_FLOPS`` (bf16-class peak, 197e12) and
``DAFT_TPU_HBM_BPS`` (819e9); both live in ``costmodel``.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import costmodel, kernels

_peak_flops = costmodel.peak_flops
_hbm_bps = costmodel.hbm_bps

#: in-jit repetitions per measurement — per-iteration time carries 1/K of
#: one dispatch + round trip
_ITERS = 16


# ------------------------------------------------------------ byte models

def argsort_bytes_model(cap: int, dtypes: Sequence) -> int:
    """Modeled HBM traffic of one packed-key argsort over ``cap`` rows:
    one read of each raw key plane (code construction) plus ≥2 streaming
    passes per radix pass over the packed word(s) + the i32 row index."""
    plan = kernels.argsort_pack_plan(dtypes)
    key_read = cap * sum(np.dtype(d).itemsize for d in dtypes)
    return int(key_read + sum(2 * cap * (8 * words + 4) for words in plan))


def join_bytes_model(c_l: int, c_r: int, out_cap: int) -> int:
    """Modeled HBM traffic of one fused join dispatch: build-side sort
    (≥2 passes over dead+key+index planes), two searchsorted probes of
    the probe keys, one pass over the sorted build keys, and the
    expansion's reads/writes."""
    return int(2 * c_r * (1 + 8 + 4)      # sort: dead i8 + key i64 + iota i32
               + 2 * c_l * 8              # two searchsorted probes
               + c_r * 8                  # sorted-keys pass
               + 2 * c_l * 8              # counts/starts planes
               + out_cap * (4 + 4))       # owner/ridx writes


def grouped_agg_models(cap: int, out_cap: int, n_keys: int,
                       n_vals: int, val_bytes: int = 4):
    """(flops, bytes) of one grouped-agg dispatch. FLOPs: the one-hot
    matmul accumulates ``2 * cap * out_cap`` per reduced plane (values +
    the count plane the kernel always reduces). Bytes: packed key sort
    (2 passes) + the inverse-permutation sort + one read of each value
    plane."""
    flops = 2.0 * cap * out_cap * (n_vals + 1)
    plan = kernels.argsort_pack_plan([jnp.int64] * max(n_keys, 1))
    sort_bytes = sum(2 * cap * (8 * w + 4) for w in plan)
    inv_bytes = 2 * cap * (4 + 4)  # (perm, seg) 2-operand inverse sort
    nbytes = int(sort_bytes + inv_bytes + (n_vals + 1) * cap * val_bytes)
    return flops, nbytes


def hash_agg_models(cap: int, out_cap: int, table_cap: int, n_words: int,
                    n_vals: int, val_bytes: int = 4):
    """(flops, bytes) of one HASH grouped-agg dispatch (round 12): ONE
    streaming pass over the packed key word(s) + liveness + each value
    plane with its contrib mask, plus the table writeback (the table
    planes live in on-chip memory across the row stream — the grid
    revisits one block — so probe traffic never touches HBM). This is
    the whole point next to :func:`grouped_agg_models`: the sort
    formulation re-streams every packed plane ≥2x per radix pass and
    pays the inverse-permutation sort on top. No MXU flops to claim —
    the family is bandwidth-bound, so the roofline%% is the currency."""
    row_bytes = cap * (8 * n_words + 1 + n_vals * (val_bytes + 1))
    # key words + occupancy/first-row + ~3 state planes at 8B each
    slot_bytes = 8 * n_words + 8 + (n_vals + 1) * 8
    return 0.0, int(row_bytes + table_cap * slot_bytes)


def dense_agg_models(cap: int, out_cap: int, n_keys: int, n_vals: int,
                     val_bytes: int = 4):
    """(flops, bytes) of one DENSE direct-indexed grouped-agg dispatch:
    one pass over each key-code plane (the mixed-radix group id is pure
    arithmetic), one scatter pass per reduced plane (values + the count
    plane), and the [out_cap] slot planes. No sort, no table — the
    lightest byte model of the three strategies, which is exactly why
    the dispatch sites prefer it whenever the dictionaries fit."""
    row_bytes = cap * (n_keys * 4 + 1 + (n_vals + 1) * (val_bytes + 1))
    slot_bytes = out_cap * (n_vals + 2) * 8
    return 0.0, int(row_bytes + slot_bytes)


def hash_join_bytes_model(c_l: int, c_r: int, out_cap: int) -> int:
    """Modeled HBM traffic of one hash join dispatch: one pass over each
    side's key+liveness planes, the chain-link plane (written once per
    build row, read once per emitted pair), the table writeback, and the
    output pair/count writes — vs ``join_bytes_model``'s ≥2 sort passes
    over the build planes plus two searchsorted probes."""
    from . import pallas_kernels as pk
    table = pk.join_table_capacity(c_r)
    return int(c_r * (8 + 1 + 4)          # build keys + live + next-link
               + table * (8 + 4 + 4 + 4)  # key/occ/head/tail writeback
               + c_l * (8 + 1)            # probe keys + live
               + out_cap * (4 + 4 + 4)    # owner/ridx/chain-read per pair
               + c_l * 4)                 # counts


# ------------------------------------------------------- timing harness

def _timed_iters(jitted, args, iters: int = _ITERS) -> float:
    """Seconds per kernel iteration: one warm (compile) dispatch, then one
    timed dispatch whose program runs ``iters`` iterations in-jit."""
    jitted(*args, iters=iters).block_until_ready()
    t0 = time.perf_counter()
    jitted(*args, iters=iters).block_until_ready()
    return max((time.perf_counter() - t0) / iters, 1e-9)


def measure_grouped_agg(n: int = 1 << 20, groups: int = 256,
                        n_vals: int = 2) -> Dict:
    """MFU of the one-hot-matmul grouped aggregation (the TPC-H Q1 shape:
    few groups, several reduced value planes)."""
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, groups, n).astype(np.int64))
    valid = jnp.ones(n, dtype=bool)
    vals = tuple(jnp.asarray(rng.uniform(0, 100, n).astype(np.float32))
                 for _ in range(n_vals))
    mask = jnp.ones(n, dtype=bool)
    out_cap = max(256, groups)
    ops = ("sum",) * n_vals

    @partial(jax.jit, static_argnames=("iters",))
    def run(k, kv, v, vv, m, iters: int):
        def body(i, carry):
            # loop-carried perturbation (0/1 added to the key plane):
            # defeats while-loop invariant code motion without changing
            # the group structure's shape
            k2 = k + carry.astype(k.dtype)
            _, _, ov, _, g = kernels.grouped_agg_block_impl(
                (k2,), (kv,), v, vv, m, ops, out_cap)
            return (g % 2).astype(jnp.int32)
        return lax.fori_loop(0, iters, body, jnp.int32(0))

    t = _timed_iters(run, (keys, valid, vals, (valid,) * n_vals, mask))
    # At TPC-H-like shapes (many rows, few groups) the kernel is
    # SORT/bandwidth-bound, not FLOP-bound — so the bytes-based roofline
    # is reported alongside MFU (the one-hot matrix is fused by XLA,
    # never materialized).
    flops, bytes_touched = grouped_agg_models(n, out_cap, 1, n_vals)
    return {"kernel": "grouped_agg_matmul", "strategy": "sort", "rows": n,
            "groups": groups,
            "iters": _ITERS, "time_s": round(t, 6), "flops": flops,
            "achieved_tflops": round(flops / t / 1e12, 3),
            "mfu_pct": round(100.0 * flops / t / _peak_flops(), 3),
            "achieved_gbps": round(bytes_touched / t / 1e9, 2),
            "roofline_pct": round(
                100.0 * bytes_touched / t / _hbm_bps(), 3)}


def measure_hash_grouped_agg(n: int = 1 << 20, groups: int = 256,
                             n_vals: int = 2) -> Dict:
    """Roofline % of the ONE-PASS hash grouped-agg (round 12): same shape
    as :func:`measure_grouped_agg` so the two rows are directly
    comparable — the hash row's win over the sort row IS the ledger's
    promised improvement. interpret/block resolve OUTSIDE the jit (the
    jit-hygiene contract), and the in-jit ``lax.fori_loop`` repetition
    keeps tunnel RTT out of the number, exactly like the sort kernels."""
    from . import pallas_kernels as pk
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, groups, n).astype(np.int64))
    valid = jnp.ones(n, dtype=bool)
    vals = tuple(jnp.asarray(rng.uniform(0, 100, n).astype(np.float32))
                 for _ in range(n_vals))
    mask = jnp.ones(n, dtype=bool)
    out_cap = max(256, groups)
    ops = ("sum",) * n_vals
    interpret = pk.interpret_default()
    block = pk.block_rows(n)
    table = pk.table_capacity(out_cap)

    @partial(jax.jit, static_argnames=("iters",))
    def run(k, kv, v, vv, m, iters: int):
        def body(i, carry):
            # 0/1 key perturbation: defeats loop-invariant code motion
            # without changing the group structure's shape
            k2 = k + carry.astype(k.dtype)
            _, _, ov, _, g = pk.hash_grouped_agg_impl(
                (k2,), (kv,), v, vv, m, ops, out_cap,
                interpret=interpret, block=block)
            return (g % 2).astype(jnp.int32)
        return lax.fori_loop(0, iters, body, jnp.int32(0))

    t = _timed_iters(run, (keys, valid, vals, (valid,) * n_vals, mask))
    _, bytes_touched = hash_agg_models(n, out_cap, table, 1, n_vals)
    return {"kernel": "grouped_agg_hash", "strategy": "hash", "rows": n,
            "groups": groups, "table_slots": table,
            "interpret": interpret, "iters": _ITERS,
            "time_s": round(t, 6), "bytes": bytes_touched,
            "achieved_gbps": round(bytes_touched / t / 1e9, 2),
            "roofline_pct": round(
                100.0 * bytes_touched / t / _hbm_bps(), 3)}


def measure_join(n: int = 1 << 20) -> Dict:
    """Roofline % of the FUSED sort-merge join kernel (one dispatch:
    build sort + probe counts + prefix-sum expansion)."""
    rng = np.random.default_rng(1)
    r_key = jnp.asarray(rng.integers(0, n // 2, n).astype(np.int64))
    l_key = jnp.asarray(rng.integers(0, n // 2, n).astype(np.int64))
    ones = jnp.ones(n, dtype=bool)

    @partial(jax.jit, static_argnames=("iters",))
    def run(lk, rk, m, iters: int):
        def body(i, carry):
            packed = kernels.join_fused_impl(
                lk + carry.astype(lk.dtype), m, m, rk, m, m, n)
            return packed[2, 0] % 2
        return lax.fori_loop(0, iters, body, jnp.int32(0))

    t = _timed_iters(run, (l_key, r_key, ones))
    bytes_touched = join_bytes_model(n, n, n)
    return {"kernel": "join_fused", "strategy": "sort", "rows": n,
            "iters": _ITERS,
            "time_s": round(t, 6), "bytes": bytes_touched,
            "achieved_gbps": round(bytes_touched / t / 1e9, 2),
            "roofline_pct": round(
                100.0 * bytes_touched / t / _hbm_bps(), 3)}


def measure_hash_join(n: int = 1 << 20) -> Dict:
    """Roofline % of the hash build/probe join — same key distribution
    as :func:`measure_join` so the rows compare directly. ``n`` is
    clamped so the measured configuration is one the strategy model
    would actually dispatch: the build table is 2×``n`` slots and must
    stay within ``DAFT_TPU_KERNEL_MAX_TABLE`` (an inadmissible config
    fails to lower on silicon and would erase the roofline row)."""
    from . import pallas_kernels as pk
    n = max(min(n, pk.max_table_slots() // 2), 128)
    rng = np.random.default_rng(1)
    r_key = jnp.asarray(rng.integers(0, n // 2, n).astype(np.int64))
    l_key = jnp.asarray(rng.integers(0, n // 2, n).astype(np.int64))
    ones = jnp.ones(n, dtype=bool)
    interpret = pk.interpret_default()
    block = pk.block_rows(n)

    @partial(jax.jit, static_argnames=("iters",))
    def run(lk, rk, m, iters: int):
        def body(i, carry):
            packed = pk.hash_join_impl(
                lk + carry.astype(lk.dtype), m, m, rk, m, m, n,
                interpret=interpret, block=block)
            return packed[2, 0] % 2
        return lax.fori_loop(0, iters, body, jnp.int32(0))

    t = _timed_iters(run, (l_key, r_key, ones))
    bytes_touched = hash_join_bytes_model(n, n, n)
    return {"kernel": "join_hash", "strategy": "hash", "rows": n,
            "table_slots": pk.join_table_capacity(n),
            "interpret": interpret, "iters": _ITERS,
            "time_s": round(t, 6), "bytes": bytes_touched,
            "achieved_gbps": round(bytes_touched / t / 1e9, 2),
            "roofline_pct": round(
                100.0 * bytes_touched / t / _hbm_bps(), 3)}


def measure_argsort(n: int = 1 << 20, n_keys: int = 2) -> Dict:
    """Roofline % of the packed-key multi-key argsort behind ORDER BY /
    window partitioning (two f32 keys + null ranks + the dead bit pack
    into one 67-bit word pair: a single 3-operand sort pass)."""
    rng = np.random.default_rng(2)
    keys = tuple(jnp.asarray(rng.uniform(0, 1e6, n).astype(np.float32))
                 for _ in range(n_keys))
    ones = jnp.ones(n, dtype=bool)
    flags = tuple(False for _ in range(n_keys))

    @partial(jax.jit, static_argnames=("iters",))
    def run(ks, m, iters: int):
        def body(i, carry):
            k0 = ks[0] + carry.astype(ks[0].dtype)
            perm = kernels.argsort_kernel((k0,) + ks[1:], (m,) * n_keys,
                                          m, flags, flags)
            return perm[0] % 2
        return lax.fori_loop(0, iters, body, jnp.int32(0))

    t = _timed_iters(run, (keys, ones))
    bytes_touched = argsort_bytes_model(n, [k.dtype for k in keys])
    return {"kernel": "argsort_packed", "strategy": "sort", "rows": n,
            "n_keys": n_keys,
            "iters": _ITERS, "time_s": round(t, 6), "bytes": bytes_touched,
            "sort_passes": len(kernels.argsort_pack_plan(
                [k.dtype for k in keys])),
            "achieved_gbps": round(bytes_touched / t / 1e9, 2),
            "roofline_pct": round(
                100.0 * bytes_touched / t / _hbm_bps(), 3)}


def report(n: int = 1 << 20) -> Dict:
    """All kernel families + the per-dispatch ledger; the bench device
    child embeds this in its detail and the compact summary carries the
    headline numbers. The synthetic sections isolate silicon (in-jit
    repetition); ``ledger`` is what REAL engine dispatches achieved
    end-to-end (includes link time on a tunnel — a lower bound)."""
    out = {"peak_flops": _peak_flops(), "hbm_bps": _hbm_bps(),
           "method": f"in-jit lax.fori_loop x{_ITERS}, one fence"}
    try:
        out["grouped_agg"] = measure_grouped_agg(n)
        out["join"] = measure_join(n)
        out["argsort"] = measure_argsort(n)
    except Exception as exc:  # a wedged backend must not kill the bench
        out["error"] = str(exc)[:200]
    # hash-strategy rows (round 12). Under the Pallas INTERPRETER (CPU
    # dev box) the kernels run as a python-level emulation — timings
    # would measure the emulator, not silicon — so the rows shrink to a
    # smoke size and are flagged `interpret`; roofline claims come from
    # real-chip runs only (bench --kernels reports parity + dispatch
    # contracts instead on CPU).
    from . import pallas_kernels as pk
    n_hash = n if not pk.interpret_default() else min(n, 1 << 12)
    try:
        out["grouped_agg_hash"] = measure_hash_grouped_agg(n_hash)
        out["join_hash"] = measure_hash_join(n_hash)
    except Exception as exc:
        out["hash_error"] = str(exc)[:200]
    out["ledger"] = costmodel.ledger_snapshot()
    return out
