"""Async device execution pipeline: overlap host encode/decode with
device compute and batch every device→host transfer.

Round 17 closes the second half of ROADMAP item 1.  r16 killed the
recompile tax; this module kills the per-morsel *transfer* tax.  The
synchronous chain — Arrow→numpy encode, ``jnp.asarray`` upload, dispatch,
blocking ``jax.device_get``, decode — serialized every stage even though
JAX dispatch is already asynchronous.  Three fixes live here:

- **a bounded in-flight window** (``DAFT_TPU_DEVICE_INFLIGHT``, default
  2) of double-buffered morsel slots driven by :func:`run_pipelined`:
  morsel N+1's host-side encode+upload runs on a dedicated submit pool
  while morsel N computes on device and morsel N−1 downloads/decodes on
  the consumer thread.  Each slot acquires MemoryManager admission for
  its host+HBM footprint on submit (:func:`acquire_slot`) and releases
  it when the slot drains (:func:`release_slot`) — the pairing is one
  row in the daft-lint Contract table (``device-slot-leak``), so the
  dataflow solver proves no slot leaks on any path, exception edges
  included.
- **one transfer per drain**: :func:`fetch_host` pulls a whole pytree of
  device arrays in ONE ``jax.device_get`` (per-leaf host copies start
  asynchronously and complete together) instead of one blocking get per
  column plane.
- **device-resident hand-off**: when a device op's decoded output feeds
  another device op, :func:`note_decoded` keeps the device planes alive
  (bounded LRU, keyed weakly by the host Series) and
  :func:`resident_planes` hands them back to the next ``encode`` —
  no host round-trip.  Reused tables are marked
  ``DeviceTable.resident`` so the r12/r14 donation discipline (proven
  by daft-lint's donation rules) keeps the shared buffers safe.

``DAFT_TPU_CHAOS_SERIALIZE=1`` (or an active fault plan) degrades every
caller to the verbatim synchronous path — :func:`inflight_window`
returns 0 — so chaos replay stays bit-identical, matching the
scan-prefetch and parallel-fetch precedents.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, Iterator, Optional

_MAX_WINDOW = 64


# context handle memo: get_context() takes the process-wide context
# lock on EVERY call — cache the singleton so the env-unset default
# path stays lock-free at decode/morsel rate (the execution_config
# attr read itself is a GIL-atomic load of the current config)
_ctx_memo = None


def _config_window() -> int:
    global _ctx_memo
    if _ctx_memo is None:
        try:
            from ..context import get_context
            # daft-lint: allow(unguarded-global-mutation) -- benign
            # last-wins memo of the process context singleton
            _ctx_memo = get_context()
        except Exception:
            return 2
    try:
        return int(_ctx_memo.execution_config.tpu_device_inflight)
    except Exception:
        return 2


def sequential_fallback() -> bool:
    """True when the pipeline must degrade to the synchronous path:
    ``DAFT_TPU_CHAOS_SERIALIZE=1`` or an active fault plan — the chaos
    replay contract requires the event order of the serial chain."""
    from ..analysis import knobs
    if knobs.env_bool("DAFT_TPU_CHAOS_SERIALIZE"):
        return True
    try:
        from ..distributed.resilience import active_fault_plan
        return active_fault_plan() is not None
    except Exception:
        return False


def inflight_window() -> int:
    """In-flight device slots (``DAFT_TPU_DEVICE_INFLIGHT``; the
    ``tpu_device_inflight`` config field is the per-query value).  0 =
    synchronous dispatch (also forced under chaos serialization)."""
    from ..analysis import knobs
    if sequential_fallback():
        return 0
    w = knobs.env_int("DAFT_TPU_DEVICE_INFLIGHT", default=None)
    if w is None:
        w = _config_window()
    return max(0, min(int(w), _MAX_WINDOW))


def fetch_host(tree):
    """ONE ``jax.device_get`` for a whole pytree of device arrays.

    JAX starts the host copy of every leaf asynchronously and waits for
    all of them together, so a table's data+validity planes (or a
    window's packed result matrices) cost one batched transfer instead
    of one blocking round-trip per plane."""
    import jax
    return jax.device_get(tree)


# ------------------------------------------------------------- submit pool

_PIPE_POOL = None
# guards pool creation (the executor's _pools_lock pattern): two racing
# first callers must not each build a pool and leak the loser's threads
_pipe_lock = threading.Lock()


def _pipe_pool():
    """Dedicated pool for pipeline submit bodies (encode + dispatch).
    NOT the shared exec pool: a submit body blocked on the window gate
    or memory admission must never hold an exec slot that a nested
    classify/load future needs (the scan-pool precedent)."""
    global _PIPE_POOL
    if _PIPE_POOL is not None:
        return _PIPE_POOL
    import concurrent.futures as cf
    import os
    with _pipe_lock:
        if _PIPE_POOL is None:
            _PIPE_POOL = cf.ThreadPoolExecutor(
                max_workers=max((os.cpu_count() or 4), 4),
                thread_name_prefix="daft-tpu-devpipe")
        return _PIPE_POOL


# -------------------------------------------------------- in-flight slots

class PipelineAborted(Exception):
    """The consumer tore the pipeline down while this slot waited."""


class WindowGate:
    """Window admission for in-flight device slots.

    A submit body may acquire a slot when fewer than ``window`` slots
    are live OR it owns the oldest undrained sequence number — the
    head-of-line slot is always admitted, so pool workers running out
    of order can never deadlock the consumer (which drains strictly in
    sequence).  ``is_set`` makes the gate double as a cancel signal for
    ``MemoryManager.try_acquire``."""

    def __init__(self, window: int):
        self.window = max(int(window), 1)
        self._cond = threading.Condition()
        self._live = 0
        self._drained = 0          # next sequence the consumer will drain
        self._aborted = False

    def is_set(self) -> bool:     # cancel-token protocol for try_acquire
        return self._aborted

    def acquire(self, seq: int) -> None:
        with self._cond:
            while (self._live >= self.window and seq > self._drained
                   and not self._aborted):
                self._cond.wait(0.1)
            if self._aborted:
                raise PipelineAborted()
            self._live += 1

    def note_drained(self, seq: int) -> None:
        with self._cond:
            self._drained = max(self._drained, seq + 1)
            self._cond.notify_all()

    def slot_released(self) -> None:
        with self._cond:
            self._live = max(self._live - 1, 0)
            self._cond.notify_all()

    def abort(self) -> None:
        with self._cond:
            self._aborted = True
            self._cond.notify_all()


class Slot:
    """One admitted in-flight pipeline slot: window-gate occupancy plus
    the MemoryManager bytes for its host+HBM footprint.  Created only by
    :func:`acquire_slot`; dies only through :func:`release_slot`."""

    __slots__ = ("gate", "mem", "nbytes", "released", "seq")

    def __init__(self, gate: WindowGate, mem, nbytes: int, seq: int):
        self.gate = gate
        self.mem = mem
        self.nbytes = int(nbytes)
        self.seq = seq
        self.released = False


#: bound on a slot's wait for memory admission.  Slots hold their bytes
#: from submit to DRAIN, and submit bodies run out of sequence order on
#: the pool — an unbounded wait could deadlock against bytes held by a
#: later-sequence slot the consumer cannot drain yet.  On timeout the
#: slot proceeds UNADMITTED (footprint 0, counted): backpressure is
#: advisory here exactly like the pre-pipeline morsel path, which never
#: admission-gated device dispatches at all.
_ADMIT_DEADLINE_S = 5.0


def acquire_slot(gate: WindowGate, seq: int, mem=None,
                 nbytes: int = 0) -> Slot:
    """Admit one in-flight device slot: window gate first (head-of-line
    exempt, deadlock-free), then memory admission for the slot's
    host+HBM footprint.  The returned Slot OWNS both; every caller must
    :func:`release_slot` it on all paths or hand it off whole (the
    ``device-slot-leak`` Contract row proves this statically)."""
    gate.acquire(seq)
    if mem is not None and nbytes > 0:
        # gate doubles as the cancel signal: a torn-down pipeline must
        # not leave a worker waiting forever on admission it will never
        # get (the consumer that would release bytes is gone)
        # daft-lint: allow(memory-admission-leak) -- the admitted bytes
        # transfer into the returned Slot by design (acquire-on-submit,
        # release-on-drain); the device-slot-leak contract proves every
        # acquire_slot caller releases or hands the Slot off whole
        if not mem.try_acquire(
                nbytes, deadline=time.monotonic() + _ADMIT_DEADLINE_S,
                cancel=gate):
            if gate.is_set():
                gate.slot_released()
                raise PipelineAborted()
            _count("admission_timeouts")
            nbytes = 0
    return Slot(gate, mem, nbytes if mem is not None else 0, seq)


def release_slot(slot: Optional[Slot]) -> None:
    """Release a slot's admission + window occupancy. Idempotent — safe
    to call from both the drain path and teardown."""
    if slot is None or slot.released:
        return
    slot.released = True
    if slot.mem is not None and slot.nbytes > 0:
        slot.mem.release(slot.nbytes)
    slot.gate.slot_released()


# ------------------------------------------------------------- the driver

#: process-wide pipeline counters (bench evidence): slots run, stage
#: seconds, serial-equivalent vs pipelined wall
_counters_lock = threading.Lock()
_counters: Dict[str, float] = {}


def _count(key: str, v: float = 1.0) -> None:
    with _counters_lock:
        _counters[key] = _counters.get(key, 0) + v


def counters_snapshot() -> Dict[str, float]:
    with _counters_lock:
        return dict(_counters)


def reset_counters() -> None:
    with _counters_lock:
        _counters.clear()


class InflightItem:
    """A submit callback's in-flight device work: the acquired Slot, an
    opaque dispatch token for the drain callback, and the submit-stage
    wall (overlap accounting).  Submit callbacks that route an item to
    the host return a plain value instead — only InflightItems count
    against the window and the overlap ledger."""

    __slots__ = ("slot", "token", "sub_s", "t_dispatched_us")

    def __init__(self, slot: Optional[Slot], token, sub_s: float = 0.0,
                 t_dispatched_us: int = 0):
        self.slot = slot
        self.token = token
        self.sub_s = sub_s
        self.t_dispatched_us = t_dispatched_us


def run_pipelined(items: Iterator, submit: Callable, drain: Callable, *,
                  window: int, width: Optional[int] = None,
                  poll: Optional[Callable] = None) -> Iterator:
    """Drive the bounded-window async device pipeline.

    ``submit(item, seq, gate) -> InflightItem | host result`` runs on
    the dedicated submit pool: host-side encode + asynchronous device
    dispatch, acquiring an in-flight Slot (``acquire_slot(gate, seq,
    mem, bytes)``) for device work, or any plain value for host-routed
    items (which never touch the window — a host-heavy stream keeps the
    pool's full parallelism).  ``drain(ret, seq) -> result`` runs on
    the consumer thread: ONE batched fetch + decode for InflightItems,
    passthrough for host values.  Results yield in submission order.
    ``poll`` (the executor's cancellation poll) runs before each drain.

    Overlap comes from the three stages living on three threads: while
    the consumer blocks in slot N's fetch, slot N+1 computes on device
    and slot N+2 encodes on the pool.  Teardown (exception, early
    close, cancellation) aborts the gate, waits out in-flight submits,
    and releases every undrained slot — the admission-leak and
    cancellation tests pin this."""
    from .. import observability as obs

    gate = WindowGate(window)
    pool = _pipe_pool()
    pending = collections.deque()  # (future, seq)
    it = iter(items)
    seq_next = [0]
    # ACTIVE wall only: time the driver spends working (or waiting on
    # its own stages), excluding the stretches it sits suspended at
    # `yield` while downstream operators run — charging those would
    # dilute overlap_x toward zero on consumer-bound queries
    active_s = [0.0]
    serial_s = [0.0]
    slots_run = [0]
    if width is None:
        import os
        width = max((os.cpu_count() or 4), 4) * 2
    width = max(width, window + 1)
    # adaptive enqueue cap: device submits past the window BLOCK in
    # gate.acquire while holding a submit-pool thread, so a pipeline
    # must not park `width` of them — concurrent (serving) or stacked
    # (push-executor stage) pipelines sharing the bounded pool could
    # starve each other's head futures. Start at window+2 (a
    # device-heavy stream never blocks more than ~2 threads) and grow
    # toward full width only as HOST-routed results prove the stream
    # doesn't occupy slots.
    cap = [min(width, window + 2)]

    def _enqueue() -> bool:
        try:
            item = next(it)
        except StopIteration:
            return False
        seq = seq_next[0]
        seq_next[0] += 1
        fut = pool.submit(obs.run_attributed, obs.current_attribution(),
                          submit, item, seq, gate)
        pending.append((fut, seq))
        return True

    def _fill() -> None:
        while len(pending) < cap[0] and _enqueue():
            pass

    t_resume = time.perf_counter()
    try:
        _fill()
        while pending:
            fut, seq = pending.popleft()
            try:
                ret = fut.result()
            except PipelineAborted:
                gate.note_drained(seq)
                continue
            slot = ret.slot if isinstance(ret, InflightItem) else None
            try:
                if poll is not None:
                    poll()
                t0 = time.perf_counter()
                result = drain(ret, seq)
                if isinstance(ret, InflightItem):
                    serial_s[0] += ret.sub_s + (time.perf_counter() - t0)
                    slots_run[0] += 1
                else:
                    # host-routed item: it held no slot, so the stream
                    # can afford more in-flight futures
                    cap[0] = min(width, cap[0] * 2)
            finally:
                release_slot(slot)
                gate.note_drained(seq)
            active_s[0] += time.perf_counter() - t_resume
            yield result
            t_resume = time.perf_counter()
            _fill()
    finally:
        active_s[0] += time.perf_counter() - t_resume
        gate.abort()
        for fut, seq in pending:
            if fut.cancel():
                continue
            try:
                ret = fut.result()
                if isinstance(ret, InflightItem):
                    release_slot(ret.slot)
            except BaseException:
                pass  # the submit body released its own slot
        if slots_run[0] > 0:
            _count("slots", slots_run[0])
            _count("runs")
            _count("serial_equiv_s", serial_s[0])
            _count("wall_s", active_s[0])
            # MFU-ledger overlap evidence: serial-equivalent stage
            # seconds vs the pipeline's ACTIVE wall, per dispatch family
            from . import costmodel
            costmodel.ledger_record("pipeline", dispatches=slots_run[0],
                                    seconds=active_s[0],
                                    serial_seconds=serial_s[0])


# ------------------------------------------------------- pipeline spans

def upload_span(seq: int, window: int):
    """``device:upload`` span covering a slot's host encode + async
    dispatch (the submit stage), on its own lane with the in-flight
    slot id annotated — perfetto shows the overlap (or its absence)
    directly.  Keys are deterministic (morsel sequence), so chaos runs
    replay bit-identical span ids."""
    from .. import tracing
    return tracing.span("device:upload", key=f"devpipe.up.{seq}",
                        attrs={"slot": seq % max(window, 1), "seq": seq},
                        lane="dev:upload")


def note_compute_span(seq: int, window: int, t_dispatched_us: int) -> None:
    """``device:compute`` span from dispatch completion to drain start —
    the interval the device computes while the host works on neighbor
    slots.  Emitted at drain time (the host never blocks mid-flight to
    observe the device)."""
    from .. import tracing
    ctx = tracing.current()
    if ctx is None or not t_dispatched_us:
        return
    rec = ctx.recorder
    now = tracing._now_us()
    rec.add("device:compute", rec.unique_span_id(f"devpipe.comp.{seq}"),
            ctx.span_id, t_dispatched_us,
            max(now - t_dispatched_us, 0),
            attrs={"slot": seq % max(window, 1), "seq": seq},
            lane="dev:compute")


def download_span(seq: int, window: int):
    """``device:download`` span covering a slot's batched fetch +
    decode (the drain stage)."""
    from .. import tracing
    return tracing.span("device:download", key=f"devpipe.down.{seq}",
                        attrs={"slot": seq % max(window, 1), "seq": seq},
                        lane="dev:download")


def now_us() -> int:
    from .. import tracing
    return tracing._now_us() if tracing.current() is not None else 0


# ------------------------------------------- device-resident hand-off

#: bounded LRU of decoded-output device planes, keyed by id(Series) with
#: a weakref reaper — a fragment output consumed by another device op
#: (fragment→join, fragment→topk) re-enters the device without a host
#: round trip.  Strong refs here pin HBM, so the budget is a slice of
#: the HBM cache's.
# RLock: the weakref reaper (_drop) can fire from GC while this thread
# already holds the lock (e.g. an eviction drops the last strong ref)
_res_lock = threading.RLock()
_resident: "collections.OrderedDict" = collections.OrderedDict()
_res_bytes = [0]
_res_counters: Dict[str, int] = {"hits": 0, "misses": 0, "evictions": 0}


def _res_budget() -> int:
    from ..analysis import knobs
    return int(knobs.env_bytes("DAFT_TPU_HBM_CACHE_BYTES")) // 8


def residency_counters() -> Dict[str, int]:
    with _res_lock:
        out = dict(_res_counters)
        out["entries"] = len(_resident)
        out["bytes"] = int(_res_bytes[0])
    return out


def reset_residency() -> None:
    with _res_lock:
        _resident.clear()
        _res_bytes[0] = 0
        for k in _res_counters:
            _res_counters[k] = 0


def _entry_nbytes(data, validity) -> int:
    try:
        return int(data.nbytes) + int(validity.nbytes)
    except Exception:
        return 0


def note_decoded(series, data, validity, dictionary, count: int,
                 capacity: int) -> None:
    """Register a decoded device column's planes for residency reuse.
    Called from ``column.decode_column`` when the planes are real device
    arrays and the pipeline is enabled; lossy encodings (decimals) must
    not register — reuse has to be bit-identical with a re-encode."""
    import weakref
    key = id(series)
    try:
        ref = weakref.ref(series, lambda _r, _k=key: _drop(_k))
    except TypeError:
        return
    nbytes = _entry_nbytes(data, validity)
    with _res_lock:
        if key in _resident:
            return
        budget = _res_budget()
        if nbytes > budget:
            return
        while _res_bytes[0] + nbytes > budget and _resident:
            _, old = _resident.popitem(last=False)
            _res_bytes[0] -= old[6]
            _res_counters["evictions"] += 1
        _resident[key] = (ref, data, validity, dictionary, count,
                          capacity, nbytes)
        _res_bytes[0] += nbytes


def _drop(key: int) -> None:
    with _res_lock:
        ent = _resident.pop(key, None)
        if ent is not None:
            _res_bytes[0] -= ent[6]


def resident_planes(series, n: int):
    """``(data, validity, dictionary, capacity)`` for a Series whose
    device planes are still resident, or None.  ``validity`` comes back
    masked to the live rows (one tiny jitted AND per reuse — the planes
    beyond the decoded count carry kernel garbage, where a fresh encode
    zero-pads)."""
    if not _resident:     # lock-free fast path: nothing ever registered
        return None
    if inflight_window() <= 0:
        # chaos-serialize / fault-plan degradation (or an explicit
        # window 0) must replay the VERBATIM synchronous chain — a
        # reuse hit would skip the upload events the replay contract
        # expects, even though planes registered before degradation
        # are still sitting in the registry
        return None
    key = id(series)
    with _res_lock:
        ent = _resident.get(key)
        if ent is None:
            _res_counters["misses"] += 1
            return None
        ref, data, validity, dictionary, count, capacity, _nb = ent
        if ref() is not series or count != n:
            _res_counters["misses"] += 1
            return None
        _resident.move_to_end(key)
        _res_counters["hits"] += 1
    if count == capacity:
        # no garbage tail to mask (rows [count:capacity) is empty) —
        # skip the identity dispatch on exactly the path built to
        # avoid round trips
        return data, validity, dictionary, capacity
    return data, _masked_validity(validity, n), dictionary, capacity


_mask_cache: Dict[int, object] = {}


def _masked_validity(validity, n: int):
    import jax
    import jax.numpy as jnp
    from ..analysis import retrace_sanitizer
    fn = _mask_cache.get(0)
    if fn is None:
        fn = jax.jit(
            lambda v, k: v & (jnp.arange(v.shape[0]) < k))
        _mask_cache[0] = fn
    # one trace per validity-plane capacity class (n rides as a traced
    # scalar, so literal-different live counts re-enter the program)
    with retrace_sanitizer.dispatch_scope(
            "pipeline.mask", (int(validity.shape[0]),)):
        return fn(validity, n)
