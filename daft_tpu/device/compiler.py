"""Expression → fused XLA program compiler.

The device twin of the reference's expression evaluator
(``eval_expression_list``, ``src/daft-recordbatch/src/lib.rs:755``): a whole
projection/predicate list compiles into ONE jit function over the
DeviceTable's arrays, so XLA fuses the elementwise graph into a single kernel
(SURVEY.md §7.2: "compile a bound expression projection/filter into one fused
jit function per (schema, expr-set) with a compile cache keyed on padded
shapes").

String semantics ride on *sorted-dictionary codes*: comparisons against string
literals become integer comparisons against per-batch literal ranks, which are
computed host-side by "scalar specs" and passed as dynamic args (no recompile
per batch).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

import jax
import jax.numpy as jnp

from ..datatype import DataType
from ..expressions.expressions import Expression
from ..schema import Schema

# ops the device compiler understands ------------------------------------
_ARITH = {"add", "sub", "mul", "div", "floordiv", "mod", "pow"}
_CMP = {"lt", "le", "gt", "ge", "eq", "neq"}
_BOOL = {"and", "or", "xor"}
_UNARY_F = {"sqrt": jnp.sqrt, "exp": jnp.exp, "ln": jnp.log, "log2": jnp.log2,
            "log10": jnp.log10, "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
            "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
            "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
            "cbrt": jnp.cbrt, "degrees": jnp.degrees, "radians": jnp.radians}


class NotCompilable(Exception):
    pass


class ScalarSpec:
    """Host-side per-batch preparation: computes a scalar/array argument from
    a string column's sorted dictionary (e.g. the rank of a literal)."""

    def __init__(self, col: str, fn: Callable[[pa.Array], np.ndarray]):
        self.col = col
        self.fn = fn


def _dict_np(d: pa.Array) -> np.ndarray:
    return np.asarray(d.to_pylist(), dtype=object)


def _rank_spec(col: str, lit, side: str) -> ScalarSpec:
    def fn(d: pa.Array) -> np.ndarray:
        dn = _dict_np(d)
        if side == "eq":
            i = np.searchsorted(dn, lit)
            if i < len(dn) and dn[i] == lit:
                return np.int32(i)
            return np.int32(-1)
        i = np.searchsorted(dn, lit, side=side)
        return np.int32(i)
    return ScalarSpec(col, fn)


class Compiled:
    """A compiled projection: jitted fn + per-batch scalar preparation."""

    def __init__(self, fn, scalar_specs: List[ScalarSpec],
                 out_fields, needs_cols: List[str]):
        self.fn = fn
        self.scalar_specs = scalar_specs
        self.out_fields = out_fields
        self.needs_cols = needs_cols


class _Ctx:
    def __init__(self, schema: Schema):
        self.schema = schema
        self.scalar_specs: List[ScalarSpec] = []
        self.needs: List[str] = []

    def add_scalar(self, spec: ScalarSpec) -> int:
        self.scalar_specs.append(spec)
        return len(self.scalar_specs) - 1

    def need(self, col: str):
        if col not in self.needs:
            self.needs.append(col)


def _f64(backend_f32: bool):
    return jnp.float32 if backend_f32 else jnp.float64


def compile_projection(exprs: List[Expression], schema: Schema,
                       jit: bool = True) -> Compiled:
    """Compile an expression list; raises NotCompilable on unsupported ops.

    With ``jit=False`` the returned fn is the raw traceable composition, for
    embedding into larger fused programs (scan fragments)."""
    from .column import supports_f64
    ctx = _Ctx(schema)
    builders = [_build(e, ctx, not supports_f64()) for e in exprs]
    out_fields = [e.to_field(schema) for e in exprs]

    def run(arrays, valids, row_mask, scalars):
        env = (arrays, valids, row_mask, scalars)
        outs = []
        for b in builders:
            v, m = b(env)
            if v.ndim == 0:  # scalar literal broadcast
                v = jnp.broadcast_to(v, row_mask.shape)
                m = jnp.broadcast_to(m, row_mask.shape)
            outs.append((v, m))
        return tuple(outs)

    return Compiled(jax.jit(run) if jit else run, ctx.scalar_specs,
                    out_fields, ctx.needs)


def can_compile(e: Expression, schema: Schema) -> bool:
    from .column import supports_f64
    try:
        e.to_field(schema)
        _build(e, _Ctx(schema), not supports_f64())
        return True
    except (NotCompilable, NotImplementedError, ValueError, TypeError,
            KeyError, OverflowError):
        return False


def _dtype_of(e: Expression, ctx: _Ctx) -> DataType:
    return e.to_field(ctx.schema).dtype


def _is_str(e: Expression, ctx) -> bool:
    try:
        return _dtype_of(e, ctx).is_string()
    except Exception:
        return False


def _build(e: Expression, ctx: _Ctx, f32: bool):
    """Returns closure env -> (value_array, valid_array)."""
    op = e.op

    if op == "col":
        name = e.params[0]
        if name not in ctx.schema:
            raise NotCompilable(f"unknown column {name}")
        dt = ctx.schema[name].dtype
        if dt.device_repr() is None:
            raise NotCompilable(f"column {name}: {dt!r} not device-representable")
        ctx.need(name)
        return lambda env: (env[0][name], env[1][name])

    if op == "alias":
        return _build(e.args[0], ctx, f32)

    if op == "lit":
        v = e.params[0]
        if v is None:
            return lambda env: (jnp.zeros((), jnp.int32), jnp.zeros((), jnp.bool_))
        if isinstance(v, bool):
            c = jnp.asarray(v)
        elif isinstance(v, int):
            if f32 and not (-(2**31) <= v < 2**31):
                raise NotCompilable("int literal exceeds int32 on f32 backend")
            if not (-(2**63) <= v < 2**63):
                raise NotCompilable("int literal exceeds int64")
            c = jnp.asarray(v, jnp.int64 if not f32 else jnp.int32)
        elif isinstance(v, float):
            c = jnp.asarray(v, jnp.float32 if f32 else jnp.float64)
        else:
            import datetime
            if isinstance(v, datetime.datetime):
                c = jnp.asarray(int(v.timestamp() * 1_000_000), jnp.int64)
            elif isinstance(v, datetime.date):
                c = jnp.asarray((v - datetime.date(1970, 1, 1)).days, jnp.int32)
            else:
                raise NotCompilable(f"literal {type(v)} not device-representable")
        return lambda env: (c, jnp.ones((), jnp.bool_))

    if op == "cast":
        target = e.params[0]
        child_dt = _dtype_of(e.args[0], ctx)
        if child_dt.is_string() and not target.is_string():
            raise NotCompilable("string cast on device")
        rep = target.device_repr()
        if rep is None or target.is_string():
            raise NotCompilable(f"cast to {target!r} on device")
        c = _build(e.args[0], ctx, f32)
        jdt = jnp.dtype(rep) if not (rep == np.float64 and f32) else jnp.float32
        return lambda env: (lambda v_m: (v_m[0].astype(jdt), v_m[1]))(c(env))

    # string comparisons against literals --------------------------------
    if op in _CMP:
        l, r = e.args
        l_str, r_str = _is_str(l, ctx), _is_str(r, ctx)
        if l_str or r_str:
            return _build_str_cmp(e, ctx, f32)

    if op in _ARITH or op in _CMP:
        cl = _build(e.args[0], ctx, f32)
        cr = _build(e.args[1], ctx, f32)
        ldt, rdt = _dtype_of(e.args[0], ctx), _dtype_of(e.args[1], ctx)
        if ldt.is_temporal() or rdt.is_temporal():
            if op in _ARITH and not (op in ("sub", "add")):
                raise NotCompilable("temporal arithmetic beyond add/sub")

        def fn(env, _op=op):
            lv, lm = cl(env)
            rv, rm = cr(env)
            m = lm & rm
            if _op == "add":
                v = lv + rv
            elif _op == "sub":
                v = lv - rv
            elif _op == "mul":
                v = lv * rv
            elif _op == "div":
                # IEEE semantics (matches the host tier): x/0 = ±inf, 0/0 = nan
                dt = jnp.float32 if f32 else jnp.float64
                v = lv.astype(dt) / rv.astype(dt)
            elif _op == "floordiv":
                v = jnp.floor_divide(lv, jnp.where(rv == 0, 1, rv))
            elif _op == "mod":
                v = jnp.mod(lv, jnp.where(rv == 0, 1, rv))
            elif _op == "pow":
                v = jnp.power(lv.astype(jnp.float32 if f32 else jnp.float64), rv)
            elif _op == "lt":
                v = lv < rv
            elif _op == "le":
                v = lv <= rv
            elif _op == "gt":
                v = lv > rv
            elif _op == "ge":
                v = lv >= rv
            elif _op == "eq":
                v = lv == rv
            else:
                v = lv != rv
            return v, m
        return fn

    if op in _BOOL:
        cl = _build(e.args[0], ctx, f32)
        cr = _build(e.args[1], ctx, f32)
        ldt = _dtype_of(e.args[0], ctx)
        if ldt.is_integer():
            jop = {"and": jnp.bitwise_and, "or": jnp.bitwise_or,
                   "xor": jnp.bitwise_xor}[op]
            return lambda env: (lambda a, b: (jop(a[0], b[0]), a[1] & b[1]))(
                cl(env), cr(env))

        def bfn(env, _op=op):
            lv, lm = cl(env)
            rv, rm = cr(env)
            lv = lv.astype(jnp.bool_)
            rv = rv.astype(jnp.bool_)
            if _op == "and":
                # Kleene: F & x = F even if x null
                v = lv & rv
                m = (lm & rm) | (lm & ~lv) | (rm & ~rv)
            elif _op == "or":
                v = lv | rv
                m = (lm & rm) | (lm & lv) | (rm & rv)
            else:
                v = lv ^ rv
                m = lm & rm
            return v, m
        return bfn

    if op == "not":
        c = _build(e.args[0], ctx, f32)
        return lambda env: (lambda v_m: (~v_m[0].astype(jnp.bool_), v_m[1]))(c(env))
    if op == "negate":
        c = _build(e.args[0], ctx, f32)
        return lambda env: (lambda v_m: (-v_m[0], v_m[1]))(c(env))
    if op == "abs":
        c = _build(e.args[0], ctx, f32)
        return lambda env: (lambda v_m: (jnp.abs(v_m[0]), v_m[1]))(c(env))
    if op == "is_null":
        c = _build(e.args[0], ctx, f32)
        return lambda env: (lambda v_m: (~v_m[1], jnp.ones_like(v_m[1])))(c(env))
    if op == "not_null":
        c = _build(e.args[0], ctx, f32)
        return lambda env: (lambda v_m: (v_m[1], jnp.ones_like(v_m[1])))(c(env))
    if op == "fill_null":
        if _is_str(e.args[0], ctx):
            raise NotCompilable("fill_null on strings")
        c = _build(e.args[0], ctx, f32)
        cf = _build(e.args[1], ctx, f32)

        def ffn(env):
            v, m = c(env)
            fv, fm = cf(env)
            return jnp.where(m, v, fv.astype(v.dtype)), m | fm
        return ffn
    if op == "between":
        inner = Expression("and", (Expression("ge", (e.args[0], e.args[1])),
                                   Expression("le", (e.args[0], e.args[2]))))
        return _build(inner, ctx, f32)
    if op == "is_in":
        target = e.args[0]
        items = e.args[1:]
        if not all(i.op == "lit" for i in items):
            raise NotCompilable("is_in with non-literal items")
        if _is_str(target, ctx):
            src = target._unalias()
            if src.op != "col":
                raise NotCompilable("string is_in on computed values")
            ctx.need(src.params[0])
            lits = [i.params[0] for i in items]

            def spec_fn(d: pa.Array) -> np.ndarray:
                dn = _dict_np(d)
                out = []
                for L in lits:
                    i = np.searchsorted(dn, L)
                    out.append(i if i < len(dn) and dn[i] == L else -1)
                return np.asarray(out, dtype=np.int32)
            si = ctx.add_scalar(ScalarSpec(src.params[0], spec_fn))
            name = src.params[0]
            return lambda env: (
                (env[0][name][:, None] == env[3][si][None, :]).any(axis=-1),
                env[1][name])
        c = _build(target, ctx, f32)
        vals = [i.params[0] for i in items]
        consts = jnp.asarray(np.asarray(vals))

        def ifn(env):
            v, m = c(env)
            return (v[:, None] == consts[None, :]).any(axis=-1), m
        return ifn
    if op == "if_else":
        cp = _build(e.args[0], ctx, f32)
        ct = _build(e.args[1], ctx, f32)
        cf2 = _build(e.args[2], ctx, f32)
        if _is_str(e.args[1], ctx) or _is_str(e.args[2], ctx):
            raise NotCompilable("if_else over strings")

        def iefn(env):
            pv, pm = cp(env)
            tv, tm = ct(env)
            fv, fm = cf2(env)
            tv, fv = jnp.broadcast_arrays(tv, fv)
            v = jnp.where(pv.astype(jnp.bool_), tv, fv)
            m = jnp.where(pv.astype(jnp.bool_), tm, fm) & pm
            return v, m
        return iefn
    if op in ("ceil", "floor", "round", "sign"):
        c = _build(e.args[0], ctx, f32)
        j = {"ceil": jnp.ceil, "floor": jnp.floor, "sign": jnp.sign}.get(op)
        if op == "round":
            nd = e.params[0]
            return lambda env: (lambda v_m: (jnp.round(v_m[0], nd), v_m[1]))(c(env))
        return lambda env: (lambda v_m: (j(v_m[0]), v_m[1]))(c(env))
    if op in _UNARY_F:
        c = _build(e.args[0], ctx, f32)
        j = _UNARY_F[op]
        fdt = jnp.float32 if f32 else jnp.float64
        return lambda env: (lambda v_m: (j(v_m[0].astype(fdt)), v_m[1]))(c(env))
    if op == "log":
        c = _build(e.args[0], ctx, f32)
        base = math.log(e.params[0])
        fdt = jnp.float32 if f32 else jnp.float64
        return lambda env: (lambda v_m: (jnp.log(v_m[0].astype(fdt)) / base,
                                         v_m[1]))(c(env))
    if op == "clip":
        c = _build(e.args[0], ctx, f32)
        lo = e.args[1].params[0] if len(e.args) > 1 and e.args[1].op == "lit" else None
        hi = e.args[2].params[0] if len(e.args) > 2 and e.args[2].op == "lit" else None
        return lambda env: (lambda v_m: (
            jnp.clip(v_m[0], lo if lo is not None else -jnp.inf,
                     hi if hi is not None else jnp.inf), v_m[1]))(c(env))
    if op == "float.is_nan":
        c = _build(e.args[0], ctx, f32)
        return lambda env: (lambda v_m: (jnp.isnan(v_m[0]), v_m[1]))(c(env))
    if op == "float.is_inf":
        c = _build(e.args[0], ctx, f32)
        return lambda env: (lambda v_m: (jnp.isinf(v_m[0]), v_m[1]))(c(env))
    if op == "float.not_nan":
        c = _build(e.args[0], ctx, f32)
        return lambda env: (lambda v_m: (~jnp.isnan(v_m[0]), v_m[1]))(c(env))
    if op == "float.fill_nan":
        c = _build(e.args[0], ctx, f32)
        cf = _build(e.args[1], ctx, f32)

        def fnan(env):
            v, m = c(env)
            fv, _ = cf(env)
            return jnp.where(jnp.isnan(v), fv.astype(v.dtype), v), m
        return fnan

    if op in ("dt.year", "dt.month", "dt.day", "dt.day_of_week", "dt.quarter",
              "dt.hour", "dt.minute", "dt.second", "dt.date"):
        return _build_dt(e, ctx, f32)

    if op == "hash":
        c = _build(e.args[0], ctx, f32)

        def hfn(env):
            v, m = c(env)
            x = v.view(jnp.uint64) if v.dtype.itemsize == 8 else \
                v.astype(jnp.uint64)
            x = (x + jnp.uint64(0x9E3779B97F4A7C15))
            x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
            x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
            return x ^ (x >> 31), jnp.ones_like(m)
        return hfn

    raise NotCompilable(f"device compile for {op}")


def _build_str_cmp(e: Expression, ctx: _Ctx, f32: bool):
    op = e.op
    l, r = e.args
    # normalize to (col, lit)
    flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
            "eq": "eq", "neq": "neq"}
    if l.op == "lit" and r.op != "lit":
        l, r = r, l
        op = flip[op]
    src = l._unalias()
    if src.op != "col" or r.op != "lit" or not isinstance(r.params[0], str):
        raise NotCompilable("string comparison requires col vs str literal")
    name = src.params[0]
    if name not in ctx.schema or not ctx.schema[name].dtype.is_string():
        raise NotCompilable("string cmp on non-string column")
    ctx.need(name)
    lit = r.params[0]
    if op == "eq":
        si = ctx.add_scalar(_rank_spec(name, lit, "eq"))
        return lambda env: (env[0][name] == env[3][si], env[1][name])
    if op == "neq":
        si = ctx.add_scalar(_rank_spec(name, lit, "eq"))
        return lambda env: (env[0][name] != env[3][si], env[1][name])
    if op == "lt":
        si = ctx.add_scalar(_rank_spec(name, lit, "left"))
        return lambda env: (env[0][name] < env[3][si], env[1][name])
    if op == "ge":
        si = ctx.add_scalar(_rank_spec(name, lit, "left"))
        return lambda env: (env[0][name] >= env[3][si], env[1][name])
    if op == "le":
        si = ctx.add_scalar(_rank_spec(name, lit, "right"))
        return lambda env: (env[0][name] < env[3][si], env[1][name])
    if op == "gt":
        si = ctx.add_scalar(_rank_spec(name, lit, "right"))
        return lambda env: (env[0][name] >= env[3][si], env[1][name])
    raise NotCompilable(op)


def _build_dt(e: Expression, ctx: _Ctx, f32: bool):
    """Civil-calendar decomposition on device (days-from-epoch integer math)."""
    fn = e.op[3:]
    child = e.args[0]
    cdt = _dtype_of(child, ctx)
    c = _build(child, ctx, f32)

    if cdt.kind == "timestamp":
        unit = cdt.timeunit.value
        per_day = {"s": 86_400, "ms": 86_400_000, "us": 86_400_000_000,
                   "ns": 86_400_000_000_000}[unit]
        per_sec = {"s": 1, "ms": 1_000, "us": 1_000_000, "ns": 1_000_000_000}[unit]
    elif cdt.kind == "date":
        per_day, per_sec = 1, None
    else:
        raise NotCompilable(f"dt.{fn} on {cdt!r}")

    def days_of(v):
        return jnp.floor_divide(v.astype(jnp.int64), per_day) if per_day != 1 \
            else v.astype(jnp.int64)

    def civil(z):
        z = z + 719468
        era = jnp.floor_divide(z, 146097)
        doe = z - era * 146097
        yoe = jnp.floor_divide(
            doe - jnp.floor_divide(doe, 1460) + jnp.floor_divide(doe, 36524)
            - jnp.floor_divide(doe, 146096), 365)
        y = yoe + era * 400
        doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4)
                     - jnp.floor_divide(yoe, 100))
        mp = jnp.floor_divide(5 * doy + 2, 153)
        d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
        m = jnp.where(mp < 10, mp + 3, mp - 9)
        y = jnp.where(m <= 2, y + 1, y)
        return y, m, d

    def out(env):
        v, mvalid = c(env)
        days = days_of(v)
        if fn == "date":
            return days.astype(jnp.int32), mvalid
        if fn in ("year", "month", "day", "quarter"):
            y, m, d = civil(days)
            if fn == "year":
                return y.astype(jnp.int32), mvalid
            if fn == "month":
                return m.astype(jnp.uint32), mvalid
            if fn == "quarter":
                return (jnp.floor_divide(m - 1, 3) + 1).astype(jnp.uint32), mvalid
            return d.astype(jnp.uint32), mvalid
        if fn == "day_of_week":
            return ((days + 3) % 7).astype(jnp.uint32), mvalid  # 1970-01-01 = Thu
        secs = jnp.floor_divide(v.astype(jnp.int64), per_sec) if per_sec else None
        sod = secs - days * 86400
        if fn == "hour":
            return jnp.floor_divide(sod, 3600).astype(jnp.uint32), mvalid
        if fn == "minute":
            return (jnp.floor_divide(sod, 60) % 60).astype(jnp.uint32), mvalid
        if fn == "second":
            return (sod % 60).astype(jnp.uint32), mvalid
        raise NotCompilable(fn)
    return out
