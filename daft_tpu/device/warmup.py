"""AOT session warm-up: compile the device program library before the
first query needs it (``DAFT_TPU_AOT_WARMUP=1``).

ROADMAP item 1's warm-up tax (55s of first-query traces + compiles in
r12) is paid once per (program, size class) — so pay it at session
start, off the query path, and PERSIST it: with
``DAFT_TPU_COMPILE_CACHE_DIR`` set, every ``jit(...).lower().compile()``
here lands in the XLA compilation cache, and the next process re-loads
the executable from disk instead of re-compiling (tracing still runs,
but tracing is milliseconds; compiling was the seconds).  This is the
piece the r11 serving plane's single-flight compile cache needed to
amortize across a fleet: one warm-up populates the shared directory,
every replica reads it.

Two grids, both over the ``column.size_classes`` ladder:

- :func:`warmup_kernels` — the shared device kernel library (argsort,
  grouped-agg, compaction) at representative key layouts;
- :func:`warmup_fragments` — every fused-agg program compiled so far
  (``fragment.fused_programs()``), per strategy, at the first-dispatch
  out-cap bucket.  Fragments with data-dependent scalar planes (string
  dictionaries) are skipped and counted: their shapes aren't knowable
  ahead of data.

All compiles run under the ``warmup.aot`` dispatch scope, which the
dispatch registry marks exempt — the retrace sanitizer counts them but
never budget-fails a deliberate warm-up.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

#: default top of the warm-up grid: programs above this capacity are
#: compiled on demand (one trace each, amortized by the same cache)
_DEFAULT_MAX_CAPACITY = 1 << 20
#: ...and default bottom: morsel-sized batches below this are cheap to
#: trace on demand, so the grid starts where compiles start to matter
_DEFAULT_MIN_CAPACITY = 1 << 10


def warmup_kernels(classes: List[int]) -> Dict[str, int]:
    """AOT-compile the shared kernel library over the size-class grid.
    Returns ``{"programs": n, "errors": m}``."""
    import jax

    from ..analysis import retrace_sanitizer
    from . import column as dcol
    from . import kernels
    programs = errors = 0
    fval = np.dtype(np.float64 if dcol.supports_f64() else np.float32)
    for cap in classes:
        k = jax.ShapeDtypeStruct((cap,), np.int64)
        b = jax.ShapeDtypeStruct((cap,), np.bool_)
        v = jax.ShapeDtypeStruct((cap,), fval)
        grid = []
        for nk in (1, 2):
            grid.append(lambda nk=nk: kernels.argsort_kernel.lower(
                (k,) * nk, (b,) * nk, b,
                descending=(False,) * nk,
                nulls_first=(False,) * nk).compile())
        grid.append(lambda: kernels.grouped_agg_kernel.lower(
            (k,), (b,), (v,), (b,), b, ops=("sum",)).compile())
        grid.append(lambda: kernels.compaction_perm.lower(b).compile())
        for fn in grid:
            with retrace_sanitizer.dispatch_scope("warmup.aot",
                                                  ("kernels", cap)):
                try:
                    fn()
                    programs += 1
                except Exception:
                    errors += 1
    return {"programs": programs, "errors": errors}


def warmup_fragments(classes: List[int],
                     progs: Optional[list] = None) -> Dict[str, int]:
    """AOT-compile the fused fragment library over size class x
    strategy.  Returns program/skip/error counts."""
    import jax

    from ..analysis import retrace_sanitizer
    from . import fragment, pallas_kernels
    progs = fragment.fused_programs() if progs is None else progs
    programs = skipped = errors = 0
    for prog in progs:
        if prog.in_np_dtypes is None or prog.compiled.scalar_specs:
            skipped += 1   # string-scalar planes are data-shaped
            continue
        strategies = ["sort"]
        if prog.nk and not prog.hash_unfit:
            strategies.append("hash")
        for cap in classes:
            arrays = {n: jax.ShapeDtypeStruct((cap,), dt)
                      for n, dt in prog.in_np_dtypes.items()}
            valids = {n: jax.ShapeDtypeStruct((cap,), np.bool_)
                      for n in prog.in_np_dtypes}
            mask = jax.ShapeDtypeStruct((cap,), np.bool_)
            out_cap = min(fragment._OUT_CAP0, cap)
            for strategy in strategies:
                with retrace_sanitizer.dispatch_scope(
                        "warmup.aot", ("fragment", id(prog), cap,
                                       strategy)):
                    try:
                        prog.packed_fn.lower(
                            arrays, valids, mask, (),
                            out_cap=out_cap,
                            strategy=strategy).compile()
                        programs += 1
                    except pallas_kernels.HashKeyWidthError:
                        prog.hash_unfit = True
                    except Exception:
                        errors += 1
    return {"programs": programs, "skipped": skipped, "errors": errors}


def warmup_regions(classes: List[int],
                   progs: Optional[list] = None) -> Dict[str, int]:
    """AOT-compile every fusion-region program seen so far (round 21's
    whole-query compilation library, ``fragment.fused_region_programs()``)
    over the size-class grid at each shape's first-dispatch width rung.
    join_agg regions warm the probe=build diagonal of their 2-D capacity
    grid — off-diagonal pairs compile on demand into the same cache."""
    import jax

    from ..analysis import retrace_sanitizer
    from . import column as dcol
    from . import fragment
    progs = fragment.fused_region_programs() if progs is None else progs
    programs = skipped = errors = 0
    for prog in progs:
        if prog.in_np_dtypes is None:
            skipped += 1
            continue
        is_join = isinstance(prog, fragment.FusedJoinAggProgram)
        if is_join:
            if prog.build_np_dtypes is None or prog.c_post.scalar_specs \
                    or (prog.c_pred is not None
                        and prog.c_pred.scalar_specs):
                skipped += 1
                continue
        elif prog.compiled.scalar_specs:
            skipped += 1   # string-scalar planes are data-shaped
            continue
        for cap in classes:
            arrays = {n: jax.ShapeDtypeStruct((cap,), dt)
                      for n, dt in prog.in_np_dtypes.items()}
            valids = {n: jax.ShapeDtypeStruct((cap,), np.bool_)
                      for n in prog.in_np_dtypes}
            mask = jax.ShapeDtypeStruct((cap,), np.bool_)
            with retrace_sanitizer.dispatch_scope(
                    "warmup.aot", ("region", id(prog), cap)):
                try:
                    if is_join:
                        b_arrays = {n: jax.ShapeDtypeStruct((cap,), dt)
                                    for n, dt
                                    in prog.build_np_dtypes.items()}
                        b_valids = {n: jax.ShapeDtypeStruct(
                            (cap,), np.bool_)
                            for n in prog.build_np_dtypes}
                        b_sorted = jax.ShapeDtypeStruct(
                            (cap,), prog.build_np_dtypes[prog.rkey])
                        b_perm = jax.ShapeDtypeStruct((cap,), np.int32)
                        b_live = jax.ShapeDtypeStruct((), np.int32)
                        prog.packed_fn.lower(
                            arrays, valids, mask, (), b_arrays, b_valids,
                            b_sorted, b_perm, b_live, (), W=cap,
                            out_cap=min(fragment._OUT_CAP0, cap)
                        ).compile()
                    else:
                        if prog.shape == "topk":
                            out_w = min(dcol.bucket_capacity(
                                max(prog.limit, 1)), cap)
                        elif not prog.has_pred:
                            out_w = cap
                        else:
                            out_w = min(dcol.bucket_capacity(
                                max(cap // 4, fragment._OUT_CAP0)), cap)
                        prog.packed_fn.lower(
                            arrays, valids, mask, (),
                            out_w=out_w).compile()
                    programs += 1
                except Exception:
                    errors += 1
    return {"programs": programs, "skipped": skipped, "errors": errors}


def warmup_session(max_capacity: int = _DEFAULT_MAX_CAPACITY,
                   min_capacity: int = _DEFAULT_MIN_CAPACITY,
                   kernels: bool = True,
                   fragments: bool = True,
                   regions: bool = True) -> Dict[str, object]:
    """Run the full warm-up (kernel library + fragment library + fusion
    regions) over the configured size-class ladder; returns a stats
    dict.  Callers gate on ``DAFT_TPU_AOT_WARMUP`` (the serving
    scheduler does at startup)."""
    from . import column as dcol
    t0 = time.perf_counter()
    classes = dcol.size_classes(max_capacity, min_capacity)
    stats: Dict[str, object] = {"size_classes": list(classes)}
    if kernels:
        stats["kernels"] = warmup_kernels(classes)
    if fragments:
        stats["fragments"] = warmup_fragments(classes)
    if regions:
        stats["regions"] = warmup_regions(classes)
    stats["seconds"] = round(time.perf_counter() - t0, 3)
    return stats


def warmup_enabled() -> bool:
    """Env var is the per-process override; unset, the per-query
    ``ExecutionConfig.tpu_aot_warmup`` field applies."""
    from ..analysis import knobs
    if knobs.env_is_set("DAFT_TPU_AOT_WARMUP"):
        return bool(knobs.env_bool("DAFT_TPU_AOT_WARMUP"))
    try:
        from ..context import get_context
        return bool(get_context().execution_config.tpu_aot_warmup)
    except Exception:
        return False


def maybe_warmup_session() -> Optional[Dict[str, object]]:
    """Knob-gated warm-up for session/serving startup; never raises
    (a warm-up failure must not take the serving plane down)."""
    if not warmup_enabled():
        return None
    try:
        return warmup_session()
    except Exception:
        return None
