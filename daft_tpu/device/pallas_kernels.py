"""Hash-based relational kernels as Pallas programs (round 12).

The r6 sort-based kernels close the dispatch-count gap but leave ~400x of
the roofline on the table (BENCH_r05 ``mfu`` block: grouped-agg at 0.012%
MFU / 0.067% of the memory roofline, join at 0.004%): every radix pass
re-streams every packed key plane through HBM, and the segment reductions
re-stream the value planes once more. The kernels here are the one-pass
hash formulation the reference engine uses host-side (probe tables,
``src/daft-recordbatch/src/probeable/probe_table.rs``), rebuilt as
TPU Pallas programs:

- ``hash_grouped_agg_impl``: an open-addressing hash table (linear
  probing over the r6 packed u64 key codes — ``kernels._sort_codes`` /
  ``kernels._packed_chunks`` are reused verbatim, so hash and sort agree
  bit-for-bit on key identity), accumulating the DECOMPOSABLE partial
  states of ``aggs.AGG_DECOMPOSITION`` (count / sum / sumsq / min / max /
  first) directly in the table slots. One pass over the data replaces
  sort + inverse-permutation sort + segment reductions.
- ``hash_join_impl``: build the same table over the build side with
  per-slot insertion-order chains (head/tail/next links), then stream the
  probe side through a second Pallas kernel emitting matched index pairs
  into the r6 packed ``[3, W]`` result matrix — same overflow
  re-dispatch contract as ``kernels.join_fused_impl``, same pair order
  (left-major, ascending right row), so it is a drop-in strategy swap.

Kernel shape (why the table rides VMEM values, not per-element refs):
each grid step streams one row block HBM→VMEM, loads the table planes
into loop-carried VALUES, runs the probe/insert loop as pure JAX
(``lax.while_loop`` probing, ``.at[].set/add/min/max`` updates — XLA
keeps loop-carried buffers in place), and writes the planes back once.
Grid steps execute sequentially on TPU, so the single-writer table needs
no atomics, and the only HBM traffic is ONE pass over the rows plus the
table spill/fill per block — the one-pass story the MFU ledger prices.
Tables above ``DAFT_TPU_KERNEL_MAX_TABLE`` slots do not fit VMEM and the
cost model keeps those dispatches on the sort path.

CPU backends (the tier-1 dev box) run the identical kernels under the
Pallas interpreter (``interpret=True``) so parity is provable without
silicon; ``DAFT_TPU_KERNEL_INTERPRET`` overrides the auto-detection.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

# same x64 requirement as kernels.py: the packed key codes are u64 words
jax.config.update("jax_enable_x64", True)

_M1 = np.uint64(0x9E3779B97F4A7C15)
_M2 = np.uint64(0xBF58476D1CE4E5B9)
_M3 = np.uint64(0x94D049BB133111EB)


class HashKeyWidthError(ValueError):
    """The key set packs wider than the hash-table key budget — the
    dispatch site must route this (program, key set) to the sort path,
    which handles any width as a stable LSD radix. A DEDICATED type so
    fallback handlers cannot swallow unrelated ``ValueError``s raised
    while tracing the hash program (those must surface, not silently pin
    the program to sort)."""


# ------------------------------------------------------------ configuration

def interpret_default() -> bool:
    """Pallas interpreter mode unless a real accelerator is attached.
    Stable per process (the backend cannot change under us), so reading it
    at trace time cannot mask a retrace."""
    from ..analysis import knobs
    v = knobs.env_raw("DAFT_TPU_KERNEL_INTERPRET")
    if v is not None:
        s = v.strip().lower()
        if s in ("1", "true", "on", "yes"):
            return True
        if s in ("0", "false", "off", "no"):
            return False
        # "auto" (the documented default spelling) or anything else:
        # backend autodetection — an operator exporting the displayed
        # default must not silently force the emulator onto silicon
    from . import backend
    return not backend.is_accelerator()


def block_rows(cap: int) -> int:
    """Rows per Pallas grid step (power of two, divides the padded
    capacity — both are powers of two)."""
    from ..analysis import knobs
    b = knobs.env_int("DAFT_TPU_KERNEL_BLOCK")
    b = 1 << max(int(b).bit_length() - 1, 0)  # round down to a power of 2
    return max(min(b, cap), 1)


def max_table_slots() -> int:
    from ..analysis import knobs
    return knobs.env_int("DAFT_TPU_KERNEL_MAX_TABLE")


def hash_load_factor() -> float:
    """Clamped STRICTLY below 1.0: the overflow contract needs the table
    to hold more slots than the group budget (a table with exactly
    ``out_cap`` slots fills silently instead of signalling ``group_count
    > out_cap``, dropping groups from the answer)."""
    from ..analysis import knobs
    return min(max(knobs.env_float("DAFT_TPU_KERNEL_HASH_LOAD"), 0.05),
               0.95)


def hash_pack_words(dtypes: Sequence) -> Optional[int]:
    """u64 words one table key occupies for these key dtypes (per-key
    null-rank bit + value bits, no dead bit — liveness is a separate
    mask), or None when the pack exceeds the hash-key budget
    (``DAFT_TPU_KERNEL_HASH_MAX_BITS``, ≤128) and the caller must take
    the sort path (which handles any width as a stable LSD radix)."""
    from ..analysis import knobs
    from . import kernels
    bits = sum(1 + kernels._key_bits(jnp.dtype(dt)) for dt in dtypes)
    limit = min(int(knobs.env_int("DAFT_TPU_KERNEL_HASH_MAX_BITS")), 128)
    if bits > limit:
        return None
    return 1 if bits <= 64 else 2


def table_capacity(out_cap: int) -> int:
    """Table slots for a group budget of ``out_cap``: the load-factor
    knob bounds probe-chain length (power of two for the mask probe)."""
    want = int(np.ceil(out_cap / hash_load_factor()))
    t = 128
    while t < want:
        t <<= 1
    return t


def _mix(w0: jnp.ndarray, w1: Optional[jnp.ndarray], tmask: int) -> jnp.ndarray:
    """splitmix64 finalizer over the packed key word(s) → table slot."""
    x = w0 if w1 is None else w0 ^ (w1 * _M1)
    x = (x + _M1)
    x = (x ^ (x >> jnp.uint64(30))) * _M2
    x = (x ^ (x >> jnp.uint64(27))) * _M3
    x = x ^ (x >> jnp.uint64(31))
    return (x.astype(jnp.uint32) & jnp.uint32(tmask)).astype(jnp.int32)


# --------------------------------------------------------- agg state planes

def agg_state_specs(ops: Tuple[str, ...], val_dtypes: Sequence
                    ) -> List[Tuple[int, str, str, object]]:
    """Table state planes for one agg list: ``(val_index, op, kind,
    dtype)`` rows, ``kind`` ∈ {cnt, sum, sumsq, min, max, first}.

    Accumulator dtypes mirror the sort kernels exactly so the two
    strategies stay value-parity (int/bool sums exact in i64, float sums
    in the value's own float width)."""
    specs: List[Tuple[int, str, str, object]] = []
    for i, (op, dt) in enumerate(zip(ops, val_dtypes)):
        dt = jnp.dtype(dt)
        is_float = jnp.issubdtype(dt, jnp.floating)
        acc = dt if is_float else jnp.int64
        specs.append((i, op, "cnt", jnp.int32))
        if op in ("sum", "mean", "var", "stddev"):
            specs.append((i, op, "sum", acc))
        if op in ("var", "stddev"):
            fdt = dt if dt == jnp.float32 else \
                jnp.zeros((), jnp.float64).dtype
            specs.append((i, op, "sumsq", fdt))
        if op in ("min", "bool_and"):
            specs.append((i, op, "min", jnp.int8 if dt == jnp.bool_ else dt))
        if op in ("max", "bool_or"):
            specs.append((i, op, "max", jnp.int8 if dt == jnp.bool_ else dt))
        if op == "any_value":
            specs.append((i, op, "first", jnp.int8 if dt == jnp.bool_
                          else dt))
    return specs


def _plane_identity(kind: str, dtype) -> jnp.ndarray:
    from . import kernels
    if kind in ("cnt", "sum", "sumsq", "first"):
        return jnp.zeros((), dtype)
    return kernels._identity_for(dtype, "min" if kind == "min" else "max")


# --------------------------------------------------- grouped-agg build call

def _agg_build_call(n_words: int, specs, val_dtypes, T: int, B: int,
                    C: int, interpret: bool):
    """The table-build ``pallas_call`` for one static (key width, agg
    plane set, table size, block size) signature."""
    tmask = T - 1

    def kernel(*refs):
        w_refs = refs[:n_words]
        live_ref = refs[n_words]
        v_refs = refs[n_words + 1: n_words + 1 + len(val_dtypes)]
        c_refs = refs[n_words + 1 + len(val_dtypes):
                      n_words + 1 + 2 * len(val_dtypes)]
        out = refs[n_words + 1 + 2 * len(val_dtypes):]
        tk_refs = out[:n_words]
        occ_ref, frow_ref = out[n_words], out[n_words + 1]
        plane_refs = out[n_words + 2: n_words + 2 + len(specs)]
        info_ref = out[-1]
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            for tr in tk_refs:
                tr[...] = jnp.zeros_like(tr)
            occ_ref[...] = jnp.zeros_like(occ_ref)
            frow_ref[...] = jnp.zeros_like(frow_ref)
            for pr, (_, _, kind, dt) in zip(plane_refs, specs):
                pr[...] = jnp.full_like(pr, _plane_identity(kind, dt))
            info_ref[...] = jnp.zeros_like(info_ref)

        words = [r[0, :] for r in w_refs]
        live = live_ref[0, :]
        vals = [r[0, :] for r in v_refs]
        contribs = [r[0, :] for r in c_refs]
        base = i * B

        def row(r, st):
            tks = list(st[:n_words])
            occ, frow = st[n_words], st[n_words + 1]
            planes = list(st[n_words + 2: n_words + 2 + len(specs)])
            g = st[-1]
            w0 = words[0][r]
            w1 = words[1][r] if n_words == 2 else None
            h = _mix(w0, w1, tmask)

            def cond(pst):
                j, steps = pst
                same = tks[0][j] == w0
                if n_words == 2:
                    same = same & (tks[1][j] == w1)
                return (occ[j] != 0) & (~same) & (steps < T)

            def step(pst):
                j, steps = pst
                return ((j + 1) & tmask, steps + 1)

            j, steps = lax.while_loop(cond, step, (h, jnp.int32(0)))
            ok = live[r] & (steps < T)  # steps == T: table full, drop —
            # the claim count then reads T > out_cap, forcing the caller's
            # overflow re-dispatch, so the dropped rows are never decoded
            claim = ok & (occ[j] == 0)
            tks[0] = jnp.where(claim, tks[0].at[j].set(w0), tks[0])
            if n_words == 2:
                tks[1] = jnp.where(claim, tks[1].at[j].set(w1), tks[1])
            frow = jnp.where(claim, frow.at[j].set(base + r), frow)
            g = g + claim.astype(jnp.int32)
            occ = jnp.where(claim, occ.at[j].set(1), occ)
            cnt_cache = {}
            for pi, (vi, op, kind, dt) in enumerate(specs):
                p = planes[pi]
                contrib = ok & contribs[vi][r]
                v = vals[vi][r]
                if kind == "cnt":
                    cnt_cache[vi] = p[j]  # pre-update count, for `first`
                    planes[pi] = p.at[j].add(contrib.astype(jnp.int32))
                elif kind in ("sum", "sumsq"):
                    x = v.astype(dt)
                    if kind == "sumsq":
                        x = x * x
                    planes[pi] = p.at[j].add(
                        jnp.where(contrib, x, jnp.zeros((), dt)))
                elif kind == "min":
                    planes[pi] = jnp.where(
                        contrib, p.at[j].min(v.astype(dt)), p)
                elif kind == "max":
                    planes[pi] = jnp.where(
                        contrib, p.at[j].max(v.astype(dt)), p)
                else:  # first (any_value): write on the 0→1 count edge
                    planes[pi] = jnp.where(
                        contrib & (cnt_cache[vi] == 0),
                        p.at[j].set(v.astype(dt)), p)
            return tuple(tks) + (occ, frow) + tuple(planes) + (g,)

        st0 = tuple(tr[0, :] for tr in tk_refs) \
            + (occ_ref[0, :], frow_ref[0, :]) \
            + tuple(pr[0, :] for pr in plane_refs) + (info_ref[0, 0],)
        st = lax.fori_loop(0, B, row, st0)
        for tr, v in zip(tk_refs, st[:n_words]):
            tr[0, :] = v
        occ_ref[0, :] = st[n_words]
        frow_ref[0, :] = st[n_words + 1]
        for pr, v in zip(plane_refs,
                         st[n_words + 2: n_words + 2 + len(specs)]):
            pr[0, :] = v
        info_ref[0, 0] = st[-1]

    blk = lambda: pl.BlockSpec((1, B), lambda i: (0, i))      # noqa: E731
    tbl = lambda n: pl.BlockSpec((1, n), lambda i: (0, 0))    # noqa: E731
    in_specs = [blk() for _ in range(n_words)] + [blk()] \
        + [blk() for _ in range(2 * len(val_dtypes))]
    out_specs = [tbl(T) for _ in range(n_words)] \
        + [tbl(T), tbl(T)] + [tbl(T) for _ in specs] + [tbl(8)]
    out_shape = [jax.ShapeDtypeStruct((1, T), jnp.uint64)
                 for _ in range(n_words)] \
        + [jax.ShapeDtypeStruct((1, T), jnp.int32),
           jax.ShapeDtypeStruct((1, T), jnp.int32)] \
        + [jax.ShapeDtypeStruct((1, T), dt) for _, _, _, dt in specs] \
        + [jax.ShapeDtypeStruct((1, 8), jnp.int32)]
    return pl.pallas_call(kernel, grid=(C // B,), in_specs=in_specs,
                          out_specs=out_specs, out_shape=out_shape,
                          interpret=interpret)


def hash_grouped_agg_impl(keys, key_valids, vals, val_valids, row_mask,
                          ops: Tuple[str, ...], out_cap: int,
                          table_cap: Optional[int] = None,
                          interpret: Optional[bool] = None,
                          block: Optional[int] = None):
    """One-pass hash grouped aggregation over padded device columns.

    Pure and traceable (composable inside the fused scan fragments);
    drop-in for :func:`kernels.grouped_agg_block_impl` — same argument
    shapes, same ``([out_cap] keys/valids/vals/valids, group_count)``
    return contract, same overflow discipline (``group_count > out_cap``
    → the caller re-dispatches at a grown bucket). Requires the key set
    to pack into ≤2 u64 words (``hash_pack_words``); wider key sets must
    stay on the sort path.

    Groups come back in table-slot order (deterministic for a given
    input, NOT key-sorted — grouped-aggregate output order is
    unspecified engine-wide, and partial blocks get re-merged anyway).
    """
    from . import kernels
    C = row_mask.shape[0]
    codes = kernels._sort_codes(keys, key_valids, row_mask,
                                (False,) * len(keys), (False,) * len(keys),
                                with_dead=False)
    chunks = kernels._packed_chunks(codes)
    if len(chunks) != 1:
        raise HashKeyWidthError(
            "hash grouped-agg requires ≤128-bit packed keys (caller must "
            "route wide key sets to the sort path)")
    words = chunks[0]
    n_words = len(words)
    T = table_cap if table_cap is not None else table_capacity(out_cap)
    B = block if block is not None else block_rows(C)
    if interpret is None:
        interpret = interpret_default()
    val_dtypes = tuple(v.dtype for v in vals)
    specs = agg_state_specs(ops, val_dtypes)

    def as_block(x, dt=None):
        x = x.astype(dt) if dt is not None else x
        return x.reshape(1, C)

    contribs = [as_block(vv & row_mask) for vv in val_valids]
    call = _agg_build_call(n_words, specs, val_dtypes, T, B, C, interpret)
    outs = call(*[as_block(w) for w in words], as_block(row_mask),
                *[as_block(v) for v in vals], *contribs)
    tk = outs[:n_words]
    occ, frow = outs[n_words][0], outs[n_words + 1][0]
    planes = [o[0] for o in outs[n_words + 2: n_words + 2 + len(specs)]]
    group_count = outs[-1][0, 0]

    # compact occupied slots to the front ([T]-sized 2-operand sort — tiny
    # next to the row pass, and stable so slot order is deterministic)
    order = lax.sort(((1 - occ).astype(jnp.int8),
                      jnp.arange(T, dtype=jnp.int32)), num_keys=1,
                     is_stable=True)[1]
    sel = order[:out_cap] if out_cap <= T else jnp.pad(
        order, (0, out_cap - T))
    j = jnp.arange(out_cap, dtype=jnp.int32)
    live_group = j < jnp.minimum(group_count, out_cap)

    first_row = jnp.clip(jnp.take(frow, sel), 0, C - 1)
    out_keys = tuple(jnp.take(k, first_row) for k in keys)
    out_kvalids = tuple(jnp.take(kv & row_mask, first_row) & live_group
                        for kv in key_valids)

    by_val: dict = {}
    for pi, (vi, op, kind, dt) in enumerate(specs):
        by_val.setdefault(vi, {})[kind] = jnp.take(planes[pi], sel)

    out_vals = []
    out_valids = []
    for vi, (v, op) in enumerate(zip(vals, ops)):
        st = by_val[vi]
        cnt = st["cnt"]
        has = live_group & (cnt > 0)
        if op == "count":
            out_vals.append(cnt.astype(jnp.int64))
            out_valids.append(live_group)
            continue
        if op in ("sum", "mean", "var", "stddev"):
            s1 = st["sum"]
            if op == "sum":
                out_vals.append(s1)
                out_valids.append(has)
                continue
            fdt = jnp.float32 if s1.dtype == jnp.float32 \
                else s1.astype(jnp.float64).dtype
            safe = jnp.maximum(cnt, 1).astype(fdt)
            mean = s1.astype(fdt) / safe
            if op == "mean":
                out_vals.append(mean)
                out_valids.append(has)
                continue
            var = jnp.maximum(st["sumsq"].astype(fdt) / safe - mean * mean,
                              0.0)
            out_vals.append(jnp.sqrt(var) if op == "stddev" else var)
            out_valids.append(has)
            continue
        if op in ("min", "bool_and", "max", "bool_or"):
            r = st["min" if op in ("min", "bool_and") else "max"]
            if v.dtype == jnp.bool_:
                r = r.astype(jnp.bool_)
            out_vals.append(r)
            out_valids.append(has)
            continue
        if op == "any_value":
            r = st["first"]
            if v.dtype == jnp.bool_:
                r = r.astype(jnp.bool_)
            out_vals.append(r)
            out_valids.append(has)
            continue
        raise ValueError(f"unsupported device agg {op}")

    return out_keys, out_kvalids, tuple(out_vals), tuple(out_valids), \
        group_count


_hash_agg_jit_cache: dict = {}


def hash_grouped_agg_kernel(keys, key_valids, vals, val_valids, row_mask,
                            ops: Tuple[str, ...], out_cap: int,
                            table_cap: Optional[int] = None):
    """Jitted entry (interpret/block resolved OUTSIDE the trace so the
    jit-hygiene contract — no host reads inside the program — holds)."""
    C = row_mask.shape[0]
    key = (len(keys), len(vals), ops, out_cap, table_cap,
           interpret_default(), block_rows(C))
    fn = _hash_agg_jit_cache.get(key)
    if fn is None:
        fn = jax.jit(partial(hash_grouped_agg_impl, ops=ops,
                             out_cap=out_cap, table_cap=table_cap,
                             interpret=key[5], block=key[6]))
        _hash_agg_jit_cache[key] = fn
    return fn(keys, key_valids, vals, val_valids, row_mask)


# ----------------------------------------------------------- hash join

def _join_build_call(T: int, B: int, C: int, interpret: bool):
    """Chained-bucket build: one pass over the build side inserting every
    live row into its key's slot chain (head/tail/next), ascending row
    order so probe output matches the sort path's pair order."""
    tmask = T - 1

    def kernel(code_ref, live_ref, tk_ref, occ_ref, head_ref, tail_ref,
               nxt_ref, info_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            tk_ref[...] = jnp.zeros_like(tk_ref)
            occ_ref[...] = jnp.zeros_like(occ_ref)
            head_ref[...] = jnp.full_like(head_ref, -1)
            tail_ref[...] = jnp.full_like(tail_ref, -1)
            nxt_ref[...] = jnp.full_like(nxt_ref, -1)
            info_ref[...] = jnp.zeros_like(info_ref)

        codes = code_ref[0, :]
        live = live_ref[0, :]
        base = i * B

        def row(r, st):
            tk, occ, head, tail, nxt, g = st
            code = codes[r]
            h = _mix(code, None, tmask)

            def cond(pst):
                j, steps = pst
                return (occ[j] != 0) & (tk[j] != code) & (steps < T)

            def step(pst):
                j, steps = pst
                return ((j + 1) & tmask, steps + 1)

            j, steps = lax.while_loop(cond, step, (h, jnp.int32(0)))
            # T ≥ 2 × build capacity: distinct keys ≤ live rows ≤ T/2, so
            # the table can never fill — `steps < T` is purely defensive
            ok = live[r] & (steps < T)
            claim = ok & (occ[j] == 0)
            rowid = jnp.int32(base + r)
            tk = jnp.where(claim, tk.at[j].set(code), tk)
            occ = jnp.where(claim, occ.at[j].set(1), occ)
            head = jnp.where(claim, head.at[j].set(rowid), head)
            # append at the tail: chains stay in ascending build-row order
            prev_tail = tail[j]
            nxt = jnp.where(ok & ~claim,
                            nxt.at[jnp.clip(prev_tail, 0, C - 1)]
                            .set(rowid), nxt)
            tail = jnp.where(ok, tail.at[j].set(rowid), tail)
            g = g + claim.astype(jnp.int32)
            return tk, occ, head, tail, nxt, g

        st0 = (tk_ref[0, :], occ_ref[0, :], head_ref[0, :], tail_ref[0, :],
               nxt_ref[0, :], info_ref[0, 0])
        tk, occ, head, tail, nxt, g = lax.fori_loop(0, B, row, st0)
        tk_ref[0, :] = tk
        occ_ref[0, :] = occ
        head_ref[0, :] = head
        tail_ref[0, :] = tail
        nxt_ref[0, :] = nxt
        info_ref[0, 0] = g

    blk = pl.BlockSpec((1, B), lambda i: (0, i))
    tbl = lambda n: pl.BlockSpec((1, n), lambda i: (0, 0))  # noqa: E731
    return pl.pallas_call(
        kernel, grid=(C // B,), in_specs=[blk, blk],
        out_specs=[tbl(T), tbl(T), tbl(T), tbl(T), tbl(C), tbl(8)],
        out_shape=[jax.ShapeDtypeStruct((1, T), jnp.uint64),
                   jax.ShapeDtypeStruct((1, T), jnp.int32),
                   jax.ShapeDtypeStruct((1, T), jnp.int32),
                   jax.ShapeDtypeStruct((1, T), jnp.int32),
                   jax.ShapeDtypeStruct((1, C), jnp.int32),
                   jax.ShapeDtypeStruct((1, 8), jnp.int32)],
        interpret=interpret)


def _join_probe_call(T: int, B: int, C_l: int, C_r: int, cap: int,
                     interpret: bool):
    """Probe stream: per probe row, walk the matched slot's chain emitting
    (left, right) pairs at a running cursor. Writes past ``cap`` are
    dropped but still COUNTED — the caller compares ``counts.sum()``
    against ``cap`` and re-dispatches at a grown bucket (the r6 overflow
    contract), so a too-small bucket costs one extra dispatch, never a
    wrong answer."""
    tmask = T - 1

    def kernel(code_ref, live_ref, tk_ref, occ_ref, head_ref, nxt_ref,
               counts_ref, owner_ref, ridx_ref, info_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            owner_ref[...] = jnp.zeros_like(owner_ref)
            ridx_ref[...] = jnp.zeros_like(ridx_ref)
            info_ref[...] = jnp.zeros_like(info_ref)

        codes = code_ref[0, :]
        live = live_ref[0, :]
        tk = tk_ref[0, :]
        occ = occ_ref[0, :]
        head = head_ref[0, :]
        nxt = nxt_ref[0, :]

        def row(r, st):
            counts, owner, ridx, cursor = st
            code = codes[r]
            h = _mix(code, None, tmask)

            def cond(pst):
                j, steps = pst
                return (occ[j] != 0) & (tk[j] != code) & (steps < T)

            def step(pst):
                j, steps = pst
                return ((j + 1) & tmask, steps + 1)

            j, steps = lax.while_loop(cond, step, (h, jnp.int32(0)))
            found = live[r] & (steps < T) & (occ[j] != 0) \
                & (tk[j] == code)
            ptr0 = jnp.where(found, head[j], jnp.int32(-1))

            def wcond(wst):
                return wst[0] != -1

            def wbody(wst):
                ptr, c, ow, ri = wst
                slot = cursor + c
                fits = slot < cap
                slot_c = jnp.clip(slot, 0, cap - 1)
                ow = jnp.where(fits, ow.at[slot_c].set(i * B + r), ow)
                ri = jnp.where(fits, ri.at[slot_c].set(ptr), ri)
                return nxt[jnp.clip(ptr, 0, C_r - 1)], c + 1, ow, ri

            _, c, owner, ridx = lax.while_loop(
                wcond, wbody, (ptr0, jnp.int32(0), owner, ridx))
            counts = counts.at[r].set(c)
            return counts, owner, ridx, cursor + c

        st0 = (jnp.zeros(B, jnp.int32), owner_ref[0, :], ridx_ref[0, :],
               info_ref[0, 0])
        counts, owner, ridx, cursor = lax.fori_loop(0, B, row, st0)
        counts_ref[0, :] = counts
        owner_ref[0, :] = owner
        ridx_ref[0, :] = ridx
        info_ref[0, 0] = cursor

    blk = pl.BlockSpec((1, B), lambda i: (0, i))
    tbl = lambda n: pl.BlockSpec((1, n), lambda i: (0, 0))  # noqa: E731
    return pl.pallas_call(
        kernel, grid=(C_l // B,),
        in_specs=[blk, blk, tbl(T), tbl(T), tbl(T), tbl(C_r)],
        out_specs=[blk, tbl(cap), tbl(cap), tbl(8)],
        out_shape=[jax.ShapeDtypeStruct((1, C_l), jnp.int32),
                   jax.ShapeDtypeStruct((1, cap), jnp.int32),
                   jax.ShapeDtypeStruct((1, cap), jnp.int32),
                   jax.ShapeDtypeStruct((1, 8), jnp.int32)],
        interpret=interpret)


def join_table_capacity(c_r: int) -> int:
    """Build-table slots: 2× the (power-of-two) build capacity, so the
    table can never fill (distinct keys ≤ live rows ≤ T/2)."""
    return max(2 * c_r, 128)


def hash_join_impl(l_key, l_valid, l_mask, r_key, r_valid, r_mask,
                   out_capacity: int,
                   interpret: Optional[bool] = None,
                   block: Optional[int] = None,
                   block_build: Optional[int] = None,
                   block_probe: Optional[int] = None):
    """Hash build/probe inner-equi-join index generation, one jit program
    returning the SAME packed int32 ``[3, max(out_capacity, C_l)]``
    matrix as :func:`kernels.join_fused_impl` (row 0/1: left/right row
    per output slot, row 2: per-left-row match counts; slots at or past
    ``counts.sum()`` are garbage; a total above ``out_capacity`` means
    the caller re-dispatches at a grown bucket). Pair order matches the
    sort path: left-major, ascending right row within a left row."""
    C_l, C_r = l_key.shape[0], r_key.shape[0]
    T = join_table_capacity(C_r)
    if interpret is None:
        interpret = interpret_default()
    if block_build is None:
        block_build = block if block is not None else block_rows(C_r)
    if block_probe is None:
        block_probe = block if block is not None else block_rows(C_l)
    b_build, b_probe = block_build, block_probe
    # NULL keys never match: liveness folds validity in, and dead rows
    # skip insert/probe entirely (their key word is never compared)
    r_code = r_key.astype(jnp.uint64).reshape(1, C_r)
    l_code = l_key.astype(jnp.uint64).reshape(1, C_l)
    r_live = (r_valid & r_mask).reshape(1, C_r)
    l_live = (l_valid & l_mask).reshape(1, C_l)
    tk, occ, head, _tail, nxt, _info = _join_build_call(
        T, b_build, C_r, interpret)(r_code, r_live)
    counts, owner, ridx, _cursor = _join_probe_call(
        T, b_probe, C_l, C_r, out_capacity, interpret)(
        l_code, l_live, tk, occ, head, nxt)
    W = max(out_capacity, C_l)
    packed = jnp.zeros((3, W), dtype=jnp.int32)
    packed = packed.at[0, :out_capacity].set(owner[0])
    packed = packed.at[1, :out_capacity].set(ridx[0])
    packed = packed.at[2, :C_l].set(counts[0])
    return packed


_hash_join_jit_cache: dict = {}


def hash_join_kernel(l_key, l_valid, l_mask, r_key, r_valid, r_mask,
                     out_capacity: int):
    """The jitted single-dispatch hash join. Build-side buffers are
    DONATED off-cpu (dead after the in-program table build, so XLA reuses
    their HBM for the table planes) — the same discipline as
    ``kernels.join_fused_kernel``."""
    from . import backend
    # daft-lint: allow(donation-unguarded) -- same as join_fused_kernel:
    # the donated build planes are per-dispatch packed key codes owned by
    # this call, never cache-shared DeviceTable buffers; residency is not
    # a concept for them
    donate = backend.is_accelerator()
    key = (donate, out_capacity, interpret_default(),
           block_rows(l_key.shape[0]), block_rows(r_key.shape[0]))
    fn = _hash_join_jit_cache.get(key)
    if fn is None:
        # interpret/block resolved OUTSIDE the trace and passed in (the
        # knob reads are host effects; the jit-hygiene discipline of
        # hash_grouped_agg_kernel) — the cache key already carries them
        fn = jax.jit(partial(hash_join_impl, out_capacity=out_capacity,
                             interpret=key[2], block_probe=key[3],
                             block_build=key[4]),
                     donate_argnums=(3, 4, 5) if donate else ())
        _hash_join_jit_cache[key] = fn
    return fn(l_key, l_valid, l_mask, r_key, r_valid, r_mask)
