"""TPU device tier: columns as padded JAX arrays, operators as XLA programs.

Design (SURVEY.md §7 "hard parts"):
- **Static shapes**: every morsel is padded to a power-of-two capacity bucket;
  a ``row_mask`` marks live rows. jax.jit caches one executable per
  (bucket, dtypes, op-structure) — bounded recompiles.
- **Selection as masks**: filters AND into ``row_mask`` instead of moving
  data; compaction happens only at sort/join/materialize boundaries.
- **Strings** dictionary-encode host-side with a *sorted* dictionary so code
  order == string order; device compares/sorts/groups int32 codes.
- **Group-by / join** are sort-based (``lax.sort`` + ``segment_sum``): the
  XLA-friendly formulation of the reference's hash tables
  (``probeable/probe_table.rs``).
"""

from . import column, compiler, kernels, runtime  # noqa: F401
