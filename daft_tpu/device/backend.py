"""Watchdog-guarded JAX backend initialization.

JAX initializes its PJRT client lazily on the first ``jax.default_backend()``
/ ``jnp`` call, and a broken or slow device plugin (e.g. a remote-tunnel TPU
plugin) can hang that call forever. The reference engine never has this
problem because its backend is the CPU it is already running on; for a
device-tiered engine the backend is a *fallible external resource* and must
be probed exactly once, under a timeout, from a single thread — never raced
from N scan workers (cf. the frozen-per-query config bootstrap discipline in
the reference, ``src/common/daft-config/src/lib.rs:40-68``).

Semantics:
- :func:`probe` starts (once) a daemon thread that touches the backend.
- :func:`backend_name` / :func:`device_ready` wait up to the configured
  timeout for that probe; on timeout or error the device tier is marked
  unavailable for the life of the process and the engine pins itself to the
  host tier. The stuck thread is left to its fate (daemon).
- ``DAFT_TPU_BACKEND_TIMEOUT`` (seconds, default 60) bounds the wait.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

_lock = threading.Lock()
_probe_thread: Optional[threading.Thread] = None
_done = threading.Event()
_backend: Optional[str] = None
_failed = False


def _timeout() -> float:
    from ..analysis import knobs
    return knobs.env_float("DAFT_TPU_BACKEND_TIMEOUT")


def _probe_body() -> None:
    global _backend, _failed
    try:
        import jax

        # daft-lint: allow(unguarded-global-mutation) -- the _done Event is
        # the sync point: readers wait on it, this write happens-before set()
        _backend = jax.default_backend()

        # persistent XLA compilation cache: suite runs stop paying the
        # (remote, 10-160s) compile for every (bucket, dtype, op) shape a
        # fresh process touches — the round-3 device suite lost to its own
        # host fallback largely on warm-compile tax. TPU-only: CPU AOT
        # artifacts are machine-feature-pinned and reload with SIGILL-risk
        # warnings across hosts. Opt out with DAFT_TPU_COMPILATION_CACHE=0
        # or point it elsewhere via =path.
        from ..analysis import knobs
        cache = knobs.env_str("DAFT_TPU_COMPILATION_CACHE") \
            or knobs.env_str("DAFT_TPU_COMPILE_CACHE") or ""
        # DAFT_TPU_COMPILE_CACHE_DIR is the round-16 explicit opt-in:
        # a persistent cache on ANY backend (CPU included), for AOT
        # warm-up artifacts that must survive process restarts on the
        # SAME machine.  The TPU-only default below stays: CPU AOT
        # artifacts are machine-feature-pinned and unsafe to share.
        explicit = knobs.env_str("DAFT_TPU_COMPILE_CACHE_DIR")
        if explicit:
            try:
                os.makedirs(explicit, exist_ok=True)
            except OSError as exc:
                # an EXPLICIT opt-in pointing at an unwritable path is
                # misconfiguration, not version skew — say so instead of
                # silently recompiling from scratch on every replica
                import sys
                print(f"daft-tpu: DAFT_TPU_COMPILE_CACHE_DIR="
                      f"{explicit!r} is unusable ({exc}); persistent "
                      f"compile cache DISABLED", file=sys.stderr)
            else:
                try:
                    jax.config.update("jax_compilation_cache_dir",
                                      explicit)
                    jax.config.update(
                        "jax_persistent_cache_min_compile_time_secs",
                        0.0)
                    jax.config.update(
                        "jax_persistent_cache_min_entry_size_bytes", -1)
                except Exception:
                    pass  # older jax without the knobs: in-memory only
        elif cache != "0" and _backend == "tpu":
            path = cache or os.path.join(
                os.path.expanduser("~"), ".cache", "daft_tpu_xla")
            try:
                os.makedirs(path, exist_ok=True)
                jax.config.update("jax_compilation_cache_dir", path)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.5)
            except Exception:
                pass  # older jax without the knob: in-memory cache only
    except Exception:
        # daft-lint: allow(unguarded-global-mutation) -- Event-synchronized
        # with readers (see _backend above)
        _failed = True
    finally:
        _done.set()


def probe() -> None:
    """Kick off backend initialization in the background (idempotent)."""
    global _probe_thread
    with _lock:
        if _probe_thread is None:
            _probe_thread = threading.Thread(
                target=_probe_body, name="daft-tpu-backend-probe", daemon=True)
            _probe_thread.start()


def backend_name(wait: bool = True) -> Optional[str]:
    """The initialized backend name, or None if unavailable/timed out."""
    global _failed
    if _failed:
        return None
    probe()
    if wait and not _done.is_set():
        _done.wait(_timeout())
        if not _done.is_set():
            # timed out: permanently mark the device tier unusable so later
            # callers don't re-block for another full timeout.
            # daft-lint: allow(unguarded-global-mutation) -- worst case two
            # timed-out threads both store True; probe never clears it
            _failed = True
            return None
    if not _done.is_set():
        return None  # non-waiting peek while the probe is in flight
    return None if _failed else _backend


def device_ready() -> bool:
    """True once the JAX backend initialized successfully within timeout."""
    return backend_name() is not None


def is_accelerator() -> bool:
    """True when the initialized backend is real silicon, not the CPU
    tier — the SINGLE predicate for buffer donation, compiled-Pallas
    capability (vs the interpreter), and the hash-strategy gate. New
    backend strings (gpu, tunneled devices) get classified here once,
    not at every dispatch site."""
    return (backend_name() or "cpu") != "cpu"


def reset_for_tests() -> None:
    global _probe_thread, _backend, _failed
    with _lock:
        _probe_thread = None
        _backend = None
        _failed = False
        _done.clear()
