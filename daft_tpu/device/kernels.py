"""Sort-based relational kernels as jit-compiled XLA programs.

These replace the reference's hash-table kernels (probe tables
``src/daft-recordbatch/src/probeable/probe_table.rs:19``, grouped aggregate
``src/daft-local-execution/src/sinks/grouped_aggregate.rs``) with the
XLA-friendly sort + segment-reduce formulation (SURVEY.md §7 hard-part #3):

- ``grouped_agg``: lexicographic ``lax.sort`` on key planes → segment ids via
  boundary cumsum → ``jax.ops.segment_*`` reductions. Static shapes
  throughout; outputs padded to capacity with a live-group count.
- ``argsort``: multi-key, per-key descending + nulls-first, returns a
  permutation (host applies it with Arrow take — device computes *indices*,
  variable-width payloads never leave the host).
- ``merge_join_indices``: two-phase sort/searchsorted inner-equi-join index
  generation with the prefix-sum expansion trick.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def _sort_key_plane(v: jnp.ndarray, valid: jnp.ndarray, descending: bool,
                    nulls_first: bool) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(null_rank, transformed_value) planes for one sort key."""
    null_rank = jnp.where(valid,
                          jnp.int8(1) if nulls_first else jnp.int8(0),
                          jnp.int8(0) if nulls_first else jnp.int8(1))
    x = v
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.int8)
    if descending:
        if jnp.issubdtype(x.dtype, jnp.unsignedinteger):
            x = jnp.asarray(jnp.iinfo(x.dtype).max, x.dtype) - x
        elif jnp.issubdtype(x.dtype, jnp.floating):
            x = -x
        else:
            x = -x.astype(jnp.int64) if x.dtype == jnp.int64 else -x.astype(jnp.int32) \
                if x.dtype in (jnp.int8, jnp.int16, jnp.int32) else -x
    x = jnp.where(valid, x, jnp.zeros((), x.dtype))
    return null_rank, x


@partial(jax.jit, static_argnames=("descending", "nulls_first"))
def argsort_kernel(keys, valids, row_mask, descending: Tuple[bool, ...],
                   nulls_first: Tuple[bool, ...]):
    """Returns the permutation placing live rows first in key order."""
    C = row_mask.shape[0]
    operands = [(~row_mask).astype(jnp.int8)]
    for v, valid, d, nf in zip(keys, valids, descending, nulls_first):
        nr, x = _sort_key_plane(v, valid & row_mask, d, nf)
        operands.append(nr)
        operands.append(x)
    operands.append(jnp.arange(C, dtype=jnp.int32))
    out = lax.sort(tuple(operands), num_keys=len(operands) - 1, is_stable=True)
    return out[-1]


@partial(jax.jit)
def compaction_perm(row_mask):
    """Permutation moving live rows to the front (stable)."""
    C = row_mask.shape[0]
    out = lax.sort(((~row_mask).astype(jnp.int8),
                    jnp.arange(C, dtype=jnp.int32)), num_keys=1, is_stable=True)
    return out[1]


# ---------------------------------------------------------------------------
# grouped aggregation

_SEGMENT_AGGS = ("sum", "count", "min", "max", "mean", "var", "stddev",
                 "any_value", "bool_and", "bool_or")


def _identity_for(dtype, op):
    if op == "min":
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(jnp.inf, dtype)
        return jnp.asarray(jnp.iinfo(dtype).max, dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(-jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).min, dtype)


def grouped_agg_impl(keys, key_valids, vals, val_valids, row_mask,
                     ops: Tuple[str, ...]):
    """Sort-based grouped aggregation over padded device columns (pure —
    composable inside larger jit programs, e.g. fused scan fragments).

    keys/vals: tuples of [C] arrays. Returns (out_keys, out_key_valids,
    out_vals, out_val_valids, group_count); outputs are [C]-padded, groups in
    ascending key order (so string-code groups decode in sorted order).
    """
    C = row_mask.shape[0]
    dead = (~row_mask).astype(jnp.int8)
    operands = [dead]
    for k, kv in zip(keys, key_valids):
        nr, x = _sort_key_plane(k, kv & row_mask, False, False)
        operands.append(nr)
        operands.append(x)
    # Sort ONLY key planes + a row index, then gather payloads through the
    # permutation: TPU sort compile time and runtime grow steeply with
    # operand count (a 21-operand sort took >5 min to compile where this
    # shape compiles in seconds), while gathers are cheap single-fusion ops.
    operands.append(jnp.arange(C, dtype=jnp.int32))
    out = lax.sort(tuple(operands), num_keys=len(operands) - 1,
                   is_stable=True)
    perm = out[-1]
    s_keys = [jnp.take(k, perm) for k in keys]
    s_kvalids = [jnp.take(kv & row_mask, perm) for kv in key_valids]
    s_vals = [jnp.take(v, perm) for v in vals]
    s_vvalids = [jnp.take(vv & row_mask, perm) for vv in val_valids]
    s_live = jnp.take(row_mask, perm)

    # boundary detection over (key value, key validity) among live rows
    idx = jnp.arange(C)
    diff = jnp.zeros(C, dtype=jnp.bool_).at[0].set(True)
    for k, kv in zip(s_keys, s_kvalids):
        prev_k = jnp.concatenate([k[:1], k[:-1]])
        prev_v = jnp.concatenate([kv[:1], kv[:-1]])
        diff = diff | (k != prev_k) | (kv != prev_v)
    prev_live = jnp.concatenate([jnp.zeros(1, jnp.bool_), s_live[:-1]])
    diff = diff | (s_live & ~prev_live)
    flags = diff & s_live
    seg = jnp.cumsum(flags.astype(jnp.int32)) - 1
    seg = jnp.where(s_live, seg, C - 1)  # dead rows -> trailing segment
    group_count = jnp.sum(flags.astype(jnp.int32))

    first_idx = jax.ops.segment_min(
        jnp.where(s_live, idx, C - 1), seg, num_segments=C)
    first_idx = jnp.clip(first_idx, 0, C - 1)

    out_keys = tuple(jnp.take(k, first_idx) for k in s_keys)
    out_kvalids = tuple(jnp.take(kv, first_idx) for kv in s_kvalids)

    out_vals = []
    out_valids = []
    live_group = idx < group_count
    for v, vv, op in zip(s_vals, s_vvalids, ops):
        contrib = s_live & vv
        cnt = jax.ops.segment_sum(contrib.astype(jnp.int64), seg, num_segments=C)
        if op == "count":
            out_vals.append(cnt)
            out_valids.append(live_group)
            continue
        if op in ("sum", "mean", "var", "stddev"):
            acc_dt = v.dtype if jnp.issubdtype(v.dtype, jnp.floating) else jnp.int64
            x = jnp.where(contrib, v, jnp.zeros((), v.dtype)).astype(acc_dt)
            s1 = jax.ops.segment_sum(x, seg, num_segments=C)
            if op == "sum":
                out_vals.append(s1)
                out_valids.append(live_group & (cnt > 0))
                continue
            # widest float the backend supports (f64, or f32 under TPU x32)
            fdt = s1.astype(jnp.float64).dtype if s1.dtype != jnp.float32 \
                else jnp.float32
            safe_cnt = jnp.maximum(cnt, 1).astype(fdt)
            mean = s1.astype(fdt) / safe_cnt
            if op == "mean":
                out_vals.append(mean)
                out_valids.append(live_group & (cnt > 0))
                continue
            x2 = x.astype(fdt) * x.astype(fdt)
            s2 = jax.ops.segment_sum(x2, seg, num_segments=C)
            var = s2 / safe_cnt - mean * mean
            var = jnp.maximum(var, 0.0)
            out_vals.append(jnp.sqrt(var) if op == "stddev" else var)
            out_valids.append(live_group & (cnt > 0))
            continue
        if op in ("min", "max", "bool_and", "bool_or"):
            base = v.astype(jnp.int8) if v.dtype == jnp.bool_ else v
            red_op = "min" if op in ("min", "bool_and") else "max"
            ident = _identity_for(base.dtype, red_op)
            x = jnp.where(contrib, base, ident)
            fn = jax.ops.segment_min if red_op == "min" else jax.ops.segment_max
            r = fn(x, seg, num_segments=C)
            if v.dtype == jnp.bool_:
                r = r.astype(jnp.bool_)
            out_vals.append(r)
            out_valids.append(live_group & (cnt > 0))
            continue
        if op == "any_value":
            fi = jax.ops.segment_min(
                jnp.where(contrib, idx, C - 1), seg, num_segments=C)
            fi = jnp.clip(fi, 0, C - 1)
            out_vals.append(jnp.take(v, fi))
            out_valids.append(live_group & (cnt > 0))
            continue
        raise ValueError(f"unsupported device agg {op}")

    return out_keys, out_kvalids, tuple(out_vals), tuple(out_valids), group_count


grouped_agg_kernel = partial(jax.jit, static_argnames=("ops",))(grouped_agg_impl)


# ---------------------------------------------------------------------------
# block-width grouped aggregation (the fused-fragment fast path)

def grouped_agg_block_impl(keys, key_valids, vals, val_valids, row_mask,
                           ops: Tuple[str, ...], out_cap: int):
    """Grouped aggregation emitting [out_cap]-wide group blocks directly.

    TPU-shaped replacement for the scatter-based ``grouped_agg_impl`` on the
    hot path, built around two facts measured on a v5e: row-width GATHERS
    are the enemy (~22 ms per 1M-row `take`, the dominant cost of the naive
    sort+gather formulation), and one-hot matmuls ride the MXU for ~free.
    So: (1) sort ONLY the key planes plus a row index; (2) invert the
    permutation with a second tiny 2-operand sort, yielding each ORIGINAL
    row's segment id — after which every reduction (one-hot matmul sums /
    counts, block-width scatter min/max) runs over the original, un-gathered
    value planes. The only gathers left are [out_cap]-sized.

    Returns (out_keys, out_kvalids, out_vals, out_valids, group_count) with
    every output [out_cap]; groups beyond out_cap are dropped (the caller
    re-runs at a grown bucket when group_count > out_cap).
    """
    C = row_mask.shape[0]
    dead = (~row_mask).astype(jnp.int8)
    operands = [dead]
    for k, kv in zip(keys, key_valids):
        nr, x = _sort_key_plane(k, kv & row_mask, False, False)
        operands.append(nr)
        operands.append(x)
    operands.append(jnp.arange(C, dtype=jnp.int32))
    out = lax.sort(tuple(operands), num_keys=len(operands) - 1,
                   is_stable=True)
    perm = out[-1]
    s_live = out[0] == 0  # dead flag sorts live rows first
    s_nr = [out[1 + 2 * i] for i in range(len(keys))]
    s_x = [out[2 + 2 * i] for i in range(len(keys))]

    # group boundaries on the sorted (null_rank, transformed_value) planes —
    # equivalent to (key, validity) boundaries, and they come free from the
    # sort outputs (no payload gathers)
    diff = jnp.zeros(C, dtype=jnp.bool_).at[0].set(True)
    for nr, x in zip(s_nr, s_x):
        diff = diff | (x != jnp.concatenate([x[:1], x[:-1]])) \
            | (nr != jnp.concatenate([nr[:1], nr[:-1]]))
    flags = diff & s_live
    segf = jnp.cumsum(flags.astype(jnp.int32)) - 1
    group_count = jnp.sum(flags.astype(jnp.int32))
    seg_sorted = jnp.where(s_live, jnp.minimum(segf, out_cap),
                           out_cap).astype(jnp.int32)
    # invert the permutation with one more (cheap, 2-operand) sort: the
    # segment id of every ORIGINAL row
    seg = lax.sort((perm, seg_sorted), num_keys=1, is_stable=True)[1]

    j = jnp.arange(out_cap, dtype=jnp.int32)
    starts = jnp.searchsorted(seg_sorted, j, side="left")
    starts_c = jnp.clip(starts, 0, C - 1)
    live_group = j < group_count

    # group keys: [out_cap]-sized gathers from the sorted key planes (the
    # ascending transform is the identity on valid values)
    out_keys = []
    out_kvalids = []
    for (nr, x), k in zip(zip(s_nr, s_x), keys):
        kx = jnp.take(x, starts_c)
        if k.dtype == jnp.bool_:
            kx = kx.astype(jnp.bool_)
        out_keys.append(kx.astype(k.dtype))
        out_kvalids.append((jnp.take(nr, starts_c) == 0) & live_group)
    out_keys = tuple(out_keys)
    out_kvalids = tuple(out_kvalids)

    # One-hot matmul rides the MXU but materializes [C, out_cap]; past a
    # width threshold that escalates to HBM-exhausting sizes (overflow
    # retries grow out_cap ×16), so wide group blocks fall back to the
    # O(C)-memory scatter segment-sum. HIGHEST precision keeps the f32
    # matmul in true f32 (TPU default would drop the operands to bf16).
    f32_ok = all(v.dtype != jnp.float64 for v in vals)
    acc_dt = jnp.float32 if f32_ok else jnp.float64
    use_matmul = out_cap <= 2048
    oh = jax.nn.one_hot(seg, out_cap, dtype=acc_dt) if use_matmul else None

    def matmul_sum(x):
        if use_matmul:
            return jnp.matmul(x.astype(acc_dt), oh,
                              precision=lax.Precision.HIGHEST)
        # seg is in ORIGINAL row order (inverse-permuted) — not sorted
        return jax.ops.segment_sum(x.astype(acc_dt), seg,
                                   num_segments=out_cap + 1)[:out_cap]

    idx = jnp.arange(C, dtype=jnp.int32)
    out_vals = []
    out_valids = []
    for v, vv, op in zip(vals, val_valids, ops):
        contrib = row_mask & vv  # ORIGINAL row order — no gathers
        cnt = matmul_sum(contrib)  # counts < 2^24 → exact in f32
        has = live_group & (cnt > 0)
        if op == "count":
            out_vals.append(cnt.astype(jnp.int64))
            out_valids.append(live_group)
            continue
        if op in ("sum", "mean", "var", "stddev"):
            if jnp.issubdtype(v.dtype, jnp.integer) or v.dtype == jnp.bool_:
                # exact integer sums: scatter segment-add at block width
                x = jnp.where(contrib, v, jnp.zeros((), v.dtype)) \
                    .astype(jnp.int64)
                s1 = jax.ops.segment_sum(x, seg,
                                         num_segments=out_cap + 1)[:out_cap]
            else:
                s1 = matmul_sum(jnp.where(contrib, v,
                                          jnp.zeros((), v.dtype)))
            if op == "sum":
                out_vals.append(s1)
                out_valids.append(has)
                continue
            # widest float the backend supports (f64, or f32 under TPU x32)
            # — mirrors grouped_agg_impl so int means don't round at f32
            fdt = s1.astype(jnp.float64).dtype if s1.dtype != jnp.float32 \
                else jnp.float32
            safe = jnp.maximum(cnt, 1).astype(fdt)
            mean = s1.astype(fdt) / safe
            if op == "mean":
                out_vals.append(mean)
                out_valids.append(has)
                continue
            xf = jnp.where(contrib, v, jnp.zeros((), v.dtype)).astype(fdt)
            if fdt == acc_dt:
                s2 = matmul_sum(xf * xf)
            else:  # keep the wide accumulator (matmul lanes run in acc_dt)
                s2 = jax.ops.segment_sum(xf * xf, seg,
                                         num_segments=out_cap + 1)[:out_cap]
            var = jnp.maximum(s2 / safe - mean * mean, 0.0)
            out_vals.append(jnp.sqrt(var) if op == "stddev" else var)
            out_valids.append(has)
            continue
        if op in ("min", "max", "bool_and", "bool_or"):
            base = v.astype(jnp.int8) if v.dtype == jnp.bool_ else v
            red = "min" if op in ("min", "bool_and") else "max"
            ident = _identity_for(base.dtype, red)
            x = jnp.where(contrib, base, ident)
            fn = jax.ops.segment_min if red == "min" else jax.ops.segment_max
            r = fn(x, seg, num_segments=out_cap + 1)[:out_cap]
            if v.dtype == jnp.bool_:
                r = r.astype(jnp.bool_)
            out_vals.append(r)
            out_valids.append(has)
            continue
        if op == "any_value":
            fi = jax.ops.segment_min(jnp.where(contrib, idx, C - 1), seg,
                                     num_segments=out_cap + 1)[:out_cap]
            out_vals.append(jnp.take(v, jnp.clip(fi, 0, C - 1)))
            out_valids.append(has)
            continue
        raise ValueError(f"unsupported device agg {op}")

    return out_keys, out_kvalids, tuple(out_vals), tuple(out_valids), \
        group_count


# ---------------------------------------------------------------------------
# global aggregation

def global_agg_impl(vals, val_valids, row_mask, ops: Tuple[str, ...]):
    outs = []
    for v, vv, op in zip(vals, val_valids, ops):
        contrib = row_mask & vv
        cnt = jnp.sum(contrib.astype(jnp.int64))
        if op == "count":
            outs.append((cnt, jnp.asarray(True)))
            continue
        if op in ("sum", "mean", "var", "stddev"):
            acc_dt = v.dtype if jnp.issubdtype(v.dtype, jnp.floating) else jnp.int64
            x = jnp.where(contrib, v, jnp.zeros((), v.dtype)).astype(acc_dt)
            s1 = jnp.sum(x)
            if op == "sum":
                outs.append((s1, cnt > 0))
                continue
            fdt = jnp.float32 if v.dtype == jnp.float32 else s1.astype(jnp.float64).dtype
            safe = jnp.maximum(cnt, 1).astype(fdt)
            mean = s1.astype(fdt) / safe
            if op == "mean":
                outs.append((mean, cnt > 0))
                continue
            s2 = jnp.sum(x.astype(fdt) * x.astype(fdt))
            var = jnp.maximum(s2 / safe - mean * mean, 0.0)
            outs.append((jnp.sqrt(var) if op == "stddev" else var, cnt > 0))
            continue
        if op in ("min", "max", "bool_and", "bool_or"):
            base = v.astype(jnp.int8) if v.dtype == jnp.bool_ else v
            red = "min" if op in ("min", "bool_and") else "max"
            ident = _identity_for(base.dtype, red)
            x = jnp.where(contrib, base, ident)
            r = jnp.min(x) if red == "min" else jnp.max(x)
            if v.dtype == jnp.bool_:
                r = r.astype(jnp.bool_)
            outs.append((r, cnt > 0))
            continue
        if op == "any_value":
            C = row_mask.shape[0]
            fi = jnp.min(jnp.where(contrib, jnp.arange(C), C - 1))
            outs.append((v[fi], cnt > 0))
            continue
        raise ValueError(f"unsupported device agg {op}")
    return tuple(outs)


global_agg_kernel = partial(jax.jit, static_argnames=("ops",))(global_agg_impl)


# ---------------------------------------------------------------------------
# sort-merge equi-join (index generation)

@partial(jax.jit)
def join_phase_sort(r_key, r_valid, r_mask):
    """Sort the right side's key column; invalid/dead rows to the end."""
    C = r_key.shape[0]
    live = r_valid & r_mask
    nr, x = _sort_key_plane(r_key, live, False, False)
    dead = (~live).astype(jnp.int8)
    s = lax.sort((dead, x, jnp.arange(C, dtype=jnp.int32)), num_keys=2,
                 is_stable=True)
    live_count = jnp.sum(live.astype(jnp.int32))
    # dead/padding slots carry value 0 after sort; overwrite with the dtype max
    # so the array stays monotonic for searchsorted
    maxval = jnp.asarray(jnp.inf, x.dtype) \
        if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.asarray(jnp.iinfo(x.dtype).max, x.dtype)
    sorted_keys = jnp.where(jnp.arange(C) < live_count, s[1], maxval)
    return sorted_keys, s[2], live_count


@partial(jax.jit)
def join_phase_count(l_key, l_valid, l_mask, r_sorted, r_live_count):
    """Per-left-row match counts against the sorted right keys."""
    live = l_valid & l_mask
    starts = jnp.searchsorted(r_sorted, l_key, side="left")
    ends = jnp.searchsorted(r_sorted, l_key, side="right")
    ends = jnp.minimum(ends, r_live_count)
    starts = jnp.minimum(starts, r_live_count)
    counts = jnp.where(live, ends - starts, 0)
    return counts, starts, jnp.sum(counts)


@partial(jax.jit, static_argnames=("out_capacity",))
def join_phase_expand(counts, starts, r_perm, out_capacity: int):
    """Prefix-sum expansion: slot j → (left row, right row) index pair."""
    C = counts.shape[0]
    cum = jnp.cumsum(counts)
    total = cum[-1]
    j = jnp.arange(out_capacity, dtype=counts.dtype)
    owner = jnp.searchsorted(cum, j, side="right")
    owner = jnp.clip(owner, 0, C - 1)
    cum0 = cum - counts  # exclusive prefix
    offset = j - jnp.take(cum0, owner)
    r_slot = jnp.take(starts, owner) + offset
    # clip against the RIGHT side's capacity — the two sides' buckets can
    # differ, and clipping to C (the left capacity) would remap legitimate
    # high right slots onto wrong rows
    r_idx = jnp.take(r_perm, jnp.clip(r_slot, 0, r_perm.shape[0] - 1))
    valid = j < total
    return owner.astype(jnp.int32), r_idx.astype(jnp.int32), valid
