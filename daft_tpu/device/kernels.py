"""Sort-based relational kernels as jit-compiled XLA programs.

These replace the reference's hash-table kernels (probe tables
``src/daft-recordbatch/src/probeable/probe_table.rs:19``, grouped aggregate
``src/daft-local-execution/src/sinks/grouped_aggregate.rs``) with the
XLA-friendly sort + segment-reduce formulation (SURVEY.md §7 hard-part #3):

- ``grouped_agg``: packed-key ``lax.sort`` → segment ids via boundary
  cumsum → ``jax.ops.segment_*`` reductions. Static shapes throughout;
  outputs padded to capacity with a live-group count.
- ``argsort``: multi-key, per-key descending + nulls-first, returns a
  permutation (host applies it with Arrow take — device computes *indices*,
  variable-width payloads never leave the host).
- ``join_fused_kernel``: sort/searchsorted/expand inner-equi-join index
  generation as ONE jit program returning ONE packed result matrix.

Roofline discipline (round 6): TPU sort cost grows steeply with operand
count — every log2(C) bitonic pass re-streams every operand plane through
HBM, and the 2k+1-plane lexicographic formulation hit a compile-time cliff
past ~10 operands. All sorts here therefore bit-pack their key planes into
at most two u64 *radix words* whose unsigned order equals the requested
lexicographic order (IEEE-total-order float codes, sign-flipped ints, XOR
for descending, null-rank bits above each value), so any key count sorts
as ≤ 3 operands (word(s) + row index). Key sets wider than 128 bits run as
a stable LSD radix: one ≤3-operand pass per 128-bit chunk.
"""

from __future__ import annotations

from functools import partial
from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

# the u64 radix words require real 64-bit lanes — idempotent here so the
# kernels are safe to import without the column transport layer
jax.config.update("jax_enable_x64", True)

_U64_TOP = np.uint64(1 << 63)


def _key_bits(dtype) -> int:
    """Static value-code width (bits) of one sort key of this dtype."""
    if dtype == jnp.bool_:
        return 1
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.finfo(dtype).bits
    return jnp.iinfo(dtype).bits


def _value_code(x: jnp.ndarray, valid: jnp.ndarray,
                descending: bool) -> jnp.ndarray:
    """u64 radix code: unsigned-ascending code order == key order.

    Floats use the IEEE total-order transform (flip all bits when
    negative, else set the sign bit) — this matches ``lax.sort``'s
    -NaN < -inf < … < inf < NaN ordering bit-for-bit, so the packed and
    plane formulations agree on every input including NaNs and -0.0.
    Signed ints flip the sign bit; descending XOR-inverts the code
    (negation would wrap INT64_MIN). Invalid rows collapse to 0 — null
    placement is the separate rank bit the caller packs above."""
    w = _key_bits(x.dtype)
    if x.dtype == jnp.bool_ or jnp.issubdtype(x.dtype, jnp.unsignedinteger):
        c = x.astype(jnp.uint64)
    elif jnp.issubdtype(x.dtype, jnp.floating):
        if w == 32:
            b = lax.bitcast_convert_type(x, jnp.uint32)
            c = jnp.where(b >> 31 != 0, ~b,
                          b | jnp.uint32(1 << 31)).astype(jnp.uint64)
        else:
            b = lax.bitcast_convert_type(x, jnp.uint64)
            c = jnp.where(b >> 63 != 0, ~b, b | _U64_TOP)
    elif w == 64:
        c = lax.bitcast_convert_type(x, jnp.uint64) ^ _U64_TOP
    else:
        c = (x.astype(jnp.int64) + (1 << (w - 1))).astype(jnp.uint64)
    if descending:
        c = c ^ np.uint64((1 << w) - 1 if w < 64 else 0xFFFFFFFFFFFFFFFF)
    return jnp.where(valid, c, jnp.uint64(0))


def _null_rank_code(valid: jnp.ndarray, nulls_first: bool) -> jnp.ndarray:
    """1-bit code placed ABOVE the value code: the null-placement plane."""
    rank_of_valid = 1 if nulls_first else 0
    return jnp.where(valid, jnp.uint64(rank_of_valid),
                     jnp.uint64(1 - rank_of_valid))


def _sort_codes(keys, valids, row_mask, descending, nulls_first,
                with_dead: bool = True):
    """The (code, width) list for one multi-key sort: optional dead-row
    bit, then per-key (null_rank, value) codes, most-significant first."""
    codes: list = []
    if with_dead:
        codes.append(((~row_mask).astype(jnp.uint64), 1))
    for v, valid, d, nf in zip(keys, valids, descending, nulls_first):
        live = valid & row_mask
        codes.append((_null_rank_code(live, nf), 1))
        codes.append((_value_code(v, live, d), _key_bits(v.dtype)))
    return codes


def _packed_chunks(codes) -> List[Tuple[jnp.ndarray, ...]]:
    """Pack (code, width) planes — big-endian concatenated — into 128-bit
    chunks of one or two u64 words each.

    Layout rules (all shifts static):

    - The global bit string is cut every 128 bits REGARDLESS of code
      boundaries: a code may straddle two chunks (stable LSD radix
      composes on arbitrary digit boundaries, so per-chunk comparisons
      still realize the full lexicographic order). Pass count is thus
      exactly ``ceil(total_bits / 128)``.
    - Each chunk is LEFT-aligned: the first code's top bit lands on bit
      63 of the chunk's first word, so a leading dead-row bit is always
      ``word0 >> 63``.
    - Within a two-word chunk, lexicographic unsigned (hi, lo) order —
      what ``lax.sort`` with num_keys=2 compares — equals 128-bit
      unsigned order of the concatenation."""
    offs: List[int] = []
    off = 0
    for _, w in codes:
        offs.append(off)  # MSB-first global bit offset of this code
        off += w
    W = off
    C = codes[0][0].shape[0]
    zero = jnp.zeros(C, dtype=jnp.uint64)
    chunks: List[Tuple[jnp.ndarray, ...]] = []
    for cs in range(0, W, 128):
        ce = min(cs + 128, W)
        span = 64 if ce - cs <= 64 else 128  # chunk word span in bits
        words = [zero, zero]
        for (c, w), s in zip(codes, offs):
            a, b = max(s, cs), min(s + w, ce)
            if a >= b:
                continue  # no overlap with this chunk
            ln = b - a
            piece = c >> (s + w - b) if s + w - b else c
            if ln < 64:
                piece = piece & np.uint64((1 << ln) - 1)
            p = span - (a - cs) - ln  # LSB bit position within the chunk
            if span == 64:
                words[0] = words[0] | (piece << p)
            elif p >= 64:
                words[0] = words[0] | (piece << (p - 64))
            elif p + ln <= 64:
                words[1] = words[1] | (piece << p)
            else:  # straddles the word boundary: split (shift truncates)
                words[1] = words[1] | (piece << p)
                words[0] = words[0] | (piece >> (64 - p))
        chunks.append(tuple(words[:1] if span == 64 else words))
    return chunks


def _packed_argsort(codes, C: int,
                    want_words: bool = False):
    """Stable permutation ordering rows ascending by the big-endian
    concatenation of ``codes``. Chunks wider than 128 bits run as an LSD
    radix — least-significant chunk first, each pass ONE stable
    ``lax.sort`` with ≤3 operands (this is the operand-count cliff the
    plane formulation hit). ``want_words`` additionally returns every
    chunk's word planes in final sorted order (for boundary detection)."""
    chunks = _packed_chunks(codes)
    perm = jnp.arange(C, dtype=jnp.int32)
    sorted_last: Tuple[jnp.ndarray, ...] = ()
    for i, words in enumerate(reversed(chunks)):
        if i > 0:
            words = tuple(jnp.take(w, perm) for w in words)
        out = lax.sort(tuple(words) + (perm,), num_keys=len(words),
                       is_stable=True)
        perm = out[-1]
        sorted_last = out[:-1]
    if not want_words:
        return perm
    sorted_words: List[jnp.ndarray] = []
    for ci, words in enumerate(chunks):
        if ci == 0 and len(chunks) >= 1:
            # the most-significant chunk ran last: its sort outputs are
            # already in final order — no gathers in the common 1-chunk case
            sorted_words.extend(sorted_last)
        else:
            sorted_words.extend(jnp.take(w, perm) for w in words)
    return perm, tuple(sorted_words)


def argsort_pack_plan(dtypes) -> List[int]:
    """Words per sort pass for keys of these dtypes (dead bit + per-key
    null-rank bit + value bits) — the traffic model behind the mfu
    ledger. Length of the list = number of radix passes
    (``ceil(total_bits / 128)``)."""
    total = 1 + sum(1 + _key_bits(jnp.dtype(dt)) for dt in dtypes)
    return [2 if min(total - cs, 128) > 64 else 1
            for cs in range(0, total, 128)]


@partial(jax.jit, static_argnames=("descending", "nulls_first"))
def argsort_kernel(keys, valids, row_mask, descending: Tuple[bool, ...],
                   nulls_first: Tuple[bool, ...]):
    """Returns the permutation placing live rows first in key order."""
    C = row_mask.shape[0]
    codes = _sort_codes(keys, valids, row_mask, descending, nulls_first)
    return _packed_argsort(codes, C)


@partial(jax.jit)
def compaction_perm(row_mask):
    """Permutation moving live rows to the front (stable)."""
    C = row_mask.shape[0]
    out = lax.sort(((~row_mask).astype(jnp.int8),
                    jnp.arange(C, dtype=jnp.int32)), num_keys=1, is_stable=True)
    return out[1]


# ---------------------------------------------------------------------------
# grouped aggregation

_SEGMENT_AGGS = ("sum", "count", "min", "max", "mean", "var", "stddev",
                 "any_value", "bool_and", "bool_or")


def _identity_for(dtype, op):
    if op == "min":
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(jnp.inf, dtype)
        return jnp.asarray(jnp.iinfo(dtype).max, dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(-jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).min, dtype)


def grouped_agg_impl(keys, key_valids, vals, val_valids, row_mask,
                     ops: Tuple[str, ...]):
    """Sort-based grouped aggregation over padded device columns (pure —
    composable inside larger jit programs, e.g. fused scan fragments).

    keys/vals: tuples of [C] arrays. Returns (out_keys, out_key_valids,
    out_vals, out_val_valids, group_count); outputs are [C]-padded, groups in
    ascending key order (so string-code groups decode in sorted order).
    """
    C = row_mask.shape[0]
    # Sort ONLY packed key words + a row index, then gather payloads
    # through the permutation: TPU sort compile time and runtime grow
    # steeply with operand count (a 21-operand sort took >5 min to compile
    # where this shape compiles in seconds), while gathers are cheap
    # single-fusion ops. The u64 packing caps the sort at 3 operands.
    codes = _sort_codes(keys, key_valids, row_mask,
                        (False,) * len(keys), (False,) * len(keys))
    perm = _packed_argsort(codes, C)
    s_keys = [jnp.take(k, perm) for k in keys]
    s_kvalids = [jnp.take(kv & row_mask, perm) for kv in key_valids]
    s_vals = [jnp.take(v, perm) for v in vals]
    s_vvalids = [jnp.take(vv & row_mask, perm) for vv in val_valids]
    s_live = jnp.take(row_mask, perm)

    # boundary detection over (key value, key validity) among live rows
    idx = jnp.arange(C)
    diff = jnp.zeros(C, dtype=jnp.bool_).at[0].set(True)
    for k, kv in zip(s_keys, s_kvalids):
        prev_k = jnp.concatenate([k[:1], k[:-1]])
        prev_v = jnp.concatenate([kv[:1], kv[:-1]])
        diff = diff | (k != prev_k) | (kv != prev_v)
    prev_live = jnp.concatenate([jnp.zeros(1, jnp.bool_), s_live[:-1]])
    diff = diff | (s_live & ~prev_live)
    flags = diff & s_live
    seg = jnp.cumsum(flags.astype(jnp.int32)) - 1
    seg = jnp.where(s_live, seg, C - 1)  # dead rows -> trailing segment
    group_count = jnp.sum(flags.astype(jnp.int32))

    first_idx = jax.ops.segment_min(
        jnp.where(s_live, idx, C - 1), seg, num_segments=C)
    first_idx = jnp.clip(first_idx, 0, C - 1)

    out_keys = tuple(jnp.take(k, first_idx) for k in s_keys)
    out_kvalids = tuple(jnp.take(kv, first_idx) for kv in s_kvalids)

    out_vals = []
    out_valids = []
    live_group = idx < group_count
    for v, vv, op in zip(s_vals, s_vvalids, ops):
        contrib = s_live & vv
        cnt = jax.ops.segment_sum(contrib.astype(jnp.int64), seg, num_segments=C)
        if op == "count":
            out_vals.append(cnt)
            out_valids.append(live_group)
            continue
        if op in ("sum", "mean", "var", "stddev"):
            acc_dt = v.dtype if jnp.issubdtype(v.dtype, jnp.floating) else jnp.int64
            x = jnp.where(contrib, v, jnp.zeros((), v.dtype)).astype(acc_dt)
            s1 = jax.ops.segment_sum(x, seg, num_segments=C)
            if op == "sum":
                out_vals.append(s1)
                out_valids.append(live_group & (cnt > 0))
                continue
            # widest float the backend supports (f64, or f32 under TPU x32)
            fdt = s1.astype(jnp.float64).dtype if s1.dtype != jnp.float32 \
                else jnp.float32
            safe_cnt = jnp.maximum(cnt, 1).astype(fdt)
            mean = s1.astype(fdt) / safe_cnt
            if op == "mean":
                out_vals.append(mean)
                out_valids.append(live_group & (cnt > 0))
                continue
            x2 = x.astype(fdt) * x.astype(fdt)
            s2 = jax.ops.segment_sum(x2, seg, num_segments=C)
            var = s2 / safe_cnt - mean * mean
            var = jnp.maximum(var, 0.0)
            out_vals.append(jnp.sqrt(var) if op == "stddev" else var)
            out_valids.append(live_group & (cnt > 0))
            continue
        if op in ("min", "max", "bool_and", "bool_or"):
            base = v.astype(jnp.int8) if v.dtype == jnp.bool_ else v
            red_op = "min" if op in ("min", "bool_and") else "max"
            ident = _identity_for(base.dtype, red_op)
            x = jnp.where(contrib, base, ident)
            fn = jax.ops.segment_min if red_op == "min" else jax.ops.segment_max
            r = fn(x, seg, num_segments=C)
            if v.dtype == jnp.bool_:
                r = r.astype(jnp.bool_)
            out_vals.append(r)
            out_valids.append(live_group & (cnt > 0))
            continue
        if op == "any_value":
            fi = jax.ops.segment_min(
                jnp.where(contrib, idx, C - 1), seg, num_segments=C)
            fi = jnp.clip(fi, 0, C - 1)
            out_vals.append(jnp.take(v, fi))
            out_valids.append(live_group & (cnt > 0))
            continue
        raise ValueError(f"unsupported device agg {op}")

    return out_keys, out_kvalids, tuple(out_vals), tuple(out_valids), group_count


grouped_agg_kernel = partial(jax.jit, static_argnames=("ops",))(grouped_agg_impl)


# ---------------------------------------------------------------------------
# block-width grouped aggregation (the fused-fragment fast path)

def grouped_agg_block_impl(keys, key_valids, vals, val_valids, row_mask,
                           ops: Tuple[str, ...], out_cap: int):
    """Grouped aggregation emitting [out_cap]-wide group blocks directly.

    TPU-shaped replacement for the scatter-based ``grouped_agg_impl`` on the
    hot path, built around two facts measured on a v5e: row-width GATHERS
    are the enemy (~22 ms per 1M-row `take`, the dominant cost of the naive
    sort+gather formulation), and one-hot matmuls ride the MXU for ~free.
    So: (1) sort ONLY the key planes plus a row index; (2) invert the
    permutation with a second tiny 2-operand sort, yielding each ORIGINAL
    row's segment id — after which every reduction (one-hot matmul sums /
    counts, block-width scatter min/max) runs over the original, un-gathered
    value planes. The only gathers left are [out_cap]-sized.

    Returns (out_keys, out_kvalids, out_vals, out_valids, group_count) with
    every output [out_cap]; groups beyond out_cap are dropped (the caller
    re-runs at a grown bucket when group_count > out_cap).
    """
    C = row_mask.shape[0]
    codes = _sort_codes(keys, key_valids, row_mask,
                        (False,) * len(keys), (False,) * len(keys))
    perm, s_words = _packed_argsort(codes, C, want_words=True)
    # dead bit is the MSB of the first sorted word: live rows sort first
    s_live = (s_words[0] >> np.uint64(63)) == 0

    # group boundaries on the sorted packed words — word equality ⟺
    # (null_rank, value) equality for every key, and the words come free
    # from the sort outputs (no payload gathers)
    diff = jnp.zeros(C, dtype=jnp.bool_).at[0].set(True)
    for w in s_words:
        diff = diff | (w != jnp.concatenate([w[:1], w[:-1]]))
    flags = diff & s_live
    segf = jnp.cumsum(flags.astype(jnp.int32)) - 1
    group_count = jnp.sum(flags.astype(jnp.int32))
    seg_sorted = jnp.where(s_live, jnp.minimum(segf, out_cap),
                           out_cap).astype(jnp.int32)
    # invert the permutation with one more (cheap, 2-operand) sort: the
    # segment id of every ORIGINAL row
    seg = lax.sort((perm, seg_sorted), num_keys=1, is_stable=True)[1]

    j = jnp.arange(out_cap, dtype=jnp.int32)
    starts = jnp.searchsorted(seg_sorted, j, side="left")
    starts_c = jnp.clip(starts, 0, C - 1)
    live_group = j < group_count

    # group keys: [out_cap]-sized gathers from the ORIGINAL key planes
    # through perm∘starts (the packed words no longer carry the raw
    # values, but two tiny composed gathers are as cheap as one)
    first_row = jnp.take(perm, starts_c)
    out_keys = tuple(jnp.take(k, first_row) for k in keys)
    out_kvalids = tuple(jnp.take(kv & row_mask, first_row) & live_group
                        for kv in key_valids)

    # One-hot matmul rides the MXU but materializes [C, out_cap]; past a
    # width threshold that escalates to HBM-exhausting sizes (overflow
    # retries grow out_cap ×16), so wide group blocks fall back to the
    # O(C)-memory scatter segment-sum. HIGHEST precision keeps the f32
    # matmul in true f32 (TPU default would drop the operands to bf16).
    f32_ok = all(v.dtype != jnp.float64 for v in vals)
    acc_dt = jnp.float32 if f32_ok else jnp.float64
    use_matmul = out_cap <= 2048
    oh = jax.nn.one_hot(seg, out_cap, dtype=acc_dt) if use_matmul else None

    def matmul_sum(x):
        if use_matmul:
            return jnp.matmul(x.astype(acc_dt), oh,
                              precision=lax.Precision.HIGHEST)
        # seg is in ORIGINAL row order (inverse-permuted) — not sorted
        return jax.ops.segment_sum(x.astype(acc_dt), seg,
                                   num_segments=out_cap + 1)[:out_cap]

    idx = jnp.arange(C, dtype=jnp.int32)
    out_vals = []
    out_valids = []
    for v, vv, op in zip(vals, val_valids, ops):
        contrib = row_mask & vv  # ORIGINAL row order — no gathers
        cnt = matmul_sum(contrib)  # counts < 2^24 → exact in f32
        has = live_group & (cnt > 0)
        if op == "count":
            out_vals.append(cnt.astype(jnp.int64))
            out_valids.append(live_group)
            continue
        if op in ("sum", "mean", "var", "stddev"):
            if jnp.issubdtype(v.dtype, jnp.integer) or v.dtype == jnp.bool_:
                # exact integer sums: scatter segment-add at block width
                x = jnp.where(contrib, v, jnp.zeros((), v.dtype)) \
                    .astype(jnp.int64)
                s1 = jax.ops.segment_sum(x, seg,
                                         num_segments=out_cap + 1)[:out_cap]
            else:
                s1 = matmul_sum(jnp.where(contrib, v,
                                          jnp.zeros((), v.dtype)))
            if op == "sum":
                out_vals.append(s1)
                out_valids.append(has)
                continue
            # widest float the backend supports (f64, or f32 under TPU x32)
            # — mirrors grouped_agg_impl so int means don't round at f32
            fdt = s1.astype(jnp.float64).dtype if s1.dtype != jnp.float32 \
                else jnp.float32
            safe = jnp.maximum(cnt, 1).astype(fdt)
            mean = s1.astype(fdt) / safe
            if op == "mean":
                out_vals.append(mean)
                out_valids.append(has)
                continue
            xf = jnp.where(contrib, v, jnp.zeros((), v.dtype)).astype(fdt)
            if fdt == acc_dt:
                s2 = matmul_sum(xf * xf)
            else:  # keep the wide accumulator (matmul lanes run in acc_dt)
                s2 = jax.ops.segment_sum(xf * xf, seg,
                                         num_segments=out_cap + 1)[:out_cap]
            var = jnp.maximum(s2 / safe - mean * mean, 0.0)
            out_vals.append(jnp.sqrt(var) if op == "stddev" else var)
            out_valids.append(has)
            continue
        if op in ("min", "max", "bool_and", "bool_or"):
            base = v.astype(jnp.int8) if v.dtype == jnp.bool_ else v
            red = "min" if op in ("min", "bool_and") else "max"
            ident = _identity_for(base.dtype, red)
            x = jnp.where(contrib, base, ident)
            fn = jax.ops.segment_min if red == "min" else jax.ops.segment_max
            r = fn(x, seg, num_segments=out_cap + 1)[:out_cap]
            if v.dtype == jnp.bool_:
                r = r.astype(jnp.bool_)
            out_vals.append(r)
            out_valids.append(has)
            continue
        if op == "any_value":
            fi = jax.ops.segment_min(jnp.where(contrib, idx, C - 1), seg,
                                     num_segments=out_cap + 1)[:out_cap]
            out_vals.append(jnp.take(v, jnp.clip(fi, 0, C - 1)))
            out_valids.append(has)
            continue
        raise ValueError(f"unsupported device agg {op}")

    return out_keys, out_kvalids, tuple(out_vals), tuple(out_valids), \
        group_count


# ---------------------------------------------------------------------------
# dense direct-indexed grouped aggregation (dictionary-coded keys)

def grouped_agg_dense_impl(keys, key_valids, vals, val_valids, row_mask,
                           ops: Tuple[str, ...], out_cap: int,
                           dims: Tuple[int, ...]):
    """Grouped aggregation by DIRECT slot indexing — no sort, no hash table.

    When every group key rides sorted-dictionary codes (string/binary
    planes encode as dense ints < dictionary size, ``column._np_encode``),
    a row's group id is pure arithmetic over its codes: a mixed-radix
    number over the per-key slot widths ``dims`` (each dictionary size
    rounded up to a power of two so the static-arg space stays bounded;
    slot ``d`` of a key holds its nulls). Aggregation is then ONE O(C)
    scatter pass per reduced plane over ``K = prod(d+1)`` slots — the
    radix sort + inverse-permutation sort of the sort strategy (≥4
    streaming passes over the packed row planes) disappears entirely.

    Strides are most-significant-first over the keys with nulls at each
    key's top slot, so occupied slots enumerate groups in ascending key
    order with nulls last — the same group order the sort strategy emits.
    Requires ``K <= out_cap`` (the dispatch site sizes the bucket);
    dense output can never overflow, because group_count <= K.

    Returns the [out_cap]-wide block layout of
    :func:`grouped_agg_block_impl`.
    """
    C = row_mask.shape[0]
    K = 1
    for d in dims:
        K *= d + 1
    if K > out_cap:
        raise ValueError("dense dispatch requires K <= out_cap")
    # mixed-radix group id per ORIGINAL row (no gathers, no sort)
    gid = jnp.zeros(C, dtype=jnp.int32)
    for k, kv, d in zip(keys, key_valids, dims):
        comp = jnp.where(kv & row_mask,
                         jnp.clip(k.astype(jnp.int32), 0, d), d)
        gid = gid * (d + 1) + comp
    seg = jnp.where(row_mask, gid, out_cap).astype(jnp.int32)

    # ONE [C, K] one-hot shared by every additive reduction below: the
    # per-slot sums become a single stacked matmul instead of a scatter
    # per plane. XLA CPU lowers scatter to a serial per-row update loop
    # (the q1 profile showed it dominating the whole dispatch), while a
    # [C, K]·[K] GEMM is multithreaded there and rides the MXU on TPU.
    # K is the tiny static slot count (dictionary product), NOT out_cap,
    # so the materialized one-hot stays ~C·K·8 bytes.
    acc_dt = jnp.float64 if any(
        v.dtype == jnp.float64 for v in vals) else jnp.float32
    oh = jax.nn.one_hot(jnp.where(row_mask, gid, K), K, dtype=acc_dt)

    def slot_pad(x):
        """[K] slot vector → [out_cap] (slots past K are empty)."""
        return jnp.zeros((out_cap,), x.dtype).at[:K].set(x)

    # pass 1 — collect every additive plane (slot occupancy, contrib
    # counts, float sums, squared sums) into ONE [ncols, C] matrix for a
    # single GEMM against the shared one-hot. Integer sums keep the
    # exact int64 scatter, and min/max/any/bool reductions scatter too
    # (no additive form).
    mm_cols = []
    col_ix = {}

    def want(i, tag, x, src):
        # queries reuse planes (q1 sums l_quantity three ways over one
        # validity mask) — identical sources collapse to one matrix row
        shared = (tag,) + src
        ix = col_ix.get(shared)
        if ix is None:
            ix = len(mm_cols)
            mm_cols.append(x.astype(acc_dt))
            col_ix[shared] = ix
        col_ix[(i, tag)] = ix

    want(-1, "occ", row_mask, (id(row_mask),))
    for i, (v, vv, op) in enumerate(zip(vals, val_valids, ops)):
        contrib = row_mask & vv
        want(i, "cnt", contrib, (id(vv),))
        if op in ("sum", "mean", "var", "stddev") \
                and jnp.issubdtype(v.dtype, jnp.floating):
            x = jnp.where(contrib, v, jnp.zeros((), v.dtype))
            want(i, "s1", x, (id(v), id(vv)))
            if op in ("var", "stddev"):
                xa = x.astype(acc_dt)
                want(i, "s2", xa * xa, (id(v), id(vv)))
    # stack along axis 0 (each column lands contiguously) and contract
    # the row axis directly — the axis=1/transpose formulation pays an
    # extra interleaving copy of the whole matrix
    M = jnp.stack(mm_cols, axis=0)
    R = jnp.matmul(M, oh, precision=lax.Precision.HIGHEST)  # [ncols, K]

    occ = R[col_ix[(-1, "occ")]]
    occupied = slot_pad(occ > 0.0)
    group_count = jnp.sum(occ > 0.0).astype(jnp.int32)
    j = jnp.arange(out_cap, dtype=jnp.int32)
    # compact occupied slots to the front: one stable [out_cap]-sized
    # 2-operand sort (ascending slot order — the group order — survives)
    slot_of = lax.sort((jnp.where(occupied, 0, 1).astype(jnp.int32), j),
                       num_keys=1, is_stable=True)[1]
    live_group = j < group_count

    # each slot's key codes come back by mixed-radix decomposition —
    # nothing is gathered from the row planes
    strides = []
    s = 1
    for d in reversed(dims):
        strides.append(s)
        s *= d + 1
    strides.reverse()
    out_keys = []
    out_kvalids = []
    for k, d, st in zip(keys, dims, strides):
        comp = (slot_of // st) % (d + 1)
        out_keys.append(comp.astype(k.dtype))
        out_kvalids.append(live_group & (comp != d))

    def slot_take(r):
        """[K] slot sums → compacted [out_cap] group order."""
        return jnp.take(slot_pad(r), slot_of)

    def red_scatter(x, fn=jax.ops.segment_sum):
        return jnp.take(fn(x, seg, num_segments=out_cap + 1)[:out_cap],
                        slot_of)

    idx = jnp.arange(C, dtype=jnp.int32)
    out_vals = []
    out_vvalids = []
    for i, (v, vv, op) in enumerate(zip(vals, val_valids, ops)):
        contrib = row_mask & vv
        cntf = slot_take(R[col_ix[(i, "cnt")]])  # counts exact in float
        cnt = cntf.astype(jnp.int64)
        has = live_group & (cnt > 0)
        if op == "count":
            out_vals.append(cnt)
            out_vvalids.append(live_group)
            continue
        if op in ("sum", "mean", "var", "stddev"):
            if (i, "s1") in col_ix:
                s1 = slot_take(R[col_ix[(i, "s1")]])
            else:  # integer/bool input: exact int64 scatter sum
                x = jnp.where(contrib, v, jnp.zeros((), v.dtype)) \
                    .astype(jnp.int64)
                s1 = red_scatter(x)
            if op == "sum":
                out_vals.append(s1)
                out_vvalids.append(has)
                continue
            # widest float the backend supports (mirrors the sort path)
            fdt = s1.astype(jnp.float64).dtype if s1.dtype != jnp.float32 \
                else jnp.float32
            safe = jnp.maximum(cnt, 1).astype(fdt)
            mean = s1.astype(fdt) / safe
            if op == "mean":
                out_vals.append(mean)
                out_vvalids.append(has)
                continue
            if (i, "s2") in col_ix:
                s2 = slot_take(R[col_ix[(i, "s2")]]).astype(fdt)
            else:
                xf = jnp.where(contrib, v,
                               jnp.zeros((), v.dtype)).astype(fdt)
                s2 = red_scatter(xf * xf)
            var = jnp.maximum(s2 / safe - mean * mean, 0.0)
            out_vals.append(jnp.sqrt(var) if op == "stddev" else var)
            out_vvalids.append(has)
            continue
        if op in ("min", "max", "bool_and", "bool_or"):
            base = v.astype(jnp.int8) if v.dtype == jnp.bool_ else v
            red = "min" if op in ("min", "bool_and") else "max"
            ident = _identity_for(base.dtype, red)
            x = jnp.where(contrib, base, ident)
            fn = jax.ops.segment_min if red == "min" else jax.ops.segment_max
            r = red_scatter(x, fn)
            if v.dtype == jnp.bool_:
                r = r.astype(jnp.bool_)
            out_vals.append(r)
            out_vvalids.append(has)
            continue
        if op == "any_value":
            fi = jax.ops.segment_min(jnp.where(contrib, idx, C - 1), seg,
                                     num_segments=out_cap + 1)[:out_cap]
            fi = jnp.take(jnp.clip(fi, 0, C - 1), slot_of)
            out_vals.append(jnp.take(v, fi))
            out_vvalids.append(has)
            continue
        raise ValueError(f"unsupported device agg {op}")

    return tuple(out_keys), tuple(out_kvalids), tuple(out_vals), \
        tuple(out_vvalids), group_count


# ---------------------------------------------------------------------------
# global aggregation

def global_agg_impl(vals, val_valids, row_mask, ops: Tuple[str, ...]):
    outs = []
    for v, vv, op in zip(vals, val_valids, ops):
        contrib = row_mask & vv
        cnt = jnp.sum(contrib.astype(jnp.int64))
        if op == "count":
            outs.append((cnt, jnp.asarray(True)))
            continue
        if op in ("sum", "mean", "var", "stddev"):
            acc_dt = v.dtype if jnp.issubdtype(v.dtype, jnp.floating) else jnp.int64
            x = jnp.where(contrib, v, jnp.zeros((), v.dtype)).astype(acc_dt)
            s1 = jnp.sum(x)
            if op == "sum":
                outs.append((s1, cnt > 0))
                continue
            fdt = jnp.float32 if v.dtype == jnp.float32 else s1.astype(jnp.float64).dtype
            safe = jnp.maximum(cnt, 1).astype(fdt)
            mean = s1.astype(fdt) / safe
            if op == "mean":
                outs.append((mean, cnt > 0))
                continue
            s2 = jnp.sum(x.astype(fdt) * x.astype(fdt))
            var = jnp.maximum(s2 / safe - mean * mean, 0.0)
            outs.append((jnp.sqrt(var) if op == "stddev" else var, cnt > 0))
            continue
        if op in ("min", "max", "bool_and", "bool_or"):
            base = v.astype(jnp.int8) if v.dtype == jnp.bool_ else v
            red = "min" if op in ("min", "bool_and") else "max"
            ident = _identity_for(base.dtype, red)
            x = jnp.where(contrib, base, ident)
            r = jnp.min(x) if red == "min" else jnp.max(x)
            if v.dtype == jnp.bool_:
                r = r.astype(jnp.bool_)
            outs.append((r, cnt > 0))
            continue
        if op == "any_value":
            C = row_mask.shape[0]
            fi = jnp.min(jnp.where(contrib, jnp.arange(C), C - 1))
            outs.append((v[fi], cnt > 0))
            continue
        raise ValueError(f"unsupported device agg {op}")
    return tuple(outs)


global_agg_kernel = partial(jax.jit, static_argnames=("ops",))(global_agg_impl)


# ---------------------------------------------------------------------------
# sort-merge equi-join (index generation)
#
# Pure phase impls (composable inside larger programs — the mesh broadcast
# join runs them inside its own shard_map program) plus ONE fused jitted
# kernel: the three-dispatch formulation paid two host round-trips between
# phases (sort → count → fetch total → expand), which on a tunneled chip
# cost more than the kernels themselves.

def join_sort_impl(r_key, r_valid, r_mask):
    """Sort the right side's key column; invalid/dead rows to the end."""
    C = r_key.shape[0]
    live = r_valid & r_mask
    x = jnp.where(live, r_key, jnp.zeros((), r_key.dtype))
    dead = (~live).astype(jnp.int8)
    s = lax.sort((dead, x, jnp.arange(C, dtype=jnp.int32)), num_keys=2,
                 is_stable=True)
    live_count = jnp.sum(live.astype(jnp.int32))
    # dead/padding slots carry value 0 after sort; overwrite with the dtype max
    # so the array stays monotonic for searchsorted
    maxval = jnp.asarray(jnp.inf, x.dtype) \
        if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.asarray(jnp.iinfo(x.dtype).max, x.dtype)
    sorted_keys = jnp.where(jnp.arange(C) < live_count, s[1], maxval)
    return sorted_keys, s[2], live_count


def join_count_impl(l_key, l_valid, l_mask, r_sorted, r_live_count):
    """Per-left-row match counts against the sorted right keys."""
    live = l_valid & l_mask
    starts = jnp.searchsorted(r_sorted, l_key, side="left")
    ends = jnp.searchsorted(r_sorted, l_key, side="right")
    ends = jnp.minimum(ends, r_live_count)
    starts = jnp.minimum(starts, r_live_count)
    counts = jnp.where(live, ends - starts, 0)
    return counts, starts, jnp.sum(counts)


def join_expand_impl(counts, starts, r_perm, out_capacity: int):
    """Prefix-sum expansion: slot j → (left row, right row) index pair."""
    C = counts.shape[0]
    cum = jnp.cumsum(counts)
    total = cum[-1]
    j = jnp.arange(out_capacity, dtype=counts.dtype)
    owner = jnp.searchsorted(cum, j, side="right")
    owner = jnp.clip(owner, 0, C - 1)
    cum0 = cum - counts  # exclusive prefix
    offset = j - jnp.take(cum0, owner)
    r_slot = jnp.take(starts, owner) + offset
    # clip against the RIGHT side's capacity — the two sides' buckets can
    # differ, and clipping to C (the left capacity) would remap legitimate
    # high right slots onto wrong rows
    r_idx = jnp.take(r_perm, jnp.clip(r_slot, 0, r_perm.shape[0] - 1))
    valid = j < total
    return owner.astype(jnp.int32), r_idx.astype(jnp.int32), valid


def join_fused_impl(l_key, l_valid, l_mask, r_key, r_valid, r_mask,
                    out_capacity: int):
    """Build-sort + probe-count + expand as one program, result as ONE
    packed int32 matrix ``[3, max(out_capacity, C_l)]``:

    - row 0: left row index per output slot (``[:out_capacity]``)
    - row 1: right row index per output slot (``[:out_capacity]``)
    - row 2: per-left-row match counts (``[:C_l]``)

    The true match total is ``counts.sum()`` host-side; output slots at or
    past it are garbage, and a total above ``out_capacity`` means the
    caller re-dispatches at a grown static bucket (the grouped-agg
    overflow discipline). One dispatch + one transfer replaces the
    three-dispatch, two-round-trip phase pipeline."""
    C_l = l_key.shape[0]
    r_sorted, r_perm, r_live_count = join_sort_impl(r_key, r_valid, r_mask)
    counts, starts, _total = join_count_impl(l_key, l_valid, l_mask,
                                             r_sorted, r_live_count)
    owner, r_idx, _valid = join_expand_impl(counts, starts, r_perm,
                                            out_capacity)
    W = max(out_capacity, C_l)
    packed = jnp.zeros((3, W), dtype=jnp.int32)
    packed = packed.at[0, :out_capacity].set(owner)
    packed = packed.at[1, :out_capacity].set(r_idx)
    packed = packed.at[2, :C_l].set(counts.astype(jnp.int32))
    return packed


_join_fused_cache: dict = {}


def join_fused_kernel(l_key, l_valid, l_mask, r_key, r_valid, r_mask,
                      out_capacity: int):
    """The jitted single-dispatch join. The build side's buffers are
    DONATED on real chips (they are dead after the in-program sort, so
    XLA reuses their HBM for the sorted planes); CPU backends ignore
    donation and would warn per call, so the donating executable is only
    built off-cpu."""
    from . import backend
    # daft-lint: allow(donation-unguarded) -- the donated build-side
    # planes are per-dispatch packed key codes minted by the caller for
    # exactly this call; they are never DeviceTable buffers, so the
    # HBM-cache resident guard does not apply (only the backend gate does)
    donate = (backend.backend_name() or "cpu") != "cpu"
    fn = _join_fused_cache.get(donate)
    if fn is None:
        fn = jax.jit(join_fused_impl, static_argnames=("out_capacity",),
                     donate_argnums=(3, 4, 5) if donate else ())
        _join_fused_cache[donate] = fn
    return fn(l_key, l_valid, l_mask, r_key, r_valid, r_mask,
              out_capacity=out_capacity)
