"""Host⇄device column transport: Series/RecordBatch → DeviceTable and back.

The DeviceTable is the device twin of a RecordBatch (SURVEY.md §7.1
"DeviceColumnSet"): a dict of fixed-width JAX arrays plus validity planes and a
live-row mask, padded to a power-of-two capacity bucket.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

import jax
import jax.numpy as jnp

from ..datatype import DataType
from ..schema import Field, Schema
from ..series import Series

jax.config.update("jax_enable_x64", True)

# Persistent compile-cache configuration lives in backend.py (TPU-only:
# the TPU executables survive process restarts and machine moves, while
# CPU AOT artifacts are machine-feature-pinned — a cache written on one
# host reloads on another with SIGILL-risk warnings and forces a native
# recompile per (bucket, dtype, op) shape that burned minutes per SF100
# scan before the guard). column.py must not configure it at import:
# this module loads before the backend probe decides cpu vs tpu.

_MIN_CAPACITY = 16

# parsed DAFT_TPU_SIZE_CLASSES memo: (raw spec value, (step, explicit
# ladder)) — the knob is read per bucket_capacity call (morsel-rate), so
# the parse is cached against the raw string
_ladder_memo: "tuple" = (None, (2, None))
# context handle memo: get_context() takes the process-wide context
# lock on EVERY call — cache the singleton so the env-unset default
# path stays lock-free at morsel rate (the execution_config attr read
# itself is a GIL-atomic load of whatever config is current)
_ctx_memo = None


def _config_spec() -> str:
    global _ctx_memo
    if _ctx_memo is None:
        try:
            from ..context import get_context
            # daft-lint: allow(unguarded-global-mutation) -- benign
            # last-wins memo of the process context singleton
            _ctx_memo = get_context()
        except Exception:
            return "pow2"
    try:
        return _ctx_memo.execution_config.tpu_size_classes or "pow2"
    except Exception:
        return "pow2"


def _ladder() -> "tuple":
    """(geometric step, explicit capacities|None) from the
    ``DAFT_TPU_SIZE_CLASSES`` ladder spec: ``pow2`` (default) /
    ``pow4`` / an explicit comma list.  The env var is the per-process
    override; unset, the per-query ``ExecutionConfig.tpu_size_classes``
    field applies (the registry's config_field contract)."""
    global _ladder_memo
    from ..analysis import knobs
    raw = knobs.env_raw("DAFT_TPU_SIZE_CLASSES") or _config_spec()
    memo_raw, memo_val = _ladder_memo
    if raw == memo_raw:
        return memo_val
    if raw == "pow2":
        val = (2, None)
    elif raw == "pow4":
        val = (4, None)
    else:
        try:
            caps = sorted({max(int(x), _MIN_CAPACITY)
                           for x in raw.split(",") if x.strip()})
        except ValueError:
            raise ValueError(
                f"DAFT_TPU_SIZE_CLASSES={raw!r}: expected 'pow2', "
                f"'pow4', or a comma list of integer capacities")
        val = (2, tuple(caps) or None)
    # daft-lint: allow(unguarded-global-mutation) -- benign last-wins
    # memo of a pure parse; a racing duplicate computes the same value
    _ladder_memo = (raw, val)
    return val


def bucket_capacity(n: int) -> int:
    """Pad row counts to canonical size-class buckets so literal-
    different row counts re-enter already-jitted programs instead of
    re-tracing.  THE sanctioned chokepoint between row counts and
    shapes: ``rule_shapes`` statically flags any raw count reaching a
    device shape without passing through here.  The ladder is
    power-of-two by default (``DAFT_TPU_SIZE_CLASSES``)."""
    step, explicit = _ladder()
    if explicit is not None:
        for c in explicit:
            if c >= n:
                return c
        c = explicit[-1]
        while c < n:   # above the ladder top: keep doubling
            c <<= 1
        return c
    c = _MIN_CAPACITY
    while c < n:
        c *= step
    return c


def size_classes(max_capacity: int, min_capacity: int = _MIN_CAPACITY
                 ) -> "List[int]":
    """The ladder's capacities in ``[min_capacity, max_capacity]`` — the
    AOT warm-up grid (device/warmup.py) compiles each of these once so
    cold queries land on warm programs."""
    out = []
    c = bucket_capacity(min_capacity)
    while c <= max_capacity:
        out.append(c)
        nxt = bucket_capacity(c + 1)
        if nxt <= c:
            break
        c = nxt
    return out


def _backend() -> str:
    from . import backend
    return backend.backend_name() or "cpu"


def device_np_dtype(dt: DataType) -> np.dtype:
    """The numpy dtype a column of this logical type encodes to on
    device (mirrors ``_np_encode``'s physical lowering) — the AOT
    warm-up grid builds abstract ``ShapeDtypeStruct`` inputs from it.
    Raises ``ValueError`` for non-device-representable types."""
    if dt.is_null() or dt.is_string() or dt.is_binary():
        return np.dtype("int32")      # dict codes / zero payload plane
    if dt.kind == "date":
        return np.dtype("int32")
    if dt.is_boolean():
        return np.dtype("bool")
    if dt.is_temporal():
        return np.dtype("int64")
    rep = np.float64 if dt.is_decimal() \
        else dt.to_physical().device_repr()
    if rep is None:
        raise ValueError(f"{dt!r} is not device-representable")
    d = np.dtype(rep)
    if d == np.float64 and not supports_f64():
        d = np.dtype("float32")
    return d


def supports_f64() -> bool:
    """TPUs have no native f64; compute those columns in f32 on TPU."""
    return _backend() not in ("tpu", "axon")


def is_lossless_device_dtype(dtype: DataType) -> bool:
    """True when the device encoding round-trips bit-exactly: required for
    pure data-movement paths (mesh repartition) where the engine must not
    perturb values. Decimals ride float64 (lossy); float64 itself downcasts
    to float32 on backends without f64."""
    if dtype.is_decimal():
        return False
    if dtype.is_string() or dtype.is_binary():
        return False
    phys = dtype.to_physical()
    if phys.device_repr() is None:
        return False
    if phys.device_repr() == np.float64 and not supports_f64():
        return False
    return True


@dataclass
class DeviceColumn:
    data: jax.Array                  # [capacity]
    validity: jax.Array              # [capacity] bool
    dtype: DataType                  # logical dtype
    dictionary: Optional[pa.Array] = None  # sorted dictionary for code columns

    @property
    def is_coded(self) -> bool:
        return self.dictionary is not None


@dataclass
class DeviceTable:
    columns: Dict[str, DeviceColumn]
    row_mask: jax.Array              # [capacity] bool — live rows
    row_count: int                   # host-side live count
    capacity: int
    #: True for HBM-cache-resident tables — their buffers are SHARED with
    #: the cache and must never be donated to a fused program
    resident: bool = False

    def schema(self) -> Schema:
        return Schema([Field(n, c.dtype) for n, c in self.columns.items()])


def _np_encode(s: Series) -> "tuple[np.ndarray, np.ndarray, Optional[pa.Array]]":
    """Series → (values ndarray, validity ndarray, dictionary|None)."""
    arr = s.to_arrow()
    dt = s.datatype()
    n = len(arr)
    validity = np.asarray(pc.is_valid(arr).to_numpy(zero_copy_only=False),
                          dtype=np.bool_)
    if dt.is_null():
        # all-null column: zero payload plane, validity already all-False
        return np.zeros(n, dtype=np.int32), validity, None
    if dt.is_string() or dt.is_binary():
        enc = arr.dictionary_encode()
        d = enc.dictionary
        sort_idx = pc.array_sort_indices(d).to_numpy()
        ranks = np.empty(len(d), dtype=np.int32)
        ranks[sort_idx] = np.arange(len(d), dtype=np.int32)
        codes_raw = pc.fill_null(enc.indices, 0).to_numpy(zero_copy_only=False)
        codes = ranks[np.asarray(codes_raw, dtype=np.int64)] if len(d) else \
            np.zeros(n, dtype=np.int32)
        sorted_dict = d.take(pa.array(sort_idx))
        return codes.astype(np.int32), validity, sorted_dict
    phys = dt.to_physical()
    rep = phys.device_repr()
    if rep is None:
        raise ValueError(f"column {s.name()!r}: {dt!r} is not device-representable")
    if dt.kind == "date":
        arr = arr.cast(pa.int32())
    elif dt.is_temporal():
        arr = arr.cast(pa.int64())
    elif dt.is_decimal():
        arr = arr.cast(pa.float64())
    if dt.is_boolean():
        vals = np.asarray(pc.fill_null(arr, False).to_numpy(zero_copy_only=False),
                          dtype=np.bool_)
    else:
        if not validity.all():
            # fill at the Arrow level so nullable ints don't decay to float64
            arr = pc.fill_null(arr, pa.scalar(0, type=arr.type))
        vals = np.asarray(arr.to_numpy(zero_copy_only=False))
    if vals.dtype == np.float64 and not supports_f64():
        vals = vals.astype(np.float32)
    return vals, validity, None


def encode_series(s: Series, capacity: int,
                  allow_resident: bool = False) -> DeviceColumn:
    # device-resident hand-off (round 17): a series decoded from a device
    # op whose planes are still resident re-enters the device without a
    # host round trip (pipeline.py bounds + reaps the registry).  Opt-in
    # only: the returned planes are SHARED with the registry, so callers
    # that might donate buffers must stay on the fresh-encode path (the
    # all-or-nothing table reuse in encode_batch marks its table
    # ``resident`` instead).
    res = _resident_column(s, capacity) if allow_resident else None
    if res is not None:
        return res
    vals, validity, dictionary = _np_encode(s)
    n = len(vals)
    if n < capacity:
        vals = np.concatenate(
            [vals, np.zeros(capacity - n, dtype=vals.dtype)])
        validity = np.concatenate(
            [validity, np.zeros(capacity - n, dtype=np.bool_)])
    return DeviceColumn(jnp.asarray(vals), jnp.asarray(validity),
                        s.datatype(), dictionary)


def _resident_column(s: Series, capacity: int) -> Optional[DeviceColumn]:
    """Resident device planes for a decoded series, when their capacity
    matches the requested bucket exactly (encode_batch's table-wide reuse
    handles the larger-bucket case)."""
    from . import pipeline
    hit = pipeline.resident_planes(s, len(s))
    if hit is None:
        return None
    data, validity, dictionary, cap = hit
    if cap != capacity:
        return None
    return DeviceColumn(data, validity, s.datatype(), dictionary)


def encoded_nbytes(batch, columns) -> int:
    """Wire/HBM bytes these columns occupy once encoded: device-repr
    itemsize (f64→f32 on chips without f64, strings→i32 dict codes) times
    the power-of-two bucket capacity, plus one validity byte per slot.
    This is what uploads actually cost and what the HBM cache stores —
    ``_batch_cols_nbytes``'s raw-Arrow bytes overstated f64-heavy TPC-H
    columns ~2×, which both inflated upload-cost estimates and made the
    cache-fit check refuse workloads that fit (r4: SF10 Q1 never
    invested)."""
    n = len(batch)
    cap = bucket_capacity(max(n, 1))
    total = 0
    for nm in columns:
        dt = batch.get_column(nm).datatype()
        if dt.is_string() or dt.is_binary():
            itemsize = 4  # dictionary codes; the dictionary stays host-side
        else:
            rep = dt.to_physical().device_repr()
            if rep is None:
                itemsize = 8
            elif rep == np.float64 and not supports_f64():
                itemsize = 4
            else:
                itemsize = np.dtype(rep).itemsize
        total += cap * (itemsize + 1)  # +1: validity mask
    return total


def encode_batch(batch, columns: Optional[List[str]] = None) -> DeviceTable:
    names = columns if columns is not None else batch.column_names()
    n = len(batch)
    cap = bucket_capacity(n)
    resident = _resident_batch(batch, names, n, cap)
    if resident is not None:
        return resident
    cols = {nm: encode_series(batch.get_column(nm), cap) for nm in names}
    mask = np.zeros(cap, dtype=np.bool_)
    mask[:n] = True
    return DeviceTable(cols, jnp.asarray(mask), n, cap)


def _resident_batch(batch, names, n: int, cap: int
                    ) -> Optional[DeviceTable]:
    """Table-wide residency reuse: when EVERY requested column's decoded
    device planes are still resident at one shared capacity ≥ the
    requested bucket, rebuild the DeviceTable from them — zero uploads
    beyond the tiny live-row mask.  Marked ``resident``: the planes are
    shared with the registry (and the host Series that keys it), so the
    donation discipline must never hand them to a fused program."""
    from . import pipeline
    hits = {}
    shared_cap = None
    for nm in names:
        hit = pipeline.resident_planes(batch.get_column(nm), n)
        if hit is None:
            return None
        data, validity, dictionary, ccap = hit
        if ccap < cap or (shared_cap is not None and ccap != shared_cap):
            return None
        shared_cap = ccap
        hits[nm] = DeviceColumn(data, validity,
                                batch.get_column(nm).datatype(), dictionary)
    if shared_cap is None:
        return None
    mask = np.zeros(shared_cap, dtype=np.bool_)
    mask[:n] = True
    return DeviceTable(hits, jnp.asarray(mask), n, shared_cap,
                       resident=True)


def decode_column(name: str, col: DeviceColumn, count: int) -> Series:
    """DeviceColumn → Series, taking the first ``count`` rows (post-compaction).
    Data + validity come back in ONE batched ``device_get`` (round 17: the
    two sequential blocking gets here were a full extra RTT per column on
    a transfer-bound link)."""
    return decode_columns([(name, col)], count)[0]


def decode_columns(named: "List[tuple]", count: int) -> "List[Series]":
    """Decode many DeviceColumns with ONE batched pytree ``device_get``
    for every data+validity plane (round 17's single-transfer
    discipline).  Each decoded Series registers its still-live device
    planes for residency hand-off when the async pipeline is enabled —
    a downstream device op then re-enters without a host round trip."""
    from . import pipeline
    fetched = pipeline.fetch_host([(c.data, c.validity) for _, c in named])
    register = pipeline.inflight_window() > 0
    out = []
    for (name, col), (vals, validity) in zip(named, fetched):
        s = _decode_np(name, col, np.asarray(vals)[:count],
                       np.asarray(validity)[:count], count)
        if register and _is_device_array(col.data) \
                and not col.dtype.is_decimal() and not col.dtype.is_null():
            # decimals are excluded: their f64 encoding is lossy, so a
            # reuse would not be bit-identical with a fresh re-encode
            pipeline.note_decoded(s, col.data, col.validity,
                                  col.dictionary, count,
                                  int(col.data.shape[0]))
        out.append(s)
    return out


def _is_device_array(x) -> bool:
    return isinstance(x, jax.Array)


def _decode_np(name: str, col: DeviceColumn, vals: np.ndarray,
               validity: np.ndarray, count: int) -> Series:
    """Host-side decode of already-fetched planes (the single-transfer
    table path lands here with numpy arrays)."""
    dt = col.dtype
    if dt.is_null():
        return Series(name, dt, arrow=pa.nulls(count))
    if col.dictionary is not None:
        codes = np.where(validity, vals.astype(np.int64), 0)
        arr = col.dictionary.take(pa.array(codes, type=pa.int64()))
        if arr.type != dt.to_arrow():
            arr = arr.cast(dt.to_arrow())
        if not validity.all():
            arr = pc.if_else(pa.array(validity), arr,
                             pa.nulls(count, type=dt.to_arrow()))
        return Series(name, dt, arrow=arr)
    target = dt.to_arrow()
    if dt.kind == "date":
        arr = pa.array(vals.astype(np.int32), mask=~validity).cast(target)
    elif dt.is_temporal():
        arr = pa.array(vals.astype(np.int64), mask=~validity).cast(target)
    elif dt.is_boolean():
        arr = pa.array(vals.astype(np.bool_), mask=~validity)
    else:
        rep = dt.device_repr()
        if rep is not None and vals.dtype != rep:
            vals = vals.astype(rep)
        arr = pa.array(vals, mask=~validity)
        if arr.type != target:
            arr = arr.cast(target)
    return Series(name, dt, arrow=arr)


def decode_table(dt: DeviceTable, compact_perm: Optional[np.ndarray] = None):
    """DeviceTable → RecordBatch. If rows are not already compacted (live rows
    first), pass a permutation from ``kernels.compaction_perm``.

    The whole table downloads as ONE pytree ``device_get`` (round 17):
    every column's data+validity host copies start together instead of
    2×n_cols sequential blocking round trips."""
    from ..recordbatch import RecordBatch
    named = []
    for name, col in dt.columns.items():
        if compact_perm is not None:
            data = jnp.take(col.data, compact_perm, axis=0)
            valid = jnp.take(col.validity, compact_perm, axis=0)
            col = DeviceColumn(data, valid, col.dtype, col.dictionary)
        named.append((name, col))
    cols = decode_columns(named, dt.row_count)
    return RecordBatch.from_series(cols) if cols else RecordBatch.empty()
