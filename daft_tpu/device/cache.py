"""HBM-resident device column cache.

The TPU sits behind a transfer link that is orders of magnitude slower than
host RAM (measured on this tunnel: ~36 ms RTT, ~30-50 MB/s), so the device
tier can only win when hot columns *stay resident in HBM across queries* —
the TPU-native analogue of the reference's ``PartitionSetCache``
(``daft/runners/runner.py:22-35``) one level down: instead of caching result
partitions host-side, we cache *encoded scan columns* device-side, keyed by
scan-task fingerprint.

Granularity is (task, column): different queries touching different column
subsets of the same file share entries. Entries are LRU-evicted to a byte
budget (``DAFT_TPU_HBM_CACHE_BYTES``, default 8 GiB — leaves headroom on a
16 GiB v5e chip for kernel workspace).

Invalidation: the fingerprint covers file paths, sizes, mtimes, row-group
selection and row-affecting pushdowns, so a changed file re-encodes.
In-memory / generator-backed tasks have no stable identity and bypass the
cache.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import column as dcol


def _budget() -> int:
    # 8 GiB of a 16 GiB v5e: encoded columns are compact (f64 rides f32,
    # strings ride i32 codes), and the grouped-agg workspace peaks well
    # under the remaining half. 4 GiB (r4) turned away SF10's ~3.4 GiB
    # hot-column set that residency would have repaid.
    from ..analysis import knobs
    return knobs.env_bytes("DAFT_TPU_HBM_CACHE_BYTES")


def task_fingerprint(task) -> Optional[Tuple]:
    """Stable identity of a scan task's *loaded rows*, or None if the task
    has no cacheable identity (generator source, unstat-able paths)."""
    if getattr(task, "generator", None) is not None:
        return None
    try:
        stats = []
        for p in task.paths:
            if not os.path.exists(p):
                return None  # remote path: no cheap invalidation signal
            st = os.stat(p)
            stats.append((p, st.st_size, st.st_mtime_ns))
    except OSError:
        return None
    pd = task.pushdowns
    filt = pd.filters._key() if getattr(pd, "filters", None) is not None \
        else None
    rg = tuple(tuple(r) if r is not None else None
               for r in task.row_groups) if task.row_groups else None
    return (tuple(stats), task.file_format, rg, filt, pd.limit)


class _Entry:
    __slots__ = ("col", "nbytes")

    def __init__(self, col: dcol.DeviceColumn, nbytes: int):
        self.col = col
        self.nbytes = nbytes


class DeviceColumnCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._cols: "OrderedDict[Tuple, _Entry]" = OrderedDict()
        self._masks: "OrderedDict[Tuple, Tuple]" = OrderedDict()  # fp -> (mask, rows, cap)
        self._bytes = 0

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._cols), "bytes": self._bytes}

    def clear(self) -> None:
        with self._lock:
            self._cols.clear()
            self._masks.clear()
            self._bytes = 0

    # ------------------------------------------------------------------
    def get_table(self, fp: Tuple, cols: List[str]
                  ) -> Optional[dcol.DeviceTable]:
        """All requested columns cached → assembled DeviceTable, else None."""
        with self._lock:
            mask = self._masks.get(fp)
            if mask is None:
                return None
            out = {}
            for c in cols:
                e = self._cols.get((fp, c))
                if e is None:
                    return None
                self._cols.move_to_end((fp, c))
                out[c] = e.col
            self._masks.move_to_end(fp)
            row_mask, rows, cap = mask
            return dcol.DeviceTable(out, row_mask, rows, cap, resident=True)

    def put_table(self, fp: Tuple, dt: dcol.DeviceTable) -> None:
        add = 0
        sized = []
        for name, col in dt.columns.items():
            nbytes = int(col.data.nbytes) + int(col.validity.nbytes)
            sized.append((name, col, nbytes))
            add += nbytes
        if add > _budget():
            return
        # the caller's table now SHARES buffers with the cache — it must
        # never be donated to a fused program from here on
        dt.resident = True
        with self._lock:
            self._masks[fp] = (dt.row_mask, dt.row_count, dt.capacity)
            for name, col, nbytes in sized:
                key = (fp, name)
                old = self._cols.pop(key, None)
                if old is not None:
                    self._bytes -= old.nbytes
                self._cols[key] = _Entry(col, nbytes)
                self._bytes += nbytes
            self._evict_locked()

    def _evict_locked(self) -> None:
        budget = _budget()
        while self._bytes > budget and self._cols:
            _, e = self._cols.popitem(last=False)
            self._bytes -= e.nbytes
        live_fps = {k[0] for k in self._cols}
        for fp in [f for f in self._masks if f not in live_fps]:
            del self._masks[fp]


_cache: Optional[DeviceColumnCache] = None
_cache_lock = threading.Lock()


def get_cache() -> DeviceColumnCache:
    global _cache
    with _cache_lock:
        if _cache is None:
            _cache = DeviceColumnCache()
        return _cache
