"""Device dispatch: decides per-batch whether an op runs as XLA or on host.

This is the dispatch seam the reference has per-operator
(SURVEY.md §7 hard-part #2: "keep a principled host-fallback per operator").
Returns None from ``try_*`` → caller falls back to the Arrow host tier.

Controls:
- ``DAFT_TPU_DEVICE=0`` disables the device tier entirely.
- ``DAFT_TPU_DEVICE_MIN_ROWS`` (default 0) bypasses the device for small
  batches where transfer overhead dominates.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

import jax
import jax.numpy as jnp

from ..datatype import DataType
from ..expressions.expressions import Expression
from ..schema import Schema
from ..series import Series
from . import column as dcol
from . import compiler, kernels

_DEVICE_AGGS = {"sum", "mean", "min", "max", "count", "stddev", "var",
                "any_value", "bool_and", "bool_or"}


def device_enabled() -> bool:
    from ..analysis import knobs
    if not knobs.env_bool("DAFT_TPU_DEVICE"):
        return False
    from . import backend
    return backend.device_ready()


def _is_transfer_bound() -> bool:
    """True when the device sits behind a slow host↔device link (real TPU,
    possibly tunneled) rather than sharing host memory (CPU backend)."""
    from . import backend
    return (backend.backend_name() or "cpu") not in ("cpu",)


def _min_rows() -> int:
    from ..analysis import knobs
    env = knobs.env_int("DAFT_TPU_DEVICE_MIN_ROWS", default=None)
    if env is not None:
        return env
    # on a transfer-bound link, tiny batches are pure round-trip overhead
    return 4096 if _is_transfer_bound() else 0


def _series_nbytes(s: Series) -> int:
    try:
        return int(s.to_arrow().nbytes)
    except Exception:
        return 9 * len(s)


def _batch_cols_nbytes(batch, cols) -> int:
    return sum(_series_nbytes(batch.get_column(c)) for c in cols)


def _min_rows_override(n_rows: int) -> Optional[bool]:
    """An explicit DAFT_TPU_DEVICE_MIN_ROWS keeps its documented meaning on
    every backend (device runs at or above that many rows); FORCE trumps it.
    None → no override, consult the cost model."""
    from ..analysis import knobs
    env = knobs.env_int("DAFT_TPU_DEVICE_MIN_ROWS", default=None)
    if env is None or knobs.env_is_set("DAFT_TPU_DEVICE_FORCE"):
        return None
    return n_rows >= max(env, 1)


def _row_output_profitable(batch, needs_cols, n_outputs: int,
                           out_bytes_per_row: int = 8) -> bool:
    """Cost gate for ops whose OUTPUT is row-shaped (projection values, sort
    permutations, filter masks): the measured-link cost model compares
    transfer+RTT against a host vector pass (``costmodel.py``). On the
    bench tunnel (~40 MB/s) this picks host, on a local chip it picks the
    device — same code, measured numbers. Reduction-shaped ops are gated
    separately (their outputs are packed group blocks). An explicit
    DAFT_TPU_DEVICE_MIN_ROWS keeps its documented meaning (the device runs
    at or above that many rows) on every backend."""
    from . import costmodel
    n_rows = len(batch)
    ov = _min_rows_override(n_rows)
    if ov is not None:
        return ov
    bytes_up = dcol.encoded_nbytes(batch, needs_cols)
    bytes_down = n_rows * out_bytes_per_row * max(n_outputs, 1)
    return costmodel.row_output_op_wins(
        bytes_up, bytes_down,
        host_bytes=_batch_cols_nbytes(batch, needs_cols))


_projection_cache: Dict[Tuple, compiler.Compiled] = {}
# single-flight compile coordination for the serving plane: N concurrent
# identical cold queries must produce ONE trace/lowering, with the other
# N-1 waiting on the winner instead of burning N duplicate compiles
_compile_lock = threading.Lock()
_compile_inflight: Dict[Tuple, threading.Event] = {}
_compile_counters: Dict[str, int] = {"hits": 0, "misses": 0, "compiles": 0,
                                     "waits": 0}


def compile_cache_counters() -> Dict[str, int]:
    """Process-wide projection-compile cache counters (the serving
    bench's evidence that jitted fragments are reused across
    submissions)."""
    with _compile_lock:
        out = dict(_compile_counters)
    out["entries"] = len(_projection_cache)
    return out


def _schema_key(schema: Schema) -> Tuple:
    return tuple((f.name, hash(f.dtype)) for f in schema)


def _get_compiled(exprs: List[Expression], schema: Schema
                  ) -> Optional[compiler.Compiled]:
    key = (tuple(e._key() for e in exprs), _schema_key(schema))
    while True:
        with _compile_lock:
            hit = _projection_cache.get(key)
            if hit is not None:
                _compile_counters["hits"] += 1
                return hit
            ev = _compile_inflight.get(key)
            if ev is None:
                _compile_inflight[key] = threading.Event()
                _compile_counters["misses"] += 1
                break
            _compile_counters["waits"] += 1
        # someone else is compiling this projection — wait, then re-check
        # (compile failures don't cache, so the loop may compile after all)
        ev.wait()
    try:
        try:
            c = compiler.compile_projection(exprs, schema)
        except (compiler.NotCompilable, NotImplementedError, ValueError,
                TypeError, KeyError, OverflowError):
            return None
        with _compile_lock:
            _projection_cache[key] = c
            _compile_counters["compiles"] += 1
        return c
    finally:
        with _compile_lock:
            ev2 = _compile_inflight.pop(key, None)
        if ev2 is not None:
            ev2.set()


def _string_out_source(e: Expression) -> Optional[str]:
    """If expr output is a passthrough of a string column, its source name."""
    inner = e._unalias()
    return inner.params[0] if inner.op == "col" else None


def _prep_scalars(c: compiler.Compiled, dt: dcol.DeviceTable):
    scalars = []
    for spec in c.scalar_specs:
        d = dt.columns[spec.col].dictionary
        if d is None:
            d = pa.array([], type=pa.large_string())
        scalars.append(jnp.asarray(spec.fn(d)))
    return tuple(scalars)


def encode_for(c: compiler.Compiled, batch):
    """Encode a batch's needed columns for a compiled program.
    Returns (DeviceTable, arrays, valids, scalars)."""
    dt = dcol.encode_batch(batch, c.needs_cols)
    arrays = {n: col.data for n, col in dt.columns.items()}
    valids = {n: col.validity for n, col in dt.columns.items()}
    scalars = _prep_scalars(c, dt)
    return dt, arrays, valids, scalars


def decode_group_key(e: Expression, field, kv, km, dt: dcol.DeviceTable,
                     count: int) -> Series:
    """Decode one group-key output, routing string dictionaries from the
    encoded source column."""
    dictionary = None
    if field.dtype.is_string() or field.dtype.is_binary():
        dictionary = dt.columns[_string_out_source(e)].dictionary
    dc = dcol.DeviceColumn(kv, km, field.dtype, dictionary)
    return dcol.decode_column(field.name, dc, count)


def _run_compiled(c: compiler.Compiled, batch, exprs: List[Expression]):
    """Encode inputs, run the fused program, return per-expr device outputs."""
    from ..analysis import retrace_sanitizer
    dt, arrays, valids, scalars = encode_for(c, batch)
    # declared trace signature (dispatch_registry: compiler.projection):
    # one trace per compiled projection x capacity class x scalar-plane
    # shapes — never per raw row count
    with retrace_sanitizer.dispatch_scope(
            "compiler.projection",
            (id(c), dt.capacity, tuple(s.shape for s in scalars))):
        outs = c.fn(arrays, valids, dt.row_mask, scalars)
    return dt, outs


def try_eval_projection(batch, exprs: List[Expression]):
    """Full projection on device; None → host fallback."""
    from ..recordbatch import RecordBatch
    if not device_enabled():
        return None
    schema = batch.schema
    out_fields = []
    try:
        for e in exprs:
            out_fields.append(e.to_field(schema))
    except Exception:
        return None
    # every output must be decodable
    for e, f in zip(exprs, out_fields):
        if f.dtype.is_string() or f.dtype.is_binary():
            if _string_out_source(e) is None:
                return None
        elif f.dtype.device_repr() is None:
            return None
    c = _get_compiled(exprs, schema)
    if c is None:
        return None
    if not _row_output_profitable(batch, c.needs_cols, len(exprs)):
        return None
    for name in c.needs_cols:
        if batch.get_column(name).is_pyobject():
            return None
    import time as _time

    from . import costmodel
    t0 = _time.perf_counter()
    dt, outs = _run_compiled(c, batch, exprs)
    n = len(batch)
    named = []
    for e, f, (val, valid) in zip(exprs, out_fields, outs):
        dictionary = None
        if f.dtype.is_string() or f.dtype.is_binary():
            dictionary = dt.columns[_string_out_source(e)].dictionary
        named.append((f.name,
                      dcol.DeviceColumn(val, valid, f.dtype, dictionary)))
    # ONE batched transfer for every output plane (round 17) — and each
    # decoded column registers for device-resident hand-off, so a device
    # consumer (argsort/topk, grouped agg) skips the re-upload
    cols = dcol.decode_columns(named, n)
    costmodel.ledger_record(
        "projection", rows=n,
        nbytes=dcol.encoded_nbytes(batch, c.needs_cols)
        + n * 8 * max(len(exprs), 1),
        seconds=_time.perf_counter() - t0)
    return RecordBatch.from_series(cols)


def try_eval_predicate(batch, predicate: Expression) -> Optional[np.ndarray]:
    """Predicate → host boolean mask (for arrow-side filtering)."""
    if not device_enabled():
        return None
    c = _get_compiled([predicate], batch.schema)
    if c is None:
        return None
    if not _row_output_profitable(batch, c.needs_cols, 1,
                                  out_bytes_per_row=1):
        return None
    for name in c.needs_cols:
        if batch.get_column(name).is_pyobject():
            return None
    dt, outs = _run_compiled(c, batch, [predicate])
    val, valid = outs[0]
    mask = np.asarray(jax.device_get(val & valid))[:len(batch)]
    return mask.astype(bool)


def try_argsort(key_series: List[Series], descending: List[bool],
                nulls_first: List[bool]) -> Optional[np.ndarray]:
    from . import costmodel
    if not device_enabled() or not key_series:
        return None
    n = len(key_series[0])
    if n < 2:
        return None
    ov = _min_rows_override(n)
    if ov is False:
        return None
    if ov is None and not costmodel.argsort_wins(
            n, sum(_series_nbytes(s) for s in key_series), len(key_series)):
        return None
    for s in key_series:
        if s.is_pyobject():
            return None
        dt = s.datatype()
        if not (dt.is_device_representable() or dt.is_string()):
            return None
    cap = dcol.bucket_capacity(n)
    try:
        # allow_resident: a key column decoded off a device projection
        # re-enters without re-uploading (argsort never donates planes)
        cols = [dcol.encode_series(s, cap, allow_resident=True)
                for s in key_series]
    except (ValueError, pa.ArrowInvalid):
        return None
    mask = np.zeros(cap, dtype=np.bool_)
    mask[:n] = True
    import time as _time

    from ..analysis import retrace_sanitizer
    from . import mfu
    t0 = _time.perf_counter()
    desc = tuple(bool(d) for d in descending)
    nf = tuple(bool(x) for x in nulls_first)
    with retrace_sanitizer.dispatch_scope(
            "kernels.argsort",
            (tuple(str(c.data.dtype) for c in cols), cap, desc, nf)):
        perm = kernels.argsort_kernel(
            tuple(c.data for c in cols), tuple(c.validity for c in cols),
            jnp.asarray(mask), desc, nf)
    out = np.asarray(jax.device_get(perm))[:n].astype(np.int64)
    costmodel.ledger_record(
        "argsort", rows=n,
        nbytes=mfu.argsort_bytes_model(cap, [c.data.dtype for c in cols]),
        seconds=_time.perf_counter() - t0)
    return out


def try_agg(batch, to_agg: List[Expression], group_by: List[Expression]):
    """Grouped/global aggregation on device; None → host fallback."""
    from ..aggs import split_agg_expr
    from ..recordbatch import RecordBatch
    from . import costmodel
    if not device_enabled() or len(batch) < max(_min_rows(), 1):
        return None
    schema = batch.schema
    try:
        specs = [split_agg_expr(e) for e in to_agg]
    except ValueError:
        return None
    for op, child, name, params in specs:
        if op not in _DEVICE_AGGS:
            return None
        if op == "count" and params and params[0] != "valid":
            return None
    try:
        out_fields = [e.to_field(schema) for e in to_agg]
        key_fields = [e.to_field(schema) for e in group_by]
    except Exception:
        return None
    for e, f in zip(group_by, key_fields):
        if f.dtype.is_string() or f.dtype.is_binary():
            if _string_out_source(e) is None:
                return None
        elif f.dtype.device_repr() is None:
            return None
    for (op, child, _, _), f in zip(specs, out_fields):
        if f.dtype.is_string() or f.dtype.is_binary():
            if child is None or _string_out_source(child) is None:
                return None
        elif f.dtype.device_repr() is None:
            return None

    # compile keys + agg children as one projection
    child_exprs = []
    for i, (op, child, name, params) in enumerate(specs):
        child_exprs.append((child if child is not None
                            else Expression._lit(True)).alias(f"__in{i}__"))
    proj = list(group_by) + child_exprs
    c = _get_compiled(proj, schema)
    if c is None:
        return None
    for nm in c.needs_cols:
        if batch.get_column(nm).is_pyobject():
            return None
    # in-memory batch: no HBM-cache identity, the upload is one-shot.
    # The strategy model runs FIRST so the gate prices the kernel the
    # dispatch would actually take (one-pass hash vs radix sort) —
    # UNLOGGED here: the gate below may still decline the upload, and
    # decision_counts tallies acted-on dispatches, not estimates.
    nk = len(group_by)
    cap = dcol.bucket_capacity(max(len(batch), 1))
    strategy, load_factor = ("sort", 0.0) if nk == 0 else \
        costmodel.groupby_strategy(
            len(batch), None,
            [np.dtype(f.dtype.device_repr() or "int32")
             for f in key_fields], cap, log=False)
    from .fragment import _OUT_CAP0, packed_bytes_per_group
    packed_out = packed_bytes_per_group(len(group_by),
                                        len(to_agg)) * _OUT_CAP0
    if not costmodel.agg_upload_wins(
            dcol.encoded_nbytes(batch, c.needs_cols),
            packed_out, cacheable=False,
            host_bytes=_batch_cols_nbytes(batch, c.needs_cols),
            strategy=strategy):
        return None

    dt, outs = _run_compiled(c, batch, proj)
    nk = len(group_by)
    key_outs = outs[:nk]
    val_outs = outs[nk:]
    ops = tuple(s[0] for s in specs)

    def bcast(v, m):
        if v.ndim == 0:
            v = jnp.broadcast_to(v, dt.row_mask.shape)
            m = jnp.broadcast_to(m, dt.row_mask.shape)
        return v, m

    from ..analysis import retrace_sanitizer
    if nk == 0:
        vals, valids = zip(*[bcast(v, m) for v, m in val_outs]) if val_outs \
            else ((), ())
        with retrace_sanitizer.dispatch_scope(
                "kernels.grouped_agg",
                ("global", ops, tuple(str(v.dtype) for v in vals),
                 dt.capacity)):
            results = kernels.global_agg_kernel(tuple(vals), tuple(valids),
                                                dt.row_mask, ops)
        # ONE batched transfer for all scalar results (round 17: the
        # per-scalar get pair cost 2 RTTs per aggregate)
        from . import pipeline as dpipe
        host_results = dpipe.fetch_host(results)
        cols = []
        for (op, child, name, params), f, (rv, rm) in zip(
                specs, out_fields, host_results):
            v = np.asarray(rv).reshape(1)
            m = np.asarray(rm).reshape(1)
            cols.append(_decode_scalar(name, f.dtype, v, m))
        return RecordBatch.from_series(cols)

    keys_b = [bcast(v, m) for v, m in key_outs]
    vals_b = [bcast(v, m) for v, m in val_outs]
    import time as _time

    from . import mfu, pallas_kernels as pk
    t0 = _time.perf_counter()
    karg = (tuple(v for v, _ in keys_b), tuple(m for _, m in keys_b),
            tuple(v for v, _ in vals_b), tuple(m for _, m in vals_b),
            dt.row_mask, ops)
    kdtypes = tuple(str(v.dtype) for v, _ in keys_b)
    vdtypes = tuple(str(v.dtype) for v, _ in vals_b)
    if strategy == "hash":
        try:
            # [capacity]-wide group budget: groups ≤ live rows ≤ capacity,
            # so the hash path can never overflow here
            with retrace_sanitizer.dispatch_scope(
                    "pallas.hash_agg",
                    (ops, kdtypes, vdtypes, dt.capacity)):
                out_keys, out_kvalids, out_vals, out_valids, gcount = \
                    pk.hash_grouped_agg_kernel(*karg, out_cap=dt.capacity)
        except pk.HashKeyWidthError:
            # key set packs wider than the table key budget (the pre-ask
            # estimated from declared dtypes; the kernel's own trace is
            # the exact check) — run the any-width sort path instead
            strategy, load_factor = "sort", 0.0
    if strategy == "sort":
        with retrace_sanitizer.dispatch_scope(
                "kernels.grouped_agg",
                (ops, kdtypes, vdtypes, dt.capacity)):
            out_keys, out_kvalids, out_vals, out_valids, gcount = \
                kernels.grouped_agg_kernel(*karg)
    # the decision that actually dispatched (post width-gate fallback)
    costmodel.log_strategy_decision("groupby_strategy", strategy,
                                    rows=len(batch), out_cap=cap,
                                    load_factor=load_factor)
    # ONE batched transfer for the group count and every output plane
    # (round 17: this path issued 1 + 2×(nk+nvals) sequential gets)
    from . import pipeline as dpipe
    g, out_keys, out_kvalids, out_vals, out_valids = dpipe.fetch_host(
        (gcount, out_keys, out_kvalids, out_vals, out_valids))
    g = int(g)
    # both formulations are bytes-bound: no MXU flops to claim
    if strategy == "hash":
        words = pk.hash_pack_words([v.dtype for v, _ in keys_b]) or 2
        _, nbytes = mfu.hash_agg_models(
            dt.capacity, dt.capacity, pk.table_capacity(dt.capacity),
            words, len(ops))
    else:
        _, nbytes = mfu.grouped_agg_models(dt.capacity, dt.capacity, nk,
                                           len(ops))
    costmodel.ledger_record("grouped_agg", rows=len(batch), nbytes=nbytes,
                            seconds=_time.perf_counter() - t0,
                            strategy=strategy,
                            load_factor=load_factor or None)
    cols = []
    for e, f, kv, km in zip(group_by, key_fields, out_keys, out_kvalids):
        cols.append(decode_group_key(e, f, kv, km, dt, g))
    for (op, child, name, params), f, vv, vm in zip(specs, out_fields,
                                                    out_vals, out_valids):
        dictionary = None
        if f.dtype.is_string() or f.dtype.is_binary():
            dictionary = dt.columns[_string_out_source(child)].dictionary
        dc = dcol.DeviceColumn(vv, vm, f.dtype, dictionary)
        cols.append(dcol.decode_column(name, dc, g))
    return RecordBatch.from_series(cols)


def _decode_scalar(name: str, dtype: DataType, v: np.ndarray, m: np.ndarray
                   ) -> Series:
    # v/m are already host-side numpy (fetched in the caller's single packed
    # transfer) — wrapping them in jnp.asarray would re-upload to the device
    # only for decode_column to fetch them straight back: 2 extra RTTs per
    # scalar (~0.2 s each on the tunnel; this was the whole Q6 regression)
    dc = dcol.DeviceColumn(v, m, dtype, None)
    return dcol.decode_column(name, dc, 1)
