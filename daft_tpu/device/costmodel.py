"""Measured transfer-aware dispatch cost model.

Round 2's dispatch gate reasoned about *output shape only* ("row-shaped
results never pay for the link"). That heuristic was right on the bench
tunnel and wrong everywhere else — a local v5e's host↔HBM link is ~1000×
faster, where row-shaped outputs are perfectly fine. This module replaces
the shape heuristic with the comparison the reference's per-operator
dispatch seam implies (SURVEY.md §7 hard-part #2):

    device_time = bytes_up/up_bw + bytes_down/down_bw + round_trips·RTT
                  (+ kernel time, usually negligible next to the link terms)
    host_time   = bytes_touched / host_kernel_bandwidth

and runs the op on whichever side is cheaper. The link terms are MEASURED,
not assumed: the first decision on a non-CPU backend calibrates RTT and
both bandwidths (see ``_measure`` — a few tiny round trips plus 8 MiB
transfers, once per process). Host
kernel bandwidths are coarse constants for pyarrow's SIMD kernels — they
only need to be right to an order of magnitude because real decisions are
dominated by the link terms (40 MB/s tunnel vs GB/s host, or 100 GB/s
local HBM vs GB/s host).

Env overrides (testing / ops):
- ``DAFT_TPU_LINK_RTT_MS`` / ``DAFT_TPU_LINK_UP_MBPS`` /
  ``DAFT_TPU_LINK_DOWN_MBPS``: skip measurement, use these numbers.
- ``DAFT_TPU_DEVICE_FORCE=1``: the device always wins (existing knob).
"""

from __future__ import annotations

import math
import os
import threading
import time
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

# host-side kernel bandwidths (bytes/s) for the Arrow compute tier these
# decisions compare against; coarse on purpose (see module docstring)
HOST_VECTOR_BPS = 2.0e9     # elementwise eval / filter, per byte touched
HOST_AGG_BPS = 3.0e8        # hash/grouped aggregation, per byte touched
HOST_SORT_ROWS_PER_S = 12.0e6   # multi-key argsort, rows/s
HOST_JOIN_ROWS_PER_S = 25.0e6   # hash join build+probe, rows/s
HOST_PIL_BPS = 85e6             # per-image PIL resize, input bytes/s
#                                 (measured: 64x64 RGB -> 32x32, 1 core)

# device-side terms: without these a zero-cost link (CPU backend, local
# HBM) degenerates to "device always wins" no matter how slow the kernel
DEV_VECTOR_BPS = 8.0e9      # fused elementwise XLA, per byte touched
DEV_AGG_BPS = 4.0e9         # fused grouped-agg (sort strategy), per byte
DEV_AGG_HASH_BPS = 8.0e9    # one-pass hash grouped-agg, per byte touched
DEV_AGG_DENSE_BPS = 1.6e10  # direct-indexed dense grouped-agg (round 21):
#                             pure arithmetic group ids + one scatter pass
#                             per plane — no sort, no table
DEV_SORT_ROWS_PER_S = 50.0e6    # XLA multi-key sort, rows/s
DEV_JOIN_ROWS_PER_S = 40.0e6    # sort/searchsorted/expand join, rows/s
DEV_JOIN_HASH_ROWS_PER_S = 80.0e6  # hash build/probe join, rows/s: ONE
#                             pass per side instead of the build-side
#                             radix sort's ≥2 passes per plane
DEV_DISPATCH_S = 2.0e-3     # per-decision executable launch + (amortized)
#                             shape-bucket compile overhead
INVEST_MAX_RATIO = 8.0      # max cache-fill cost vs one host pass (see
#                             agg_upload_wins' bounded-investment rule).
#                             Sized to realistic reuse: a TPC-H suite pass
#                             re-touches a hot column ~3-6×, so a fill
#                             costing more than ~8 host passes cannot repay
#                             within a workload; 64 (r4) let 20-30× fills
#                             through on slow-link days, which one-shot
#                             suites never amortized


@dataclass(frozen=True)
class LinkProfile:
    rtt_s: float
    up_bps: float
    down_bps: float

    def device_seconds(self, bytes_up: float, bytes_down: float,
                       round_trips: float, kernel_s: float = 0.0) -> float:
        return (bytes_up / self.up_bps + bytes_down / self.down_bps
                + round_trips * self.rtt_s + kernel_s)

    def pipelined_seconds(self, bytes_up: float, bytes_down: float,
                          round_trips: float, kernel_s: float = 0.0
                          ) -> float:
        """Steady-state per-morsel cost with the async device pipeline
        (round 17) overlapping the transfer legs with neighbor morsels'
        compute: the bottleneck stage sets throughput, so the effective
        cost is the slower of (wire time, kernel time) plus one RTT for
        the dispatch tail — never more than the serial chain.  The
        serial model charged full upload+download+RTT per morsel, which
        made the strategy ladder under-dispatch to the device exactly
        when overlap would hide the transfer."""
        link_s = bytes_up / self.up_bps + bytes_down / self.down_bps
        serial = link_s + round_trips * self.rtt_s + kernel_s
        steady = max(link_s, kernel_s) + self.rtt_s
        return min(serial, steady)


_SHARED_MEMORY = LinkProfile(0.0, math.inf, math.inf)

_lock = threading.Lock()
_profile: Optional[LinkProfile] = None


def _cal(name: str, default: float) -> float:
    """Read one costmodel constant through the calibration store (round
    20): the learned per-backend value once its sample floor is met and
    ``DAFT_TPU_CALIBRATION`` is on; the hard-coded default otherwise
    (and always under the chaos-determinism freeze)."""
    from . import calibration
    return calibration.const(name, default)


def _env_profile() -> Optional[LinkProfile]:
    from ..analysis import knobs
    rtt = knobs.env_float("DAFT_TPU_LINK_RTT_MS", default=None)
    up = knobs.env_float("DAFT_TPU_LINK_UP_MBPS", default=None)
    down = knobs.env_float("DAFT_TPU_LINK_DOWN_MBPS", default=None)
    if rtt is None and up is None and down is None:
        return None
    return LinkProfile(
        rtt_s=(rtt if rtt is not None else 1.0) / 1e3,
        up_bps=(up if up is not None else 100.0) * 1e6,
        down_bps=(down if down is not None else 100.0) * 1e6)


def _measure() -> LinkProfile:
    """One-time link calibration: 4 tiny round trips plus two timed 8 MiB
    one-way legs per round, two rounds (seconds on a ~10-40 MB/s tunnel,
    microseconds on a local chip; paid once per boot — see the persisted
    profile in ``link_profile``).

    Robustness notes learned on the tunneled chip: the FIRST tiny round
    trip pays lazy-init costs (~10-20× a steady-state RTT) — warm up and
    take the median of three. ``block_until_ready`` after ``jnp.asarray``
    does not reliably reflect wire time for uploads (staged copies), and
    a cold timed pass would absorb XLA compile time on a local chip — so
    an UNTIMED pass compiles + stages first, then the upload rate comes
    from a verified round trip (upload, force a kernel, fetch) minus the
    separately measured download time. Two rounds, and the SLOWER one
    wins: single 8 MiB samples over-reported the r4 tunnel by 2-10×
    (25-150 MB/s measured vs ~10 MB/s sustained), and an optimistic link
    estimate buys expensive device mispredicts (Q22: +8.8 s at SF10)
    while a pessimistic one merely leaves the op on the host."""
    import statistics

    import jax
    import jax.numpy as jnp

    tiny = np.zeros(8, dtype=np.float32)
    jax.device_get(jnp.asarray(tiny))  # warmup: lazy init paid here
    rtts = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(jnp.asarray(tiny))
        rtts.append(time.perf_counter() - t0)
    rtt = max(statistics.median(rtts), 1e-7)

    nbytes = 1 << 23  # 8 MiB
    big = np.zeros(nbytes // 4, dtype=np.float32)
    # untimed first pass: compiles the +0 executable AND leaves the data
    # resident, so the timed rounds below measure pure wire time
    dev = jnp.asarray(big) + 0
    dev.block_until_ready()
    down_best, up_best = None, None
    for rnd in range(2):
        t0 = time.perf_counter()
        jax.device_get(dev)
        down_s = max(time.perf_counter() - t0 - rtt / 2, 1e-7)
        # verified round trip (compile-cached): upload + fetch. NB: must
        # use a FRESH buffer — jax dedupes transfers of the same numpy
        # object, which would make the upload leg look free
        big2 = big + (1.0 + rnd)
        t0 = time.perf_counter()
        jax.device_get(jnp.asarray(big2) + 0)
        round_s = time.perf_counter() - t0
        # a sane floor: the upload leg of an 8 MiB round cannot beat 10×
        # the measured download rate even on asymmetric links
        up_s = max(round_s - down_s - rtt, down_s / 10, 1e-7)
        # keep the SLOWER (conservative) of the rounds
        down_best = down_s if down_best is None else max(down_best, down_s)
        up_best = up_s if up_best is None else max(up_best, up_s)
    return LinkProfile(rtt_s=rtt,
                       up_bps=nbytes / up_best,
                       down_bps=nbytes / down_best)


_LINK_CACHE_TTL_S = 1800.0   # reuse a stored profile this long
_LINK_BLEND_MAX_S = 6 * 3600.0  # blend with a stale profile up to this age


def _link_cache_path() -> str:
    from ..analysis import knobs
    p = knobs.env_str("DAFT_TPU_LINK_CACHE_PATH")
    if p:
        return p
    return os.path.join(os.path.expanduser("~"), ".cache", "daft_tpu",
                        "link_profile.json")


def _load_stored(backend_name: str):
    """(LinkProfile, age_s) from the persisted cache, or (None, None)."""
    import json
    try:
        with open(_link_cache_path()) as f:
            d = json.load(f)
        if d.get("backend") != backend_name:
            return None, None
        age = time.time() - float(d["ts"])
        return LinkProfile(rtt_s=float(d["rtt_s"]),
                           up_bps=float(d["up_bps"]),
                           down_bps=float(d["down_bps"])), age
    except (OSError, ValueError, KeyError, TypeError, AttributeError):
        return None, None


def _store(backend_name: str, p: LinkProfile) -> None:
    import json
    path = _link_cache_path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"backend": backend_name, "ts": time.time(),
                       "rtt_s": p.rtt_s, "up_bps": p.up_bps,
                       "down_bps": p.down_bps}, f)
        os.replace(tmp, path)
    except OSError:
        pass


def link_profile() -> LinkProfile:
    """The measured (or overridden) host↔device link profile. CPU backends
    share host memory: zero-cost link.

    Non-CPU profiles persist across processes (``~/.cache/daft_tpu/
    link_profile.json``, ``DAFT_TPU_LINK_CACHE_PATH`` to move,
    ``DAFT_TPU_LINK_CACHE=0`` to disable): re-measuring every process cost
    seconds on a slow tunnel AND made dispatch decisions flip-flop between
    processes when a single noisy sample landed on the other side of a
    threshold (r4 postmortem). Within the TTL the stored profile is used
    as-is; after it, a fresh measurement is geometric-blended with the
    stored one (if not too stale) to damp sample noise."""
    global _profile
    if _profile is not None:
        return _profile
    with _lock:
        if _profile is not None:
            return _profile
        env = _env_profile()
        if env is not None:
            _profile = env
            return _profile
        from . import backend
        bname = backend.backend_name() or "cpu"
        if bname == "cpu":
            _profile = _SHARED_MEMORY
            return _profile
        from ..analysis import knobs
        use_cache = bool(knobs.env_bool("DAFT_TPU_LINK_CACHE"))
        # daft-lint: allow(blocking-under-lock) -- intentional: _lock held
        # across load/measure/store so threads wait for the ONE calibration
        # instead of racing duplicate multi-second link measurements
        stored, age = _load_stored(bname) if use_cache else (None, None)
        if stored is not None and age is not None and age < _LINK_CACHE_TTL_S:
            _profile = stored
            return _profile
        try:
            meas = _measure()
        except Exception:
            # can't measure → reuse a not-too-stale stored profile, else
            # assume a slow link (conservative: host wins row-shaped ops,
            # device still wins reductions). A days-old profile from a
            # good-link day must not drive today's dispatch.
            if stored is not None and age is not None \
                    and age < _LINK_BLEND_MAX_S:
                _profile = stored
            else:
                _profile = LinkProfile(rtt_s=0.04, up_bps=40e6,
                                       down_bps=40e6)
            return _profile
        if stored is not None and age is not None \
                and age < _LINK_BLEND_MAX_S:
            meas = LinkProfile(
                rtt_s=math.sqrt(meas.rtt_s * stored.rtt_s),
                up_bps=math.sqrt(meas.up_bps * stored.up_bps),
                down_bps=math.sqrt(meas.down_bps * stored.down_bps))
        if use_cache:
            # daft-lint: allow(blocking-under-lock) -- tiny atomic JSON
            # write, same single-calibration critical section as above
            _store(bname, meas)
        _profile = meas
        return _profile


def reset_for_tests() -> None:
    global _profile, _ici
    with _lock:
        _profile = None
    with _ici_lock:
        _ici = None
    decision_counts.clear()
    ledger_reset()
    from . import calibration
    calibration.reset_for_tests()


# ------------------------------------------------------ silicon peak specs

def peak_flops() -> float:
    """Accelerator peak FLOP/s (bf16-class). Defaults to TPU v5e public
    specs; override per chip with ``DAFT_TPU_PEAK_FLOPS``."""
    from ..analysis import knobs
    return knobs.env_float("DAFT_TPU_PEAK_FLOPS")


def hbm_bps() -> float:
    """Accelerator HBM bandwidth (bytes/s); ``DAFT_TPU_HBM_BPS`` overrides."""
    from ..analysis import knobs
    return knobs.env_float("DAFT_TPU_HBM_BPS")


# ------------------------------------------------- per-dispatch MFU ledger

#: achieved-work accounting per kernel family, recorded at every REAL
#: dispatch site (argsort / join / grouped_agg / projection …) — not the
#: synthetic microbenchmarks. ``mfu.report()`` embeds a snapshot so bench
#: artifacts carry the per-dispatch evidence behind any efficiency claim.
kernel_ledger: dict = {}
_ledger_lock = threading.Lock()

_LEDGER_RAW = ("dispatches", "rows", "bytes", "flops", "seconds")
#: strategy accounting (round 12): per-family hash/sort dispatch counts
#: plus the summed hash-table load factor — the per-query stats block
#: derives `strategy` and the mean `load_factor` from these.  ``serial_s``
#: (round 17) is the serial-equivalent stage seconds the async pipeline
#: measured against its pipelined wall — the overlap evidence.
_LEDGER_STRATEGY = ("strategy_hash", "strategy_sort", "strategy_dense",
                    "lf_sum", "serial_s", "fused_ops", "rt_saved",
                    "fusion_serial_s")


def ledger_record(kind: str, *, rows: int = 0, nbytes: float = 0.0,
                  flops: float = 0.0, seconds: float = 0.0,
                  dispatches: int = 1, strategy: Optional[str] = None,
                  load_factor: Optional[float] = None,
                  serial_seconds: Optional[float] = None,
                  fused_ops: Optional[int] = None,
                  round_trips_saved: Optional[int] = None,
                  fusion_serial_seconds: Optional[float] = None) -> None:
    """Record one real dispatch's achieved work.

    ``seconds`` is wall time from dispatch to host-visible result — on a
    tunneled chip that includes link time, so the derived utilization is a
    LOWER bound on silicon utilization (the synthetic ``mfu.report``
    isolates the silicon with in-jit repetition). ``nbytes``/``flops``
    are the kernel's modeled HBM traffic / arithmetic, conservative.
    ``strategy`` (``hash``/``sort``/``dense``) and the hash table's
    achieved ``load_factor`` land in the same family row for the stats
    block. The ``region`` family (round 21) additionally carries
    ``fused_ops`` (operators compiled into the region programs),
    ``round_trips_saved`` (host round-trips the fusion eliminated vs the
    per-fragment chain), and ``fusion_serial_seconds`` — the modeled
    serial per-fragment equivalent, from which the stats block derives
    the ``fusion_x`` ratio the way ``serial_seconds`` yields
    ``overlap_x``."""
    fields = [("dispatches", dispatches), ("rows", rows),
              ("bytes", float(nbytes)), ("flops", float(flops)),
              ("seconds", float(seconds))]
    if strategy in ("hash", "sort", "dense"):
        fields.append((f"strategy_{strategy}", dispatches))
    if load_factor is not None:
        fields.append(("lf_sum", float(load_factor) * dispatches))
    if serial_seconds is not None:
        fields.append(("serial_s", float(serial_seconds)))
    if fused_ops is not None:
        fields.append(("fused_ops", int(fused_ops)))
    if round_trips_saved is not None:
        fields.append(("rt_saved", int(round_trips_saved)))
    if fusion_serial_seconds is not None:
        fields.append(("fusion_serial_s", float(fusion_serial_seconds)))
    with _ledger_lock:
        d = kernel_ledger.setdefault(
            kind, {k: 0 if k in ("dispatches", "rows") else 0.0
                   for k in _LEDGER_RAW})
        for f, v in fields:
            d[f] = d.get(f, 0) + v
    # outside the ledger lock: also credit the thread-attributed stats
    # context (concurrent queries must not read each other's dispatches
    # out of the shared ledger diff)
    from .. import observability as obs
    for field, v in fields:
        if v:
            obs.bump_plane("device_kernels", f"{kind}\x00{field}", v)
    # calibration chokepoint (round 20): every real dispatch's achieved
    # rate feeds the learned cost-model profile (no-op unless
    # DAFT_TPU_CALIBRATION is on and the chaos freeze is off)
    from . import calibration
    calibration.observe_dispatch(kind, strategy, rows=rows, nbytes=nbytes,
                                 seconds=seconds, dispatches=dispatches)
    # tracing plane: one span per real dispatch, carrying the ledger's
    # roofline story onto the query timeline (guard-checked: untraced
    # queries build nothing here)
    from .. import tracing
    tctx = tracing.current()
    if tctx is not None:
        attrs = {"rows": rows, "bytes": int(nbytes), "flops": int(flops)}
        if strategy:
            attrs["strategy"] = strategy
        if load_factor is not None:
            attrs["load_factor"] = round(float(load_factor), 3)
        if seconds > 0:
            attrs["gbps"] = round(nbytes / seconds / 1e9, 3)
            attrs["roofline_pct"] = round(
                100.0 * nbytes / seconds / hbm_bps(), 4)
            if flops:
                attrs["mfu_pct"] = round(
                    100.0 * flops / seconds / peak_flops(), 4)
        dur_us = int(seconds * 1e6)
        rec = tctx.recorder
        rec.add(f"device:{kind}",
                rec.unique_span_id(f"device:{kind}"), tctx.span_id,
                tracing._now_us() - dur_us, dur_us, attrs=attrs,
                lane="device")


def _derive(d: dict) -> dict:
    out = {k: (round(v, 6) if isinstance(v, float) else v)
           for k, v in d.items() if k not in _LEDGER_STRATEGY}
    s = d.get("seconds", 0.0)
    if s > 0 and d.get("bytes"):
        out["achieved_gbps"] = round(d["bytes"] / s / 1e9, 3)
        out["roofline_pct"] = round(100.0 * d["bytes"] / s / hbm_bps(), 4)
    if s > 0:
        if d.get("flops"):
            out["achieved_tflops"] = round(d["flops"] / s / 1e12, 4)
            out["mfu_pct"] = round(100.0 * d["flops"] / s / peak_flops(), 4)
    counts = {nm: int(d.get(f"strategy_{nm}", 0))
              for nm in ("hash", "sort", "dense")}
    ran = [nm for nm, c in counts.items() if c]
    if ran:
        out["strategy"] = ran[0] if len(ran) == 1 else "mixed"
        if len(ran) > 1:
            for nm in ran:
                out[f"strategy_{nm}"] = counts[nm]
    nh = counts["hash"]
    if nh and d.get("lf_sum"):
        out["load_factor"] = round(d["lf_sum"] / nh, 3)
    ser = d.get("serial_s", 0.0)
    if ser and s > 0:
        # round 17 overlap evidence: serial-equivalent stage seconds vs
        # the pipelined wall — >1.0 means the async window really hid
        # host encode/decode + transfer behind device compute
        out["serial_equiv_s"] = round(ser, 6)
        out["overlap_x"] = round(ser / s, 3)
    if d.get("fused_ops"):
        out["fused_ops"] = int(d["fused_ops"])
    if d.get("rt_saved"):
        out["round_trips_saved"] = int(d["rt_saved"])
    fser = d.get("fusion_serial_s", 0.0)
    if fser and s > 0:
        # round 21 fusion evidence: modeled serial per-fragment seconds
        # vs the fused-region wall — >1.0 means compiling the chain into
        # one program really beat dispatching it operator-at-a-time
        out["fusion_serial_s"] = round(fser, 6)
        out["fusion_x"] = round(fser / s, 3)
    return out


def ledger_snapshot(raw: bool = False) -> dict:
    """Per-family sums; with derived GB/s + roofline/MFU percentages
    unless ``raw`` (raw snapshots are what ``ledger_delta`` diffs)."""
    with _ledger_lock:
        snap = {k: dict(v) for k, v in kernel_ledger.items()}
    if raw:
        return snap
    return {k: _derive(d) for k, d in snap.items()}


def ledger_delta(before: dict, after: dict) -> dict:
    """Derived ledger for the work BETWEEN two raw snapshots (per-query
    accounting in observability)."""
    out = {}
    for kind, d in after.items():
        b = before.get(kind, {})
        diff = {k: d.get(k, 0) - b.get(k, 0)
                for k in _LEDGER_RAW + _LEDGER_STRATEGY}
        if diff["dispatches"] > 0:
            out[kind] = _derive(diff)
    return out


def ledger_from_tallies(flat: dict) -> dict:
    """Derived per-kind ledger from a context-attributed flat tally
    (``"<kind>\\x00<field>"`` keys, the shape ``ledger_record`` bumps into
    a RuntimeStatsContext plane) — same output shape as ``ledger_delta``."""
    kinds: dict = {}
    for key, v in flat.items():
        kind, _, field = key.partition("\x00")
        if field not in _LEDGER_RAW + _LEDGER_STRATEGY:
            continue
        d = kinds.setdefault(
            kind, {k: 0 if k in ("dispatches", "rows") else 0.0
                   for k in _LEDGER_RAW})
        d[field] = int(v) if field in ("dispatches", "rows") else float(v)
    return {k: _derive(d) for k, d in kinds.items()
            if d["dispatches"] > 0}


def ledger_reset() -> None:
    with _ledger_lock:
        kernel_ledger.clear()


def _forced() -> Optional[bool]:
    from ..analysis import knobs
    v = knobs.env_raw("DAFT_TPU_DEVICE_FORCE")
    if v is None:
        return None
    # spellings documented in the knob registry: 1/device force device,
    # 0/host force host
    if v.lower() in ("1", "device", "on", "true"):
        return True
    if v.lower() in ("0", "host", "off", "false"):
        return False
    return None


# ------------------------------------------------------- decision logging

#: in-process decision counters {kind: {"device": n, "host": n}} — surfaced
#: by explain_analyze; reset_for_tests clears them
decision_counts: dict = {}
_counts_lock = threading.Lock()


def _log(kind: str, device: bool, host_s: float, dev_s: float,
         **extras) -> None:
    """Record one dispatch decision. Always counts in-process; additionally
    appends a JSONL record when ``DAFT_TPU_DISPATCH_LOG`` names a file —
    the raw material for regressing predicted-vs-actual residuals (r4:
    per-query mispredicts like Q22-at-SF10 could only be diagnosed by
    re-deriving which decisions each query made)."""
    from ..analysis import knobs
    path = knobs.env_str("DAFT_TPU_DISPATCH_LOG")
    rec = None
    if path:
        import json
        rec = {"kind": kind, "device": bool(device),
               "host_s": round(host_s, 6), "dev_s": round(dev_s, 6)}
        rec.update({k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in extras.items()})
        rec = json.dumps(rec) + "\n"
    with _counts_lock:
        d = decision_counts.setdefault(kind, {"device": 0, "host": 0})
        d["device" if device else "host"] += 1
        if rec is None:
            return
        # the JSONL append stays under the SAME lock: concurrent executor
        # threads must not interleave partial lines (single small O_APPEND
        # writes are usually atomic on Linux, but that is not guaranteed,
        # and the handle is reopened per record)
        try:
            # daft-lint: allow(blocking-under-lock) -- the serialization IS
            # the point (see comment above); sub-ms local append
            with open(path, "a") as f:
                f.write(rec)
        except OSError:
            pass


# ---------------------------------------------------------------- decisions

def row_output_op_wins(bytes_up: float, bytes_down: float,
                       round_trips: float = 2.0,
                       host_bytes: Optional[float] = None) -> bool:
    """Projection / predicate / similar: output is row-shaped; host cost is
    a vector pass over the touched bytes. ``bytes_up`` is wire (encoded)
    bytes; ``host_bytes`` the raw Arrow bytes a host pass touches
    (defaults to ``bytes_up``)."""
    f = _forced()
    if f is not None:
        return f
    host_s = ((host_bytes if host_bytes is not None else bytes_up)
              + bytes_down) / HOST_VECTOR_BPS
    kernel_s = DEV_DISPATCH_S + (bytes_up + bytes_down) \
        / _cal("DEV_VECTOR_BPS", DEV_VECTOR_BPS)
    dev_s = link_profile().device_seconds(
        bytes_up, bytes_down, round_trips, kernel_s)
    _log("row_output", dev_s < host_s, host_s, dev_s,
         bytes_up=bytes_up, bytes_down=bytes_down)
    return dev_s < host_s


def image_resize_wins(bytes_up: float, bytes_down: float) -> bool:
    """Batched device image resize vs per-image PIL. The host alternative
    is PIL's scalar loop (~85 MB/s single-core), far slower than a SIMD
    vector pass — so on a local chip the batch wins by orders of
    magnitude, while on a slow tunnel the transfer dominates and PIL
    keeps the work (r4: the ungated device path shipped 50 MB per batch
    over a ~10 MB/s tunnel, 6× slower than host end to end)."""
    f = _forced()
    if f is not None:
        return f
    host_s = bytes_up / HOST_PIL_BPS
    kernel_s = DEV_DISPATCH_S + (bytes_up + bytes_down) \
        / _cal("DEV_VECTOR_BPS", DEV_VECTOR_BPS)
    dev_s = link_profile().device_seconds(bytes_up, bytes_down, 2.0,
                                          kernel_s)
    _log("image_resize", dev_s < host_s, host_s, dev_s,
         bytes_up=bytes_up, bytes_down=bytes_down)
    return dev_s < host_s


def argsort_wins(n_rows: int, key_bytes: float, n_keys: int) -> bool:
    f = _forced()
    if f is not None:
        return f
    host_s = n_rows * max(n_keys, 1) / HOST_SORT_ROWS_PER_S
    bytes_down = n_rows * 8  # the permutation
    kernel_s = DEV_DISPATCH_S + n_rows * max(n_keys, 1) \
        / _cal("DEV_SORT_ROWS_PER_S", DEV_SORT_ROWS_PER_S)
    dev_s = link_profile().device_seconds(key_bytes, bytes_down, 2.0,
                                          kernel_s)
    _log("argsort", dev_s < host_s, host_s, dev_s,
         n_rows=n_rows, key_bytes=key_bytes)
    return dev_s < host_s


def agg_upload_wins(bytes_up: float, bytes_down: float,
                    cacheable: bool, round_trips: float = 2.0,
                    host_bytes: Optional[float] = None,
                    strategy: str = "sort", window: int = 1) -> bool:
    """Aggregation whose inputs are NOT already device-resident.

    ``bytes_up`` is the WIRE cost (encoded device bytes: f64 rides f32,
    strings ride i32 codes); ``host_bytes`` is what a host pass actually
    touches (raw Arrow bytes — defaults to ``bytes_up`` for callers that
    only know one number). Conflating them double-counted f64-heavy
    uploads while under-counting the host pass.

    Cacheable inputs (stable scan-task fingerprint, fits the HBM budget) are
    an *investment*: buffer-pool semantics — you don't refuse to fill the
    cache because the fill run is slower than one host query; you fill
    because every later query over the same scan runs resident (one packed
    transfer, ~10× under the host tier measured on Q1/Q6). Opt out with
    ``DAFT_TPU_CACHE_INVEST=0`` for strict one-shot workloads, where the
    upload must beat the host outright.

    Non-cacheable inputs pay full freight against a host pass at
    ``HOST_AGG_BPS`` over the touched bytes.

    The investment is BOUNDED (r4: TPC-H Q22's tiny per-task aggregates
    were 'invested' at ~20x a host pass — 16 RTT-dominated round trips
    the cache never paid back, 10.9s vs 2.1s host on the SF10 suite): a
    fill may cost up to ``INVEST_MAX_RATIO``x the host pass, enough to
    absorb genuinely profitable cache fills (Q1/Q6 measured ~8-9x fill
    for ~10x steady-state) while rejecting fills that would need dozens
    of repeat queries to break even."""
    f = _forced()
    if f is not None:
        return f
    lp = link_profile()
    host_s = (host_bytes if host_bytes is not None else bytes_up) \
        / HOST_AGG_BPS
    # round 12: the fused-agg gate prices the kernel at the strategy the
    # dispatch would actually take — the one-pass hash kernel streams the
    # data once where the sort strategy pays ≥2 passes per packed plane
    bps = _cal("DEV_AGG_HASH_BPS", DEV_AGG_HASH_BPS) if strategy == "hash" \
        else _cal("DEV_AGG_DENSE_BPS", DEV_AGG_DENSE_BPS) \
        if strategy == "dense" else _cal("DEV_AGG_BPS", DEV_AGG_BPS)
    kernel_s = DEV_DISPATCH_S + bytes_up / bps
    # round 17: with the async pipeline active (window ≥ 2 in-flight
    # morsel slots) the transfer legs overlap neighbor morsels' compute,
    # so the dispatch is priced at the steady-state bottleneck instead
    # of the full serial chain — the serial price under-dispatched to
    # the device exactly when overlap would have hidden the transfer
    dev_s = lp.pipelined_seconds(bytes_up, bytes_down, round_trips,
                                 kernel_s) if window >= 2 else \
        lp.device_seconds(bytes_up, bytes_down, round_trips, kernel_s)
    from ..analysis import knobs
    if cacheable and knobs.env_bool("DAFT_TPU_CACHE_INVEST"):
        # invest only when residency PAYS: a resident rerun (no upload,
        # but every dispatch still pays its — window-amortized, see
        # _fragment_scan_tasks' single packed fetch — round trips) must
        # beat the host pass, else the cache can never repay the fill no
        # matter how many times the query repeats (r4: TPC-H Q22's tiny
        # per-task aggregates burned 10.9s vs 2.1s host at SF10). The
        # ratio bound additionally rejects pathological fill costs.
        resident_s = lp.pipelined_seconds(0.0, bytes_down, round_trips,
                                          kernel_s) if window >= 2 else \
            lp.device_seconds(0.0, bytes_down, round_trips, kernel_s)
        win = resident_s < host_s and dev_s < INVEST_MAX_RATIO * host_s
        _log("agg_upload_invest", win, host_s, dev_s,
             resident_s=resident_s, bytes_up=bytes_up,
             bytes_down=bytes_down, round_trips=round_trips)
        return win
    _log("agg_upload", dev_s < host_s, host_s, dev_s,
         bytes_up=bytes_up, bytes_down=bytes_down, round_trips=round_trips)
    return dev_s < host_s


def fusion_serial_estimate(rows: int, n_ops: int) -> float:
    """Modeled wall of the PER-FRAGMENT serial chain a fused region
    replaced: each of the ``n_ops`` fused operators would have paid its
    own dispatch + transfer legs + round trips. Recorded per dispatch
    into the ``region`` ledger family, where ``_derive`` turns it into
    the ``fusion_x`` ratio (modeled serial / achieved fused wall)."""
    lp = link_profile()
    b = max(rows, 1) * 8.0
    per_op = lp.device_seconds(
        b, b, 2.0,
        DEV_DISPATCH_S + b / _cal("DEV_VECTOR_BPS", DEV_VECTOR_BPS))
    return max(n_ops, 1) * per_op


def fusion_wins(shape: str, rows: int, bytes_up: float, bytes_down: float,
                n_ops: int, host_bytes: Optional[float] = None,
                window: int = 1) -> bool:
    """Admission gate for one FusedRegion morsel (round 21): the single
    fused dispatch — one upload, one kernel, one packed download — against
    the host running the region's whole operator chain. Shapes price the
    host side differently: a chain is ``n_ops`` vectorized passes, a topk
    adds the host sort, a join_agg is the hash join plus the aggregation
    pass. ``DAFT_TPU_FUSION=1`` bypasses this gate entirely (the executor
    force-admits); ``auto`` calls it per morsel."""
    f = _forced()
    if f is not None:
        return f
    lp = link_profile()
    hb = host_bytes if host_bytes is not None else bytes_up
    if shape == "join_agg":
        host_s = rows / HOST_JOIN_ROWS_PER_S + hb / HOST_AGG_BPS
        kernel_s = DEV_DISPATCH_S \
            + rows / _cal("DEV_JOIN_ROWS_PER_S", DEV_JOIN_ROWS_PER_S) \
            + bytes_up / _cal("DEV_AGG_BPS", DEV_AGG_BPS)
    elif shape == "topk":
        host_s = hb / HOST_VECTOR_BPS * max(n_ops - 1, 1) \
            + rows / HOST_SORT_ROWS_PER_S
        kernel_s = DEV_DISPATCH_S \
            + rows / _cal("DEV_SORT_ROWS_PER_S", DEV_SORT_ROWS_PER_S) \
            + bytes_up / _cal("DEV_VECTOR_BPS", DEV_VECTOR_BPS)
    else:
        host_s = hb / HOST_VECTOR_BPS * max(n_ops, 1)
        kernel_s = DEV_DISPATCH_S \
            + bytes_up / _cal("DEV_VECTOR_BPS", DEV_VECTOR_BPS)
    dev_s = lp.pipelined_seconds(bytes_up, bytes_down, 2.0, kernel_s) \
        if window >= 2 else \
        lp.device_seconds(bytes_up, bytes_down, 2.0, kernel_s)
    _log("fusion", dev_s < host_s, host_s, dev_s,
         shape=shape, rows=rows, n_ops=n_ops)
    return dev_s < host_s


# distributed-shuffle wire model: the DCN-tier host↔host transport
# (flight/HTTP shuffle service), NOT the host↔device link profiled above.
# Coarse constants in the same spirit as the host kernel bandwidths — the
# decision only needs the ratio between an agg pass and a row's full
# shuffle trip (serialize + wire + deserialize + reduce-side agg) to the
# right order of magnitude. DAFT_TPU_SHUFFLE_WIRE_MBPS overrides for real
# pod DCN numbers.
SHUFFLE_SER_BPS = 2.0e9   # arrow IPC write/read, per side, per byte


def shuffle_wire_bps() -> float:
    """Wire bandwidth the shuffle/exchange decisions price against. An
    EXPLICIT env setting wins (ops know their DCN); otherwise the
    calibrated rate — observed at every sizable shuffle fetch — beats
    the hard-coded 1000 MB/s default once its sample floor is met."""
    from ..analysis import knobs
    if knobs.env_raw("DAFT_TPU_SHUFFLE_WIRE_MBPS") is not None:
        return knobs.env_float("DAFT_TPU_SHUFFLE_WIRE_MBPS") * 1e6
    return _cal("SHUFFLE_WIRE_BPS",
                knobs.env_float("DAFT_TPU_SHUFFLE_WIRE_MBPS") * 1e6)


# ----------------------------------------------- ICI (mesh) link model
# The third link tier: intra-mesh collective bandwidth (ICI on a pod,
# shared memory on the virtual CPU mesh). MEASURED like the host↔device
# link: one warm timed all_to_all repartition over the process mesh, once
# per process, memoized — the effective rate includes the collective
# kernel's own bucketing work, which is exactly what an exchanged byte
# pays. DAFT_TPU_ICI_MBPS skips measurement (ops / tests / real pod
# numbers); measurement failure falls back to a conservative constant.

MESH_DISPATCH_S = 3e-3     # collective dispatch + amortized per-size-class
#                            compile (programs are memoized per shape
#                            bucket, so the trace cost spreads across
#                            every same-class exchange)
HOST_EXCHANGE_BPS = 6.0e8  # host hash-partition pass (hash + scatter),
#                            per byte — between the vector and agg rates
_ICI_FALLBACK_BPS = 2.0e9  # can't measure → assume a modest link
_ICI_PROBE_ROWS = 1 << 14  # per-shard probe rows (i64 planes)

_ici_lock = threading.Lock()
_ici: Optional[float] = None


def _measure_ici() -> float:
    """MARGINAL collective-exchange bandwidth: two warm timed
    ``sharded_hash_repartition`` probes (the very program the collective
    exchange path dispatches) at 1× and 4× the probe size; the rate comes
    from the byte and time DIFFERENCES, so the fixed dispatch overhead —
    which ``MESH_DISPATCH_S`` models separately — doesn't masquerade as
    link slowness (a single-size probe on the CPU mesh under-reported the
    link ~10× because one small dispatch is overhead-dominated)."""
    import jax

    from ..parallel import exchange, mesh as pmesh
    mesh = pmesh.get_mesh()
    n = pmesh.mesh_size()
    if mesh is None or n < 2:
        raise RuntimeError("no multi-device mesh to calibrate against")

    def timed(rows_per_shard: int):
        total = n * rows_per_shard
        plane = np.arange(total, dtype=np.int64)
        valid = np.ones(total, dtype=bool)
        pid = (np.arange(total) % n).astype(np.int32)

        def run():
            sb = lambda a: exchange.shard_blocks(mesh, a)
            out = exchange.sharded_hash_repartition(
                mesh, (sb(plane),), (sb(valid),), sb(valid), sb(pid))
            jax.block_until_ready(out)

        run()  # warm-up: compile + stage paid here, not in the timed pass
        t0 = time.perf_counter()
        run()
        # full exchanged payload: value plane + valid + row mask + pid
        return time.perf_counter() - t0, total * (8 + 1 + 1 + 4)

    t1, b1 = timed(_ICI_PROBE_ROWS)
    t2, b2 = timed(4 * _ICI_PROBE_ROWS)
    if t2 > t1:
        return (b2 - b1) / (t2 - t1)
    return b2 / max(t2, 1e-7)  # noisy clock: effective rate of the big probe


def ici_bps() -> float:
    """The calibrated (or overridden) intra-mesh collective bandwidth,
    bytes/s."""
    global _ici
    if _ici is not None:
        return _ici
    with _ici_lock:
        if _ici is not None:
            return _ici
        from ..analysis import knobs
        env = knobs.env_float("DAFT_TPU_ICI_MBPS", default=None)
        if env is not None:
            _ici = env * 1e6
            return _ici
        measured = None
        try:
            # daft-lint: allow(blocking-under-lock) -- intentional: one
            # calibration per process; concurrent deciders wait for it
            # instead of racing duplicate mesh probes
            measured = _measure_ici()
            _ici = measured
        except Exception:
            # can't probe this process → the calibrated (cross-process)
            # rate beats the hard-coded fallback once it has samples
            _ici = _cal("ICI_BPS", _ICI_FALLBACK_BPS)
    if measured is not None:
        # outside the probe lock: fold only a REAL measurement into the
        # persisted per-backend profile (feeding the fallback constant
        # back in would let it masquerade as evidence) so meshless
        # processes start calibrated
        from . import calibration
        calibration.observe("ICI_BPS", measured)
    return _ici


def mesh_exchange_wins(rows: Optional[int], row_bytes: float = 32.0,
                       n_shards: int = 2) -> bool:
    """Admission for a LOCAL mesh collective (DeviceExchangeAgg, the
    in-process hash repartition): price the collective — dispatch +
    amortized compile + the bytes over the calibrated ICI rate — against
    one host hash-partition pass over the same bytes. Replaces the static
    64Ki-row gate, which measured rows and ignored row width: a 50k-row
    200-byte-row exchange was wrongly declined while a 100k-row 8-byte
    one was wrongly accepted on a slow mesh. Unknown ``rows`` keeps the
    old optimistic behavior (the structural gates already vetted the
    plan). ``DAFT_TPU_MESH_MIN_ROWS`` (when set) force-overrides in
    ``parallel/mesh.py`` before this is consulted."""
    if rows is None:
        return True
    if rows <= 0:
        return False
    nbytes = rows * max(row_bytes, 1.0)
    host_s = nbytes / HOST_EXCHANGE_BPS
    dev_s = MESH_DISPATCH_S + nbytes / ici_bps()
    _log("mesh_exchange", dev_s < host_s, host_s, dev_s,
         rows=rows, row_bytes=row_bytes, n_shards=n_shards)
    return dev_s < host_s


def exchange_collective_wins(rows: Optional[int],
                             row_bytes: float = 32.0) -> bool:
    """Price a DISTRIBUTED hash boundary's collective path against the
    Flight wire: the collective pays one mesh dispatch plus the bytes
    over ICI; the Flight trip pays IPC serialize + wire + deserialize per
    byte. With no cardinality evidence the collective wins by default —
    an intra-mesh boundary riding the wire is the pathology this decision
    exists to stop, and the runtime admission gate
    (``mesh.mesh_admits``) re-checks with exact rows before dispatching
    the program. Logged under ``exchange_path`` ("device" = collective
    family)."""
    if not rows:
        _log("exchange_path", True, 0.0, 0.0, rows=rows or 0)
        return True
    nbytes = rows * max(row_bytes, 1.0)
    wire_s = nbytes * (2.0 / SHUFFLE_SER_BPS + 1.0 / shuffle_wire_bps())
    coll_s = MESH_DISPATCH_S + nbytes / ici_bps()
    _log("exchange_path", coll_s < wire_s, wire_s, coll_s,
         rows=rows, row_bytes=row_bytes)
    return coll_s < wire_s


def shuffle_combine_wins(rows: Optional[int], groups: Optional[int],
                         num_partitions: int, n_cols: int = 4,
                         bytes_per_col: float = 8.0,
                         exact_groups: bool = False) -> bool:
    """Price the map-side shuffle combine for a hash boundary feeding a
    decomposable grouped aggregation (Partial Partial Aggregates).

    The combine pays one extra grouped-agg pass over the map output
    (``rows`` state rows at ``HOST_AGG_BPS``) and saves the full shuffle
    trip — IPC serialize, wire, deserialize, reduce-side agg — for every
    row it eliminates: without the combine the wire carries ~``rows``
    per-morsel group states, with it at most ``groups × num_partitions``
    (each map task holds ≤ groups states per partition). Near-unique keys
    (TPC-H Q18's shape) eliminate almost nothing and decline; reductive
    group-bys (Q1's shape) accept.

    With no cardinality evidence the combine wins by default — for
    decomposable aggs the pre-shuffle combine is the literature's default,
    and its worst case (zero reduction) costs one extra linear pass while
    its best saves the whole wire. The decision lands in
    ``decision_counts``/the dispatch log under ``shuffle_combine``
    ("device" = combine applied)."""
    row_bytes = max(n_cols, 1) * bytes_per_col
    if not rows or not groups:
        # no cardinality evidence: default-accept, logged like every
        # other decision so the combine is always traceable
        _log("shuffle_combine", True, 0.0, 0.0, rows=rows or 0,
             groups=groups or 0, num_partitions=num_partitions)
        return True
    if not exact_groups:
        # round 20: footer NDV evidence is damped by the calibrated
        # actual/footer ratio — parquet min/max range NDV systematically
        # over-predicts (a sparse key set reads as near-unique), which
        # declined combines that would have collapsed the wire. EXACT
        # evidence (measured by the re-planner) is never damped.
        from . import calibration
        groups = max(groups * calibration.ndv_ratio(), 1.0)
    groups_out = min(rows, groups * max(num_partitions, 1))
    saved_rows = max(rows - groups_out, 0)
    per_byte_trip = (2.0 / SHUFFLE_SER_BPS + 1.0 / shuffle_wire_bps()
                     + 1.0 / HOST_AGG_BPS)
    saved_s = saved_rows * row_bytes * per_byte_trip
    extra_s = rows * row_bytes / HOST_AGG_BPS
    _log("shuffle_combine", saved_s > extra_s, extra_s, saved_s,
         rows=rows, groups=groups, num_partitions=num_partitions)
    return saved_s > extra_s


def combine_wins_pure(rows: Optional[int], groups: Optional[int],
                      num_partitions: int, n_cols: int = 4,
                      bytes_per_col: float = 8.0) -> bool:
    """The HARD-CODED combine decision — same math as
    ``shuffle_combine_wins`` but with no calibration damping, no
    logging, and no side effects. The runtime re-planner compares the
    evidence-priced decision against this to count ``combine_flips``
    without double-tallying ``decision_counts``."""
    if not rows or not groups:
        return True
    row_bytes = max(n_cols, 1) * bytes_per_col
    groups_out = min(rows, groups * max(num_partitions, 1))
    saved_rows = max(rows - groups_out, 0)
    per_byte_trip = (2.0 / SHUFFLE_SER_BPS + 1.0 / shuffle_wire_bps()
                     + 1.0 / HOST_AGG_BPS)
    return saved_rows * row_bytes * per_byte_trip \
        > rows * row_bytes / HOST_AGG_BPS


# --------------------------------------------- out-of-core spill pricing

SPILL_DISK_BPS = 1.5e9   # spill-tier IPC write/read rate, per byte per
#                          direction (local NVMe with lz4 buffer
#                          compression; coarse like the host constants —
#                          the decision only needs the ratio of one extra
#                          disk round trip to an in-memory pass)


def spill_plan_wins(nbytes: float, resident_budget: float) -> bool:
    """Price a spill-partitioned plan (grace join pairwise phase /
    spill-partitioned agg) against the in-memory single-unit plan for
    ``nbytes`` of materialized input with ``resident_budget`` bytes
    allowed resident.

    A spilled partition is a price, not a failure (HiFrames): past the
    resident budget the in-memory plan is INFEASIBLE (an OOM has
    infinite cost) and the partitioned plan wins outright; under it the
    partitioned plan pays one extra IPC write+read of the overflow it
    would have spilled — zero when everything stayed resident — so small
    inputs keep the whole-input single join/merge. Logged under
    ``spill_plan`` ("device" = partitioned plan chosen).

    Pressure-aware (r23): under governor memory pressure the resident
    budget this decision prices against halves — a gather that fits on
    paper is still the wrong plan when the PROCESS is already at its
    high watermark, so borderline inputs flip to the partitioned plan
    early. Inert when the governor is (no limit / chaos freeze)."""
    try:
        from ..execution import governor
        scale = governor.budget_scale()
        if scale != 1.0:
            resident_budget = resident_budget * scale
    except Exception:
        pass
    agg_s = nbytes / HOST_AGG_BPS
    if nbytes > resident_budget:
        part_s = agg_s + 2.0 * (nbytes - resident_budget) / SPILL_DISK_BPS
        _log("spill_plan", True, 1e12, part_s,
             nbytes=nbytes, budget=resident_budget)
        return True
    # everything fits resident: the partitioned plan would spill nothing
    # but still forfeits the whole-input kernel pass — in-memory wins
    _log("spill_plan", False, agg_s, agg_s,
         nbytes=nbytes, budget=resident_budget)
    return False


def join_wins(n_left: int, n_right: int, bytes_up: float,
              bytes_down: float, window: int = 1) -> bool:
    """Equi-join as one fused device program (hash build/probe when the
    strategy model picks it, else sort/searchsorted/expand): output is
    one packed index matrix; host cost is a hash build+probe. ONE
    dispatch and ONE result transfer (the r5 three-phase pipeline paid 3
    dispatches + 4 round trips). Round 12 re-pricing: when the hash
    strategy would run, the kernel term uses the one-pass hash rate
    instead of the radix-sort rate — the device now affords joins the
    sort pricing declined."""
    f = _forced()
    if f is not None:
        return f
    n = n_left + n_right
    host_s = n / HOST_JOIN_ROWS_PER_S
    rate = _cal("DEV_JOIN_HASH_ROWS_PER_S", DEV_JOIN_HASH_ROWS_PER_S) \
        if _join_strategy(n_left, n_right) == "hash" \
        else _cal("DEV_JOIN_ROWS_PER_S", DEV_JOIN_ROWS_PER_S)
    kernel_s = DEV_DISPATCH_S + n / rate
    lp = link_profile()
    # round 17: overlap pricing when the async pipeline is active (the
    # join's upload/download legs hide behind neighbor dispatches)
    dev_s = lp.pipelined_seconds(bytes_up, bytes_down, 2.0, kernel_s) \
        if window >= 2 else \
        lp.device_seconds(bytes_up, bytes_down, 2.0, kernel_s)
    _log("join", dev_s < host_s, host_s, dev_s,
         n_left=n_left, n_right=n_right, bytes_up=bytes_up)
    return dev_s < host_s


# ------------------------------------------------ kernel strategy (round 12)

def _hash_capable_backend() -> bool:
    """Compiled Pallas needs silicon; the interpreter exists for parity,
    not speed — in ``auto`` mode a CPU backend keeps the XLA sort path."""
    from . import backend
    return backend.is_accelerator()


def _join_strategy(n_left: int, n_right: int) -> str:
    """Hash-vs-sort for the device join, without logging (join_wins
    pre-prices with it; ``join_strategy`` is the logged decision the
    dispatch site acts on)."""
    from ..analysis import knobs
    from . import pallas_kernels as pk
    forced = (knobs.env_str("DAFT_TPU_KERNEL_JOIN") or "auto").lower()
    if forced in ("hash", "sort"):
        return forced
    if not _hash_capable_backend():
        return "sort"
    from .column import bucket_capacity
    if pk.join_table_capacity(bucket_capacity(max(n_right, 1))) \
            > pk.max_table_slots():
        return "sort"  # build table exceeds the on-chip budget
    if bucket_capacity(max(n_left, n_right, 1)) > pk.max_table_slots():
        # the probe kernel pins two output-capacity-sized index planes
        # on-chip (whole-plane BlockSpecs), and the first dispatch's
        # bucket is sized from the larger side — past the slot ceiling
        # those planes belong to the sort kernel, whose buffers live
        # in HBM
        return "sort"
    # the hash build streams each side once; the sort build pays ≥2
    # passes over the build planes — one-pass wins whenever it fits
    return "hash"


def join_strategy(n_left: int, n_right: int) -> str:
    """The join kernel strategy for this dispatch, logged like every
    other decision (``join_strategy`` in decision_counts / the dispatch
    log; "device" = hash)."""
    s = _join_strategy(n_left, n_right)
    _log("join_strategy", s == "hash", 0.0, 0.0,
         n_left=n_left, n_right=n_right, strategy=s)
    return s


def groupby_strategy(rows: int, groups: Optional[float],
                     key_dtypes, out_cap: int,
                     log: bool = True) -> Tuple[str, float]:
    """Hash-vs-sort for one grouped-agg dispatch → ``(strategy,
    est_load_factor)``. ``log=False`` for pricing-only pre-asks (upload
    gates) so decision_counts tallies acted-on dispatches, not estimates.

    Evidence, best-first: the parquet-footer NDV that already flows to
    the fused-agg gate (``groups``), else the group budget ``out_cap``.
    The hash path declines when (a) the key set packs wider than the
    table key budget (``pallas_kernels.hash_pack_words`` → sort handles
    any width as an LSD radix), (b) the table exceeds the on-chip slot
    ceiling, (c) footer evidence shows near-unique keys
    (``DAFT_TPU_KERNEL_HASH_NDV_FRAC``: the table grows as large as the
    data and the one-pass advantage is gone — TPC-H Q18's shape; absent
    evidence is NOT evidence of high NDV, matching the fused-agg gate's
    optimistic default), or (d) the backend can only interpret Pallas.
    ``DAFT_TPU_KERNEL_GROUPBY=hash|sort`` force-overrides (hash still
    requires a packable key set). Logged under ``groupby_strategy``
    ("device" = hash)."""
    from ..analysis import knobs
    from . import calibration
    from . import pallas_kernels as pk
    words = pk.hash_pack_words(key_dtypes) if key_dtypes else None
    table = pk.table_capacity(max(out_cap, 1))
    # footer NDV evidence damped by the calibrated actual/footer ratio
    # (round 20): over-predicted NDV pushed dispatches onto the sort
    # path whose one-pass hash rival would have won
    ndv = max(groups * calibration.ndv_ratio(), 1.0) if groups \
        else float(out_cap)
    lf = min(ndv / table, 1.0)
    forced = (knobs.env_str("DAFT_TPU_KERNEL_GROUPBY") or "auto").lower()
    if forced == "sort" or words is None:
        s = "sort"
    elif forced == "hash":
        s = "hash"
    elif not _hash_capable_backend():
        s = "sort"
    elif table > pk.max_table_slots():
        s = "sort"
    elif groups and rows > 0 and ndv / rows > knobs.env_float(
            "DAFT_TPU_KERNEL_HASH_NDV_FRAC"):
        s = "sort"
    else:
        from . import mfu
        sort_bytes = mfu.grouped_agg_models(
            rows, out_cap, max(len(key_dtypes), 1), 1)[1]
        hash_bytes = mfu.hash_agg_models(rows, out_cap, table, words, 1)[1]
        s = "hash" if hash_bytes < sort_bytes else "sort"
    if log:
        log_strategy_decision("groupby_strategy", s, rows=rows,
                              groups=float(ndv), out_cap=out_cap,
                              load_factor=lf)
    return s, lf


def log_strategy_decision(kind: str, strategy: str, **extras) -> None:
    """Tally an ACTED-ON kernel-strategy decision. Dispatch sites call
    this once the strategy really ran (after width-gate fallbacks);
    pricing-only pre-asks pass ``log=False`` to the strategy model and
    stay out of ``decision_counts`` — the counts and the dispatch log
    describe what dispatched, not what was estimated."""
    _log(kind, strategy == "hash", 0.0, 0.0, strategy=strategy, **extras)
